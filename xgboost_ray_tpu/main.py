"""Driver API: ``train()`` / ``predict()`` / ``RayParams`` — the coordinator.

API-compatible re-implementation of ``xgboost_ray/main.py`` (L3 of SURVEY §1)
for the TPU runtime. The architectural inversion (SURVEY §7.1): the
reference's N OS-process actors + Rabit tracker become virtual workers that
own data shards and a single jitted SPMD program over the device mesh
(``engine.TpuEngine``); the driver keeps the exact same responsibilities —
validation, checkpointing every k rounds, the retry loop with
restart-from-checkpoint arithmetic, elastic fault tolerance, the queue/event
side-channel, and result merging (evals_result / additional_results).

Fault model: TPU mesh failures surface as exceptions from the round step (or
from fault-injection callbacks in tests); the driver marks ranks dead and —
exactly like the reference (``main.py:1644-1713``) — either continues with
survivors (elastic) or recreates the failed workers, then resumes from the
last checkpoint with the world recompiled for the new mesh size.
"""

import dataclasses
import logging
import os
import pickle
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from xgboost_ray_tpu.callback import (
    DistributedCallback,
    DistributedCallbackContainer,
    TrainingCallback,
)
from xgboost_ray_tpu import faults, obs
from xgboost_ray_tpu.domains import DeathCoalescer, DomainMap, derive_domain_map
from xgboost_ray_tpu.engine import TpuEngine
from xgboost_ray_tpu.exceptions import (
    RayActorError,
    RayTaskError,
    RayXGBoostActorAvailable,
    RayXGBoostTrainingError,
    RayXGBoostTrainingStopped,
)
from xgboost_ray_tpu.matrix import (
    RayDMatrix,
    RayShardingMode,
    _get_sharding_indices,
    combine_data,
    translate_shard_categories,
)
from xgboost_ray_tpu.models.booster import RayXGBoostBooster
from xgboost_ray_tpu.params import parse_params
from xgboost_ray_tpu import session as session_mod
from xgboost_ray_tpu.util import Event, Queue, restart_backoff_s

logger = logging.getLogger(__name__)

LEGACY_MATRIX = False


# ---------------------------------------------------------------------------
# Env-var config system (mirror of ``xgboost_ray/main.py:110-162``): every
# field is overridable via RXGB_<NAME>, re-read live on each access.
# ---------------------------------------------------------------------------


def _get_environ(item: str, old_val: Any):
    env_var = f"RXGB_{item}"
    new_val = old_val
    if env_var in os.environ:
        raw = os.environ[env_var]
        if isinstance(old_val, bool):
            new_val = bool(int(raw))
        elif isinstance(old_val, int):
            new_val = int(raw)
        elif isinstance(old_val, float):
            new_val = float(raw)
        else:
            new_val = raw
    return new_val


@dataclass
class _XGBoostEnv:
    USE_SPREAD_STRATEGY: bool = True
    PLACEMENT_GROUP_TIMEOUT_S: int = 100
    STATUS_FREQUENCY_S: int = 30
    # when set, wrap each training attempt in a jax.profiler trace written
    # to this directory (xprof/tensorboard-compatible) — SURVEY §5.1 upgrade
    PROFILE_DIR: str = ""
    ELASTIC_RESTART_DISABLED: bool = False
    ELASTIC_RESTART_RESOURCE_CHECK_S: float = 30.0
    ELASTIC_RESTART_GRACE_PERIOD_S: float = 10.0
    # fault domains: 0 = derive from placement (process_index groups on a
    # real multi-host mesh, per-rank domains on one host); H > 0 = logical
    # H-way partition of the rank space so domain-granular failure behavior
    # is exercisable on the single-process CPU CI mesh
    FAULT_DOMAINS: int = 0
    # how long the in-flight recovery lingers to fold near-simultaneous
    # deaths (a whole domain dying at once) into ONE shrink; 0 still sweeps
    # once for already-dead ranks, it just doesn't wait for stragglers
    ELASTIC_DEATH_COALESCE_S: float = 0.0
    COMMUNICATION_SOFT_PLACEMENT: bool = True
    # upper bound on rounds fused into one compiled lax.scan program in the
    # batched fast path. Bounds compiled-program size and the stacked
    # per-round outputs held live at once (the round-2 HIGGS-11M run fused
    # all 100 rounds into a single program and crashed the TPU worker,
    # tpu_logs/r2.log:180); 10 divides the usual 100-round protocols so the
    # driver compiles exactly one scan program.
    SCAN_MAX_CHUNK: int = 10
    # SPMD prediction: shard predict rows over the device mesh and run the
    # gather walk as one compiled shard_map program instead of a host-side
    # per-actor loop. Set RXGB_SPMD_PREDICT=0 to force the host loop.
    SPMD_PREDICT: bool = True

    def __getattribute__(self, item):
        old_val = object.__getattribute__(self, item)
        if item.startswith("_"):
            return old_val
        return _get_environ(item, old_val)


ENV = _XGBoostEnv()


# ---------------------------------------------------------------------------
# RayParams
# ---------------------------------------------------------------------------


@dataclass
class RayParams:
    """Parameters to configure distributed-training behavior.

    API mirror of ``xgboost_ray/main.py:448-504`` with one TPU addition:
    ``tpus_per_actor`` (the number of mesh devices each logical actor may
    occupy; the total mesh size is min(num_actors, available devices)).
    """

    # Actor scheduling
    num_actors: int = 0
    cpus_per_actor: int = 0
    gpus_per_actor: int = -1
    tpus_per_actor: int = -1
    resources_per_actor: Optional[Dict] = None

    # Fault tolerance
    elastic_training: bool = False
    max_failed_actors: int = 0
    max_actor_restarts: int = 0
    checkpoint_frequency: int = 5

    # Distributed callbacks
    distributed_callbacks: Optional[List[DistributedCallback]] = None

    verbose: Optional[bool] = None
    placement_options: Optional[Dict[str, Any]] = None

    def get_tune_resources(self):
        """Resources for a Tune trial running this training."""
        from xgboost_ray_tpu.tune import _get_tune_resources

        if self.num_actors <= 0:
            raise ValueError("num_actors must be greater than 0.")
        return _get_tune_resources(
            num_actors=self.num_actors,
            cpus_per_actor=max(0, self.cpus_per_actor),
            gpus_per_actor=max(0, self.gpus_per_actor),
            tpus_per_actor=max(0, self.tpus_per_actor),
            resources_per_actor=self.resources_per_actor,
            placement_options=self.placement_options,
        )


def _validate_ray_params(ray_params: Union[None, RayParams, dict]) -> RayParams:
    if ray_params is None:
        ray_params = RayParams()
    elif isinstance(ray_params, dict):
        ray_params = RayParams(**ray_params)
    elif not isinstance(ray_params, RayParams):
        raise ValueError(
            f"`ray_params` must be a `RayParams` instance, a dict, or None, "
            f"but it was {type(ray_params)}."
        )
    if ray_params.num_actors <= 0:
        raise ValueError(
            "The `num_actors` parameter is set to 0. Please always specify "
            "the number of distributed workers you want to use "
            "(`RayParams(num_actors=X)`)."
        )
    elif ray_params.num_actors < 2:
        warnings.warn(
            f"`num_actors` in `ray_params` is smaller than 2 "
            f"({ray_params.num_actors}). Training will NOT be distributed!"
        )
    return ray_params


@dataclass
class _Checkpoint:
    iteration: int = 0
    value: Optional[bytes] = None


# ---------------------------------------------------------------------------
# Virtual worker ("actor"): owns a rank and its data shards. The compute
# itself runs in the shared mesh program; this object carries the lifecycle
# (load_data, liveness, callbacks) so the reference's scheduling/FT logic and
# tests have the same surface to hook into (``xgboost_ray/main.py:543-815``).
# ---------------------------------------------------------------------------


class RayXGBoostActor:
    def __init__(
        self,
        rank: int,
        num_actors: int,
        queue: Optional[Queue] = None,
        stop_event: Optional[Event] = None,
        distributed_callbacks: Optional[List[DistributedCallback]] = None,
    ):
        self.rank = rank
        self.num_actors = num_actors
        self.queue = queue
        self.stop_event = stop_event
        self.alive = True
        # death-coalescing mailbox (domains.DeathCoalescer) wired up by the
        # driver so an out-of-band kill() lands in the same shrink as its
        # domain siblings
        self._coalescer = None
        self._domain: Optional[int] = None
        self._data: Dict[RayDMatrix, Dict[str, Optional[np.ndarray]]] = {}
        self._local_n: Dict[RayDMatrix, int] = {}
        self._distributed_callbacks = DistributedCallbackContainer(
            distributed_callbacks
        )
        self._distributed_callbacks.on_init(self)

    def pid(self) -> int:
        if not self.alive:
            raise RayActorError(f"actor {self.rank} is dead", ranks=[self.rank])
        return os.getpid()

    def set_queue(self, queue: Queue):
        self.queue = queue

    def set_stop_event(self, stop_event: Event):
        self.stop_event = stop_event

    def load_data(self, data: RayDMatrix):
        if data in self._data:
            return
        faults.fire("actor.load_shard", rank=self.rank)
        self._distributed_callbacks.before_data_loading(self, data)
        shard = data.get_data(self.rank, self.num_actors)
        if shard.get("stream") is not None:
            n = shard["stream"].n_rows
        else:
            n = shard["data"].shape[0] if shard.get("data") is not None else 0
        self._local_n[data] = n
        self._data[data] = shard
        self._distributed_callbacks.after_data_loading(self, data)

    def get_shard(self, data: RayDMatrix) -> Dict[str, Optional[np.ndarray]]:
        return self._data[data]

    def local_n(self, data: RayDMatrix) -> int:
        return self._local_n.get(data, 0)

    def has_data(self, data: RayDMatrix) -> bool:
        return data in self._data

    def kill(self):
        """Mark this worker dead (fault injection / failure detection)."""
        self.alive = False
        coalescer = self._coalescer
        if coalescer is not None:
            coalescer.note(self.rank, self._domain)


# ---------------------------------------------------------------------------
# Training state shared across attempts (mirror of ``main.py:1038-1058``).
# ---------------------------------------------------------------------------


@dataclass
class _TrainingState:
    actors: List[Optional[RayXGBoostActor]]
    queue: Queue
    stop_event: Event
    checkpoint: _Checkpoint
    additional_results: Dict

    failed_actor_ranks: set

    # elastic: dead ranks awaiting background reintegration — NOT recreated
    # by the next attempt (mirror of clearing start ranks, main.py:1659)
    elastic_dead_ranks: set = dataclasses.field(default_factory=set)

    # elastic scheduling (mirror of elastic.py state)
    pending_actors: Optional[Dict[int, Any]] = None  # rank -> elastic.PendingActor
    restart_training_at: Optional[float] = None
    last_resource_check_at: float = 0.0

    # fault domains (ROADMAP item 4): the attempt's rank -> domain
    # assignment, the per-domain reintegration grace clocks, the domains
    # whose replacements are complete and past grace (set by the elastic
    # updater, consumed atomically by the round-boundary grow), and the
    # mailbox that folds near-simultaneous deaths into one shrink
    domain_map: Optional[DomainMap] = None
    domain_restart_at: Dict[int, float] = dataclasses.field(default_factory=dict)
    domains_due: List[int] = dataclasses.field(default_factory=list)
    death_coalescer: DeathCoalescer = dataclasses.field(
        default_factory=DeathCoalescer
    )

    # in-flight elastic continuation: live engines keyed by world signature
    # (tuple of alive ranks), so a shrink->grow cycle revives the cached
    # engine's compiled programs instead of retracing. Bounded to the two
    # most recent worlds (each entry pins device arrays).
    engine_cache: Dict[tuple, Any] = dataclasses.field(default_factory=dict)

    training_started_at: float = 0.0

    # robustness accounting: rounds completed inside the CURRENT attempt
    # (replay arithmetic), when the last failure was detected (so the next
    # attempt's first completed round closes the time-to-recover clock),
    # and failures since the last real forward progress (backoff index —
    # an isolated failure in a long job must not inherit an escalated wait)
    rounds_this_attempt: int = 0
    recover_started_at: Optional[float] = None
    consecutive_failures: int = 0


def _mark_recovered(state: "_TrainingState") -> None:
    """First forward progress after a restart: close the recovery clock and
    rewind the backoff escalation."""
    state.consecutive_failures = 0
    if state.recover_started_at is None:
        return
    delta = time.time() - state.recover_started_at
    rob = state.additional_results.get("robustness")
    if rob is not None:
        rob["time_to_recover_s"] = round(
            rob.get("time_to_recover_s", 0.0) + delta, 4,
        )
    state.recover_started_at = None
    # timeline closure of the clock the matching "failure.detected" opened:
    # bench --chaos reconstructs time-to-recover from these two timestamps
    obs.get_tracer().event(
        "recovered", attrs={"time_to_recover_s": round(delta, 4)}
    )


def _create_actor(
    rank: int,
    num_actors: int,
    queue: Queue,
    stop_event: Event,
    distributed_callbacks: Optional[List[DistributedCallback]],
) -> RayXGBoostActor:
    return RayXGBoostActor(
        rank,
        num_actors,
        queue=queue,
        stop_event=stop_event,
        distributed_callbacks=distributed_callbacks,
    )


def _get_placement_strategy(in_tune_session: bool) -> str:
    """SPREAD for standalone training (fault isolation), PACK inside tuning
    trials — the reference's strategy choice (``main.py:1581-1599``,
    ``tune.py:123``), gated on RXGB_USE_SPREAD_STRATEGY. Consumed by
    ``_select_mesh_devices`` (actual mesh placement) and re-exported through
    ``get_tune_resources()`` for schedulers above."""
    if in_tune_session:
        return "PACK"
    return "SPREAD" if ENV.USE_SPREAD_STRATEGY else "PACK"


def _select_mesh_devices(num: int, strategy: str, devices=None) -> list:
    """Choose which physical devices form the training mesh — the TPU analog
    of the reference's placement group (``main.py:958-1019``): there,
    SPREAD/PACK decides which *nodes* host the actors; here it decides which
    devices (and thereby hosts) host the mesh shards.

    PACK fills hosts/devices in order — fewest hosts touched, the locality
    choice for tune trials sharing one machine. SPREAD takes an equal share
    from every host and an even stride across each host's device ring —
    fault isolation across hosts and maximal spacing on the ICI ring, the
    reference's default for standalone training.

    The selection is returned in jax.devices() order (process-contiguous),
    which the engine's multi-host row layout requires.
    """
    import jax

    devices = list(devices) if devices is not None else list(jax.devices())
    if num >= len(devices) or num <= 0:
        return devices
    if strategy == "PACK":
        return devices[:num]
    by_proc: Dict[int, list] = {}
    for pos, d in enumerate(devices):
        by_proc.setdefault(getattr(d, "process_index", 0), []).append((pos, d))
    procs = sorted(by_proc)
    # Distribute quotas, redistributing any host's deficit (a host may hold
    # fewer devices than its even share) to hosts with spare devices so the
    # returned mesh always matches the requested actor count.
    quotas = {p: 0 for p in procs}
    remaining = num
    while remaining:
        active = [p for p in procs if quotas[p] < len(by_proc[p])]
        base, extra = divmod(remaining, len(active))
        for i, p in enumerate(active):
            k = min(base + (1 if i < extra else 0), len(by_proc[p]) - quotas[p])
            quotas[p] += k
            remaining -= k
    chosen = []
    for p in procs:
        group, k = by_proc[p], quotas[p]
        if k >= len(group):
            chosen.extend(group)
        else:
            # int(j * len / k) is strictly increasing when len > k
            chosen.extend(group[int(j * len(group) / k)] for j in range(k))
    chosen.sort(key=lambda t: t[0])
    assert len(chosen) == num
    return [d for _, d in chosen]


def _resolve_mesh_devices(num: int, ray_params: Optional["RayParams"]) -> list:
    """The one place that decides WHICH devices form a mesh of ``num`` slots:
    a concurrent tune trial's device slice wins; otherwise the user's
    ``placement_options`` strategy override, otherwise SPREAD/PACK by
    context. Shared by training and SPMD prediction so both place work on
    the same devices."""
    from xgboost_ray_tpu import tune as _tune_mod

    _sess = _tune_mod.get_session()
    trial_devices = getattr(_sess, "devices", None) if _sess else None
    if trial_devices is not None:
        return list(trial_devices)
    strategy = None
    if ray_params is not None and ray_params.placement_options:
        strategy = ray_params.placement_options.get("strategy")
    if strategy is None:
        strategy = _get_placement_strategy(in_tune_session=_sess is not None)
    return _select_mesh_devices(num, str(strategy).upper())


def _engine_can_reshard(engine) -> bool:
    """The ONE probe of an engine's zero-replay re-shard capability — every
    elastic decision point (caching a world, gating the in-flight recover,
    choosing boundary-grow vs the legacy ``RayXGBoostActorAvailable``
    restart) routes through here so the gate semantics cannot drift per
    call site. Every built-in engine (including ``LinearEngine``/gblinear)
    re-shards now; only a user-supplied engine without the method is
    restart-only."""
    probe = getattr(engine, "can_reshard", None)
    return bool(probe()) if probe is not None else False


def _handle_queue(queue: Queue, checkpoint: _Checkpoint, callback_returns: Dict):
    """Drain the callback queue (mirror of ``main.py:902-922``)."""
    while not queue.empty():
        rank, item = queue.get()
        if callable(item):
            item()
        elif isinstance(item, _Checkpoint):
            checkpoint.iteration = item.iteration
            checkpoint.value = item.value
            obs.get_tracer().event(
                "checkpoint.commit", round=item.iteration,
                attrs={"bytes": len(item.value or b"")},
            )
        else:
            callback_returns.setdefault(rank, []).append(item)


def _record_allreduce_bytes(state, engine) -> None:
    """Surface the engine's measured per-round collective payload bytes
    (the ``hist_quant`` traffic metric) in additional_results. One host
    read, after training only — never on the per-round path."""
    gh_getter = getattr(engine, "gh_plane_bytes_per_shard", None)
    if gh_getter is not None:
        try:
            # static layout arithmetic (no device read): the per-shard
            # gh-plane footprint the gh_precision mode shrinks — the
            # bench's memory metric, independent of the wire counter below
            state.additional_results["gh_plane_bytes_per_shard"] = int(
                gh_getter()
            )
        except Exception:  # noqa: BLE001 - diagnostics never fail training
            pass
    getter = getattr(engine, "hist_allreduce_bytes_per_round", None)
    if getter is None:
        return
    try:
        val = getter()
    except Exception:  # noqa: BLE001 - diagnostics must not fail training
        return
    if val is not None:
        state.additional_results["hist_allreduce_bytes_per_round"] = val
        obs.get_tracer().event(
            "allreduce.bytes", attrs={"bytes_per_round": int(val)}
        )


def _stop_profile_if_running():
    if not ENV.PROFILE_DIR:
        return
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001 - no trace running
        pass


def _maybe_profile_phases(engine, state) -> None:
    """End-of-training fenced phase profiling (``RXGB_TRACE_PHASES=1``):
    emits sample/hist/split/partition/margin/allreduce spans at the
    engine's true shard shapes and stashes the table for
    ``additional_results["obs"]["phase_profile"]``. Runs after the round
    loop so the standalone phase programs never pollute steady-round
    timings."""
    if not obs.phase_profiling_enabled():
        return
    tracer = obs.get_tracer()
    if not tracer.enabled:
        return
    profiler = getattr(engine, "profile_phases", None)
    if profiler is None:
        return  # gblinear's LinearEngine has no tree phases
    try:
        state.additional_results["_obs_phase_profile"] = profiler(tracer)
    except Exception as exc:  # noqa: BLE001 - diagnostics never fail training
        logger.warning("[RayXGBoost] phase profiling failed: %s", exc)


def _assemble_obs(tracer, state) -> Dict:
    """The ``additional_results["obs"]`` payload: full timeline plus the
    derived per-round and event views and the ring-buffer accounting
    (dropped records are surfaced, never silent)."""
    records = tracer.records()
    rounds = []
    for rec in records:
        if rec.get("kind") == "span" and rec.get("name") == "round":
            row = {"round": rec.get("round"), "dur_s": rec["dur_s"]}
            row.update(rec.get("attrs") or {})
            rounds.append(row)
    out = {
        "timeline": records,
        "rounds": rounds,
        "events": [r for r in records if r.get("kind") == "event"],
        "dropped_spans": tracer.dropped,
        "capacity": tracer.capacity,
    }
    profile = state.additional_results.pop("_obs_phase_profile", None)
    if profile is not None:
        out["phase_profile"] = profile
    return out


class _FauxDMatrix:
    """Lightweight stand-in passed to custom objective/metric callables,
    exposing the xgboost DMatrix accessors they use."""

    def __init__(self, label, weight, group_ptr=None):
        self._label = label
        self._weight = weight
        self._group_ptr = group_ptr

    def get_label(self):
        return self._label

    def get_weight(self):
        return self._weight if self._weight is not None else np.array([])

    def get_group(self):
        return (
            np.diff(self._group_ptr) if self._group_ptr is not None else np.array([])
        )

    def num_row(self):
        return len(self._label)


class _EngineBoosterProxy:
    """Lazy booster view handed to per-iteration callbacks; materializes the
    current forest only when a callback actually touches the model."""

    def __init__(self, engine: TpuEngine):
        self._engine = engine
        self._cached: Optional[RayXGBoostBooster] = None
        self._cached_rounds = -1

    def _rebind(self, engine) -> None:
        """Point the proxy at a new engine (in-flight world shrink/grow)."""
        self._engine = engine
        self._cached = None
        self._cached_rounds = -1

    def _materialize(self) -> RayXGBoostBooster:
        n = self._engine.num_round_trees
        if self._cached is None or self._cached_rounds != n:
            self._cached = self._engine.get_booster()
            self._cached_rounds = n
        return self._cached

    def __getattr__(self, item):
        return getattr(self._materialize(), item)


def _serialize_booster(booster: RayXGBoostBooster) -> bytes:
    return pickle.dumps(booster)


def _deserialize_booster(raw: Optional[bytes]) -> Optional[RayXGBoostBooster]:
    return pickle.loads(raw) if raw else None


def _coerce_model(model) -> Optional[RayXGBoostBooster]:
    from xgboost_ray_tpu.linear import RayLinearBooster

    if model is None:
        return None
    if isinstance(model, (RayXGBoostBooster, RayLinearBooster)):
        return model
    if isinstance(model, bytes):
        return _deserialize_booster(model)
    if isinstance(model, str):
        # parse ONCE, dispatch on the document's own booster name (a
        # malformed tree file then fails with ITS parse error, not a
        # misleading gblinear one; no double I/O on big forests)
        import json as _json

        with open(model) as f:
            doc = _json.load(f)
        name = doc.get("learner", {}).get("gradient_booster", {}).get("name")
        if name == "gblinear":
            return RayLinearBooster.import_xgboost_json(doc)
        return RayXGBoostBooster._from_dict(doc)
    raise ValueError(f"Cannot interpret xgb_model of type {type(model)}")


_KNOWN_TRAIN_KWARGS = {
    "obj",
    "feval",
    "custom_metric",
    "callbacks",
    "early_stopping_rounds",
    "verbose_eval",
    "xgb_model",
    "maximize",
    "serve_registry",
}


def _validate_kwargs_for_func(kwargs: Dict, allowed: set, func_name: str):
    unknown = [k for k in kwargs if k not in allowed]
    if unknown:
        raise TypeError(
            f"{func_name}() got unexpected keyword argument(s): {unknown}. "
            f"Supported extra arguments: {sorted(allowed)}"
        )


# ---------------------------------------------------------------------------
# One training attempt (mirror of ``_train``, ``main.py:1061-1337``).
# ---------------------------------------------------------------------------


def _train(
    params: Dict,
    dtrain: RayDMatrix,
    boost_rounds_left: int,
    *,
    evals: Sequence[Tuple[RayDMatrix, str]],
    ray_params: RayParams,
    obj: Optional[Callable],
    feval: Optional[Callable],
    callbacks: Sequence[Any],
    early_stopping_rounds: Optional[int],
    maximize: Optional[bool],
    verbose_eval: Union[bool, int],
    _training_state: _TrainingState,
) -> Tuple[RayXGBoostBooster, Dict, Dict]:
    from xgboost_ray_tpu import elastic as elastic_mod

    state = _training_state
    num_actors = ray_params.num_actors

    # 1) create (or re-create) missing actors (mirror main.py:1129-1149)
    newly_created = 0
    for rank in list(state.failed_actor_ranks):
        if state.actors[rank] is not None:
            raise RuntimeError(
                f"Trying to create actor with rank {rank}, but it already exists."
            )
        actor = _create_actor(
            rank,
            num_actors,
            state.queue,
            state.stop_event,
            ray_params.distributed_callbacks,
        )
        state.actors[rank] = actor
        state.failed_actor_ranks.remove(rank)
        newly_created += 1
    alive_actors = sum(1 for a in state.actors if a is not None)
    if ray_params.verbose:
        from xgboost_ray_tpu import tune as tune_mod

        strategy = _get_placement_strategy(tune_mod.is_session_enabled())
        logger.info(
            f"[RayXGBoost] Created {newly_created} new actors "
            f"({alive_actors} total actors, {strategy} placement)."
        )

    # 2) locality / FIXED shard assignment (mirror main.py:1161-1165);
    # fail fast when a distributed matrix has fewer files/partitions than
    # actors (mirror matrix.py:900-901), covering FIXED mode too
    num_alive = sum(1 for a in state.actors if a is not None)
    for dm in [dtrain] + [e[0] for e in evals]:
        dm.assert_enough_shards_for_actors(num_alive)
    dtrain.assign_shards_to_actors(state.actors)
    for deval, _ in evals:
        deval.assign_shards_to_actors(state.actors)

    # 3) data loading on every alive actor (mirror _PrepareActorTask)
    load_errors = []
    for actor in state.actors:
        if actor is None:
            continue
        try:
            actor.load_data(dtrain)
            for deval, _ in evals:
                actor.load_data(deval)
        except (RayActorError, RayTaskError):
            raise
        except Exception as exc:  # noqa: BLE001 - surfaced as task error
            load_errors.append((actor.rank, exc))
    if load_errors:
        err = RayTaskError(f"Data loading failed on ranks {load_errors}")
        err.ranks = [rank for rank, _ in load_errors]
        raise err
    if ray_params.verbose:
        logger.info("[RayXGBoost] Starting XGBoost training.")

    # 4) build the mesh engine over the alive actors' shards
    alive = [a for a in state.actors if a is not None]
    # RayDeviceQuantileDMatrix(max_bin=...) governs the binning of its data
    # (reference matrix.py:977-1033 honors it); an explicit conflicting
    # params['max_bin'] wins, with a warning. Injected before parse_params so
    # validation has a single source of truth.
    eff_params = dict(params or {})
    dm_max_bin = getattr(dtrain, "max_bin", None)
    if dm_max_bin:
        if "max_bin" in eff_params and int(eff_params["max_bin"]) != int(dm_max_bin):
            logger.warning(
                "params['max_bin']=%s overrides %s(max_bin=%s).",
                eff_params["max_bin"], type(dtrain).__name__, dm_max_bin,
            )
        else:
            eff_params["max_bin"] = int(dm_max_bin)
    parsed = parse_params(eff_params)
    if getattr(dtrain, "streamed", False):
        # fail the unsupported compositions (gblinear, ranking) BEFORE any
        # actor loads a chunk — the engine re-validates defensively
        from xgboost_ray_tpu.params import validate_streaming_params

        validate_streaming_params(parsed)
    train_cats = dtrain.resolved_categories

    def _build_world(world_actors, world_init, donor=None):
        """The one engine factory of this attempt: assemble the given
        actors' shards, translate eval-set categories, and build the engine
        — or revive a cached engine whose compiled programs cover exactly
        this world (shrink->grow cycles re-enter previously compiled world
        sizes; see ``_TrainingState.engine_cache``). ``donor`` is the
        engine being swapped out by an elastic shrink/grow: a STREAMED
        donor seeds the new world's binned matrix and frozen cuts in
        memory (no re-sketch, no re-stream of surviving shards — only a
        grow-back onto a brand-new replacement shard re-streams, and only
        that shard)."""
        from xgboost_ray_tpu.engine import shard_layout_fingerprint

        train_shards = [a.get_shard(dtrain) for a in world_actors]
        evals_in = []
        for deval, name in evals:
            if deval is dtrain:
                evals_in.append((train_shards, name))
            else:
                eshards = [a.get_shard(deval) for a in world_actors]
                ecats = deval.resolved_categories
                if ecats and not train_cats:
                    raise ValueError(
                        f"eval set {name!r} auto-encoded categorical columns, "
                        f"but the training matrix was built from integer "
                        f"codes — the mappings cannot be aligned. Encode the "
                        f"eval set with the same codes, or train from a "
                        f"DataFrame with enable_categorical=True."
                    )
                if train_cats and ecats != train_cats:
                    # align auto-encoded codes with the training mapping
                    eshards = [
                        translate_shard_categories(s, ecats, train_cats)
                        for s in eshards
                    ]
                evals_in.append((eshards, name))
        # 2D row x feature mesh: the engine needs R x C device slots (C=1
        # keeps the legacy R-slot request byte for byte)
        mesh_slots = len(world_actors) * max(1, parsed.feature_parallel)
        trial_devices = _resolve_mesh_devices(mesh_slots, ray_params)
        key = tuple(a.rank for a in world_actors)
        fp = shard_layout_fingerprint(train_shards)
        cached = state.engine_cache.pop(key, None)
        if cached is not None and getattr(cached, "_shard_fingerprint", None) == fp:
            try:
                cached.reset_from_booster(train_shards, evals_in, world_init)
                return cached
            except Exception as exc:  # noqa: BLE001 - cache is best-effort
                logger.warning(
                    "[RayXGBoost] cached engine for world %s unusable (%s); "
                    "rebuilding.", key, exc,
                )
        if parsed.booster == "gblinear":
            from xgboost_ray_tpu.linear import LinearEngine

            eng = LinearEngine(
                train_shards,
                parsed,
                num_actors=len(world_actors),
                evals=evals_in,
                devices=trial_devices,
                init_booster=world_init,
                feature_names=dtrain.resolved_feature_names,
                feature_types=dtrain.resolved_feature_types,
            )
        else:
            eng = TpuEngine(
                train_shards,
                parsed,
                num_actors=len(world_actors),
                evals=evals_in,
                devices=trial_devices,
                init_booster=world_init,
                feature_names=dtrain.resolved_feature_names,
                total_rounds=boost_rounds_left,
                feature_weights=dtrain.feature_weights,
                feature_types=dtrain.resolved_feature_types,
                categories=train_cats,
                stream_donor=donor,
            )
        eng._world_key = key
        eng._shard_fingerprint = fp
        return eng

    def _cache_world(eng):
        key = getattr(eng, "_world_key", None)
        if key is None or not _engine_can_reshard(eng):
            return
        state.engine_cache[key] = eng
        while len(state.engine_cache) > 2:
            state.engine_cache.pop(next(iter(state.engine_cache)))

    # fault domains for this attempt (ROADMAP item 4): the rank -> domain
    # assignment from RXGB_FAULT_DOMAINS or device placement. The faults
    # plane resolves `domain_kill` rules through it, actors carry their
    # domain into the death-coalescing mailbox, and the elastic updater
    # runs its grace clocks per domain.
    state.domain_map = derive_domain_map(
        num_actors,
        devices=_resolve_mesh_devices(
            num_actors * max(1, parsed.feature_parallel), ray_params
        ),
        logical_domains=int(ENV.FAULT_DOMAINS),
    )

    def _alive_domain_ranks(dom):
        if dom not in state.domain_map.domains():
            raise ValueError(
                f"domain_kill: unknown fault domain {dom!r}; this world has "
                f"domains {state.domain_map.domains()}"
            )
        return [
            r for r in state.domain_map.ranks_of(dom)
            if state.actors[r] is not None
        ]

    faults.set_domain_resolver(_alive_domain_ranks)
    _rewire_actors(state)  # actors pick up the coalescer + domain ids

    init_booster = _deserialize_booster(state.checkpoint.value)
    engine = _build_world(alive, init_booster)
    total_n = sum(a.local_n(dtrain) for a in alive)
    state.additional_results["total_n"] = total_n

    for actor in alive:
        actor._distributed_callbacks.before_train(actor)

    session_mod.init_session(rank=0, queue=state.queue)
    proxy = _EngineBoosterProxy(engine)
    evals_result: Dict[str, Dict[str, List[float]]] = {}
    callback_returns = state.additional_results.setdefault("callback_returns", {})

    # ------------------------------------------------------------------
    # In-flight elastic continuation (zero-replay shrink/grow). The global
    # round index of this attempt is ``attempt_offset0 + i``; after a world
    # swap the new engine's iteration_offset absorbs the rounds already
    # boosted, so ``engine_base`` tracks how many attempt rounds are folded
    # into it and the engine is stepped with the attempt round REBASED to
    # its own offset (keeping the per-round RNG stream world-schedule
    # independent: fold(seed, global_round)).
    # ------------------------------------------------------------------
    attempt_offset0 = engine.iteration_offset
    engine_base = 0
    rob = state.additional_results.get("robustness", {})

    def _fire_after_round(i_attempt, round_metrics, duration_s):
        """Fan the obs round record out to the distributed callbacks."""
        if not ray_params.distributed_callbacks:
            return
        record = {
            "round": attempt_offset0 + i_attempt,
            "iteration": i_attempt,
            "duration_s": duration_s,
            "world": sum(1 for a in state.actors if a is not None),
            "metrics": round_metrics,
        }
        for actor in state.actors:
            if actor is not None:
                actor._distributed_callbacks.after_round(actor, record)

    def _schedule_replacements(force=False):
        if ENV.ELASTIC_RESTART_DISABLED:
            return
        if force:
            state.last_resource_check_at = 0.0
        elastic_mod._maybe_schedule_new_actors(
            training_state=state,
            num_cpus_per_actor=ray_params.cpus_per_actor,
            num_gpus_per_actor=max(0, ray_params.gpus_per_actor),
            resources_per_actor=ray_params.resources_per_actor,
            ray_params=ray_params,
            load_data=[dtrain] + [e[0] for e in evals],
        )

    def _swap_engine(new_engine, kind, started):
        """Install ``new_engine`` as the attempt's engine; cache the old one
        for a later grow-back; update the robustness metrics and total_n.
        ``kind == "resume"`` (a blame-less transient failure continuing on
        the unchanged world) moves no capacity, so it counts as neither a
        shrink nor a grow."""
        nonlocal engine, engine_base, total_n
        if new_engine is not engine:
            _cache_world(engine)
            engine = new_engine
            proxy._rebind(engine)
        engine_base = engine.iteration_offset - attempt_offset0
        new_alive = [a for a in state.actors if a is not None]
        new_total = sum(a.local_n(dtrain) for a in new_alive)
        orphaned = max(0, total_n - new_total) if kind == "shrink" else 0
        if kind == "shrink":
            rob["shrinks"] = rob.get("shrinks", 0) + 1
            rob["orphaned_rows"] = rob.get("orphaned_rows", 0) + orphaned
        elif kind == "grow":
            rob["grows"] = rob.get("grows", 0) + 1
        recompile_s = round(time.time() - started, 4)
        rob["recompile_s"] = round(
            rob.get("recompile_s", 0.0) + recompile_s, 4
        )
        total_n = new_total
        state.additional_results["total_n"] = total_n
        # the machine-readable world-change record: the timeline entry every
        # chaos scenario reconstructs its shrink→grow sequence from. The
        # current global round is offset + trees boosted on this engine —
        # offset alone is stale when the immediate-reintegration fast path
        # reuses the attempt's compiled engine mid-flight.
        obs.get_tracer().event(
            # static literals (not f"world.{kind}") so the timeline event
            # vocabulary stays greppable and checkable against TRACE_NAMES
            "world.shrink" if kind == "shrink"
            else "world.grow" if kind == "grow" else "world.resume",
            round=engine.iteration_offset + engine.num_round_trees,
            attrs={
                "world": len(new_alive),
                "orphaned_rows": orphaned,
                "recompile_s": recompile_s,
            },
        )
        obs.get_registry().counter(f"rxgb_train_{kind}s_total").inc()

    def _coalesce_deaths():
        """Fold near-simultaneous deaths into the CURRENT failure: drain the
        death-coalescing mailbox and probe actor liveness, blaming every
        additional dead rank NOW so a whole lost domain costs one shrink and
        one retrace instead of N sequential shrink/recompile cycles. With
        ``RXGB_ELASTIC_DEATH_COALESCE_S > 0`` the sweep lingers until the
        window closes, catching stragglers of a correlated loss; at 0 it
        still folds everything already dead."""
        deadline = time.time() + max(
            0.0, float(ENV.ELASTIC_DEATH_COALESCE_S)
        )
        extra = []
        while True:
            noted = set(state.death_coalescer.drain())
            noted.update(
                rank for rank, a in enumerate(state.actors)
                if a is not None and not a.alive
            )
            for rank in sorted(noted):
                if state.actors[rank] is None:
                    continue  # already blamed (possibly by this sweep)
                state.actors[rank].kill()
                state.actors[rank] = None
                state.failed_actor_ranks.add(rank)
                extra.append(rank)
            now = time.time()
            if now >= deadline:
                return extra
            time.sleep(min(0.005, deadline - now))

    def _note_domains_lost(blamed):
        """Domain attribution of a failure: every domain whose LAST alive
        rank is among ``blamed`` is a lost domain — count it and put a
        ``world.domain_down`` record on the timeline."""
        dm = state.domain_map
        if dm is None or not blamed:
            return
        rnd = engine.iteration_offset + engine.num_round_trees
        for dom in dm.domains_of(blamed):
            ranks = dm.ranks_of(dom)
            if all(state.actors[r] is None for r in ranks):
                rob["domains_lost"] = rob.get("domains_lost", 0) + 1
                obs.get_tracer().event(
                    "world.domain_down", round=rnd,
                    attrs={"domain": dom, "ranks": list(ranks)},
                )

    def _note_domains_up(promoted):
        """Emit ``world.domain_up`` for every domain ``promoted`` made whole
        again — the timeline closure of its ``world.domain_down``."""
        dm = state.domain_map
        if dm is None or not promoted:
            return
        rnd = engine.iteration_offset + engine.num_round_trees
        for dom in dm.domains_of(promoted):
            if all(state.actors[r] is not None for r in dm.ranks_of(dom)):
                obs.get_tracer().event(
                    "world.domain_up", round=rnd,
                    attrs={
                        "domain": dom,
                        "ranks": [
                            r for r in promoted if dm.domain_of(r) == dom
                        ],
                    },
                )

    def _world_is_current(world_actors):
        """True when ``world_actors`` is exactly the world the CURRENT
        engine was built over (same ranks, same shard rows) — continuation
        then needs no rebuild at all: the device state is already live."""
        from xgboost_ray_tpu.engine import shard_layout_fingerprint

        if tuple(a.rank for a in world_actors) != getattr(
            engine, "_world_key", None
        ):
            return False
        return (
            shard_layout_fingerprint([a.get_shard(dtrain) for a in world_actors])
            == getattr(engine, "_shard_fingerprint", None)
        )

    def _grow_at_boundary():
        """Reintegrate the due COMPLETE domains at a round boundary by
        re-sharding the running world in place — the in-memory booster
        carries every boosted round, so reintegration replays NOTHING, and
        a domain re-admits as a unit (``state.domains_due`` holds only
        domains whose every dead rank is staged and past grace — a
        half-staged domain keeps waiting, it never half-grows). Falls back
        to the legacy restart-from-checkpoint reintegration
        (``RayXGBoostActorAvailable``) when the in-place grow fails."""
        started = time.time()
        try:
            booster_now = engine.get_booster()
        except Exception as exc:  # noqa: BLE001 - fall back to restart
            raise RayXGBoostActorAvailable(
                "A new worker is ready but the in-memory booster could not "
                "be snapshotted; restarting from the latest checkpoint."
            ) from exc
        due = list(state.domains_due or ())
        dm = state.domain_map
        if due and dm is not None:
            due_ranks = {r for dom in due for r in dm.ranks_of(dom)}
            promoted = [
                r for r, p in (state.pending_actors or {}).items()
                if p.ready and r in due_ranks
            ]
        else:
            promoted = [
                r for r, p in (state.pending_actors or {}).items() if p.ready
            ]
        state.domains_due = []
        _promote_pending_actors(state, ranks=promoted)
        _rewire_actors(state)
        target = [a for a in state.actors if a is not None]
        try:
            new_engine = _build_world(target, booster_now, donor=engine)
        except Exception as exc:  # noqa: BLE001 - fall back to restart
            raise RayXGBoostActorAvailable(
                f"In-place reintegration failed ({exc}); restarting from "
                f"the latest checkpoint with the restored world."
            ) from exc
        for r in promoted:
            if state.actors[r] is not None:
                state.actors[r]._distributed_callbacks.before_train(
                    state.actors[r]
                )
        _swap_engine(new_engine, "grow", started)
        _note_domains_up(promoted)
        logger.info(
            f"[RayXGBoost] Reintegrated ranks {promoted} in place at a round "
            f"boundary ({len(target)} workers, zero rounds replayed)."
        )

    def _inflight_recover(exc) -> bool:
        """Zero-replay elastic continuation for a mid-attempt failure:
        reintegrate immediately when every dead rank's replacement is
        already staged and no grace period applies (the world never
        actually shrinks — zero recompile, bitwise continuation), otherwise
        shrink to the survivors in place, recompiling once for the smaller
        mesh and continuing from the in-memory booster. Near-simultaneous
        deaths (a whole fault domain dying at once) are coalesced into ONE
        shrink before the target world is chosen. Returns False when the
        in-flight path is unavailable (non-elastic, an engine without
        ``can_reshard``, too many dead, rebuild failure, repeated failures
        without progress) — the caller re-raises into the
        restart-from-checkpoint policy."""
        if not ray_params.elastic_training:
            return False
        if not _engine_can_reshard(engine):
            return False
        if state.consecutive_failures >= 3:
            # repeated failures with no completed round in between: stop
            # absorbing them in-flight and let the retry loop's bounded
            # restart/backoff policy take over
            return False
        try:
            booster_now = engine.get_booster()
        except Exception as snap_exc:  # noqa: BLE001 - fall back to restart
            logger.warning(
                "[RayXGBoost] cannot snapshot the in-memory booster (%s); "
                "falling back to restart-from-checkpoint.", snap_exc,
            )
            return False
        alive_before = sum(1 for a in state.actors if a is not None)
        dead_before = {r for r, a in enumerate(state.actors) if a is None}
        _apply_failure(state, exc)
        # death coalescing: fold every near-simultaneous death (the rest of
        # a dying domain, out-of-band kills) into THIS failure so the world
        # shrinks once, retraces once, replays nothing
        _coalesce_deaths()
        alive_n = sum(1 for a in state.actors if a is not None)
        blamed = sorted(
            {r for r, a in enumerate(state.actors) if a is None} - dead_before
        )
        dead = ray_params.num_actors - alive_n
        if alive_n == 0 or dead > ray_params.max_failed_actors:
            return False
        for rank in list(state.failed_actor_ranks):
            state.elastic_dead_ranks.add(rank)
            state.failed_actor_ranks.discard(rank)
        state.recover_started_at = time.time()
        obs.get_tracer().event(
            "failure.detected", round=engine.iteration_offset
            + engine.num_round_trees,
            attrs={
                "ranks": sorted(state.elastic_dead_ranks),
                "in_flight": True,
            },
        )
        if len(blamed) > 1:
            rob["deaths_coalesced"] = (
                rob.get("deaths_coalesced", 0) + len(blamed) - 1
            )
            obs.get_tracer().event(
                "world.deaths_coalesced",
                round=engine.iteration_offset + engine.num_round_trees,
                attrs={"ranks": blamed, "extra": len(blamed) - 1},
            )
        _note_domains_lost(blamed)
        # stage replacements NOW: when every dead rank reloads within the
        # scheduler's fast path and no grace period applies, the world is
        # restored before the next round even starts
        _schedule_replacements(force=True)
        # a failure that blamed nobody (liveness probe found every actor
        # healthy) changes no capacity: continuing on the unchanged world
        # is a "resume", not a shrink — the robustness block is an
        # operator-facing contract and must not report phantom world loss
        kind = "shrink" if alive_n < alive_before else "resume"
        promoted = []
        target = [a for a in state.actors if a is not None]
        if (
            not ENV.ELASTIC_RESTART_DISABLED
            and float(ENV.ELASTIC_RESTART_GRACE_PERIOD_S) <= 0
            and state.elastic_dead_ranks
            and all(
                (state.pending_actors or {}).get(r) is not None
                and state.pending_actors[r].ready
                for r in state.elastic_dead_ranks
            )
        ):
            # immediate reintegration: build the grown world's engine from
            # the STAGED replacements first, promote only on success — a
            # rebuild failure must leave the replacements pending (for the
            # fallback restart to use), not get them killed as casualties
            # of the re-raised failure
            kind = "grow"
            promoted = sorted(state.elastic_dead_ranks)
            merged = list(state.actors)
            for r in promoted:
                merged[r] = state.pending_actors[r].actor
            target = [a for a in merged if a is not None]
        # recompile clock starts AFTER replacement staging: recompile_s is
        # the runbook's "rebuild/retrace cost" signal and must not absorb
        # the scheduler's (up to 1 s) data-load fast-path wait
        started = time.time()
        try:
            if _world_is_current(target):
                # the engine's device state already covers this exact world
                # (immediate reintegration, or a failure that blamed no
                # actor): pure resume — no rebuild, no recompile
                new_engine = engine
            else:
                new_engine = _build_world(target, booster_now, donor=engine)
        except Exception as build_exc:  # noqa: BLE001 - fall back to restart
            logger.warning(
                "[RayXGBoost] in-flight elastic %s failed (%s); falling "
                "back to restart-from-checkpoint.", kind, build_exc,
            )
            return False
        if kind == "grow":
            _promote_pending_actors(state)
            _rewire_actors(state)
            for r in promoted:
                if state.actors[r] is not None:
                    state.actors[r]._distributed_callbacks.before_train(
                        state.actors[r]
                    )
        # counted only when the in-flight path actually takes over (the
        # fallback return-False paths leave the increment to the outer
        # retry handler — one failure, one count)
        state.consecutive_failures += 1
        _swap_engine(new_engine, kind, started)
        if kind == "grow":
            _note_domains_up(promoted)
        if kind == "resume":
            logger.warning(
                f"[RayXGBoost] A transient failure blamed no worker. "
                f"Resuming in-flight with the unchanged {len(target)}-worker "
                f"world — zero rounds replayed."
            )
        else:
            logger.warning(
                f"[RayXGBoost] A worker died. Continuing in-flight ({kind}) "
                f"with {len(target)} workers — zero rounds replayed."
            )
        return True

    es_metric = None
    es_maximize = False
    es_best: Optional[float] = None
    es_best_iter = -1
    if early_stopping_rounds is not None and evals:
        from xgboost_ray_tpu.ops.metrics import is_maximize_metric

        es_set = evals[-1][1]
        es_metric = engine.metric_names[-1]
        es_maximize = maximize if maximize is not None else is_maximize_metric(es_metric)

    checkpoint_frequency = ray_params.checkpoint_frequency
    train_started = time.time()
    state.training_started_at = train_started
    profile_dir = ENV.PROFILE_DIR
    if profile_dir:
        import jax

        _stop_profile_if_running()  # clear any trace leaked by a prior abort
        jax.profiler.start_trace(profile_dir)
    round_times = state.additional_results.setdefault("round_times_s", [])
    # true per-dispatch wall times: one entry per compiled dispatch — a
    # fused scan chunk OR a single per-round step. round_times_s keeps its
    # historical shape (a fused chunk contributes its MEAN replicated per
    # round, which hides per-chunk variance); consumers that want the real
    # distribution read chunk_times_s (bench.py records both).
    chunk_times = state.additional_results.setdefault("chunk_times_s", [])
    stop_requested = False
    last_status = time.time()

    for model_cb in callbacks:
        if hasattr(model_cb, "before_training"):
            model_cb.before_training(proxy)

    # Fast path: no per-round host interaction needed -> fuse rounds into
    # compiled multi-round programs (lax.scan inside shard_map; see
    # engine.step_many). Scan length is bounded by ENV.SCAN_MAX_CHUNK and
    # clamped so no scan crosses a checkpoint boundary.
    state.rounds_this_attempt = 0
    use_batched = (
        not callbacks
        and obj is None
        and feval is None
        and early_stopping_rounds is None
        and engine.can_batch_rounds()
        and boost_rounds_left > 1
        # round-granular fault injection needs the per-round path so a
        # scheduled fault hits its exact round, not a fused-chunk boundary
        and not faults.plan_targets("actor.train_round")
    )
    if use_batched:
        # chunk size decoupled from checkpoint_frequency: scans never fuse
        # more than SCAN_MAX_CHUNK rounds into one program, but checkpoints
        # are still emitted exactly at checkpoint_frequency boundaries
        chunk = max(1, ENV.SCAN_MAX_CHUNK)
        completed = 0
        while completed < boost_rounds_left:
            if state.stop_event.is_set():
                raise RayXGBoostTrainingStopped("Training was aborted.")
            n = min(chunk, boost_rounds_left - completed)
            if checkpoint_frequency:
                # never scan across a checkpoint boundary
                to_boundary = checkpoint_frequency - (completed % checkpoint_frequency)
                n = min(n, to_boundary)
            chunk_started = time.time()
            try:
                chunk_results = engine.step_many(completed - engine_base, n)
            except (RayActorError, RayTaskError) as exc:
                if not _inflight_recover(exc):
                    raise
                completed = engine_base + engine.num_round_trees
                continue
            chunk_wall = time.time() - chunk_started
            chunk_times.append({"rounds": n, "seconds": round(chunk_wall, 6)})
            round_times.extend([chunk_wall / n] * n)
            state.rounds_this_attempt += n
            _mark_recovered(state)
            for ri, round_metrics in enumerate(chunk_results):
                for set_name, metrics in round_metrics.items():
                    for metric_name, value in metrics.items():
                        evals_result.setdefault(set_name, {}).setdefault(
                            metric_name, []
                        ).append(value)
                # same per-round interval semantics as the per-round path
                i = completed + ri
                _fire_after_round(i, round_metrics, round_times[-1])
                if verbose_eval and (
                    verbose_eval is True or (i % max(int(verbose_eval), 1) == 0)
                ):
                    flat = "\t".join(
                        f"{sn}-{mn}:{ms[mn]:.5f}"
                        for sn, ms in round_metrics.items()
                        for mn in ms
                    )
                    print(f"[{i}]\t{flat}")
            completed += n
            if checkpoint_frequency and (
                completed % checkpoint_frequency == 0
                or completed == boost_rounds_left
            ):
                booster = engine.get_booster()
                iteration = attempt_offset0 + completed - 1
                state.queue.put(
                    (0, _Checkpoint(iteration, _serialize_booster(booster)))
                )
            _handle_queue(state.queue, state.checkpoint, callback_returns)
            if ray_params.elastic_training and not ENV.ELASTIC_RESTART_DISABLED:
                _schedule_replacements()
                if elastic_mod._update_scheduled_actor_states(
                    state,
                    raise_on_ready=not _engine_can_reshard(engine),
                ):
                    _grow_at_boundary()
            if time.time() - last_status > ENV.STATUS_FREQUENCY_S:
                logger.info(
                    f"[RayXGBoost] Training in progress "
                    f"({time.time() - train_started:.0f}s, round {completed})."
                )
                last_status = time.time()

        _maybe_profile_phases(engine, state)
        booster = engine.get_booster()
        for actor in [a for a in state.actors if a is not None]:
            actor._distributed_callbacks.after_train(
                actor, {"evals_result": evals_result}
            )
        _handle_queue(state.queue, state.checkpoint, callback_returns)
        state.additional_results["callback_returns"] = callback_returns
        _record_allreduce_bytes(state, engine)
        _stop_profile_if_running()
        train_time = time.time() - train_started
        return booster, evals_result, {
            "train_n": total_n,
            "training_time_s": train_time,
            "stopped_early": False,
            "completed_rounds": completed,
        }

    completed = 0
    i = 0
    while i < boost_rounds_left:
        if state.stop_event.is_set():
            raise RayXGBoostTrainingStopped("Training was aborted.")

        try:
            for model_cb in callbacks:
                if hasattr(model_cb, "before_iteration"):
                    model_cb.before_iteration(proxy, i, evals_result)

            faults.fire(
                "actor.train_round",
                round=attempt_offset0 + i,
                world=sum(1 for a in state.actors if a is not None),
            )

            round_started = time.time()
            gh_custom = None
            if obj is not None:
                # process-local rows (the reference computes the custom
                # objective per actor on its shard, ``main.py:745-752``);
                # label_np/weight_np hold exactly this process's rows.
                # Single-host: all rows.
                margins = engine.get_margins_local()
                preds = margins[:, 0] if engine.n_outputs == 1 else margins
                faux = _FauxDMatrix(
                    engine.label_np, engine.weight_np, engine.group_ptr
                )
                g, h = obj(preds, faux)
                gh_custom = (g, h)

            round_metrics = engine.step(i - engine_base, gh_custom=gh_custom)
            completed += 1
            state.rounds_this_attempt += 1
            _mark_recovered(state)
            round_wall = time.time() - round_started
            round_times.append(round_wall)
            chunk_times.append({"rounds": 1, "seconds": round(round_wall, 6)})

            # custom metric (feval) computed per process on its local rows,
            # then combined as a weighted mean across processes (the
            # reference's per-worker metric averaging). Single-host: one
            # call over all rows.
            if feval is not None:
                for es in engine.evals:
                    margin = engine.get_margins_local(es)
                    preds = margin[:, 0] if engine.n_outputs == 1 else margin
                    faux = _FauxDMatrix(
                        es.label_np if es.label_np is not None else engine.label_np,
                        es.weight_np,
                        es.group_ptr,
                    )
                    name, value = feval(preds, faux)
                    round_metrics.setdefault(es.name, {})[name] = (
                        engine.combine_host_scalar(value, es, metric=name)
                    )

            for set_name, metrics in round_metrics.items():
                for metric_name, value in metrics.items():
                    evals_result.setdefault(set_name, {}).setdefault(
                        metric_name, []
                    ).append(value)

            _fire_after_round(i, round_metrics, round_times[-1])

            if verbose_eval and (
                verbose_eval is True or (i % max(int(verbose_eval), 1) == 0)
            ):
                flat = "\t".join(
                    f"{sn}-{mn}:{v[-1]:.5f}"
                    for sn, ms in evals_result.items()
                    for mn, v in ms.items()
                )
                print(f"[{i}]\t{flat}")

            # driver-side checkpointing (mirror of the rank-0 checkpoint
            # callback, main.py:612-626): every k rounds + after the last
            is_last = i == boost_rounds_left - 1
            if checkpoint_frequency and (
                (i + 1) % checkpoint_frequency == 0 or is_last
            ):
                booster = engine.get_booster()
                iteration = attempt_offset0 + i
                state.queue.put(
                    (0, _Checkpoint(iteration, _serialize_booster(booster)))
                )

            _handle_queue(state.queue, state.checkpoint, callback_returns)

            # elastic: reintegrate failed ranks at the round boundary —
            # in place (zero replay) for reshardable engines, via the
            # legacy RayXGBoostActorAvailable restart otherwise
            if ray_params.elastic_training and not ENV.ELASTIC_RESTART_DISABLED:
                _schedule_replacements()
                if elastic_mod._update_scheduled_actor_states(
                    state,
                    raise_on_ready=not _engine_can_reshard(engine),
                ):
                    _grow_at_boundary()

            stop = False
            for model_cb in callbacks:
                if hasattr(model_cb, "after_iteration"):
                    stop = model_cb.after_iteration(proxy, i, evals_result) or stop

            if es_metric is not None:
                try:
                    cur = evals_result[evals[-1][1]][es_metric][-1]
                except KeyError:
                    cur = None
                if cur is not None:
                    better = (
                        es_best is None
                        or (es_maximize and cur > es_best)
                        or (not es_maximize and cur < es_best)
                    )
                    if better:
                        es_best, es_best_iter = cur, i
                    elif i - es_best_iter >= early_stopping_rounds:
                        stop = True

            if time.time() - last_status > ENV.STATUS_FREQUENCY_S:
                logger.info(
                    f"[RayXGBoost] Training in progress "
                    f"({time.time() - train_started:.0f}s, round {i})."
                )
                last_status = time.time()

            if stop:
                stop_requested = True
                break
            i += 1
        except (RayActorError, RayTaskError) as exc:
            if not _inflight_recover(exc):
                raise
            # the in-memory booster is the single source of truth for how
            # many attempt rounds are complete (a failure before the step
            # re-runs round i; one after it does not)
            i = engine_base + engine.num_round_trees
            completed = i

    _maybe_profile_phases(engine, state)
    booster = engine.get_booster()
    if es_metric is not None and es_best_iter >= 0:
        # es_best_iter is attempt-local; xgboost reports the *global* boosting
        # round, so rebase by the continuation offset (xgb_model / restart).
        booster.best_iteration = attempt_offset0 + es_best_iter
        booster.best_score = es_best

    for model_cb in callbacks:
        if hasattr(model_cb, "after_training"):
            model_cb.after_training(proxy)

    for actor in [a for a in state.actors if a is not None]:
        actor._distributed_callbacks.after_train(actor, {"evals_result": evals_result})

    _handle_queue(state.queue, state.checkpoint, callback_returns)
    state.additional_results["callback_returns"] = callback_returns
    _record_allreduce_bytes(state, engine)
    _stop_profile_if_running()

    train_time = time.time() - train_started
    return booster, evals_result, {
        "train_n": total_n,
        "training_time_s": train_time,
        "stopped_early": stop_requested,
        "completed_rounds": completed,
    }


# ---------------------------------------------------------------------------
# Remote-execution tier (mirror of the reference's Ray-client mode,
# ``main.py:1413-1452``, ``util.py:82-110``): there, a thin Ray client must
# not run the training loop locally, so train/predict re-run as a 0-CPU
# remote task pinned to the server node. The TPU analog of "thin client" is a
# driver process that must not own the accelerator (e.g. it never initialized
# the backend, or another process holds the single-client tunnel):
# ``_remote=True`` ships the call to a freshly spawned server process that
# owns the devices and returns the results by pickle. Spawn (not fork) so the
# server starts with clean JAX/XLA state.
# ---------------------------------------------------------------------------


def _remote_server_main(conn, mode: str, payload):
    """Entry point of the spawned server process (top level: spawn pickles
    it by reference)."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # honor an explicit CPU-only request even when an accelerator PJRT
        # plugin self-registers at interpreter startup (same hermeticity
        # guard as tests/conftest.py — a wedged tunnel must not hang the
        # spawned server)
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        for _name in list(_xb._backend_factories):
            if _name != "cpu":
                _xb._backend_factories.pop(_name, None)
    try:
        if mode == "train":
            params, dtrain, num_boost_round, evals, ray_params, kwargs = payload
            evals_result: Dict = {}
            additional_results: Dict = {}
            bst = train(
                params, dtrain, num_boost_round, evals=evals,
                evals_result=evals_result,
                additional_results=additional_results,
                ray_params=ray_params, _remote=False, **kwargs,
            )
            conn.send((True, (bst, evals_result, additional_results)))
        else:
            model, data, ray_params, kwargs = payload
            out = predict(model, data, ray_params=ray_params, _remote=False,
                          **kwargs)
            conn.send((True, out))
    except Exception as exc:  # noqa: BLE001 - marshal any failure back
        import traceback

        conn.send((False, f"{type(exc).__name__}: {exc}\n"
                          f"{traceback.format_exc()[-2000:]}"))
    finally:
        conn.close()


def _run_remote(mode: str, payload):
    """Run one train/predict call in a spawned server process and return its
    unpickled result. Raises RayXGBoostTrainingError on remote failure or
    server death. Payload objects (matrices, callbacks, custom objectives)
    must be picklable — the same constraint the reference's client mode puts
    on its remote task arguments. NOTE: standard multiprocessing spawn
    semantics apply — a script calling ``_remote=True`` at module top level
    must guard it under ``if __name__ == "__main__":`` or the spawned server
    re-executes the script."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    parent_conn, child_conn = ctx.Pipe()
    proc = ctx.Process(
        target=_remote_server_main, args=(child_conn, mode, payload),
        daemon=False,
    )
    proc.start()
    child_conn.close()
    try:
        ok, result = parent_conn.recv()
    except EOFError:
        proc.join()
        raise RayXGBoostTrainingError(
            f"the remote {mode} server process died (exit code "
            f"{proc.exitcode}) before returning a result."
        )
    finally:
        parent_conn.close()
    proc.join()
    if not ok:
        raise RayXGBoostTrainingError(
            f"remote {mode} failed on the server process:\n{result}"
        )
    return result


# ---------------------------------------------------------------------------
# Public train() (mirror of ``main.py:1341-1747``)
# ---------------------------------------------------------------------------


def train(
    params: Dict,
    dtrain: RayDMatrix,
    num_boost_round: int = 10,
    *args,
    evals: Union[List[Tuple[RayDMatrix, str]], Tuple] = (),
    evals_result: Optional[Dict] = None,
    additional_results: Optional[Dict] = None,
    ray_params: Union[None, RayParams, Dict] = None,
    _remote: Optional[bool] = None,
    **kwargs,
) -> RayXGBoostBooster:
    """Distributed GBDT training on the TPU mesh.

    Drop-in signature mirror of ``xgboost_ray.train`` (``main.py:1341``).
    Failure handling matches the reference's three-way policy (elastic
    continuation / recreate-from-checkpoint / abort), driven by
    ``ray_params``.

    Observability: every run is traced by a fresh run-scoped
    :class:`obs.Tracer` — per-round spans from the engine, lifecycle
    events (attempts, failures, world shrink/grow, checkpoint commits,
    backoff) from the driver — and the timeline is returned under
    ``additional_results["obs"]``. ``RXGB_TRACE=0`` disables tracing,
    ``RXGB_TRACE_DIR`` streams per-rank JSONL, ``RXGB_TRACE_PHASES=1``
    adds an end-of-run fenced per-phase profile.
    """
    tracer = obs.Tracer()
    with obs.use_tracer(tracer):
        return _train_impl(
            params,
            dtrain,
            num_boost_round,
            *args,
            evals=evals,
            evals_result=evals_result,
            additional_results=additional_results,
            ray_params=ray_params,
            _remote=_remote,
            _run_tracer=tracer,
            **kwargs,
        )


def _train_impl(
    params: Dict,
    dtrain: RayDMatrix,
    num_boost_round: int = 10,
    *args,
    evals: Union[List[Tuple[RayDMatrix, str]], Tuple] = (),
    evals_result: Optional[Dict] = None,
    additional_results: Optional[Dict] = None,
    ray_params: Union[None, RayParams, Dict] = None,
    _remote: Optional[bool] = None,
    _run_tracer=None,
    **kwargs,
) -> RayXGBoostBooster:
    """The driver body behind :func:`train` (which scopes the run tracer)."""
    start_time = time.time()
    if args:
        raise TypeError(
            "train() takes keyword arguments after num_boost_round; got "
            f"positional {args}"
        )
    _validate_kwargs_for_func(kwargs, _KNOWN_TRAIN_KWARGS, "train")
    ray_params = _validate_ray_params(ray_params)
    if isinstance(evals, tuple) and len(evals) == 2 and isinstance(evals[1], str):
        evals = [evals]  # single (dm, name) tuple — normalize BEFORE remote ship

    # online-serving handoff: when a serve.ModelRegistry is passed, the
    # trained booster is hot-swapped into it on completion (drain-then-flip,
    # see serve/registry.py) so a colocated endpoint picks up the retrain
    # without a restart. Popped before the remote ship: a registry holds
    # live locks/threads and cannot cross the process boundary.
    serve_registry = kwargs.pop("serve_registry", None)
    if serve_registry is not None and _remote:
        raise ValueError(
            "serve_registry cannot be combined with _remote=True: the "
            "registry lives in this process. Train remotely, then call "
            "registry.load(booster) on the result."
        )

    if _remote:
        bst, remote_evals, remote_extra = _run_remote(
            "train",
            (params, dtrain, num_boost_round, list(evals), ray_params, kwargs),
        )
        if evals_result is not None:
            evals_result.update(remote_evals)
        if additional_results is not None:
            additional_results.update(remote_extra)
        return bst

    if not isinstance(dtrain, RayDMatrix):
        raise ValueError(
            f"The `dtrain` argument passed to `train()` is not a RayDMatrix, "
            f"but of type {type(dtrain)}. FIX THIS by instantiating a "
            f"RayDMatrix first: `dtrain = RayDMatrix(data, labels)`."
        )
    for deval, name in evals:
        if not isinstance(deval, RayDMatrix):
            raise ValueError(
                f"Evaluation data must be a RayDMatrix, got {type(deval)} "
                f"for eval set {name!r}."
            )

    # Tune integration: auto-inject the report/checkpoint callback when
    # running inside a tuning session (mirror main.py:1477-1480)
    from xgboost_ray_tpu.compat import wrap_callbacks
    from xgboost_ray_tpu import tune as tune_mod

    kwargs_callbacks = wrap_callbacks(kwargs.get("callbacks"), num_boost_round)
    kwargs_callbacks = tune_mod._try_add_tune_callback(kwargs_callbacks)

    parsed = parse_params(params)  # early validation (tree_method etc.)
    if serve_registry is not None and parsed.booster == "gblinear":
        # fail BEFORE training, not after hours of boosting: the serve
        # layer compiles the padded forest walk, which linear models lack
        raise ValueError(
            "serve_registry is not supported with booster='gblinear' "
            "(the serving layer compiles tree-walk programs). Train "
            "without serve_registry and serve the model another way."
        )
    del parsed

    if ray_params.elastic_training and ray_params.max_failed_actors == 0:
        raise ValueError(
            "Elastic training enabled but the maximum number of failed "
            "actors is set to 0. FIX THIS by setting "
            "`RayParams(max_failed_actors=N)` to something > 0."
        )
    if ray_params.elastic_training and ray_params.max_actor_restarts == 0:
        raise ValueError(
            "Elastic training enabled but the maximum number of actor "
            "restarts is set to 0. FIX THIS by setting "
            "`RayParams(max_actor_restarts=N)` (-1 for unlimited)."
        )

    max_actor_restarts = (
        ray_params.max_actor_restarts
        if ray_params.max_actor_restarts >= 0
        else float("inf")
    )

    obj = kwargs.get("obj")
    feval = kwargs.get("feval") or kwargs.get("custom_metric")
    early_stopping_rounds = kwargs.get("early_stopping_rounds")
    maximize = kwargs.get("maximize")
    verbose_eval = kwargs.get("verbose_eval", False)
    xgb_model = _coerce_model(kwargs.get("xgb_model"))

    # eager central loading on the driver (mirror main.py:1555-1556)
    dtrain.load_data(ray_params.num_actors)
    for deval, _ in evals:
        deval.load_data(ray_params.num_actors)

    state = _TrainingState(
        actors=[None] * ray_params.num_actors,
        queue=Queue(),
        stop_event=Event(),
        checkpoint=_Checkpoint(
            iteration=-1,
            value=_serialize_booster(xgb_model) if xgb_model else None,
        ),
        additional_results={},
        failed_actor_ranks=set(range(ray_params.num_actors)),
        pending_actors={},
    )

    boost_rounds_left = num_boost_round
    last_checkpoint_value = state.checkpoint.value
    tries = 0
    total_training_time = 0.0
    final_evals_result: Dict = {}
    booster: Optional[RayXGBoostBooster] = None

    # recovery observability: restarts taken, rounds replayed after each
    # restart-from-checkpoint, and failure->first-new-round latency. Present
    # (all zeros) even on clean runs so dashboards have a stable shape.
    robustness = state.additional_results.setdefault(
        "robustness",
        {
            "restarts": 0,
            "elastic_restarts": 0,
            "rounds_replayed": 0,
            "time_to_recover_s": 0.0,
            "backoff_s": 0.0,
            # in-flight elastic continuation (zero-replay shrink/grow)
            "shrinks": 0,
            "grows": 0,
            "orphaned_rows": 0,
            "recompile_s": 0.0,
            # failure-domain attribution: whole domains lost (every rank of
            # the domain dead in one failure) and deaths folded into an
            # already-detected failure's single shrink (a lost domain of K
            # ranks is 1 shrink + K-1 deaths_coalesced, never K shrinks)
            "domains_lost": 0,
            "deaths_coalesced": 0,
        },
    )

    def _xgb_base_rounds() -> int:
        return xgb_model.num_boosted_rounds() if xgb_model else 0

    def _account_failure(exc=None) -> None:
        """Called on every restart-causing exception: rounds progressed past
        the surviving checkpoint will be replayed by the next attempt."""
        progressed = (
            num_boost_round - boost_rounds_left
        ) + state.rounds_this_attempt
        if state.checkpoint.value:
            covered = (
                _deserialize_booster(state.checkpoint.value).num_boosted_rounds()
                - _xgb_base_rounds()
            )
        else:
            covered = 0
        replayed = max(0, progressed - covered)
        robustness["rounds_replayed"] += replayed
        state.rounds_this_attempt = 0
        state.recover_started_at = time.time()
        # opens the timeline clock "recovered" closes (matches the
        # robustness block's time_to_recover_s accounting)
        obs.get_tracer().event(
            "failure.detected",
            attrs={
                "ranks": sorted(getattr(exc, "ranks", None) or []),
                "rounds_replayed": replayed,
                "restart": True,
            },
        )

    attempt_no = -1
    run_tracer = obs.get_tracer()
    while tries <= max_actor_restarts:
        # restart-from-checkpoint round arithmetic (mirror main.py:1606-1612)
        if state.checkpoint.value and state.checkpoint.value != last_checkpoint_value:
            ckpt_booster = _deserialize_booster(state.checkpoint.value)
            done_rounds = ckpt_booster.num_boosted_rounds() - (
                xgb_model.num_boosted_rounds() if xgb_model else 0
            )
            boost_rounds_left = num_boost_round - done_rounds
            last_checkpoint_value = state.checkpoint.value
            if boost_rounds_left <= 0:
                # the checkpoint already covers every round: the restart IS
                # the recovery — close the clock before leaving the loop
                _mark_recovered(state)
                break

        attempt_no += 1
        attempt_ts, attempt_t0 = time.time(), time.perf_counter()

        def _close_attempt(outcome):
            run_tracer.add_span(
                "attempt", attempt_ts, time.perf_counter() - attempt_t0,
                attrs={"attempt": attempt_no, "outcome": outcome,
                       "rounds_left": boost_rounds_left},
            )

        try:
            booster, final_evals_result, stats = _train(
                params,
                dtrain,
                boost_rounds_left,
                evals=evals,
                ray_params=ray_params,
                obj=obj,
                feval=feval,
                callbacks=kwargs_callbacks,
                early_stopping_rounds=early_stopping_rounds,
                maximize=maximize,
                verbose_eval=verbose_eval,
                _training_state=state,
            )
            total_training_time += stats["training_time_s"]
            _close_attempt("ok")
            break
        except RayXGBoostActorAvailable as exc:
            _stop_profile_if_running()
            _close_attempt("elastic_restart")
            # elastic reintegration: free restart (mirror main.py:1661-1673)
            logger.info(f"[RayXGBoost] {exc} Restarting from checkpoint with "
                        f"reintegrated workers.")
            robustness["elastic_restarts"] += 1
            obs.get_registry().counter("rxgb_train_elastic_restarts_total").inc()
            _account_failure(exc)
            _promote_pending_actors(state)
            run_tracer.event(
                "world.restart",
                attrs={"elastic": True,
                       "world": sum(1 for a in state.actors if a is not None)},
            )
            state.queue = Queue()
            state.stop_event = Event()
            _rewire_actors(state)
            continue
        except (RayActorError, RayTaskError) as exc:
            _stop_profile_if_running()
            _close_attempt("failed")
            if state.training_started_at:
                total_training_time += time.time() - state.training_started_at
                state.training_started_at = 0.0
            robustness["restarts"] += 1
            obs.get_registry().counter("rxgb_train_restarts_total").inc()
            _account_failure(exc)
            # only REAL failures escalate the backoff exponent — the elastic
            # reintegration restart above replays rounds but is a planned
            # event, not a crash
            state.consecutive_failures += 1
            alive = _apply_failure(state, exc)
            if ray_params.elastic_training:
                dead = ray_params.num_actors - alive
                if dead > ray_params.max_failed_actors:
                    raise RayXGBoostTrainingError(
                        f"A worker died and too many workers are already dead "
                        f"({dead} > max_failed_actors="
                        f"{ray_params.max_failed_actors}). Aborting."
                    ) from exc
                logger.warning(
                    f"[RayXGBoost] A worker died. Continuing elastically with "
                    f"{alive} remaining workers."
                )
                # dead ranks are reintegrated in the background, not recreated
                # by the next attempt
                for rank in list(state.failed_actor_ranks):
                    state.elastic_dead_ranks.add(rank)
                    state.failed_actor_ranks.discard(rank)
            else:
                if tries + 1 > max_actor_restarts:
                    raise RayXGBoostTrainingError(
                        "A worker died during training and the maximum "
                        "number of retries is exhausted. Checkpoint the "
                        "model more frequently or raise "
                        "`RayParams(max_actor_restarts=N)`."
                    ) from exc
                logger.warning(
                    "[RayXGBoost] A worker died. Recreating it and restarting "
                    "from the latest checkpoint."
                )
            state.queue = Queue()
            state.stop_event = Event()
            _rewire_actors(state)
            # exponential backoff + jitter before the retry so a persistent
            # fault cannot crash-loop at full speed; indexed by CONSECUTIVE
            # failures (rewound on forward progress), so an isolated failure
            # hours into a job waits only the base delay
            # (RXGB_RESTART_BACKOFF_* to tune; base 0 disables)
            backoff = restart_backoff_s(state.consecutive_failures - 1)
            if backoff > 0:
                logger.warning(
                    f"[RayXGBoost] Backing off {backoff:.2f}s before "
                    f"restart {robustness['restarts']}."
                )
                robustness["backoff_s"] = round(
                    robustness["backoff_s"] + backoff, 4
                )
                run_tracer.event(
                    "backoff",
                    attrs={"seconds": round(backoff, 4),
                           "restart": robustness["restarts"]},
                )
                time.sleep(backoff)
            tries += 1
            continue
        except BaseException:
            # any other exit (user abort, unexpected error): don't leak a
            # running profiler trace into the next train() call
            _stop_profile_if_running()
            raise

    if booster is None:
        # all rounds were already covered by the checkpoint
        booster = _deserialize_booster(state.checkpoint.value)

    if evals_result is not None:
        evals_result.update(final_evals_result)

    total_time = time.time() - start_time
    state.additional_results["training_time_s"] = total_training_time
    state.additional_results["total_time_s"] = total_time
    if _run_tracer is not None and _run_tracer.enabled:
        # the queryable run timeline: per-round spans, lifecycle events,
        # ring-buffer truncation accounting, optional phase profile
        state.additional_results["obs"] = _assemble_obs(_run_tracer, state)
    if additional_results is not None:
        additional_results.update(state.additional_results)

    if ray_params.verbose:
        logger.info(
            f"[RayXGBoost] Finished training after {total_time:.2f}s "
            f"({total_training_time:.2f}s pure training)."
        )
    if serve_registry is not None:
        state.additional_results["serve_model_version"] = serve_registry.load(
            booster
        )
        if additional_results is not None:
            additional_results["serve_model_version"] = state.additional_results[
                "serve_model_version"
            ]
    return booster


def _apply_failure(state: _TrainingState, exc) -> int:
    """Mark failed ranks dead; return number of alive actors.

    If the exception carries no rank information and liveness probing finds
    every actor healthy, no actor is blamed: the retry simply rebuilds the
    engine from the last checkpoint with the same world.
    """
    ranks = getattr(exc, "ranks", None) or []
    if not ranks:
        # unknown origin: probe liveness (mirror elastic.py:145-178)
        for rank, actor in enumerate(state.actors):
            if actor is not None and not actor.alive:
                ranks.append(rank)
    for rank in ranks:
        if state.actors[rank] is not None:
            state.actors[rank].kill()
            state.actors[rank] = None
            state.failed_actor_ranks.add(rank)
    return sum(1 for a in state.actors if a is not None)


def _rewire_actors(state: _TrainingState):
    for actor in state.actors:
        if actor is not None:
            actor.set_queue(state.queue)
            actor.set_stop_event(state.stop_event)
            actor._coalescer = state.death_coalescer
            if state.domain_map is not None:
                actor._domain = state.domain_map.domain_of(actor.rank)


def _promote_pending_actors(state: _TrainingState, ranks=None):
    """Install ready pending workers as live actors. ``ranks`` restricts the
    promotion (the round-boundary grow passes only the ranks of COMPLETE due
    domains — atomic domain grow-back); ``None`` promotes every ready worker
    (the legacy restart path, which rebuilds the whole world anyway)."""
    for rank, pending in list((state.pending_actors or {}).items()):
        if not pending.ready:
            continue  # still loading in the background; promote next time
        if ranks is not None and rank not in ranks:
            continue  # its domain is not complete yet: never half-grow
        state.actors[rank] = pending.actor
        state.failed_actor_ranks.discard(rank)
        state.elastic_dead_ranks.discard(rank)
        del state.pending_actors[rank]
    state.restart_training_at = None


# ---------------------------------------------------------------------------
# predict() (mirror of ``main.py:1750-1896``)
# ---------------------------------------------------------------------------


def _predict(
    model: RayXGBoostBooster,
    data: RayDMatrix,
    ray_params: RayParams,
    **kwargs,
):
    num_actors = ray_params.num_actors
    actors = [
        _create_actor(rank, num_actors, Queue(), Event(), ray_params.distributed_callbacks)
        for rank in range(num_actors)
    ]
    data.assign_shards_to_actors(actors)
    for actor in actors:
        actor.load_data(data)
        actor._distributed_callbacks.before_predict(actor)

    predict_kwargs = dict(kwargs)
    predict_kwargs.setdefault("validate_features", False)
    model_cats = getattr(model, "categories", None)
    if data.resolved_categories and not model_cats and model.cat_features:
        raise ValueError(
            "the prediction data auto-encoded categorical columns, but the "
            "model was trained on integer codes — the mappings cannot be "
            "aligned. Encode the data with the training codes instead."
        )
    shards = []
    for actor in actors:
        shard = actor.get_shard(data)
        if model_cats and data.resolved_categories != model_cats:
            # align this frame's auto-encoded codes with the model's mapping
            shard = translate_shard_categories(
                shard, data.resolved_categories, model_cats
            )
        shards.append(shard)

    # A user-passed base_margin addresses GLOBAL rows (original order); each
    # shard must receive its own rows' slice, not the array head.
    user_bm = predict_kwargs.pop("base_margin", None)
    if user_bm is not None and len(shards) > 1:
        user_bm = np.asarray(user_bm)
        if data.sharding == RayShardingMode.FIXED:
            sizes = [sh["data"].shape[0] for sh in shards]
            bm_shards = np.split(user_bm, np.cumsum(sizes)[:-1], axis=0)
        else:
            bm_shards = [
                user_bm[_get_sharding_indices(
                    data.sharding, r, len(shards), len(user_bm)
                )]
                for r in range(len(shards))
            ]
    elif user_bm is not None:
        bm_shards = [np.asarray(user_bm)]
    else:
        bm_shards = None

    results = _predict_shards_spmd(model, shards, predict_kwargs, bm_shards,
                                   ray_params=ray_params)
    if results is None:
        results = []
        for i, shard in enumerate(shards):
            if bm_shards is not None:
                bm = bm_shards[i]
            else:
                bm = shard.get("base_margin")
            if bm is not None:
                pred = model.predict(shard["data"], base_margin=bm, **predict_kwargs)
            else:
                pred = model.predict(shard["data"], **predict_kwargs)
            results.append(pred)
    for actor, pred in zip(actors, results):
        actor._distributed_callbacks.after_predict(actor, pred)

    if data.sharding == RayShardingMode.FIXED:
        return np.concatenate(results, axis=0)
    return combine_data(data.sharding, results)


def _predict_shards_spmd(model, shards, predict_kwargs, bm_shards=None,
                         ray_params=None):
    """SPMD fast path for distributed prediction: concatenate the actor
    shards (rank order), shard the rows over the training mesh's devices, and
    run the tree walk as one compiled shard_map program (VERDICT r3 #5 — the
    reference fans ``model.predict`` out to actors,
    ``xgboost_ray/main.py:1750-1896``; here the mesh IS the actor set).

    Returns per-actor prediction arrays (so callbacks and ``combine_data``
    see exactly what the host loop produces), or None when the request needs
    the host path (SHAP/leaf outputs, multi-process meshes, or
    RXGB_SPMD_PREDICT=0).
    """
    import jax

    if (
        not ENV.SPMD_PREDICT
        or not hasattr(model, "predict_margin_spmd")  # gblinear: host matmul
    ):
        return None
    special = None  # non-margin outputs ride their own SPMD kernels
    if predict_kwargs.get("pred_interactions"):
        special = "interactions"
        if predict_kwargs.get("approx_contribs"):
            import warnings

            # mirror the host path's signal that the flag is ignored
            warnings.warn(
                "approx_contribs=True is ignored with pred_interactions: "
                "only the exact interactions kernel is implemented."
            )
    elif predict_kwargs.get("pred_contribs"):
        special = ("contribs_approx" if predict_kwargs.get("approx_contribs")
                   else "contribs")
    elif predict_kwargs.get("pred_leaf"):
        special = "leaf"
    if jax.process_count() > 1:
        if special:
            return None  # host loop: special outputs are single-process SPMD
        # multi-process world: the full global mesh participates; this
        # process's shards are its local rows (same contract as training).
        devices = list(jax.devices())
        if len(devices) % jax.process_count():
            return None  # host loop fallback on ragged worlds
    else:
        devices = _resolve_mesh_devices(max(len(shards), 1), ray_params)
        if len(devices) > len(shards) > 0:
            devices = devices[: len(shards)]
        if len(devices) <= 1 and len(shards) <= 1:
            return None

    xs = [model._coerce_features(sh["data"]) for sh in shards]
    sizes = [xv.shape[0] for xv in xs]
    x_all = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]

    base_margin = None
    if bm_shards is not None:
        base_margin = np.concatenate(
            [np.asarray(b, np.float32).reshape(sz, -1)
             for b, sz in zip(bm_shards, sizes)],
            axis=0,
        )
    elif any(sh.get("base_margin") is not None for sh in shards):
        base_margin = np.concatenate(
            [np.asarray(sh["base_margin"], np.float32).reshape(sz, -1)
             for sh, sz in zip(shards, sizes)],
            axis=0,
        )

    booster = model
    iteration_range = predict_kwargs.get("iteration_range")
    if iteration_range is not None and iteration_range != (0, 0):
        booster = model.slice_rounds(iteration_range[0], iteration_range[1])
    bounds = np.cumsum(sizes)[:-1]
    if special:
        res = booster.predict_special_spmd(
            x_all, devices, special,
            ntree_limit=int(predict_kwargs.get("ntree_limit", 0) or 0),
            base_margin=base_margin,
        )
        return np.split(res, bounds, axis=0)
    margin = booster.predict_margin_spmd(
        x_all, devices,
        ntree_limit=int(predict_kwargs.get("ntree_limit", 0) or 0),
        base_margin=base_margin,
    )
    pred = booster._margin_to_prediction(
        margin, bool(predict_kwargs.get("output_margin"))
    )
    return np.split(pred, bounds, axis=0)


def predict(
    model: RayXGBoostBooster,
    data: RayDMatrix,
    ray_params: Union[None, RayParams, Dict] = None,
    _remote: Optional[bool] = None,
    **kwargs,
) -> Optional[np.ndarray]:
    """Distributed prediction (signature mirror of ``main.py:1810``)."""
    ray_params = _validate_ray_params(ray_params)
    if _remote:
        return _run_remote("predict", (model, data, ray_params, kwargs))
    if not isinstance(data, RayDMatrix):
        raise ValueError(
            f"The `data` argument passed to `predict()` is not a RayDMatrix, "
            f"but of type {type(data)}. FIX THIS by instantiating a "
            f"RayDMatrix first: `data = RayDMatrix(data)`."
        )
    if getattr(data, "streamed", False):
        raise NotImplementedError(
            "predict() over a streamed matrix is not supported: the tree "
            "walk needs raw feature values (thresholds), which a streamed "
            "load never materializes. Streamed ingestion is a training-side "
            "memory optimization — predict from a materialized RayDMatrix "
            "(or the serve/ layer)."
        )
    model = _coerce_model(model)
    max_actor_restarts = (
        ray_params.max_actor_restarts
        if ray_params.max_actor_restarts >= 0
        else float("inf")
    )
    data.load_data(ray_params.num_actors)
    tries = 0
    while tries <= max_actor_restarts:
        try:
            return _predict(model, data, ray_params, **kwargs)
        except (RayActorError, RayTaskError):
            if tries + 1 <= max_actor_restarts:
                logger.warning(
                    "[RayXGBoost] A worker died during prediction. Trying "
                    "again with new workers."
                )
                tries += 1
            else:
                raise RayXGBoostTrainingError(
                    "A worker died during prediction and the maximum number "
                    "of retries is exhausted."
                )
    return None
