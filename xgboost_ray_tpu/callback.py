"""Worker lifecycle callbacks (API mirror of ``xgboost_ray/callback.py``).

``DistributedCallback`` hooks fire on each (virtual) worker around init, data
loading, training and prediction — same hook names and ordering as the
reference so user callbacks port unchanged.
"""

from typing import Any, List, Optional


class DistributedCallback:
    """Distributed callbacks for RayXGBoostActor lifecycle hooks."""

    def on_init(self, actor, *args, **kwargs):
        pass

    def before_data_loading(self, actor, data, *args, **kwargs):
        pass

    def after_data_loading(self, actor, data, *args, **kwargs):
        pass

    def before_train(self, actor, *args, **kwargs):
        pass

    def after_train(self, actor, result_dict, *args, **kwargs):
        pass

    def after_round(self, actor, round_record, *args, **kwargs):
        """Fired once per boosting round with the obs round record
        (``{"round", "iteration", "duration_s", "world", "metrics"}``) so
        user code can stream per-round metrics live instead of parsing
        ``additional_results`` post hoc. One extra hook over the reference
        surface; default no-op keeps ported callbacks working unchanged."""
        pass

    def before_predict(self, actor, *args, **kwargs):
        pass

    def after_predict(self, actor, predictions, *args, **kwargs):
        pass


class DistributedCallbackContainer:
    def __init__(self, callbacks: Optional[List[DistributedCallback]]):
        self.callbacks = callbacks or []

    def on_init(self, actor, *args, **kwargs):
        for callback in self.callbacks:
            callback.on_init(actor, *args, **kwargs)

    def before_data_loading(self, actor, data, *args, **kwargs):
        for callback in self.callbacks:
            callback.before_data_loading(actor, data, *args, **kwargs)

    def after_data_loading(self, actor, data, *args, **kwargs):
        for callback in self.callbacks:
            callback.after_data_loading(actor, data, *args, **kwargs)

    def before_train(self, actor, *args, **kwargs):
        for callback in self.callbacks:
            callback.before_train(actor, *args, **kwargs)

    def after_train(self, actor, result_dict, *args, **kwargs):
        for callback in self.callbacks:
            callback.after_train(actor, result_dict, *args, **kwargs)

    def after_round(self, actor, round_record, *args, **kwargs):
        for callback in self.callbacks:
            # subclasses written against the original (pre-obs) hook set
            # may not define after_round; don't break them
            hook = getattr(callback, "after_round", None)
            if hook is not None:
                hook(actor, round_record, *args, **kwargs)

    def before_predict(self, actor, *args, **kwargs):
        for callback in self.callbacks:
            callback.before_predict(actor, *args, **kwargs)

    def after_predict(self, actor, predictions, *args, **kwargs):
        for callback in self.callbacks:
            callback.after_predict(actor, predictions, *args, **kwargs)


class EnvironmentCallback(DistributedCallback):
    """Set env vars on worker init (mirror of ``callback.py:105-110``)."""

    def __init__(self, env_dict: dict):
        self.env_dict = env_dict

    def on_init(self, actor, *args, **kwargs):
        import os

        os.environ.update(self.env_dict)


class TrainingCallback:
    """xgboost-style per-iteration callback protocol.

    The subset of ``xgboost.callback.TrainingCallback`` the reference relies
    on (user callbacks forwarded at ``main.py:714-716``; legacy polyfill at
    ``compat/__init__.py:12-42``): ``before_training``/``after_training``
    return the model, ``before_iteration``/``after_iteration`` return a bool
    (True stops training).
    """

    def before_training(self, model):
        return model

    def after_training(self, model):
        return model

    def before_iteration(self, model, epoch: int, evals_log: dict) -> bool:
        return False

    def after_iteration(self, model, epoch: int, evals_log: dict) -> bool:
        return False
