"""Standalone hyperparameter sweep runner.

The reference delegates HPO orchestration to Ray Tune (``xgboost_ray/tune.py``
integrates callbacks + resources; trials are scheduled by Ray). On a TPU pod
there is no Ray scheduler, so this module provides the trial-execution layer:
grid/random search over a param space, one trial at a time on the mesh (task
parallelism across trials maps to separate slices in multi-slice
deployments), with the same report/checkpoint surface
(``tune.TuneSession`` + ``TuneReportCheckpointCallback``).

Search-space helpers mirror ``ray.tune``'s: grid_search, choice, uniform,
loguniform, randint.
"""

import dataclasses
import itertools
import logging
import os
import random
import tempfile
from typing import Any, Callable, Dict, List, Optional

from xgboost_ray_tpu import tune as tune_mod

logger = logging.getLogger(__name__)


def _partition_devices(devs: List[Any], n_slots: int) -> List[List[Any]]:
    """Split ``devs`` into ``n_slots`` contiguous slices covering EVERY
    device: the first ``len % n_slots`` slots take one extra device, so no
    trailing devices are dropped when the mesh doesn't divide evenly (the
    old ``len // n_slots``-sized slices silently idled the remainder)."""
    n_slots = max(1, min(n_slots, len(devs)))
    base, extra = divmod(len(devs), n_slots)
    out, pos = [], 0
    for j in range(n_slots):
        k = base + (1 if j < extra else 0)
        out.append(list(devs[pos : pos + k]))
        pos += k
    return out


# --- search space primitives -------------------------------------------------


@dataclasses.dataclass
class GridSearch:
    values: List[Any]


@dataclasses.dataclass
class Sampler:
    fn: Callable[[random.Random], Any]

    def sample(self, rng: random.Random) -> Any:
        return self.fn(rng)


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def choice(values: List[Any]) -> Sampler:
    return Sampler(lambda rng: rng.choice(list(values)))


def uniform(low: float, high: float) -> Sampler:
    return Sampler(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Sampler:
    import math

    return Sampler(lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))))


def randint(low: int, high: int) -> Sampler:
    return Sampler(lambda rng: rng.randrange(low, high))


def _expand_space(space: Dict[str, Any], num_samples: int, seed: int) -> List[Dict[str, Any]]:
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    grid_values = [space[k].values for k in grid_keys]
    rng = random.Random(seed)
    configs = []
    grid_product = list(itertools.product(*grid_values)) if grid_keys else [()]
    for _ in range(max(1, num_samples)):
        for combo in grid_product:
            config = {}
            for key, value in space.items():
                if isinstance(value, GridSearch):
                    config[key] = combo[grid_keys.index(key)]
                elif isinstance(value, Sampler):
                    config[key] = value.sample(rng)
                else:
                    config[key] = value
            configs.append(config)
    return configs


# --- trial schedulers --------------------------------------------------------
# The reference gets early trial termination from Ray Tune's schedulers
# (ASHAScheduler / MedianStoppingRule); these are the standalone equivalents.
# A scheduler's on_report(trial_id, iteration, metrics) is consulted at every
# per-round report (tune.TuneSession.report) and returning True stops that
# trial's training loop. Thread-safe: concurrent trials share one instance.


class ASHAScheduler:
    """Asynchronous Successive Halving: at rungs ``grace * eta^k`` a trial
    continues only if its metric is in the top ``1/eta`` of values recorded
    at that rung so far (async — no waiting for full brackets)."""

    def __init__(self, metric: str, mode: str = "min", grace_rounds: int = 5,
                 eta: int = 3, max_rounds: int = 10_000):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.eta = max(2, int(eta))
        self.rungs: List[int] = []
        r = max(1, int(grace_rounds))
        while r <= max_rounds:
            self.rungs.append(r)
            r *= self.eta
        import threading

        self._lock = threading.Lock()
        self._rung_values: Dict[int, List[float]] = {r: [] for r in self.rungs}

    def on_report(self, trial_id: str, iteration: int, metrics: Dict[str, Any]) -> bool:
        import math

        value = metrics.get(self.metric)
        if value is None or iteration not in self._rung_values:
            return False
        if math.isnan(float(value)):
            # a diverged trial is the scheduler's primary target: stop it,
            # and keep NaN out of the rung statistics
            return True
        v = float(value) if self.mode == "min" else -float(value)
        with self._lock:
            vals = self._rung_values[iteration]
            vals.append(v)
            vals.sort()
            k = max(1, len(vals) // self.eta)
            cutoff = vals[k - 1]
        return v > cutoff  # outside the top 1/eta at this rung -> stop


class MedianStoppingRule:
    """Stop a trial whose best-so-far is worse than the median of the other
    trials' best-so-far at the same iteration (after a grace period)."""

    def __init__(self, metric: str, mode: str = "min", grace_rounds: int = 5,
                 min_trials: int = 3):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.grace_rounds = max(1, int(grace_rounds))
        self.min_trials = max(2, int(min_trials))
        import threading

        self._lock = threading.Lock()
        # trial_id -> {iteration: value} (keyed by the REPORTED iteration, so
        # extra/skipped manual reports cannot misalign the comparison)
        self._histories: Dict[str, Dict[int, float]] = {}

    def on_report(self, trial_id: str, iteration: int, metrics: Dict[str, Any]) -> bool:
        import math
        import statistics

        value = metrics.get(self.metric)
        if value is None:
            return False
        if math.isnan(float(value)):
            return iteration >= self.grace_rounds  # diverged -> stop past grace
        v = float(value) if self.mode == "min" else -float(value)
        with self._lock:
            hist = self._histories.setdefault(trial_id, {})
            hist[iteration] = v
            if iteration < self.grace_rounds:
                return False
            # peers must have progressed at least this far (>=) AND have at
            # least one report at it <= iteration — a manual/skipped report
            # pattern can otherwise leave the inner min() with no entries
            others = []
            for tid, h in self._histories.items():
                if tid == trial_id or not any(it >= iteration for it in h):
                    continue
                vals = [val for it, val in h.items() if it <= iteration]
                if vals:
                    others.append(min(vals))
            if len(others) + 1 < self.min_trials:
                return False
            med = statistics.median(others)
            # symmetric window: judge the trial on the same it <= iteration
            # range its peers are reduced over (manual reports can arrive
            # out of order)
            best = min(val for it, val in hist.items() if it <= iteration)
        return best > med


# --- vectorized (vmapped-K) trials -------------------------------------------


@dataclasses.dataclass
class VectorizedTrainable:
    """Declarative training spec for the on-mesh vectorized HPO path.

    The callback trainable is opaque user code, so every trial is its own
    mesh program (and its own XLA compile). This spec instead names the
    data and round budget declaratively, letting ``Tuner.fit``:

    * ``vectorized=True`` — pack lane-compatible trials as LANES of ONE
      vmapped-K XLA program (engine.enable_lanes): one compile trains up
      to ``max_lanes`` candidates simultaneously on the same binned data,
      and an attached ``ASHAScheduler`` prunes losing lanes at round
      boundaries (``engine.repack_lanes`` re-packs survivors into a
      smaller K' program).
    * ``vectorized=False`` — run trials sequentially, but route each
      group of same-shaped trials through ONE lane-enabled engine held in
      the tuner's engine cache (``engine.reset_lanes`` between trials), so
      trials differing only in lane-vectorizable params share a single
      compile instead of retracing per trial.

    Trials whose params cannot ride the lane axis raise
    ``NotImplementedError`` naming the offending key (params.
    vectorize_params) — a lane never silently trains with the wrong
    config. Params that always force separate compiles (``max_bin``,
    ``grow_policy``, ``hist_impl``, ``feature_parallel``, objectives, ...)
    simply land in separate groups/programs.
    """

    shards: List[Any]
    num_actors: int
    num_boost_round: int = 10
    evals: List[Any] = dataclasses.field(default_factory=list)
    devices: Optional[List[Any]] = None
    vectorized: bool = True
    # lane cap per program: each lane carries a margin plane per data set,
    # so K is a memory knob as much as a throughput one
    max_lanes: int = 8


# --- trial execution ---------------------------------------------------------


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    last_result: Optional[Dict[str, Any]] = None
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    trial_dir: str = ""
    stopped_early: bool = False  # terminated by the trial scheduler


@dataclasses.dataclass
class ExperimentResult:
    trials: List[Trial]
    metric: Optional[str]
    mode: str

    def get_best_trial(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Optional[Trial]:
        metric = metric or self.metric
        mode = mode or self.mode
        scored = [
            t for t in self.trials
            if t.last_result is not None and metric in t.last_result
        ]
        if not scored:
            return None
        key = lambda t: t.last_result[metric]
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    @property
    def best_config(self) -> Optional[Dict[str, Any]]:
        best = self.get_best_trial()
        return best.config if best else None

    @property
    def best_checkpoint(self) -> Optional[str]:
        best = self.get_best_trial()
        return best.checkpoint_path if best else None


class Tuner:
    """Sequential trial runner with the tune-session report surface.

    ``trainable(config)`` runs a full training; inside it, ``train()``
    auto-injects ``TuneReportCheckpointCallback`` (because a tune session is
    active), so per-round metrics and periodic checkpoints flow into the
    trial record without user code — identical UX to the reference's
    Ray-Tune path (``xgboost_ray/tune.py:27-48``).
    """

    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], Any],
        param_space: Dict[str, Any],
        *,
        metric: Optional[str] = None,
        mode: str = "min",
        num_samples: int = 1,
        seed: int = 0,
        experiment_dir: Optional[str] = None,
        raise_on_failed_trial: bool = False,
        max_concurrent_trials: int = 1,
        scheduler=None,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.trainable = trainable
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.seed = seed
        self.experiment_dir = experiment_dir or tempfile.mkdtemp(prefix="rxgb_exp_")
        self.raise_on_failed_trial = raise_on_failed_trial
        self.max_concurrent_trials = max(1, int(max_concurrent_trials))
        # ASHAScheduler / MedianStoppingRule (or any on_report duck type):
        # early-terminates unpromising trials — the Ray Tune scheduler role
        self.scheduler = scheduler
        # vectorized-HPO engine cache (VectorizedTrainable sequential mode):
        # one lane-enabled engine per same-shaped trial group, reused across
        # trials via reset_lanes — the tuner-level analog of the driver's
        # elastic engine_cache, bounded the same way (entries pin device
        # arrays)
        self.engine_cache: Dict[Any, Any] = {}

    def _run_trial(self, i: int, config: Dict[str, Any], devices=None) -> Trial:
        trial_id = f"trial_{i:05d}"
        trial_dir = os.path.join(self.experiment_dir, trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        trial = Trial(trial_id=trial_id, config=config, trial_dir=trial_dir)
        session = tune_mod.init_session(trial_dir, devices=devices)
        session.scheduler = self.scheduler
        session.trial_id = trial_id
        try:
            self.trainable(config)
            trial.results = session.results
            trial.last_result = session.results[-1] if session.results else None
            trial.checkpoint_path = session.last_checkpoint_path
            trial.stopped_early = session.stopped_by_scheduler
        except Exception as exc:  # noqa: BLE001 - trial isolation
            trial.error = f"{type(exc).__name__}: {exc}"
            logger.warning(f"[Tuner] {trial_id} failed: {trial.error}")
            if self.raise_on_failed_trial:
                tune_mod.shutdown_session()
                raise
        finally:
            tune_mod.shutdown_session()
        if trial.last_result and self.metric and self.metric in trial.last_result:
            logger.info(
                f"[Tuner] {trial_id} {self.metric}="
                f"{trial.last_result[self.metric]:.5f} config={config}"
            )
        return trial

    # --- vectorized (vmapped-K) execution --------------------------------

    @staticmethod
    def _lane_groups(configs: List[Dict[str, Any]]) -> List[List[int]]:
        """Partition trial indices into lane-compatible groups: trials in a
        group agree on every non-lane-vectorizable parsed param (those are
        trace-shape coordinates — separate compiles by construction), and
        on the params the grower cannot mask per lane (depth under
        lossguide, subsample under GOSS)."""
        from xgboost_ray_tpu.params import (
            LANE_VECTORIZABLE_KEYS, TrainParams, parse_params,
        )

        groups: Dict[tuple, List[int]] = {}
        for i, config in enumerate(configs):
            p = parse_params(config)
            key = [
                repr(getattr(p, f.name))
                for f in dataclasses.fields(TrainParams)
                if f.name not in LANE_VECTORIZABLE_KEYS
            ]
            # params vectorize_params would reject as lane-varying for this
            # config shape become group-key coordinates instead, so every
            # group it receives is vectorizable by construction
            if p.grow_policy == "lossguide":
                key.append(("max_depth", p.max_depth))
            if p.sampling_method == "gradient_based":
                key.append(("subsample", float(p.subsample)))
            groups.setdefault(tuple(key), []).append(i)
        return list(groups.values())

    def _new_trial(self, i: int, config: Dict[str, Any]) -> Trial:
        trial_id = f"trial_{i:05d}"
        trial_dir = os.path.join(self.experiment_dir, trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        return Trial(trial_id=trial_id, config=config, trial_dir=trial_dir)

    @staticmethod
    def _flatten_lane_result(lane_res: Dict[str, Dict[str, float]],
                             iteration: int) -> Dict[str, Any]:
        flat: Dict[str, Any] = {
            f"{ename}-{m}": v
            for ename, row in lane_res.items()
            for m, v in row.items()
        }
        flat["training_iteration"] = iteration
        return flat

    @staticmethod
    def _save_lane_checkpoint(engine, slot: int, trial: Trial) -> None:
        booster = engine.get_booster_lane(slot)
        ckpt_dir = os.path.join(trial.trial_dir, "checkpoint_final")
        os.makedirs(ckpt_dir, exist_ok=True)
        path = os.path.join(ckpt_dir, "checkpoint.json")
        booster.save_model(path)
        trial.checkpoint_path = ckpt_dir

    def _fit_vectorized(self, configs: List[Dict[str, Any]]) -> ExperimentResult:
        """VectorizedTrainable execution: lane-packed vmapped-K programs
        (``vectorized=True``) or compile-deduped sequential trials
        (``vectorized=False``)."""
        spec = self.trainable
        trials = [self._new_trial(i, c) for i, c in enumerate(configs)]
        groups = self._lane_groups(configs)
        for idxs in groups:
            if spec.vectorized:
                cap = max(1, int(spec.max_lanes))
                for pos in range(0, len(idxs), cap):
                    self._train_pack(spec, idxs[pos : pos + cap], trials)
            else:
                self._train_group_sequential(spec, idxs, trials)
        return ExperimentResult(
            trials=trials, metric=self.metric, mode=self.mode
        )

    def _lane_engine(self, spec: VectorizedTrainable, lane_params,
                     group_key, force_masks: bool):
        """Build (or revive from the tuner engine cache) a lane-enabled
        engine for one trial group. A cache hit re-arms the engine via
        ``reset_lanes`` — the compiled K-lane programs carry over."""
        from xgboost_ray_tpu.engine import TpuEngine

        cached = self.engine_cache.pop(group_key, None)
        if cached is not None and cached.params == lane_params.base:
            cached.reset_lanes(lane_params)
            return cached
        evals = list(spec.evals) or [(spec.shards, "train")]
        eng = TpuEngine(
            spec.shards,
            lane_params.base,
            num_actors=spec.num_actors,
            evals=evals,
            devices=spec.devices,
            total_rounds=spec.num_boost_round,
        )
        eng.enable_lanes(lane_params, force_masks=force_masks)
        return eng

    def _cache_engine(self, group_key, engine) -> None:
        self.engine_cache[group_key] = engine
        while len(self.engine_cache) > 2:
            self.engine_cache.pop(next(iter(self.engine_cache)))

    def _train_pack(self, spec: VectorizedTrainable, idxs: List[int],
                    trials: List[Trial]) -> None:
        """Train one pack of lane-compatible trials as a vmapped-K program,
        with ASHA successive halving at round boundaries: pruned lanes are
        finalized (booster + checkpoint) and the survivors re-packed into a
        smaller K' program."""
        from xgboost_ray_tpu import obs
        from xgboost_ray_tpu.params import vectorize_params

        lp = vectorize_params([trials[i].config for i in idxs])
        group_key = ("pack",) + tuple(idxs)
        eng = self._lane_engine(spec, lp, group_key, force_masks=False)
        tracer = obs.get_tracer()
        try:
            for it in range(spec.num_boost_round):
                results = eng.step_vmapped(it)
                lane_ids = eng.lane_ids()
                stop_slots = []
                for slot, lane_res in enumerate(results):
                    trial = trials[idxs[lane_ids[slot]]]
                    flat = self._flatten_lane_result(lane_res, it + 1)
                    trial.results.append(flat)
                    trial.last_result = flat
                    if self.scheduler is not None and self.scheduler.on_report(
                        trial.trial_id, it + 1, flat
                    ):
                        trial.stopped_early = True
                        stop_slots.append(slot)
                last_round = it + 1 == spec.num_boost_round
                if stop_slots and not last_round:
                    for slot in stop_slots:
                        trial = trials[idxs[lane_ids[slot]]]
                        self._save_lane_checkpoint(eng, slot, trial)
                        tracer.event("hpo.lane_prune", attrs={
                            "trial": trial.trial_id,
                            "lane": lane_ids[slot],
                            "round": it + 1,
                            "metric": getattr(
                                self.scheduler, "metric", None
                            ),
                            "value": (trial.last_result or {}).get(
                                getattr(self.scheduler, "metric", None)
                            ),
                        })
                    keep = [
                        s for s in range(len(results)) if s not in stop_slots
                    ]
                    if not keep:
                        return
                    tracer.event("hpo.repack", attrs={
                        "k_before": len(results),
                        "k_after": len(keep),
                        "round": it + 1,
                    })
                    eng.repack_lanes(keep)
            for slot, lane_id in enumerate(eng.lane_ids()):
                self._save_lane_checkpoint(eng, slot, trials[idxs[lane_id]])
        finally:
            if eng.lane_ids():
                self._cache_engine(group_key, eng)

    def _train_group_sequential(self, spec: VectorizedTrainable,
                                idxs: List[int],
                                trials: List[Trial]) -> None:
        """Sequential trials of one lane-compatible group through ONE
        engine: trial j reuses trial 0's compiled K=1 program via
        ``reset_lanes`` (per-lane params are runtime inputs, so only the
        group's trace-shape signature compiles)."""
        import dataclasses as _dc

        from xgboost_ray_tpu.params import vectorize_params

        group_lp = vectorize_params([trials[i].config for i in idxs])
        group_key = ("group",) + tuple(idxs)
        eng = None
        for j, i in enumerate(idxs):
            trial = trials[i]
            lp_j = _dc.replace(group_lp, lanes=(group_lp.lanes[j],))
            if eng is None:
                # force_masks: later trials in the group may vary depth /
                # subsample — pre-arm the masks so they share the compile
                eng = self._lane_engine(
                    spec, lp_j, group_key, force_masks=True
                )
            else:
                eng.reset_lanes(lp_j)
            for it in range(spec.num_boost_round):
                lane_res = eng.step_vmapped(it)[0]
                flat = self._flatten_lane_result(lane_res, it + 1)
                trial.results.append(flat)
                trial.last_result = flat
                if self.scheduler is not None and self.scheduler.on_report(
                    trial.trial_id, it + 1, flat
                ):
                    trial.stopped_early = True
                    break
            self._save_lane_checkpoint(eng, 0, trial)
        if eng is not None:
            self._cache_engine(group_key, eng)

    def fit(self) -> ExperimentResult:
        """Run all trials. With ``max_concurrent_trials > 1``, trials run in
        a thread pool and the local device mesh is partitioned into disjoint
        contiguous slices, one per concurrent slot — the single-host analog of
        trials-on-separate-TPU-slices task parallelism (SURVEY §2.3; the
        reference gets this from Ray Tune's scheduler, ``tune.py:107-126``)."""
        configs = _expand_space(self.param_space, self.num_samples, self.seed)
        if isinstance(self.trainable, VectorizedTrainable):
            if self.max_concurrent_trials != 1:
                raise ValueError(
                    "VectorizedTrainable owns the whole mesh (trials are "
                    "lanes of one program); max_concurrent_trials must be 1"
                )
            return self._fit_vectorized(configs)
        if self.max_concurrent_trials == 1:
            trials = [self._run_trial(i, c) for i, c in enumerate(configs)]
            return ExperimentResult(trials=trials, metric=self.metric, mode=self.mode)

        import queue as queue_mod
        from concurrent.futures import ThreadPoolExecutor

        import jax

        devs = jax.devices()
        n_slots = min(self.max_concurrent_trials, max(1, len(devs)))
        slot_devices = _partition_devices(devs, n_slots)
        slots: "queue_mod.Queue" = queue_mod.Queue()
        for s in slot_devices:
            slots.put(s)

        def run(i_config):
            i, config = i_config
            devices = slots.get()
            try:
                return self._run_trial(i, config, devices=devices)
            finally:
                slots.put(devices)

        with ThreadPoolExecutor(max_workers=n_slots) as pool:
            trials = list(pool.map(run, enumerate(configs)))
        return ExperimentResult(trials=trials, metric=self.metric, mode=self.mode)
