"""Standalone hyperparameter sweep runner.

The reference delegates HPO orchestration to Ray Tune (``xgboost_ray/tune.py``
integrates callbacks + resources; trials are scheduled by Ray). On a TPU pod
there is no Ray scheduler, so this module provides the trial-execution layer:
grid/random search over a param space, one trial at a time on the mesh (task
parallelism across trials maps to separate slices in multi-slice
deployments), with the same report/checkpoint surface
(``tune.TuneSession`` + ``TuneReportCheckpointCallback``).

Search-space helpers mirror ``ray.tune``'s: grid_search, choice, uniform,
loguniform, randint.
"""

import dataclasses
import itertools
import logging
import os
import random
import tempfile
from typing import Any, Callable, Dict, List, Optional

from xgboost_ray_tpu import tune as tune_mod

logger = logging.getLogger(__name__)


def _partition_devices(devs: List[Any], n_slots: int) -> List[List[Any]]:
    """Split ``devs`` into ``n_slots`` contiguous slices covering EVERY
    device: the first ``len % n_slots`` slots take one extra device, so no
    trailing devices are dropped when the mesh doesn't divide evenly (the
    old ``len // n_slots``-sized slices silently idled the remainder)."""
    n_slots = max(1, min(n_slots, len(devs)))
    base, extra = divmod(len(devs), n_slots)
    out, pos = [], 0
    for j in range(n_slots):
        k = base + (1 if j < extra else 0)
        out.append(list(devs[pos : pos + k]))
        pos += k
    return out


# --- search space primitives -------------------------------------------------


@dataclasses.dataclass
class GridSearch:
    values: List[Any]


@dataclasses.dataclass
class Sampler:
    fn: Callable[[random.Random], Any]

    def sample(self, rng: random.Random) -> Any:
        return self.fn(rng)


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def choice(values: List[Any]) -> Sampler:
    return Sampler(lambda rng: rng.choice(list(values)))


def uniform(low: float, high: float) -> Sampler:
    return Sampler(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Sampler:
    import math

    return Sampler(lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))))


def randint(low: int, high: int) -> Sampler:
    return Sampler(lambda rng: rng.randrange(low, high))


def _expand_space(space: Dict[str, Any], num_samples: int, seed: int) -> List[Dict[str, Any]]:
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    grid_values = [space[k].values for k in grid_keys]
    rng = random.Random(seed)
    configs = []
    grid_product = list(itertools.product(*grid_values)) if grid_keys else [()]
    for _ in range(max(1, num_samples)):
        for combo in grid_product:
            config = {}
            for key, value in space.items():
                if isinstance(value, GridSearch):
                    config[key] = combo[grid_keys.index(key)]
                elif isinstance(value, Sampler):
                    config[key] = value.sample(rng)
                else:
                    config[key] = value
            configs.append(config)
    return configs


# --- trial schedulers --------------------------------------------------------
# The reference gets early trial termination from Ray Tune's schedulers
# (ASHAScheduler / MedianStoppingRule); these are the standalone equivalents.
# A scheduler's on_report(trial_id, iteration, metrics) is consulted at every
# per-round report (tune.TuneSession.report) and returning True stops that
# trial's training loop. Thread-safe: concurrent trials share one instance.


class ASHAScheduler:
    """Asynchronous Successive Halving: at rungs ``grace * eta^k`` a trial
    continues only if its metric is in the top ``1/eta`` of values recorded
    at that rung so far (async — no waiting for full brackets)."""

    def __init__(self, metric: str, mode: str = "min", grace_rounds: int = 5,
                 eta: int = 3, max_rounds: int = 10_000):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.eta = max(2, int(eta))
        self.rungs: List[int] = []
        r = max(1, int(grace_rounds))
        while r <= max_rounds:
            self.rungs.append(r)
            r *= self.eta
        import threading

        self._lock = threading.Lock()
        self._rung_values: Dict[int, List[float]] = {r: [] for r in self.rungs}

    def on_report(self, trial_id: str, iteration: int, metrics: Dict[str, Any]) -> bool:
        import math

        value = metrics.get(self.metric)
        if value is None or iteration not in self._rung_values:
            return False
        if math.isnan(float(value)):
            # a diverged trial is the scheduler's primary target: stop it,
            # and keep NaN out of the rung statistics
            return True
        v = float(value) if self.mode == "min" else -float(value)
        with self._lock:
            vals = self._rung_values[iteration]
            vals.append(v)
            vals.sort()
            k = max(1, len(vals) // self.eta)
            cutoff = vals[k - 1]
        return v > cutoff  # outside the top 1/eta at this rung -> stop


class MedianStoppingRule:
    """Stop a trial whose best-so-far is worse than the median of the other
    trials' best-so-far at the same iteration (after a grace period)."""

    def __init__(self, metric: str, mode: str = "min", grace_rounds: int = 5,
                 min_trials: int = 3):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.grace_rounds = max(1, int(grace_rounds))
        self.min_trials = max(2, int(min_trials))
        import threading

        self._lock = threading.Lock()
        # trial_id -> {iteration: value} (keyed by the REPORTED iteration, so
        # extra/skipped manual reports cannot misalign the comparison)
        self._histories: Dict[str, Dict[int, float]] = {}

    def on_report(self, trial_id: str, iteration: int, metrics: Dict[str, Any]) -> bool:
        import math
        import statistics

        value = metrics.get(self.metric)
        if value is None:
            return False
        if math.isnan(float(value)):
            return iteration >= self.grace_rounds  # diverged -> stop past grace
        v = float(value) if self.mode == "min" else -float(value)
        with self._lock:
            hist = self._histories.setdefault(trial_id, {})
            hist[iteration] = v
            if iteration < self.grace_rounds:
                return False
            # peers must have progressed at least this far (>=) AND have at
            # least one report at it <= iteration — a manual/skipped report
            # pattern can otherwise leave the inner min() with no entries
            others = []
            for tid, h in self._histories.items():
                if tid == trial_id or not any(it >= iteration for it in h):
                    continue
                vals = [val for it, val in h.items() if it <= iteration]
                if vals:
                    others.append(min(vals))
            if len(others) + 1 < self.min_trials:
                return False
            med = statistics.median(others)
            # symmetric window: judge the trial on the same it <= iteration
            # range its peers are reduced over (manual reports can arrive
            # out of order)
            best = min(val for it, val in hist.items() if it <= iteration)
        return best > med


# --- trial execution ---------------------------------------------------------


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    last_result: Optional[Dict[str, Any]] = None
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    trial_dir: str = ""
    stopped_early: bool = False  # terminated by the trial scheduler


@dataclasses.dataclass
class ExperimentResult:
    trials: List[Trial]
    metric: Optional[str]
    mode: str

    def get_best_trial(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Optional[Trial]:
        metric = metric or self.metric
        mode = mode or self.mode
        scored = [
            t for t in self.trials
            if t.last_result is not None and metric in t.last_result
        ]
        if not scored:
            return None
        key = lambda t: t.last_result[metric]
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    @property
    def best_config(self) -> Optional[Dict[str, Any]]:
        best = self.get_best_trial()
        return best.config if best else None

    @property
    def best_checkpoint(self) -> Optional[str]:
        best = self.get_best_trial()
        return best.checkpoint_path if best else None


class Tuner:
    """Sequential trial runner with the tune-session report surface.

    ``trainable(config)`` runs a full training; inside it, ``train()``
    auto-injects ``TuneReportCheckpointCallback`` (because a tune session is
    active), so per-round metrics and periodic checkpoints flow into the
    trial record without user code — identical UX to the reference's
    Ray-Tune path (``xgboost_ray/tune.py:27-48``).
    """

    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], Any],
        param_space: Dict[str, Any],
        *,
        metric: Optional[str] = None,
        mode: str = "min",
        num_samples: int = 1,
        seed: int = 0,
        experiment_dir: Optional[str] = None,
        raise_on_failed_trial: bool = False,
        max_concurrent_trials: int = 1,
        scheduler=None,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.trainable = trainable
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.seed = seed
        self.experiment_dir = experiment_dir or tempfile.mkdtemp(prefix="rxgb_exp_")
        self.raise_on_failed_trial = raise_on_failed_trial
        self.max_concurrent_trials = max(1, int(max_concurrent_trials))
        # ASHAScheduler / MedianStoppingRule (or any on_report duck type):
        # early-terminates unpromising trials — the Ray Tune scheduler role
        self.scheduler = scheduler

    def _run_trial(self, i: int, config: Dict[str, Any], devices=None) -> Trial:
        trial_id = f"trial_{i:05d}"
        trial_dir = os.path.join(self.experiment_dir, trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        trial = Trial(trial_id=trial_id, config=config, trial_dir=trial_dir)
        session = tune_mod.init_session(trial_dir, devices=devices)
        session.scheduler = self.scheduler
        session.trial_id = trial_id
        try:
            self.trainable(config)
            trial.results = session.results
            trial.last_result = session.results[-1] if session.results else None
            trial.checkpoint_path = session.last_checkpoint_path
            trial.stopped_early = session.stopped_by_scheduler
        except Exception as exc:  # noqa: BLE001 - trial isolation
            trial.error = f"{type(exc).__name__}: {exc}"
            logger.warning(f"[Tuner] {trial_id} failed: {trial.error}")
            if self.raise_on_failed_trial:
                tune_mod.shutdown_session()
                raise
        finally:
            tune_mod.shutdown_session()
        if trial.last_result and self.metric and self.metric in trial.last_result:
            logger.info(
                f"[Tuner] {trial_id} {self.metric}="
                f"{trial.last_result[self.metric]:.5f} config={config}"
            )
        return trial

    def fit(self) -> ExperimentResult:
        """Run all trials. With ``max_concurrent_trials > 1``, trials run in
        a thread pool and the local device mesh is partitioned into disjoint
        contiguous slices, one per concurrent slot — the single-host analog of
        trials-on-separate-TPU-slices task parallelism (SURVEY §2.3; the
        reference gets this from Ray Tune's scheduler, ``tune.py:107-126``)."""
        configs = _expand_space(self.param_space, self.num_samples, self.seed)
        if self.max_concurrent_trials == 1:
            trials = [self._run_trial(i, c) for i, c in enumerate(configs)]
            return ExperimentResult(trials=trials, metric=self.metric, mode=self.mode)

        import queue as queue_mod
        from concurrent.futures import ThreadPoolExecutor

        import jax

        devs = jax.devices()
        n_slots = min(self.max_concurrent_trials, max(1, len(devs)))
        slot_devices = _partition_devices(devs, n_slots)
        slots: "queue_mod.Queue" = queue_mod.Queue()
        for s in slot_devices:
            slots.put(s)

        def run(i_config):
            i, config = i_config
            devices = slots.get()
            try:
                return self._run_trial(i, config, devices=devices)
            finally:
                slots.put(devices)

        with ThreadPoolExecutor(max_workers=n_slots) as pool:
            trials = list(pool.map(run, enumerate(configs)))
        return ExperimentResult(trials=trials, metric=self.metric, mode=self.mode)
