"""gblinear: the linear booster, TPU-native.

xgboost's ``booster="gblinear"`` fits a (multi-output) linear model by
cyclic coordinate descent on the boosting gradients with elastic-net
regularization — the reference exposes it by params passthrough
(``xgboost_ray/main.py:745-752``; updaters ``shotgun``/``coord_descent``
in xgboost's ``src/linear``). TPU formulation: one jitted shard_map
program per round — margins and grad/hess from the row-sharded matrix,
then ONE ``lax.scan`` over features performing the cyclic pass, with the
per-coordinate sums ``psum``-merged across the mesh (the same allreduce
point the tree path uses for histograms). ``shotgun``'s hogwild
parallelism is nondeterministic by design; here both updater names run
the deterministic cyclic pass (what ``coord_descent`` means), which is
also the reproducible choice for SPMD.

Semantics matched to xgboost's ``CoordinateDelta``/``CoordinateDeltaBias``
(``src/linear/coordinate_common.h``): elastic-net soft threshold with the
penalties denormalized by the total instance weight, ``eta``-scaled
updates, and incremental gradient refresh ``g += h * x_j * dw`` within the
pass. Missing values are implicit zeros (xgboost's sparse convention).
"""

import dataclasses
import json
import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xgboost_ray_tpu import progreg
from xgboost_ray_tpu.constants import AXIS_ACTORS
from xgboost_ray_tpu.engine import strict_transfer_guard
from xgboost_ray_tpu.ops.metrics import compute_metric, parse_metric_name
from xgboost_ray_tpu.ops.objectives import get_objective
from xgboost_ray_tpu.params import TrainParams

from xgboost_ray_tpu.compat import shard_map_compat as shard_map


class RayLinearBooster:
    """A trained linear model: ``margin = x @ weights + bias + m0``.

    API mirror of the tree booster's surface where it makes sense
    (predict / save_model / load_model / save_raw / export_xgboost_json),
    so ``train(params={"booster": "gblinear"}, ...)`` drops into the same
    driver pipelines."""

    def __init__(self, weights: np.ndarray, bias: np.ndarray,
                 params: TrainParams, base_score: float,
                 feature_names: Optional[List[str]] = None,
                 rounds: int = 0):
        self.weights = np.asarray(weights, np.float32)  # [F, K]
        self.bias = np.asarray(bias, np.float32)  # [K]
        self.params = params
        self.base_score = float(base_score)
        self.feature_names = feature_names
        self.rounds = int(rounds)
        self._attrs: Dict[str, str] = {}
        self.best_iteration: Optional[int] = None

    # ---- introspection ---------------------------------------------------
    @property
    def num_features(self) -> int:
        return int(self.weights.shape[0])

    @property
    def num_outputs(self) -> int:
        return int(self.weights.shape[1])

    def num_boosted_rounds(self) -> int:
        return self.rounds

    def attributes(self) -> Dict[str, str]:
        return dict(self._attrs)

    def set_attr(self, **kwargs) -> None:
        for k, v in kwargs.items():
            if v is None:
                self._attrs.pop(k, None)
            else:
                self._attrs[k] = str(v)

    def attr(self, key: str) -> Optional[str]:
        return self._attrs.get(key)

    def _objective(self):
        return get_objective(
            self.params.objective, self.params.num_class,
            self.params.scale_pos_weight,
            tweedie_variance_power=self.params.tweedie_variance_power,
            huber_slope=self.params.huber_slope,
            quantile_alpha=self.params.quantile_alpha,
        )

    # ---- prediction ------------------------------------------------------
    def predict_margin_np(self, x: np.ndarray,
                          base_margin: Optional[np.ndarray] = None) -> np.ndarray:
        x = np.nan_to_num(np.asarray(x, np.float32), nan=0.0)
        obj = self._objective()
        m0 = float(obj.base_score_to_margin(self.base_score))
        margin = x @ self.weights + self.bias[None, :] + m0
        if base_margin is not None:
            margin = margin + np.asarray(
                base_margin, np.float32).reshape(x.shape[0], -1)
        return margin

    def predict(self, x, output_margin: bool = False,
                base_margin: Optional[np.ndarray] = None, **kwargs):
        unsupported = [
            k for k in ("pred_contribs", "pred_interactions", "pred_leaf")
            if kwargs.get(k)
        ]
        # normalize iteration_range first: [0, 0] lists and np-int (0, 0)
        # tuples all mean "the full model" (a no-op for a linear model) and
        # must not raise (ADVICE r5)
        it_range = kwargs.get("iteration_range")
        if it_range is not None:
            try:
                it_range = tuple(int(v) for v in it_range)
            except (TypeError, ValueError):
                it_range = kwargs.get("iteration_range")
        if kwargs.get("ntree_limit") or it_range not in (None, (0, 0)):
            unsupported.append("iteration_range/ntree_limit")
        if unsupported:
            raise NotImplementedError(
                f"gblinear predict does not support {unsupported} (a linear "
                f"model has no trees to slice or walk)."
            )
        x = np.asarray(x, np.float32)
        margin = self.predict_margin_np(x, base_margin=base_margin)
        if output_margin:
            return margin[:, 0] if self.num_outputs == 1 else margin
        obj = self._objective()
        return np.asarray(obj.transform(jnp.asarray(margin)))

    # ---- serialization ---------------------------------------------------
    def save_model(self, fname: str) -> None:
        self.export_xgboost_json(fname)

    @classmethod
    def load_model(cls, fname: str) -> "RayLinearBooster":
        with open(fname) as f:
            return cls.import_xgboost_json(f.read())

    def save_raw(self) -> bytes:
        return pickle.dumps(self)

    @classmethod
    def load_raw(cls, raw: bytes) -> "RayLinearBooster":
        return pickle.loads(raw)

    def export_xgboost_json(self, fname: Optional[str] = None) -> str:
        """The native xgboost gblinear JSON schema: flat ``weights`` of
        length ``(F+1)*K``, feature-major with the K bias entries last."""
        f, k = self.weights.shape
        flat = np.concatenate(
            [self.weights.reshape(f * k), self.bias]).astype(float)
        # per-objective param block shared with the tree exporter (a
        # hardcoded reg_loss_param misloads multiclass/poisson/tweedie
        # models in real xgboost — ADVICE r5)
        from xgboost_ray_tpu.models.xgb_export import objective_param_entry

        obj_name, pkey, pval = objective_param_entry(self.params)
        doc = {
            "learner": {
                "attributes": dict(self._attrs),
                "feature_names": list(self.feature_names or []),
                "feature_types": [],
                "gradient_booster": {
                    "name": "gblinear",
                    "model": {
                        "param": {"num_feature": str(f),
                                  "num_output_group": str(max(k, 1))},
                        "boosted_rounds": int(self.rounds),
                        "weights": [float(v) for v in flat],
                    },
                },
                "learner_model_param": {
                    "base_score": str(self.base_score),
                    "boost_from_average": "1",
                    "num_class": str(int(self.params.num_class or 0)),
                    "num_feature": str(f),
                    "num_target": "1",
                },
                "objective": {"name": obj_name, pkey: pval},
            },
            "version": [2, 0, 0],
        }
        out = json.dumps(doc)
        if fname:
            with open(fname, "w") as fh:
                fh.write(out)
        return out

    @classmethod
    def import_xgboost_json(cls, data) -> "RayLinearBooster":
        """Load from a parsed dict, a JSON string, or a file path.

        The three input forms are distinguished explicitly (dict type, then
        path existence) — not by sniffing a leading ``{``, which misreads
        brace-prefixed filenames and BOM-prefixed documents — and file
        reads close their handle (ADVICE r5)."""
        import os

        if isinstance(data, dict):
            doc = data
        else:
            text = os.fspath(data) if isinstance(data, os.PathLike) else data
            if isinstance(text, bytes):
                text = text.decode()
            if os.path.exists(text):
                with open(text) as fh:
                    doc = json.load(fh)
            else:
                doc = json.loads(text)
        learner = doc["learner"]
        gb = learner["gradient_booster"]
        if gb.get("name") != "gblinear":
            raise ValueError(
                f"not a gblinear model: {gb.get('name')!r} (tree models load "
                f"via RayXGBoostBooster.import_xgboost_json)"
            )
        model = gb["model"]
        f = int(model.get("param", {}).get(
            "num_feature", learner["learner_model_param"]["num_feature"]))
        k = max(1, int(model.get("param", {}).get("num_output_group", "1")))
        flat = np.asarray(model["weights"], np.float32)
        weights = flat[: f * k].reshape(f, k)
        bias = flat[f * k: (f + 1) * k]
        params = TrainParams()
        params.booster = "gblinear"
        params.objective = learner.get("objective", {}).get(
            "name", "reg:squarederror")
        params.num_class = int(
            learner["learner_model_param"].get("num_class", "0") or 0)
        out = cls(
            weights, bias, params,
            base_score=float(
                learner["learner_model_param"].get("base_score", "0.5")),
            feature_names=list(learner.get("feature_names") or []) or None,
            rounds=int(model.get("boosted_rounds", 0) or 0),
        )
        for key, val in (learner.get("attributes") or {}).items():
            out.set_attr(**{key: val})
        return out


@dataclasses.dataclass
class _LinEvalSet:
    name: str
    is_train: bool
    x: np.ndarray
    label_np: Optional[np.ndarray]
    weight_np: Optional[np.ndarray]
    base_margin: Optional[np.ndarray]
    group_ptr: Optional[np.ndarray] = None


class LinearEngine:
    """Drop-in engine for the driver loop when ``booster="gblinear"``.

    Implements the subset of ``TpuEngine``'s surface the per-round driver
    path uses (``step``/``get_booster``/``metric_names``/... —
    ``can_batch_rounds`` is False: linear rounds are a single tiny fused
    program, so per-round stepping costs one dispatch, not a tree build).
    """

    def __init__(self, shards, params: TrainParams, num_actors: int,
                 evals=None, devices=None, init_booster=None,
                 feature_names=None, feature_types=None, **_ignored):
        from xgboost_ray_tpu.params import cat_feature_indices

        if cat_feature_indices(feature_types):
            raise NotImplementedError(
                "categorical features with booster='gblinear' are not "
                "supported (a single linear coefficient on category CODES "
                "would silently misread them as ordinal); one-hot encode "
                "the columns or use a tree booster."
            )
        from xgboost_ray_tpu.engine import _concat_shards
        from xgboost_ray_tpu.ops.ranking import RankingObjective
        from xgboost_ray_tpu.ops.survival import SurvivalObjective

        self.params = params
        self.objective = get_objective(
            params.objective, params.num_class, params.scale_pos_weight,
            tweedie_variance_power=params.tweedie_variance_power,
            huber_slope=params.huber_slope,
            quantile_alpha=params.quantile_alpha,
        )
        if isinstance(self.objective, (RankingObjective, SurvivalObjective)):
            raise NotImplementedError(
                f"booster='gblinear' does not support objective "
                f"{params.objective!r} here (tree boosters do)."
            )
        self.n_outputs = self.objective.num_outputs
        self.base_score = float(
            params.base_score if params.base_score is not None
            else self.objective.default_base_score
        )
        self.base_margin0 = float(
            self.objective.base_score_to_margin(self.base_score))

        x, label, weight, base_margin, qid, lo, hi = _concat_shards(shards)
        if qid is not None:
            raise NotImplementedError("gblinear does not support qid groups.")
        self.n_rows = x.shape[0]
        self.n_features = x.shape[1]
        if label is None:
            raise ValueError("gblinear training requires labels.")
        if weight is None:
            weight = np.ones(self.n_rows, np.float32)
        self.label_np = label
        self.weight_np = weight
        self.group_ptr = None
        self.feature_names = feature_names

        devices = list(devices if devices is not None else jax.devices())
        self.n_devices = max(1, min(num_actors, len(devices)))
        self.mesh = Mesh(np.array(devices[: self.n_devices]), (AXIS_ACTORS,))
        self._rows_sharding = NamedSharding(self.mesh, P(AXIS_ACTORS))
        self._repl = NamedSharding(self.mesh, P())

        if jax.process_count() > 1:
            raise NotImplementedError(
                "gblinear multi-process training is not wired yet; train "
                "per-host or use the tree boosters."
            )
        pad_to = -(-max(self.n_rows, self.n_devices)
                   // self.n_devices) * self.n_devices
        self._pad_to = pad_to

        def put(arr, fill=0.0):
            arr = np.asarray(arr, np.float32)
            if arr.shape[0] < pad_to:
                pad = [(0, pad_to - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad, constant_values=fill)
            return jax.device_put(arr, self._rows_sharding)

        # missing = implicit zero (xgboost's sparse gblinear convention)
        self._x = put(np.nan_to_num(x, nan=0.0))
        self._label = put(label)
        self._valid = put(np.ones(self.n_rows, np.float32))
        self._weight = put(weight)
        k = self.n_outputs
        bm = np.zeros((self.n_rows, k), np.float32)
        if base_margin is not None:
            bm += np.asarray(base_margin, np.float32).reshape(self.n_rows, -1)
        self._user_margin_np = bm
        self._user_margin = put(bm)

        if init_booster is not None:
            if not isinstance(init_booster, RayLinearBooster):
                raise ValueError(
                    "xgb_model for booster='gblinear' must be a gblinear "
                    "model (got a tree booster)."
                )
            self._w = jnp.asarray(init_booster.weights)
            self._b = jnp.asarray(init_booster.bias)
            self.iteration_offset = init_booster.num_boosted_rounds()
        else:
            self._w = jnp.zeros((self.n_features, k), jnp.float32)
            self._b = jnp.zeros((k,), jnp.float32)
            self.iteration_offset = 0
        self._rounds_done = self.iteration_offset

        self.metric_names = (
            list(params.eval_metric) or [self.objective.default_metric])
        self.evals: List[_LinEvalSet] = []
        for eshards, name in (evals or []):
            if eshards is shards:
                ex, el, ew, ebm = x, label, weight, base_margin
            else:
                ex, el, ew, ebm, eq, _, _ = _concat_shards(eshards)
            self.evals.append(_LinEvalSet(
                name=name, is_train=(eshards is shards),
                x=np.nan_to_num(np.asarray(ex, np.float32), nan=0.0),
                label_np=el,
                weight_np=(np.ones(len(ex), np.float32)
                           if ew is None else ew),
                base_margin=ebm,
            ))

        self._round_fn = None
        self._warm = False  # armed after the first (compiling) dispatch

    @property
    def num_round_trees(self) -> int:
        # no trees — but the driver's booster proxy invalidates its cache on
        # change, so this must advance every round; and like TpuEngine it
        # counts only rounds boosted on THIS engine (excluding the
        # init_booster's), which the driver's post-swap round arithmetic
        # (``engine_base + num_round_trees``) depends on
        return self._rounds_done - self.iteration_offset

    def can_batch_rounds(self) -> bool:
        return False

    # ------------------------------------------------------------------
    # Elastic re-shard: gblinear is the easy booster — the whole model is a
    # replicated [F, K] weight matrix + [K] bias with no carried histogram
    # or forest state, so continuing on a changed world is just rebuilding
    # the engine over the survivors' shards (the driver's `_build_world`
    # does that) and a cache revival is re-seeding w/b from the booster.
    # ------------------------------------------------------------------
    def can_reshard(self) -> bool:
        """Zero-replay elastic continuation: the driver may shrink/grow this
        engine's world in flight and continue from the in-memory booster."""
        return True

    def reset_from_booster(self, shards, evals, init_booster) -> None:
        """Revive this cached engine for its original world: verify the
        shard layout still matches the device-resident matrix, then re-seed
        weights/bias/round bookkeeping from ``init_booster``. The compiled
        coordinate-update program and the device-resident data are reused
        as-is — zero re-upload, zero retrace."""
        from xgboost_ray_tpu.engine import _concat_shards

        x, _, _, _, _, _, _ = _concat_shards(shards)
        if x.shape[0] != self.n_rows or x.shape[1] != self.n_features:
            raise ValueError(
                f"cached gblinear engine covers a [{self.n_rows}, "
                f"{self.n_features}] matrix; got [{x.shape[0]}, "
                f"{x.shape[1]}]"
            )
        if init_booster is not None:
            if not isinstance(init_booster, RayLinearBooster):
                raise ValueError(
                    "reset_from_booster for gblinear needs a gblinear model"
                )
            if init_booster.num_features != self.n_features:
                raise ValueError(
                    f"booster has {init_booster.num_features} features; "
                    f"engine has {self.n_features}"
                )
            # replicated placement (matches the round program's P() specs) —
            # jnp.asarray would land on the default device and trip the
            # strict transfer guard on the first warm dispatch
            self._w = jax.device_put(
                np.asarray(init_booster.weights, np.float32), self._repl
            )
            self._b = jax.device_put(
                np.asarray(init_booster.bias, np.float32), self._repl
            )
            self.iteration_offset = init_booster.num_boosted_rounds()
        else:
            k = self.n_outputs
            self._w = jax.device_put(
                np.zeros((self.n_features, k), np.float32), self._repl
            )
            self._b = jax.device_put(np.zeros((k,), np.float32), self._repl)
            self.iteration_offset = 0
        self._rounds_done = self.iteration_offset

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        obj = self.objective
        eta = self.params.learning_rate
        n_feat = self.n_features
        sum_w = float(np.sum(self.weight_np))
        # penalties denormalized by total instance weight (xgboost
        # LinearTrainParam::DenormalizePenalties)
        lam = self.params.reg_lambda * sum_w
        alp = self.params.reg_alpha * sum_w
        psum = lambda v: jax.lax.psum(v, AXIS_ACTORS)

        def coordinate_delta(sg, sh, w):
            # xgboost coordinate_common.h CoordinateDelta (elastic net)
            sg_l2 = sg + lam * w
            sh_l2 = sh + lam
            tmp = w - sg_l2 / jnp.maximum(sh_l2, 1e-38)
            pos = jnp.maximum(-(sg_l2 + alp) / jnp.maximum(sh_l2, 1e-38), -w)
            neg = jnp.minimum(-(sg_l2 - alp) / jnp.maximum(sh_l2, 1e-38), -w)
            d = jnp.where(tmp >= 0, pos, neg)
            return jnp.where(sh < 1e-5, 0.0, d)

        def fn(x, label, valid, weight, user_margin, w, b):
            w_eff = weight * valid
            margins = x @ w + b[None, :] + user_margin + self.base_margin0
            g, h = obj.grad_hess(margins, label, w_eff)

            # bias first (CoordinateDeltaBias), per output
            sg = psum(jnp.sum(g, axis=0))
            sh = psum(jnp.sum(h, axis=0))
            db = eta * jnp.where(sh > 1e-5, -sg / jnp.maximum(sh, 1e-38), 0.0)
            b = b + db
            g = g + h * db[None, :]

            def step(carry, j):
                w, g = carry
                xj = jax.lax.dynamic_slice_in_dim(x, j, 1, axis=1)  # [n,1]
                sg = psum(jnp.sum(g * xj, axis=0))  # [K]
                sh = psum(jnp.sum(h * (xj * xj), axis=0))
                dw = eta * coordinate_delta(sg, sh, w[j])
                w = w.at[j].add(dw)
                g = g + h * xj * dw[None, :]
                return (w, g), None

            (w, g), _ = jax.lax.scan(step, (w, g), jnp.arange(n_feat))
            return w, b

        mapped = shard_map(
            fn, mesh=self.mesh,
            in_specs=(P(AXIS_ACTORS), P(AXIS_ACTORS), P(AXIS_ACTORS), P(AXIS_ACTORS),
                      P(AXIS_ACTORS), P(), P()),
            out_specs=(P(), P()),
        )
        return progreg.register_jit(
            "linear.update",
            mapped,
            example_args=lambda: (self._x, self._label, self._valid,
                                  self._weight, self._user_margin, self._w,
                                  self._b),
            meta={
                "world": int(self.n_devices),
                "grower": "gblinear",
                "hist_quant": "none",
                "sampling": "none",
                "n_outputs": int(self.n_outputs),
            },
        )

    def build_programs(self) -> None:
        """Force-build the coordinate-update program (jit is lazy — nothing
        compiles); under ``progreg.capture`` this registers it for the jaxpr
        verifier."""
        if self._round_fn is None:
            self._round_fn = self._build_round_fn()

    def step(self, i: int, gh_custom=None) -> Dict[str, Dict[str, float]]:
        if gh_custom is not None:
            raise NotImplementedError(
                "custom objectives with booster='gblinear' are not supported."
            )
        if self._round_fn is None:
            self._round_fn = self._build_round_fn()
        # RXGB_STRICT arms only after the first (compiling) dispatch, same
        # warm-path contract as TpuEngine's round steps
        with strict_transfer_guard(active=self._warm):
            self._w, self._b = self._round_fn(
                self._x, self._label, self._valid, self._weight,
                self._user_margin, self._w, self._b,
            )
        self._warm = True
        self._rounds_done += 1
        return self._eval_metrics()

    def _eval_metrics(self) -> Dict[str, Dict[str, float]]:
        w = np.asarray(self._w)
        b = np.asarray(self._b)
        out: Dict[str, Dict[str, float]] = {}
        for es in self.evals:
            margin = es.x @ w + b[None, :] + self.base_margin0
            if es.base_margin is not None:
                margin = margin + np.asarray(
                    es.base_margin, np.float32).reshape(len(es.x), -1)
            vals = {}
            for name in self.metric_names:
                vals[name] = compute_metric(
                    name, margin, es.label_np, es.weight_np,
                    huber_slope=self.params.huber_slope,
                    quantile_alpha=(
                        tuple(self.params.quantile_alpha)
                        if isinstance(self.params.quantile_alpha,
                                      (list, tuple))
                        else (self.params.quantile_alpha,)
                    ),
                )
            out[es.name] = vals
        return out

    # ------------------------------------------------------------------
    def get_margins_local(self, es=None) -> np.ndarray:
        w, b = np.asarray(self._w), np.asarray(self._b)
        if es is None or es.is_train:
            x = np.asarray(jax.device_get(self._x))[: self.n_rows]
            bm = self._user_margin_np  # training includes the user margin
        else:
            x, bm = es.x, es.base_margin
        margin = x @ w + b[None, :] + self.base_margin0
        if bm is not None:
            margin = margin + np.asarray(bm, np.float32).reshape(-1, margin.shape[1])
        return margin

    def combine_host_scalar(self, value, es=None, metric=None) -> float:
        return float(value)  # single-process (enforced in __init__)

    def get_booster(self) -> RayLinearBooster:
        return RayLinearBooster(
            np.asarray(self._w), np.asarray(self._b), self.params,
            self.base_score, feature_names=self.feature_names,
            rounds=self._rounds_done,
        )
