"""Deterministic, seedable fault injection for chaos testing.

The FT surfaces of this repo (driver retry loop, elastic reintegration,
launcher world restart, serve degradation) were previously exercised only by
orchestrated-timeline tests; there was no way to deterministically inject a
straggler, a corrupt checkpoint, a hung process, or a serve overload. This
module is that missing layer: a registry of **named fault sites** threaded
through all three layers, driven by a :class:`FaultPlan` that schedules an
action at the k-th occurrence of a site — so every chaos scenario is a
reproducible unit test instead of a sleep-and-kill race.

Fault sites (where ``fire()`` is called from, and the context it carries):

====================  ==========================================  ==============
site                  fired from                                  ctx keys
====================  ==========================================  ==============
actor.train_round     driver round loop (``main._train``)         ``round, world``
                                                                  (world = alive
                                                                  actors, so a
                                                                  rule can match
                                                                  the shrunk or
                                                                  restored world)
actor.load_shard      ``RayXGBoostActor.load_data``               ``rank``
checkpoint.save       ``launcher.save_round_checkpoint``          ``round, path``
checkpoint.load       ``launcher.load_round_checkpoint``          ``path``
launcher.worker       ``_launcher_worker`` bootstrap              ``process_id,
                                                                  attempt``
serve.predict         ``MicroBatcher._execute``                   ``kind, rows``
serve.route           ``Router.submit`` (serve/pool.py), per      ``replica, kind,
                      dispatch to a replica                       rows``
serve.canary          ``CanaryController.publish``                ``live_version,
                      (serve/canary.py), before the verdict       rows``
registry.swap         ``ModelRegistry.load``                      ``version``
stream.read_chunk     ``ShardStream.chunks`` (stream/reader.py)   ``chunk, rows``
stream.h2d_upload     ``DoubleBufferedUploader.submit``           ``bytes`` (the
                      (stream/upload.py)                          k-th occurrence
                                                                  = k-th submit;
                                                                  use ``at``)
====================  ==========================================  ==============

Actions: ``raise`` (an exception — ``RayActorError`` when ``ranks`` is set),
``kill`` (SIGKILL the current process — real-process sites), ``domain_kill``
(correlated host loss: kills EVERY rank of fault domain ``domain`` at once —
one ``fault.injected`` event per rank sharing a ``domain`` attr, then a
single ``RayActorError`` blaming all of them; ranks resolve through the
driver-installed resolver, see ``set_domain_resolver``), ``delay`` /
``hang`` (sleep ``delay_s``; hang defaults to an hour), and the file actions
``corrupt`` / ``truncate`` applied by ``fire_file()`` to the site's file
(checkpoints) with plan-seeded byte positions.

A plan installs programmatically (``install_plan`` / ``active_plan``) or via
the ``RXGB_FAULT_PLAN`` env var carrying the plan JSON — the env form is
inherited by spawned launcher workers, so one env var scripts a whole
cross-process chaos scenario. With no plan installed every ``fire()`` is a
near-free no-op.

This module must stay import-light (no jax/numpy): the launcher worker fires
its site before any jax-touching import.
"""

import json
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

__all__ = [
    "SITES",
    "FaultRule",
    "FaultPlan",
    "install_plan",
    "clear_plan",
    "get_plan",
    "active_plan",
    "plan_targets",
    "fire",
    "fire_file",
    "set_domain_resolver",
    "get_domain_resolver",
]

#: the fault-site catalogue (kept in sync with the table above; ``FaultRule``
#: validates against it so a typo'd site fails at plan build, not silently)
SITES = (
    "actor.train_round",
    "actor.load_shard",
    "checkpoint.save",
    "checkpoint.load",
    "launcher.worker",
    "serve.predict",
    "serve.route",
    "serve.canary",
    "registry.swap",
    "stream.read_chunk",
    "stream.h2d_upload",
)

_ENV_PLAN = "RXGB_FAULT_PLAN"


def _exception_types() -> Dict[str, type]:
    from xgboost_ray_tpu.exceptions import RayActorError, RayTaskError

    return {
        "RuntimeError": RuntimeError,
        "ValueError": ValueError,
        "OSError": OSError,
        "TimeoutError": TimeoutError,
        "RayActorError": RayActorError,
        "RayTaskError": RayTaskError,
    }


@dataclass
class FaultRule:
    """One scheduled fault: ``action`` at the ``at``-th matching occurrence
    of ``site`` (1-based), for ``times`` consecutive matching occurrences
    (``times=0`` = every occurrence from ``at`` on).

    ``match`` filters occurrences by ctx equality (e.g. ``{"round": 3}`` or
    ``{"process_id": 1, "attempt": 0}``) — only matching occurrences advance
    this rule's counter, so "the 2nd time rank 1 loads a shard" is
    expressible without knowing the global call order.
    """

    site: str
    action: str  # raise | kill | domain_kill | delay | hang | corrupt | truncate
    at: int = 1
    times: int = 1
    match: Optional[Dict[str, Any]] = None
    # action parameters
    ranks: Optional[List[int]] = None  # raise -> RayActorError(ranks=...)
    domain: Optional[int] = None  # domain_kill: fault domain to take down
    exc: str = "RuntimeError"  # raise without ranks: exception type name
    message: str = ""
    delay_s: float = 0.0  # delay; hang defaults to 3600 when unset
    nbytes: int = 0  # corrupt: bytes to flip (default 16); truncate: keep

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; one of {SITES}"
            )
        if self.action not in (
            "raise", "kill", "domain_kill", "delay", "hang", "corrupt",
            "truncate",
        ):
            raise ValueError(f"unknown fault action {self.action!r}")
        if self.action == "domain_kill" and self.domain is None:
            raise ValueError("domain_kill requires a `domain` index")
        if self.at < 1:
            raise ValueError("`at` is 1-based; must be >= 1")

    def matches(self, ctx: Dict[str, Any]) -> bool:
        if not self.match:
            return True
        return all(ctx.get(k) == v for k, v in self.match.items())

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"site": self.site, "action": self.action}
        for key in ("at", "times"):
            if getattr(self, key) != 1:
                out[key] = getattr(self, key)
        for key in ("match", "ranks", "message"):
            if getattr(self, key):
                out[key] = getattr(self, key)
        if self.domain is not None:
            out["domain"] = self.domain
        if self.exc != "RuntimeError":
            out["exc"] = self.exc
        if self.delay_s:
            out["delay_s"] = self.delay_s
        if self.nbytes:
            out["nbytes"] = self.nbytes
        return out


@dataclass
class FaultPlan:
    """An ordered set of :class:`FaultRule` with deterministic counters.

    Every rule keeps its own occurrence counter (advanced only by matching
    ``fire()`` calls), and every file-corrupting rule draws byte positions
    from ``random.Random(seed, rule_index)`` — two runs of the same plan over
    the same workload inject byte-identical faults. ``reset()`` rewinds the
    counters so one plan object can drive repeated runs.
    """

    rules: List[FaultRule] = field(default_factory=list)
    seed: int = 0

    def __post_init__(self):
        self.rules = [
            r if isinstance(r, FaultRule) else FaultRule(**r)
            for r in self.rules
        ]
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        # under the lock: a reset racing a concurrent fire() (e.g. a test
        # rewinding a plan while a launcher thread still fires) must never
        # interleave with _due()'s counter advance mid-sweep
        with self._lock:
            self._seen = [0] * len(self.rules)
            self._rngs = [
                random.Random(self.seed * 1000003 + i)
                for i in range(len(self.rules))
            ]

    # -- serialization ------------------------------------------------------

    def to_json(self) -> str:
        doc: Dict[str, Any] = {"rules": [r.to_dict() for r in self.rules]}
        if self.seed:
            doc["seed"] = self.seed
        return json.dumps(doc)

    @classmethod
    def from_json(cls, raw: Union[str, Dict[str, Any]]) -> "FaultPlan":
        doc = json.loads(raw) if isinstance(raw, str) else dict(raw)
        return cls(rules=doc.get("rules", []), seed=int(doc.get("seed", 0)))

    # -- firing -------------------------------------------------------------

    def targets(self, site: str) -> bool:
        return any(r.site == site for r in self.rules)

    def _due(self, site: str, ctx: Dict[str, Any]) -> List[int]:
        """Advance matching counters under the lock; return indices of rules
        whose action is due at this occurrence."""
        due = []
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.site != site or not rule.matches(ctx):
                    continue
                self._seen[i] += 1
                n = self._seen[i]
                if n >= rule.at and (
                    rule.times == 0 or n < rule.at + rule.times
                ):
                    due.append(i)
        return due

    def fire(self, site: str, **ctx) -> None:
        for i in self._due(site, ctx):
            self._perform(self.rules[i], site, ctx)

    def fire_file(self, site: str, path: str, **ctx) -> None:
        ctx = dict(ctx, path=path)
        for i in self._due(site, ctx):
            rule = self.rules[i]
            if rule.action in ("corrupt", "truncate"):
                _emit_fault_event(site, rule.action, ctx)
                positions: List[int] = []
                if rule.action == "corrupt":
                    size = os.path.getsize(path)
                    # draw the byte positions under the lock: the Random's
                    # state IS the determinism contract ("same plan, byte-
                    # identical faults"), and two sites due concurrently on
                    # one rule must not interleave draws from its stream
                    # (truncate never draws — don't advance it spuriously)
                    with self._lock:
                        rng = self._rngs[i]
                        n = min(rule.nbytes or 16, size)
                        positions = [rng.randrange(size) for _ in range(n)]
                self._damage_file(rule, path, positions)
            else:
                self._perform(rule, site, ctx)

    def _perform(self, rule: FaultRule, site: str, ctx: Dict[str, Any]):
        msg = rule.message or f"injected fault at {site} ({ctx})"
        if rule.action == "domain_kill":
            # correlated host loss: one event PER RANK (sharing the domain
            # attr) so the timeline shows every death, then one exception
            # blaming all of them so the driver sees ONE failure to coalesce
            ranks = _resolve_domain_ranks(rule.domain, rule.ranks)
            if not ranks:
                return  # domain already fully dead: nothing left to kill
            for r in ranks:
                _emit_fault_event(
                    site, rule.action, dict(ctx, rank=r, domain=rule.domain)
                )
            from xgboost_ray_tpu.exceptions import RayActorError

            raise RayActorError(
                rule.message
                or f"injected domain_kill of domain {rule.domain} at {site}",
                ranks=ranks,
            )
        _emit_fault_event(site, rule.action, ctx)
        if rule.action == "raise":
            if rule.ranks is not None:
                from xgboost_ray_tpu.exceptions import RayActorError

                raise RayActorError(msg, ranks=rule.ranks)
            exc_type = _exception_types().get(rule.exc, RuntimeError)
            raise exc_type(msg)
        if rule.action == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        if rule.action in ("delay", "hang"):
            time.sleep(
                rule.delay_s or (3600.0 if rule.action == "hang" else 0.0)
            )
            return
        if rule.action in ("corrupt", "truncate"):
            raise ValueError(
                f"file action {rule.action!r} at non-file site {site!r}; "
                f"use a site that calls fire_file()"
            )

    @staticmethod
    def _damage_file(rule: FaultRule, path: str, positions: List[int]) -> None:
        size = os.path.getsize(path)
        if rule.action == "truncate":
            keep = rule.nbytes if rule.nbytes else size // 2
            with open(path, "rb+") as f:
                f.truncate(min(keep, size))
            return
        with open(path, "rb+") as f:
            for pos in positions:
                f.seek(pos)
                byte = f.read(1)
                f.seek(pos)
                f.write(bytes([byte[0] ^ 0xFF]))


def _emit_fault_event(site: str, action: str, ctx: Dict[str, Any]) -> None:
    """Record every injected fault on the current trace timeline, so a
    chaos run's machine-readable story starts at the injection itself.
    Lazily imported (obs is stdlib-only) and failure-proof: observability
    must never alter the chaos under test."""
    try:
        from xgboost_ray_tpu import obs

        attrs = {"site": site, "action": action}
        attrs.update({
            k: v for k, v in ctx.items()
            if isinstance(v, (str, int, float, bool))
        })
        obs.get_tracer().event("fault.injected", **attrs)
    except Exception:  # noqa: BLE001 - never fail the fault path
        pass


# ---------------------------------------------------------------------------
# Fault-domain resolution: the driver installs a resolver mapping a domain id
# to the ranks currently alive in it (derived from the attempt's DomainMap),
# so a `domain_kill` rule written against logical domains hits whatever the
# placement layer decided those domains contain.
# ---------------------------------------------------------------------------

_DOMAIN_RESOLVER = None


def set_domain_resolver(resolver) -> None:
    """Install (or clear, with ``None``) the domain -> alive-ranks resolver.
    Called by the driver at the start of every training attempt; the last
    installed resolver wins."""
    global _DOMAIN_RESOLVER
    _DOMAIN_RESOLVER = resolver


def get_domain_resolver():
    return _DOMAIN_RESOLVER


def _resolve_domain_ranks(
    domain: Optional[int], fallback: Optional[List[int]]
) -> List[int]:
    resolver = _DOMAIN_RESOLVER
    if resolver is not None:
        return sorted(int(r) for r in resolver(domain))
    if fallback:
        return sorted(int(r) for r in fallback)
    raise RuntimeError(
        f"domain_kill: no domain resolver installed and no `ranks` fallback "
        f"for domain {domain!r} (the driver installs one per attempt; "
        f"outside a training run pass explicit ranks)"
    )


# ---------------------------------------------------------------------------
# Process-global plan: programmatic install wins over the env var.
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ENV_CACHE = (None, None)  # (raw env string, parsed plan)


def install_plan(plan: Union[FaultPlan, Dict, str, None]) -> Optional[FaultPlan]:
    global _PLAN
    if plan is not None and not isinstance(plan, FaultPlan):
        plan = FaultPlan.from_json(plan)
    _PLAN = plan
    return plan


def clear_plan() -> None:
    install_plan(None)


def get_plan() -> Optional[FaultPlan]:
    global _ENV_CACHE
    if _PLAN is not None:
        return _PLAN
    raw = os.environ.get(_ENV_PLAN)
    if not raw:
        return None
    if _ENV_CACHE[0] != raw:
        _ENV_CACHE = (raw, FaultPlan.from_json(raw))
    return _ENV_CACHE[1]


class active_plan:
    """``with faults.active_plan(plan):`` — install for the scope, always
    clear after (the test-friendly form; leaks no plan into later tests)."""

    def __init__(self, plan: Union[FaultPlan, Dict, str]):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return install_plan(self.plan)

    def __exit__(self, *exc_info) -> None:
        clear_plan()


def plan_targets(site: str) -> bool:
    """True when the active plan has any rule for ``site`` — used by the
    driver to disable the fused-scan fast path so round-granular faults hit
    exact rounds."""
    plan = get_plan()
    return plan is not None and plan.targets(site)


def fire(site: str, **ctx) -> None:
    """Hit a fault site. No-op without an active plan; otherwise the plan
    may sleep, raise, or SIGKILL per its matching rules."""
    plan = get_plan()
    if plan is not None:
        plan.fire(site, **ctx)


def fire_file(site: str, path: str, **ctx) -> None:
    """Hit a file-owning fault site: corrupt/truncate rules damage ``path``
    in place (deterministically, from the plan seed); other actions behave
    as in ``fire()``."""
    plan = get_plan()
    if plan is not None:
        plan.fire_file(site, path, **ctx)
