"""Driver-side coordination primitives.

The reference uses actor-wrapped asyncio primitives for the driver↔actor
side-channel (``xgboost_ray/util.py:16-77``: Event actor, Queue actor,
MultiActorTask). In the TPU runtime the coordinator and workers share a
process (workers are mesh slots), so these become thin wrappers over
``threading``/``queue`` with the same interface — preserved so user-facing
semantics (stop events, callback queues) and the FT tests carry over.
"""

import queue
import threading
from typing import Any, Callable, List, Optional


class Event:
    """Mirror of the reference's Event actor API (``util.py:16-47``)."""

    def __init__(self):
        self._event = threading.Event()

    def set(self):
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()

    def clear(self):
        self._event.clear()

    def shutdown(self):
        self._event.set()


class Queue:
    """Mirror of the Ray Queue actor the reference pins near the driver."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()

    def put(self, item: Any):
        self._q.put(item)

    def empty(self) -> bool:
        return self._q.empty()

    def get(self, block: bool = False, timeout: Optional[float] = None) -> Any:
        return self._q.get(block=block, timeout=timeout)

    def shutdown(self):
        while not self._q.empty():
            try:
                self._q.get_nowait()
            except queue.Empty:
                break


class MultiActorTask:
    """Readiness poll over a set of futures/callables (``util.py:52-77``)."""

    def __init__(self, checks: Optional[List[Callable[[], bool]]] = None):
        self._checks = checks or []

    def is_ready(self) -> bool:
        return all(check() for check in self._checks)


def restart_backoff_s(
    restart_index: int,
    base: Optional[float] = None,
    cap: Optional[float] = None,
    jitter: Optional[float] = None,
) -> float:
    """Delay before restart number ``restart_index`` (0-based, counted over
    CONSECUTIVE failures — callers reset their index once recovery makes
    real forward progress): full jitter on an exponential schedule,
    ``base * 2^i`` capped at ``cap``, scaled by ``1 + U(0, jitter)``.
    Shared by the driver retry loop and the launcher so a persistent fault
    cannot crash-loop storm. Env-tunable: ``RXGB_RESTART_BACKOFF_BASE_S``
    (default 0.5; 0 disables), ``RXGB_RESTART_BACKOFF_MAX_S`` (default 30),
    ``RXGB_RESTART_BACKOFF_JITTER`` (fraction, default 0.1)."""
    import os
    import random

    if base is None:
        base = float(os.environ.get("RXGB_RESTART_BACKOFF_BASE_S", "0.5"))
    if base <= 0:
        return 0.0
    if cap is None:
        cap = float(os.environ.get("RXGB_RESTART_BACKOFF_MAX_S", "30"))
    if jitter is None:
        jitter = float(os.environ.get("RXGB_RESTART_BACKOFF_JITTER", "0.1"))
    delay = min(cap, base * (2.0 ** max(0, int(restart_index))))
    if jitter > 0:
        # rxgblint: disable-next-line=DET001 - restart-schedule jitter only; never touches model state
        delay *= 1.0 + random.random() * jitter
    return delay
