"""Driver-side coordination primitives.

The reference uses actor-wrapped asyncio primitives for the driver↔actor
side-channel (``xgboost_ray/util.py:16-77``: Event actor, Queue actor,
MultiActorTask). In the TPU runtime the coordinator and workers share a
process (workers are mesh slots), so these become thin wrappers over
``threading``/``queue`` with the same interface — preserved so user-facing
semantics (stop events, callback queues) and the FT tests carry over.
"""

import queue
import threading
from typing import Any, Callable, List, Optional


class Event:
    """Mirror of the reference's Event actor API (``util.py:16-47``)."""

    def __init__(self):
        self._event = threading.Event()

    def set(self):
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()

    def clear(self):
        self._event.clear()

    def shutdown(self):
        self._event.set()


class Queue:
    """Mirror of the Ray Queue actor the reference pins near the driver."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()

    def put(self, item: Any):
        self._q.put(item)

    def empty(self) -> bool:
        return self._q.empty()

    def get(self, block: bool = False, timeout: Optional[float] = None) -> Any:
        return self._q.get(block=block, timeout=timeout)

    def shutdown(self):
        while not self._q.empty():
            try:
                self._q.get_nowait()
            except queue.Empty:
                break


class MultiActorTask:
    """Readiness poll over a set of futures/callables (``util.py:52-77``)."""

    def __init__(self, checks: Optional[List[Callable[[], bool]]] = None):
        self._checks = checks or []

    def is_ready(self) -> bool:
        return all(check() for check in self._checks)
