"""Exception types for the driver/FT control flow.

Standalone analogs of the Ray exceptions the reference catches
(``ray.exceptions.RayActorError`` / ``RayTaskError`` at
``xgboost_ray/main.py:1644``) plus the reference's own control-flow
exceptions (``RayXGBoostActorAvailable``, elastic.py:139-142).
"""


class RayActorError(RuntimeError):
    """A (virtual) training actor died. Raised by fault-injection hooks or by
    unrecoverable per-worker errors; triggers the driver FT policy."""

    def __init__(self, message: str = "actor died", ranks=None):
        super().__init__(message)
        self.ranks = list(ranks) if ranks is not None else []


class RayTaskError(RuntimeError):
    """A remote task (e.g. data loading) failed."""


class RayXGBoostTrainingError(RuntimeError):
    """Unrecoverable training error (out of retries / non-actor failure)."""


class RayXGBoostTrainingStopped(RuntimeError):
    """Training was aborted via the stop event / stop callback."""


class RayXGBoostActorAvailable(RuntimeError):
    """Elastic training: a previously failed rank is ready to rejoin; the
    driver should restart from the latest checkpoint with the larger world
    (mirrors ``xgboost_ray/elastic.py:139-142``). Does not consume a retry."""
