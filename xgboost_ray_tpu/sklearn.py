"""scikit-learn estimator facade.

API mirror of ``xgboost_ray/sklearn.py``: the five estimators
(RayXGBClassifier/Regressor/Ranker and the random-forest variants) expose the
xgboost sklearn surface (fit/predict/predict_proba, eval_set, early stopping,
clone/get_params compatibility) and route everything through our
``train()``/``predict()`` with RayDMatrix — the same delegation pattern the
reference uses via ``_wrap_evaluation_matrices`` (``sklearn.py:503-505``).

RF note: parallel trees within a round are *averaged* (see
``ops/predict.predict_margin``), giving classic random-forest semantics for
``num_parallel_tree > 1`` with a single boosting round.
"""

import logging
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from xgboost_ray_tpu.main import RayParams, predict as ray_predict, train as ray_train
from xgboost_ray_tpu.matrix import RayDMatrix, RayShardingMode
from xgboost_ray_tpu.models.booster import RayXGBoostBooster

logger = logging.getLogger(__name__)

_SKLEARN_INSTALLED = True
try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
except ImportError:  # pragma: no cover
    _SKLEARN_INSTALLED = False
    BaseEstimator = object
    ClassifierMixin = object
    RegressorMixin = object


_PARAM_NAMES = [
    "n_estimators",
    "max_depth",
    "learning_rate",
    "verbosity",
    "objective",
    "booster",
    "tree_method",
    "n_jobs",
    "gamma",
    "min_child_weight",
    "max_delta_step",
    "subsample",
    "sampling_method",
    "top_rate",
    "other_rate",
    "colsample_bytree",
    "colsample_bylevel",
    "colsample_bynode",
    "reg_alpha",
    "reg_lambda",
    "scale_pos_weight",
    "base_score",
    "random_state",
    "missing",
    "num_parallel_tree",
    "max_bin",
    "eval_metric",
    "early_stopping_rounds",
]


def _check_if_params_are_ray_dmatrix(X, sample_weight, base_margin, eval_set,
                                     sample_weight_eval_set, base_margin_eval_set):
    """RayDMatrix passthrough with warnings (mirror ``sklearn.py:280-334``)."""
    train_dmatrix = None
    evals = ()
    if isinstance(X, RayDMatrix):
        params_to_warn = []
        if sample_weight is not None:
            params_to_warn.append("sample_weight")
        if base_margin is not None:
            params_to_warn.append("base_margin")
        if params_to_warn:
            warnings.warn(
                f"X is a RayDMatrix; {params_to_warn} will be ignored "
                f"(set them on the RayDMatrix instead)."
            )
        train_dmatrix = X
        if not X.has_label:
            raise ValueError(
                "X is a RayDMatrix without a label; pass the label to the "
                "RayDMatrix constructor."
            )
        if eval_set:
            if any(not isinstance(e[0], RayDMatrix) for e in eval_set):
                raise ValueError(
                    "If X is a RayDMatrix, all eval_set entries must be "
                    "(RayDMatrix, name) tuples."
                )
            evals = [
                (e[0], e[1] if len(e) > 1 and isinstance(e[1], str) else f"validation_{i}")
                for i, e in enumerate(eval_set)
            ]
    return train_dmatrix, evals


class _SklearnObjectiveAdapter:
    """xgboost's sklearn estimators take ``objective(y_true, y_pred) ->
    (grad, hess)`` and wrap it into the Booster-level ``obj(preds, dmatrix)``
    convention (xgboost ``_objective_decorator``). Module-level class so it
    survives ``_remote=True`` spawn pickling."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, preds, dmat):
        return self.fn(dmat.get_label(), preds)


class _SklearnMetricAdapter:
    """Picklable wrapper turning a sklearn-style ``metric(y_true, y_pred)``
    into the train() custom-metric contract ``(preds, dmat) -> (name, value)``
    with the objective's prediction transform applied first. Module-level (a
    class, not a closure) so it survives the ``_remote=True`` spawn pickling."""

    def __init__(self, fn, obj_name: str, num_class: int, raw: bool = False):
        self.fn = fn
        self.obj_name = obj_name
        self.num_class = num_class
        # xgboost contract: with a CUSTOM objective the metric receives raw
        # margins (the metric applies the inverse link itself)
        self.raw = raw

    def __call__(self, preds, dmat):
        y = dmat.get_label()
        if self.raw:
            yp = np.asarray(preds).reshape(len(y), -1)
            if yp.shape[1] == 1:
                yp = yp[:, 0]
        else:
            import jax.numpy as jnp

            from xgboost_ray_tpu.ops.objectives import get_objective

            o = get_objective(self.obj_name, self.num_class, 1.0)
            yp = np.asarray(
                o.transform(jnp.asarray(np.asarray(preds).reshape(len(y), -1)))
            )
        w = dmat.get_weight()
        if w is not None and np.asarray(w).size:
            # xgboost's _metric_decorator passes eval-set weights through
            return self.fn.__name__, float(self.fn(y, yp, sample_weight=w))
        return self.fn.__name__, float(self.fn(y, yp))


class RayXGBMixin:
    """Shared plumbing for all estimators."""

    def _get_ray_params(self, ray_params) -> RayParams:
        if isinstance(ray_params, dict):
            ray_params = RayParams(**ray_params)
        if ray_params is None:
            n_jobs = getattr(self, "n_jobs", None) or 1
            ray_params = RayParams(num_actors=int(n_jobs))
        return ray_params

    def get_xgb_params(self) -> Dict[str, Any]:
        params = {}
        for name in _PARAM_NAMES:
            if name in ("n_estimators", "early_stopping_rounds", "eval_metric",
                        "missing", "n_jobs", "verbosity"):
                continue
            val = getattr(self, name, None)
            if val is not None:
                params[name] = val
        for name in getattr(self, "_extra_xgb_params", ()):
            if name in ("enable_categorical", "feature_types"):
                continue  # DMatrix-construction args, not training params
            val = getattr(self, name, None)
            if val is not None:
                params[name] = val
        if getattr(self, "eval_metric", None) is not None:
            params["eval_metric"] = self.eval_metric
        if getattr(self, "random_state", None) is not None:
            params["seed"] = self.random_state
        return params

    def _num_boost_round(self) -> int:
        return int(getattr(self, "n_estimators", None) or 100)

    def _build_matrices(
        self,
        X,
        y,
        *,
        sample_weight=None,
        base_margin=None,
        qid=None,
        eval_set=None,
        sample_weight_eval_set=None,
        base_margin_eval_set=None,
        eval_qid=None,
        feature_weights=None,
        ray_dmatrix_params=None,
    ):
        dm_params = dict(ray_dmatrix_params or {})
        missing = getattr(self, "missing", None)
        if missing is not None and not (isinstance(missing, float) and np.isnan(missing)):
            dm_params.setdefault("missing", missing)
        # estimator-level categorical knobs are DMatrix construction args
        # (reference sklearn.py:404-407 passes enable_categorical through)
        if getattr(self, "enable_categorical", False):
            dm_params.setdefault("enable_categorical", True)
        if getattr(self, "feature_types", None) is not None:
            dm_params.setdefault("feature_types", self.feature_types)
        train_dmatrix = RayDMatrix(
            X, label=y, weight=sample_weight, base_margin=base_margin,
            qid=qid, feature_weights=feature_weights, **dm_params,
        )
        evals = []
        if eval_set:
            for i, (ex, ey) in enumerate(eval_set):
                w = sample_weight_eval_set[i] if sample_weight_eval_set else None
                bm = base_margin_eval_set[i] if base_margin_eval_set else None
                q = eval_qid[i] if eval_qid else None
                if ex is X and ey is y and w is None and bm is None and q is None:
                    evals.append((train_dmatrix, f"validation_{i}"))
                else:
                    evals.append(
                        (
                            RayDMatrix(ex, label=ey, weight=w, base_margin=bm,
                                       qid=q, **dm_params),
                            f"validation_{i}",
                        )
                    )
        return train_dmatrix, evals

    def _fit_common(
        self,
        params: Dict[str, Any],
        train_dmatrix: RayDMatrix,
        evals: List[Tuple[RayDMatrix, str]],
        *,
        verbose=True,
        xgb_model=None,
        callbacks=None,
        early_stopping_rounds=None,
        ray_params=None,
        _remote=None,
        num_boost_round=None,
    ):
        evals_result: Dict = {}
        additional_results: Dict = {}
        extra = {}
        obj = None
        if callable(params.get("objective")):
            # sklearn-level custom objective: fn(y_true, y_pred) semantics
            obj = _SklearnObjectiveAdapter(params.pop("objective"))
            params["objective"] = self._default_objective_for_custom()
        if obj is not None:
            extra["obj"] = obj

        # xgboost >= 1.6 sklearn API: eval_metric may be a sklearn-style
        # callable metric(y_true, y_pred) (e.g. sklearn.metrics.log_loss);
        # route it through the train() custom-metric hook with the
        # objective's prediction transform applied first.
        em = params.get("eval_metric")
        metric_fn = None
        if callable(em):
            metric_fn = em
            params.pop("eval_metric")
        elif isinstance(em, (list, tuple)) and any(callable(m) for m in em):
            fns = [m for m in em if callable(m)]
            if len(fns) > 1:
                raise ValueError(
                    "at most one callable eval_metric is supported per fit"
                )
            metric_fn = fns[0]
            rest = [m for m in em if not callable(m)]
            if rest:
                params["eval_metric"] = list(rest)
            else:
                params.pop("eval_metric")
        if metric_fn is not None:
            extra["custom_metric"] = _SklearnMetricAdapter(
                metric_fn,
                params.get("objective", "reg:squarederror"),
                int(params.get("num_class", 0) or 0),
                raw=obj is not None,
            )
        esr = early_stopping_rounds
        if esr is None:
            esr = getattr(self, "early_stopping_rounds", None)
        if esr is not None:
            extra["early_stopping_rounds"] = esr
        # a refit must not inherit a previous fit's early-stop state: a stale
        # best_iteration would silently truncate predict() on the new model
        self.best_iteration = None
        self.best_score = None
        booster = ray_train(
            params,
            train_dmatrix,
            num_boost_round=num_boost_round or self._num_boost_round(),
            evals=evals,
            evals_result=evals_result,
            additional_results=additional_results,
            ray_params=self._get_ray_params(ray_params),
            _remote=_remote,
            verbose_eval=verbose,
            xgb_model=xgb_model,
            callbacks=callbacks,
            **extra,
        )
        self._Booster = booster
        self.evals_result_ = evals_result
        self.additional_results_ = additional_results
        if booster.best_iteration is not None:
            self.best_iteration = booster.best_iteration
            self.best_score = booster.best_score
        self.n_features_in_ = booster.num_features
        return self

    def get_booster(self) -> RayXGBoostBooster:
        if not hasattr(self, "_Booster") or self._Booster is None:
            raise ValueError("need to call fit or load_model beforehand")
        return self._Booster

    def evals_result(self) -> Dict:
        return getattr(self, "evals_result_", {})

    def _ray_predict_margin_or_value(
        self,
        X,
        output_margin=False,
        ntree_limit=None,
        validate_features=True,
        base_margin=None,
        iteration_range=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ) -> np.ndarray:
        """Route through the distributed predict (mirror ``sklearn.py:357-390``)."""
        booster = self.get_booster()
        kwargs = dict(
            output_margin=output_margin,
            validate_features=validate_features,
        )
        if ntree_limit:
            kwargs["ntree_limit"] = ntree_limit
        iteration_range = self._resolve_iteration_range(ntree_limit, iteration_range)
        if iteration_range is not None:
            kwargs["iteration_range"] = iteration_range
        if isinstance(X, RayDMatrix):
            data = X
        else:
            dm_params = dict(ray_dmatrix_params or {})
            if getattr(self, "enable_categorical", False):
                dm_params.setdefault("enable_categorical", True)
            if getattr(self, "feature_types", None) is not None:
                dm_params.setdefault("feature_types", self.feature_types)
            data = RayDMatrix(X, base_margin=base_margin, **dm_params)
        return ray_predict(
            booster, data, ray_params=self._get_ray_params(ray_params),
            _remote=_remote, **kwargs,
        )

    def _default_objective_for_custom(self) -> str:
        """The objective whose transform/base-score semantics apply when the
        user supplies a callable objective: the estimator family's default
        (keeps predict_proba meaningful, xgboost's behavior of retaining the
        class default)."""
        if getattr(self, "n_classes_", 0) > 2:
            return "multi:softprob"
        return getattr(self, "_default_objective", "reg:squarederror")

    def _resolve_iteration_range(self, ntree_limit, iteration_range):
        """The xgboost sklearn early-stopping contract, in ONE place: when
        neither ntree_limit nor an explicit range is given, default to the
        best model (reference's ported suite checks best_iteration feeding
        predict, ``tests/test_sklearn.py``)."""
        if iteration_range is None and not ntree_limit:
            best_it = getattr(self, "best_iteration", None)
            if best_it is not None:
                return (0, int(best_it) + 1)
        return iteration_range

    def apply(self, X, ntree_limit: int = 0, iteration_range=None) -> np.ndarray:
        """Per-tree leaf heap index for each sample (xgboost ``apply``
        analog, incl. the >=1.6 ``iteration_range`` and best-model default
        after early stopping)."""
        booster = self.get_booster()
        iteration_range = self._resolve_iteration_range(ntree_limit, iteration_range)
        x = booster._coerce_features(X)
        leaves = booster.predict(
            x, pred_leaf=True, validate_features=False,
            iteration_range=iteration_range,
        )
        if ntree_limit:
            leaves = leaves[:, :ntree_limit]
        return leaves

    @property
    def feature_importances_(self) -> np.ndarray:
        """Normalized importance; type from ``importance_type`` (default
        "gain", matching xgboost's sklearn wrapper), falling back to split
        counts ("weight")."""
        booster = self.get_booster()
        importance_type = getattr(self, "importance_type", None) or "gain"
        names = booster.feature_names or [
            f"f{i}" for i in range(booster.num_features)
        ]
        score = booster.get_score(importance_type=importance_type)
        vals = np.array([score.get(n, 0.0) for n in names], np.float64)
        total = vals.sum()
        return (vals / total) if total > 0 else vals

    @property
    def coef_(self) -> np.ndarray:
        """Linear coefficients — defined for ``booster="gblinear"`` only
        (xgboost sklearn convention: [F] or [K, F] for multi-output)."""
        booster = self.get_booster()
        if not hasattr(booster, "weights"):
            raise AttributeError(
                "coef_ is only defined for booster='gblinear' models."
            )
        w = np.asarray(booster.weights)  # [F, K]
        return w[:, 0] if w.shape[1] == 1 else w.T

    @property
    def intercept_(self) -> np.ndarray:
        """Linear bias — defined for ``booster="gblinear"`` only."""
        booster = self.get_booster()
        if not hasattr(booster, "weights"):
            raise AttributeError(
                "intercept_ is only defined for booster='gblinear' models."
            )
        return np.asarray(booster.bias)

    def save_model(self, fname: str):
        self.get_booster().save_model(fname)

    def load_model(self, fname: str):
        self._Booster = RayXGBoostBooster.load_model(fname)
        return self


class _RayXGBEstimator(BaseEstimator, RayXGBMixin):
    _default_objective = "reg:squarederror"

    def __init__(
        self,
        n_estimators: int = 100,
        max_depth: Optional[int] = None,
        learning_rate: Optional[float] = None,
        verbosity: Optional[int] = None,
        objective: Optional[Union[str, Callable]] = None,
        booster: Optional[str] = None,
        tree_method: Optional[str] = None,
        n_jobs: Optional[int] = None,
        gamma: Optional[float] = None,
        min_child_weight: Optional[float] = None,
        max_delta_step: Optional[float] = None,
        subsample: Optional[float] = None,
        sampling_method: Optional[str] = None,
        top_rate: Optional[float] = None,
        other_rate: Optional[float] = None,
        colsample_bytree: Optional[float] = None,
        colsample_bylevel: Optional[float] = None,
        colsample_bynode: Optional[float] = None,
        reg_alpha: Optional[float] = None,
        reg_lambda: Optional[float] = None,
        scale_pos_weight: Optional[float] = None,
        base_score: Optional[float] = None,
        random_state: Optional[int] = None,
        missing: float = np.nan,
        num_parallel_tree: Optional[int] = None,
        max_bin: Optional[int] = None,
        eval_metric: Optional[Union[str, List[str]]] = None,
        early_stopping_rounds: Optional[int] = None,
        **kwargs,
    ):
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.verbosity = verbosity
        self.objective = objective
        self.booster = booster
        self.tree_method = tree_method
        self.n_jobs = n_jobs
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.max_delta_step = max_delta_step
        self.subsample = subsample
        # explicit ctor params (not **kwargs) so sklearn clone()/set_params
        # carry the GOSS config through CV/pipelines
        self.sampling_method = sampling_method
        self.top_rate = top_rate
        self.other_rate = other_rate
        self.colsample_bytree = colsample_bytree
        self.colsample_bylevel = colsample_bylevel
        self.colsample_bynode = colsample_bynode
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.base_score = base_score
        self.random_state = random_state
        self.missing = missing
        self.num_parallel_tree = num_parallel_tree
        self.max_bin = max_bin
        self.eval_metric = eval_metric
        self.early_stopping_rounds = early_stopping_rounds
        # arbitrary xgboost params (dart knobs, constraints, ...) ride along
        # and are forwarded by get_xgb_params — the training-params parser is
        # the single place that accepts/rejects them (no silent drops)
        self._extra_xgb_params = list(kwargs)
        for key, value in kwargs.items():
            setattr(self, key, value)

    def _more_tags(self):
        return {"non_deterministic": False, "allow_nan": True}

    def fit(
        self,
        X,
        y=None,
        *,
        sample_weight=None,
        base_margin=None,
        eval_set=None,
        sample_weight_eval_set=None,
        base_margin_eval_set=None,
        verbose=False,
        xgb_model=None,
        feature_weights=None,
        callbacks=None,
        early_stopping_rounds=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ):
        params = self.get_xgb_params()
        params.setdefault("objective", self._default_objective)
        dm, evals = _check_if_params_are_ray_dmatrix(
            X, sample_weight, base_margin, eval_set,
            sample_weight_eval_set, base_margin_eval_set,
        )
        if dm is None:
            dm, evals = self._build_matrices(
                X, y, sample_weight=sample_weight, base_margin=base_margin,
                eval_set=eval_set,
                sample_weight_eval_set=sample_weight_eval_set,
                base_margin_eval_set=base_margin_eval_set,
                feature_weights=feature_weights,
                ray_dmatrix_params=ray_dmatrix_params,
            )
        return self._fit_common(
            params, dm, list(evals), verbose=verbose, xgb_model=xgb_model,
            callbacks=callbacks, early_stopping_rounds=early_stopping_rounds,
            ray_params=ray_params, _remote=_remote,
        )

    def predict(
        self,
        X,
        output_margin=False,
        ntree_limit=None,
        validate_features=True,
        base_margin=None,
        iteration_range=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ):
        return self._ray_predict_margin_or_value(
            X, output_margin=output_margin, ntree_limit=ntree_limit,
            validate_features=validate_features, base_margin=base_margin,
            iteration_range=iteration_range, ray_params=ray_params,
            _remote=_remote, ray_dmatrix_params=ray_dmatrix_params,
        )


class RayXGBRegressor(RegressorMixin, _RayXGBEstimator):
    """Distributed XGBoost-style regressor (mirror ``sklearn.py:602-644``).

    Mixin-first base order so sklearn's tag system (``__sklearn_tags__``)
    reports estimator_type="regressor" — meta-estimators (Stacking*, CV
    selectors) validate on it."""

    _default_objective = "reg:squarederror"


class RayXGBClassifier(ClassifierMixin, _RayXGBEstimator):
    """Distributed XGBoost-style classifier (mirror ``sklearn.py:451-600``)."""

    _default_objective = "binary:logistic"

    def fit(
        self,
        X,
        y=None,
        *,
        sample_weight=None,
        base_margin=None,
        eval_set=None,
        sample_weight_eval_set=None,
        base_margin_eval_set=None,
        verbose=False,
        xgb_model=None,
        feature_weights=None,
        callbacks=None,
        early_stopping_rounds=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ):
        params = self.get_xgb_params()
        dm, evals = _check_if_params_are_ray_dmatrix(
            X, sample_weight, base_margin, eval_set,
            sample_weight_eval_set, base_margin_eval_set,
        )
        if dm is not None:
            num_class = params.get("num_class", 0)
            self.classes_ = np.arange(max(2, num_class))
            self.n_classes_ = max(2, int(num_class))
            y_enc = None
        else:
            y_arr = np.asarray(y)
            self.classes_ = np.unique(y_arr)
            self.n_classes_ = len(self.classes_)
            class_to_idx = {c: i for i, c in enumerate(self.classes_)}
            y_enc = np.asarray([class_to_idx[v] for v in y_arr], dtype=np.float32)

        if self.n_classes_ > 2:
            if callable(params.get("objective")):
                # custom objective: transforms fall back to softprob semantics
                params["num_class"] = self.n_classes_
            else:
                params.setdefault("objective", "multi:softprob")
                if params["objective"].startswith("multi"):
                    params["num_class"] = self.n_classes_
        else:
            params.setdefault("objective", self._default_objective)

        if dm is None:
            enc_eval_set = None
            if eval_set:
                class_to_idx = {c: i for i, c in enumerate(self.classes_)}
                enc_eval_set = [
                    (ex, np.asarray([class_to_idx[v] for v in np.asarray(ey)],
                                    dtype=np.float32))
                    for ex, ey in eval_set
                ]
            dm, evals = self._build_matrices(
                X, y_enc, sample_weight=sample_weight, base_margin=base_margin,
                eval_set=enc_eval_set,
                sample_weight_eval_set=sample_weight_eval_set,
                base_margin_eval_set=base_margin_eval_set,
                feature_weights=feature_weights,
                ray_dmatrix_params=ray_dmatrix_params,
            )
        return self._fit_common(
            params, dm, list(evals), verbose=verbose, xgb_model=xgb_model,
            callbacks=callbacks, early_stopping_rounds=early_stopping_rounds,
            ray_params=ray_params, _remote=_remote,
        )

    def predict_proba(
        self,
        X,
        ntree_limit=None,
        validate_features=True,
        base_margin=None,
        iteration_range=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ) -> np.ndarray:
        raw = self._ray_predict_margin_or_value(
            X, output_margin=False, ntree_limit=ntree_limit,
            validate_features=validate_features, base_margin=base_margin,
            iteration_range=iteration_range, ray_params=ray_params,
            _remote=_remote, ray_dmatrix_params=ray_dmatrix_params,
        )
        raw = np.asarray(raw)
        if raw.ndim == 2:
            return raw
        return np.stack([1.0 - raw, raw], axis=1)

    def predict(
        self,
        X,
        output_margin=False,
        ntree_limit=None,
        validate_features=True,
        base_margin=None,
        iteration_range=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ):
        raw = self._ray_predict_margin_or_value(
            X, output_margin=output_margin, ntree_limit=ntree_limit,
            validate_features=validate_features, base_margin=base_margin,
            iteration_range=iteration_range, ray_params=ray_params,
            _remote=_remote, ray_dmatrix_params=ray_dmatrix_params,
        )
        if output_margin:
            return raw
        raw = np.asarray(raw)
        if raw.ndim == 2:
            idx = raw.argmax(axis=1)
        else:
            booster = self.get_booster()
            if booster.params.objective == "multi:softmax":
                idx = raw.astype(int)
            else:
                idx = (raw > 0.5).astype(int)
        classes = getattr(self, "classes_", None)
        if classes is None:
            return idx
        return np.asarray(classes)[idx]


class RayXGBRFRegressor(RayXGBRegressor):
    """Random-forest variant (mirror ``sklearn.py:880-919``): one boosting
    round of ``n_estimators`` parallel trees, lr=1, row/column subsampling."""

    def __init__(self, *, learning_rate=1.0, subsample=0.8, colsample_bynode=0.8,
                 reg_lambda=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, subsample=subsample,
                         colsample_bynode=colsample_bynode,
                         reg_lambda=reg_lambda, **kwargs)

    def get_xgb_params(self):
        params = super().get_xgb_params()
        params["num_parallel_tree"] = self.n_estimators
        return params

    def _num_boost_round(self):
        return 1


class RayXGBRFClassifier(RayXGBClassifier):
    """Random-forest classifier variant (mirror ``sklearn.py:631-637``)."""

    def __init__(self, *, learning_rate=1.0, subsample=0.8, colsample_bynode=0.8,
                 reg_lambda=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, subsample=subsample,
                         colsample_bynode=colsample_bynode,
                         reg_lambda=reg_lambda, **kwargs)

    def get_xgb_params(self):
        params = super().get_xgb_params()
        params["num_parallel_tree"] = self.n_estimators
        return params

    def _num_boost_round(self):
        return 1


class RayXGBRanker(_RayXGBEstimator):
    """Learning-to-rank estimator (mirror ``sklearn.py:921-1040``)."""

    _default_objective = "rank:pairwise"

    def fit(
        self,
        X,
        y=None,
        *,
        qid=None,
        sample_weight=None,
        base_margin=None,
        eval_set=None,
        eval_qid=None,
        sample_weight_eval_set=None,
        base_margin_eval_set=None,
        verbose=False,
        xgb_model=None,
        feature_weights=None,
        callbacks=None,
        early_stopping_rounds=None,
        ray_params=None,
        _remote=None,
        ray_dmatrix_params=None,
    ):
        params = self.get_xgb_params()
        params.setdefault("objective", self._default_objective)
        if not params["objective"].startswith("rank:"):
            raise ValueError(
                "RayXGBRanker requires a rank:* objective, got "
                f"{params['objective']!r}"
            )
        dm, evals = _check_if_params_are_ray_dmatrix(
            X, sample_weight, base_margin, eval_set,
            sample_weight_eval_set, base_margin_eval_set,
        )
        if dm is None:
            if qid is None:
                raise ValueError(
                    "RayXGBRanker requires the `qid` argument (or a RayDMatrix "
                    "constructed with qid)."
                )
            dm, evals = self._build_matrices(
                X, y, sample_weight=sample_weight, base_margin=base_margin,
                qid=qid, eval_set=eval_set, eval_qid=eval_qid,
                sample_weight_eval_set=sample_weight_eval_set,
                base_margin_eval_set=base_margin_eval_set,
                feature_weights=feature_weights,
                ray_dmatrix_params=ray_dmatrix_params,
            )
        elif dm.loader.qid is None:
            raise ValueError("RayXGBRanker requires a RayDMatrix with qid.")
        return self._fit_common(
            params, dm, list(evals), verbose=verbose, xgb_model=xgb_model,
            callbacks=callbacks, early_stopping_rounds=early_stopping_rounds,
            ray_params=ray_params, _remote=_remote,
        )
