"""Per-worker session context for user callbacks.

API mirror of ``xgboost_ray/session.py``: code running inside training
callbacks can query its actor rank and push telemetry to the driver queue
(drained into ``additional_results["callback_returns"]``,
``xgboost_ray/main.py:902-922``).
"""

from typing import Any, Optional


class RayXGBoostSession:
    def __init__(self, rank: int, queue: Optional[Any] = None):
        self._rank = rank
        self._queue = queue

    def get_actor_rank(self) -> int:
        return self._rank

    def get_rabit_rank(self) -> int:
        # ranks coincide in the mesh runtime (no separate rabit world)
        return self._rank

    def put_queue(self, item: Any):
        if self._queue is not None:
            self._queue.put((self._rank, item))

    def set_queue(self, queue: Any):
        self._queue = queue


# thread-local so concurrent tune trials (Tuner max_concurrent_trials > 1,
# one training per thread) do not cross-wire each other's driver queues
import threading as _threading

_session_tls = _threading.local()


def init_session(rank: int = 0, queue: Optional[Any] = None):
    _session_tls.value = RayXGBoostSession(rank, queue)


def get_session() -> RayXGBoostSession:
    session = getattr(_session_tls, "value", None)
    if session is None:
        raise ValueError(
            "`get_session()` was called outside an initialized session. "
            "Only call this from within xgboost_ray_tpu training callbacks."
        )
    return session


def set_session_queue(queue: Any):
    get_session().set_queue(queue)


def get_actor_rank() -> int:
    return get_session().get_actor_rank()


def get_rabit_rank() -> int:
    return get_session().get_rabit_rank()


def put_queue(item: Any):
    """Put a queue item from a training callback onto the driver queue."""
    get_session().put_queue(item)
