"""Multi-host distributed backend: the rendezvous layer.

The reference's communication backend is a Python Rabit tracker (TCP
rendezvous + binomial-tree/ring topology brokering,
``xgboost_ray/compat/tracker.py``, ``main.py:225-324``). On TPU there is no
tracker to build: rendezvous is ``jax.distributed.initialize`` (one process
per host), after which ``jax.devices()`` is the global device list, the
training mesh spans all hosts, and the per-round histogram ``psum`` compiles
onto ICI within a slice and DCN across slices (SURVEY §5.8).

This module is the thin, user-facing wrapper for that flow plus the helpers
the engine uses to place host-local shard data into globally-sharded arrays.
"""

import logging
from typing import Optional, Sequence

import numpy as np

import jax

logger = logging.getLogger(__name__)

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join the multi-host world (call once per host before train()).

    On TPU pods with default provisioning, all arguments are auto-detected by
    JAX; arguments exist for manual/DCN setups. Replaces the reference's
    tracker bootstrap: there is no port brokering and no restart-per-attempt —
    world changes are handled by recompiling for the surviving mesh
    (``xgboost_ray/main.py:256-270`` motivates the reference's restart; see
    SURVEY §5.8 for the mapping).
    """
    global _initialized
    if _initialized:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    logger.info(
        "[RayXGBoost] joined distributed world: process %d/%d, %d local / %d "
        "global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.local_devices()),
        len(jax.devices()),
    )


def shutdown_distributed() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def put_rows_global(arr: np.ndarray, sharding) -> jax.Array:
    """Place row data into a globally row-sharded array.

    Single-host: a plain ``device_put``. Multi-host: ``arr`` is this
    process's *local* rows (the shards of the ranks whose mesh devices live
    on this host, already padded to the local extent), assembled into the
    global array without any cross-host copy —
    ``jax.make_array_from_process_local_data`` is the DCN-era replacement for
    shipping shards through an object store.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)
