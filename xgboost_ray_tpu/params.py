"""xgboost-style parameter dict parsing and validation.

The reference passes the user's ``params`` dict straight to ``xgb.train``
(``xgboost_ray/main.py:745-752``) after validating distributed-compatibility
(``main.py:1506-1524``: ``exact``/``grow_colmaker`` rejected, GPU hint
warnings). We mirror the same surface: same keys, same aliases, same
rejections — resolved into a typed config for the jitted tpu_hist engine.
"""

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Union

logger = logging.getLogger(__name__)

_ALIASES = {
    "eta": "learning_rate",
    "lambda": "reg_lambda",
    "alpha": "reg_alpha",
    "min_split_loss": "gamma",
}

# accepted-and-ignored keys (no TPU meaning, kept for drop-in compatibility)
_IGNORED = {
    "nthread",
    "n_jobs",
    "verbosity",
    "silent",
    "gpu_id",
    "predictor",
    "validate_parameters",
    "single_precision_histogram",
    "use_label_encoder",
    "enable_categorical",
    "disable_default_eval_metric",
    "num_pairsample",
    "device",
    "max_cat_to_onehot",
    "eval_at",
}


@dataclasses.dataclass
class TrainParams:
    objective: str = "reg:squarederror"
    num_class: int = 0
    learning_rate: float = 0.3
    max_depth: int = 6
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    gamma: float = 0.0
    min_child_weight: float = 1.0
    max_delta_step: float = 0.0
    subsample: float = 1.0
    # row-sampling policy (ops/sampling.py): "uniform" (subsample-rate
    # without-replacement top-k) or "gradient_based" (GOSS: deterministic
    # top-|g|sqrt(h) fraction + amplified uniform remainder). Either policy
    # COMPACTS the round's rows to a fixed budget, so sampled rounds cost
    # O(M) histogram work, not O(N) with zeroed gradients.
    sampling_method: str = "uniform"
    # gradient_based fractions (LightGBM's GOSS names): keep the top
    # ``top_rate`` of rows by |g|*sqrt(h), sample ``other_rate`` of the
    # rest uniformly with unbiased weight amplification
    top_rate: float = 0.2
    other_rate: float = 0.1
    colsample_bytree: float = 1.0
    colsample_bylevel: float = 1.0
    colsample_bynode: float = 1.0
    max_bin: int = 256
    base_score: Optional[float] = None
    seed: int = 0
    num_parallel_tree: int = 1
    scale_pos_weight: float = 1.0
    tree_method: str = "tpu_hist"
    eval_metric: List[str] = dataclasses.field(default_factory=list)
    # booster selection: gbtree (default) or dart (dropout boosting)
    booster: str = "gbtree"
    rate_drop: float = 0.0
    one_drop: int = 0
    skip_drop: float = 0.0
    sample_type: str = "uniform"  # uniform | weighted
    normalize_type: str = "tree"  # tree | forest
    # survival:aft
    aft_loss_distribution: str = "normal"
    aft_loss_distribution_scale: float = 1.0
    # reg:tweedie
    tweedie_variance_power: float = 1.5
    # reg:pseudohubererror
    huber_slope: float = 1.0
    # reg:quantileerror target quantile(s): float or list of floats
    quantile_alpha: float = 0.5
    # tpu_hist internals
    hist_impl: str = "auto"  # auto | scatter | onehot | partition | mixed
    # histogram MXU precision: auto (fast on accelerators, highest on CPU) |
    # highest (f32-exact) | fast (single bf16 pass, ~0.2% bin-sum rounding)
    hist_precision: str = "auto"
    # histogram ALLREDUCE wire format: none (f32 psum, default) | int16 |
    # int8 — quantized collective payloads (~4x fewer bytes for int8) with
    # deterministic rounding and int32 accumulation — | int16_block |
    # int8_block — block-scaled ppermute-ring merge with per-block scales
    # shipped in-band and NO global absmax pre-pass (fewer bytes AND one
    # fewer full-latency collective per merge); node totals / leaf weights
    # stay exact in all modes. Orthogonal to hist_precision (which governs
    # the on-chip BUILD, this governs the cross-chip MERGE).
    hist_quant: str = "none"
    # payloads under this many bytes psum in f32 even when hist_quant is on:
    # small collectives are latency-bound (no byte win) and staying exact
    # keeps small-problem tree structure invariant to the world size
    hist_quant_min_bytes: int = 32768
    # elements per in-band scale block of the flattened histogram for the
    # *_block wire modes (power of two; ignored by the row-scale modes).
    # 512 keeps the scale overhead under 1% while staying far finer than a
    # per-(node, feature) row at production bin counts.
    hist_quant_block: int = 512
    # on-chip gradient/hessian precision: float32 (default) | int16 | int8 —
    # g/h quantized AT THE OBJECTIVE KERNEL with per-tree pmax-shared scales
    # and stochastic rounding (deterministic per seed), then carried
    # low-precision through compaction and histogram accumulation
    # (int -> int32, exact); node totals and leaf weights stay exact f32 of
    # the quantized values. ~4x smaller per-shard gh plane at int8.
    # Orthogonal to (and composable with) hist_quant, which governs only
    # the cross-chip histogram WIRE format.
    gh_precision: str = "float32"
    hist_chunk: int = 8192
    # build only the smaller child's histogram per parent, derive the sibling
    # by subtraction (xgboost hist-core behavior); disable for A/B debugging
    sibling_subtract: bool = True
    # depthwise (level-wise) or lossguide (leaf-wise best-first growth)
    grow_policy: str = "depthwise"
    # lossguide leaf budget; 0 = bounded only by max_depth (2^max_depth)
    max_leaves: int = 0
    # per-feature monotone constraints (-1/0/+1), padded with 0 to the
    # feature count at engine time; xgboost accepts "(1,-1)" strings too
    monotone_constraints: tuple = ()
    # interaction constraints: tuple of tuples of feature indices; a node may
    # only split on features sharing a constraint set with EVERY feature
    # already used on its root path (xgboost semantics)
    interaction_constraints: tuple = ()
    # feature-parallel mesh extent C: the engine's device mesh becomes the 2D
    # (num_actors, C) row x feature grid and each chip builds/allreduces only
    # its [N/R, F/C] histogram tile (psum over the actors axis only; a tiny
    # per-node best-split election rides the features axis). C=1 (default)
    # keeps the 1D row mesh and traces the exact legacy program.
    feature_parallel: int = 1


def validate_streaming_params(params: "TrainParams") -> None:
    """Composition gates for streamed (external-memory) ingestion.

    Streaming happens POST-sketch/PRE-histogram, so anything that only
    consumes the binned matrix composes: ``feature_parallel > 1`` (sharding
    happens post-bin), ``gh_precision`` (the gh plane is margin-derived),
    ``hist_quant``/``hist_impl``/``hist_precision``, row sampling (uniform
    and GOSS compact binned rows), depthwise and lossguide growers,
    monotone/interaction constraints, dart, custom objectives, survival
    bounds, and elastic training IN-FLIGHT (``TpuEngine.can_reshard`` is
    True for streamed loads: a shrink reuses the survivors' binned blocks
    and frozen cuts in memory — zero re-stream, zero re-sketch — and a
    grow-back onto a brand-new replacement actor re-streams only that one
    shard against the frozen cuts, budget-prevalidated; see
    ``stream/ingest.py``'s reuse passes. The warm-start cut-drift gate
    still guards CHECKPOINT resumes whose world or data changed — frozen
    in-memory cuts pass it trivially, re-sketched different ones raise
    instead of mis-routing split_bin).

    What does NOT compose is gated loudly here (the repo's
    no-silent-fallback invariant):

    * ``booster='gblinear'`` — the linear engine consumes raw feature
      values, which a streamed load never materializes;
    * ``rank:*`` objectives — query groups need a global qid-contiguity
      sort the chunk pipeline cannot perform (the qid column itself is also
      rejected at ingest).

    Multi-host worlds and streamed EVAL sets are gated at their own seams
    (engine init / ``_add_eval_set``).
    """
    if params.booster == "gblinear":
        raise NotImplementedError(
            "streamed ingestion is not supported with booster='gblinear': "
            "the linear engine trains on raw feature values, which a "
            "streamed load never materializes. Materialize the matrix or "
            "use a tree booster."
        )
    obj = params.objective
    if isinstance(obj, str) and obj.startswith("rank:"):
        raise NotImplementedError(
            f"streamed ingestion is not supported with objective={obj!r}: "
            f"ranking needs qid-contiguous query groups, which require a "
            f"global sort the chunk pipeline cannot do. Materialize the "
            f"matrix for ranking."
        )


def cat_feature_indices(feature_types: Optional[Sequence[Any]]) -> tuple:
    """Indices marked categorical ('c') in an xgboost feature_types list."""
    return tuple(
        i
        for i, t in enumerate(feature_types or [])
        if str(t).lower() in ("c", "categorical")
    )


def _parse_monotone_constraints(val: Any) -> tuple:
    """xgboost formats: "(1,-1,0)" string, or a sequence of -1/0/+1 ints.
    Length may be shorter than the feature count; the engine pads with 0
    (unconstrained), matching xgboost."""
    if isinstance(val, str):
        items = [s for s in val.strip().strip("()").split(",") if s.strip()]
    elif isinstance(val, dict):
        raise ValueError(
            "dict-form monotone_constraints (by feature name) are not "
            "supported; pass a tuple/list indexed by feature position."
        )
    else:
        items = list(val)
    try:
        out = tuple(int(v) for v in items)
    except (TypeError, ValueError):
        raise ValueError(f"could not parse monotone_constraints: {val!r}")
    if any(c not in (-1, 0, 1) for c in out):
        raise ValueError(
            f"monotone_constraints entries must be -1, 0, or +1; got {out}"
        )
    return out


def _parse_interaction_constraints(val: Any) -> tuple:
    """xgboost format: "[[0, 1], [2, 3, 4]]" string or a nested sequence of
    feature indices. Feature names are not supported (index positions only)."""
    if isinstance(val, str):
        import ast

        try:
            val = ast.literal_eval(val)
        except (SyntaxError, ValueError):
            raise ValueError(
                f"could not parse interaction_constraints string: {val!r}"
            )
    try:
        groups = tuple(
            tuple(sorted({int(i) for i in grp})) for grp in val
        )
    except (TypeError, ValueError):
        raise ValueError(
            f"interaction_constraints must be a sequence of index groups "
            f"(feature names are not supported); got {val!r}"
        )
    if any(i < 0 for grp in groups for i in grp):
        raise ValueError("interaction_constraints indices must be >= 0")
    return tuple(g for g in groups if g)


def parse_params(params: Optional[Dict[str, Any]]) -> TrainParams:
    params = dict(params or {})
    out = TrainParams()

    tree_method = str(params.pop("tree_method", "tpu_hist") or "tpu_hist")
    if tree_method in ("exact",):
        # parity with xgboost_ray/main.py:1509-1515 (exact unsupported distributed)
        raise ValueError(
            "`exact` tree_method doesn't support distributed training. Use "
            "`tree_method=\"tpu_hist\"` (or \"hist\"/\"approx\", which map to it)."
        )
    if tree_method in ("gpu_hist",):
        logger.warning(
            "tree_method='gpu_hist' has no meaning on TPU; using 'tpu_hist'."
        )
        tree_method = "tpu_hist"
    if tree_method in ("hist", "approx", "auto"):
        tree_method = "tpu_hist"
    if tree_method != "tpu_hist":
        raise ValueError(f"Unsupported tree_method: {tree_method!r}")
    out.tree_method = tree_method

    def _empty_constraint(val, empty_strs):
        # explicit checks — numpy arrays reject bool()/== against strings
        if val is None:
            return True
        if isinstance(val, str):
            return val.strip() in empty_strs
        try:
            return len(val) == 0
        except TypeError:
            return False

    mono = params.pop("monotone_constraints", None)
    if not _empty_constraint(mono, ("", "()")):
        out.monotone_constraints = _parse_monotone_constraints(mono)
    ic = params.pop("interaction_constraints", None)
    if not _empty_constraint(ic, ("", "()", "[]")):
        out.interaction_constraints = _parse_interaction_constraints(ic)

    updater = params.pop("updater", None)
    if updater and "grow_colmaker" in str(updater):
        # parity with xgboost_ray/main.py:1509-1515
        raise ValueError(
            "`grow_colmaker` updater doesn't support distributed training."
        )
    feature_selector = params.pop("feature_selector", None)
    # gblinear's LinearTrainParam defaults reg_lambda to 0 (the tree
    # booster's default is 1); remember whether the user set it explicitly
    had_lambda = any(k in params for k in ("lambda", "reg_lambda"))

    em = params.pop("eval_metric", None)
    if em is not None:
        out.eval_metric = [em] if isinstance(em, str) else list(em)

    for key, value in list(params.items()):
        name = _ALIASES.get(key, key)
        if name in _IGNORED:
            continue
        if name == "random_state":
            name = "seed"
        if not hasattr(out, name):
            logger.warning("Ignoring unknown xgboost parameter %r", key)
            continue
        field_type = type(getattr(TrainParams(), name))
        if value is not None:
            try:
                if name == "base_score":
                    value = float(value)
                elif field_type is bool:
                    value = (
                        value.strip().lower() in ("1", "true", "yes")
                        if isinstance(value, str)
                        else bool(value)
                    )
                elif field_type is float:
                    value = float(value)
                elif field_type is int:
                    value = int(value)
                elif field_type is str:
                    value = str(value)
            except (TypeError, ValueError):
                pass
        setattr(out, name, value)

    # hist_impl names resolve through the pluggable histogram-provider
    # registry (ops/provider.py): built-ins plus anything registered via
    # register_histogram_provider (the bench A/B hook). The import is
    # function-level so this module stays importable pre-jax.
    from xgboost_ray_tpu.ops.provider import available_hist_impls

    known_impls = available_hist_impls()
    if out.hist_impl not in known_impls:
        extra = ""
        if out.hist_impl == "pallas":
            # removed in r5: on-chip measurement showed the hand-written
            # kernel ~1.4x slower than the identical-layout XLA einsum —
            # see ops/grow.py's module docstring for the full rationale
            extra = (
                " The Pallas kernel was removed after losing to the XLA "
                "formulation on-chip; 'mixed' covers its niche."
            )
        raise ValueError(
            f"Unknown hist_impl {out.hist_impl!r}; use one of "
            f"{' | '.join(known_impls)}.{extra}"
        )

    if out.hist_quant not in (
        "none", "int16", "int8", "int16_block", "int8_block"
    ):
        raise ValueError(
            f"Unknown hist_quant {out.hist_quant!r}; use none | int16 | "
            f"int8 | int16_block | int8_block (quantized histogram "
            f"allreduce wire format)."
        )
    if out.hist_quant_block is None:
        out.hist_quant_block = 512
    out.hist_quant_block = int(out.hist_quant_block)
    if (
        out.hist_quant_block < 64
        or out.hist_quant_block > (1 << 20)
        or out.hist_quant_block & (out.hist_quant_block - 1)
    ):
        raise ValueError(
            f"hist_quant_block must be a power of two in [64, 2^20], got "
            f"{out.hist_quant_block!r} (elements per in-band scale block "
            f"of the *_block wire modes)."
        )

    if out.gh_precision is None:
        out.gh_precision = "float32"
    if out.gh_precision not in ("float32", "int16", "int8"):
        raise ValueError(
            f"Unknown gh_precision {out.gh_precision!r}; use float32 | "
            f"int16 | int8 (on-chip quantized-gradient training)."
        )
    if out.gh_precision != "float32" and out.booster == "gblinear":
        raise NotImplementedError(
            "gh_precision quantizes the per-tree gradient/hessian plane; "
            "booster='gblinear' has no gh histogram plane to quantize. "
            "Use gh_precision='float32' (silently ignoring the knob would "
            "misreport the training precision)."
        )

    if out.feature_parallel is None:
        out.feature_parallel = 1
    out.feature_parallel = int(out.feature_parallel)
    if out.feature_parallel < 1:
        raise ValueError(
            f"feature_parallel must be >= 1; got {out.feature_parallel}"
        )
    if out.feature_parallel > 1:
        # the 2D row x feature mesh supports the tree boosters' depthwise and
        # lossguide growers; combinations whose semantics would need global-F
        # state per node are gated loudly rather than silently degraded
        # (the repo's no-silent-fallback invariant)
        if out.booster in ("dart", "gblinear"):
            raise NotImplementedError(
                f"feature_parallel > 1 is not supported with "
                f"booster={out.booster!r} (dart recomputes margins from the "
                f"whole forest each round; gblinear has no histogram to "
                f"shard). Use booster='gbtree'."
            )
        for bad, name in (
            (out.colsample_bylevel < 1.0, "colsample_bylevel"),
            (out.colsample_bynode < 1.0, "colsample_bynode"),
            (bool(out.monotone_constraints)
             and any(out.monotone_constraints), "monotone_constraints"),
            (bool(out.interaction_constraints), "interaction_constraints"),
        ):
            if bad:
                raise NotImplementedError(
                    f"{name} is not supported with feature_parallel > 1 yet "
                    f"(per-level/per-node feature state is global-F); "
                    f"silently ignoring it would change model semantics."
                )

    # None means "unset" in every xgboost-adjacent API (the sklearn layer
    # filters None for exactly this reason) — normalize explicit Nones back
    # to the defaults BEFORE validating, so {'subsample': None} maps to 1.0
    # instead of crashing the range checks below
    if out.subsample is None:
        out.subsample = 1.0
    if out.sampling_method is None:
        out.sampling_method = "uniform"
    if out.top_rate is None:
        out.top_rate = 0.2
    if out.other_rate is None:
        out.other_rate = 0.1
    if not 0.0 < out.subsample <= 1.0:
        raise ValueError(
            f"subsample must be in (0, 1]; got {out.subsample}"
        )
    if out.sampling_method not in ("uniform", "gradient_based"):
        raise ValueError(
            f"Unknown sampling_method {out.sampling_method!r}; use uniform "
            f"(subsample-rate row sampling) | gradient_based (GOSS: "
            f"top_rate/other_rate)."
        )
    if not 0.0 <= out.top_rate <= 1.0 or not 0.0 <= out.other_rate <= 1.0:
        raise ValueError(
            f"top_rate/other_rate must be in [0, 1]; got "
            f"top_rate={out.top_rate} other_rate={out.other_rate}"
        )
    had_rates = (
        params.get("top_rate") is not None
        or params.get("other_rate") is not None
    )
    if out.sampling_method != "gradient_based" and had_rates:
        # explicit GOSS rates without the policy that reads them: surface
        # the misconfiguration (the block below raises/warns for every
        # neighboring combo; silence here would hide a forgotten
        # sampling_method='gradient_based')
        logger.warning(
            "top_rate/other_rate have no effect without "
            "sampling_method='gradient_based'; ignoring them."
        )
    if out.sampling_method == "gradient_based":
        # xgboost drives gradient_based sampling BY `subsample` (the
        # documented gpu_hist recipe carries no GOSS rate names), so
        # drop-in configs must keep xgboost semantics: subsample < 1 maps
        # onto the GOSS budget — half kept deterministically by
        # |g|sqrt(h), half sampled with amplification — and subsample ==
        # 1.0 without rates samples NOTHING (in xgboost that config is a
        # no-op). GOSS with this repo's explicit top_rate/other_rate
        # ALONGSIDE subsample < 1 is genuinely ambiguous and raises.
        if out.subsample < 1.0:
            if had_rates:
                raise ValueError(
                    "subsample < 1 is ambiguous with explicit "
                    "top_rate/other_rate under "
                    "sampling_method='gradient_based'; set either the "
                    "GOSS rates or subsample, not both."
                )
            out.top_rate = out.subsample / 2.0
            out.other_rate = out.subsample / 2.0
            out.subsample = 1.0
        elif not had_rates:
            logger.warning(
                "sampling_method='gradient_based' with subsample=1.0 and "
                "no top_rate/other_rate samples nothing (xgboost parity); "
                "set top_rate/other_rate (or subsample < 1) to enable "
                "GOSS."
            )
            out.sampling_method = "uniform"
    if out.sampling_method == "gradient_based":
        rate_sum = out.top_rate + out.other_rate
        if not 0.0 < rate_sum <= 1.0:
            raise ValueError(
                f"top_rate + other_rate must be in (0, 1] for "
                f"sampling_method='gradient_based'; got {rate_sum}"
            )
        if out.booster == "gblinear":
            raise NotImplementedError(
                "sampling_method='gradient_based' samples rows per TREE; "
                "it does not apply to booster='gblinear'."
            )

    if out.grow_policy not in ("depthwise", "lossguide"):
        raise ValueError(
            f"grow_policy must be 'depthwise' or 'lossguide'; got "
            f"{out.grow_policy!r}"
        )
    if out.max_leaves < 0:
        raise ValueError("max_leaves must be >= 0")
    if out.grow_policy == "depthwise" and out.max_leaves > 0:
        raise NotImplementedError(
            "max_leaves with grow_policy='depthwise' (leaf-budget pruning of "
            "level-wise growth) is not supported; use "
            "grow_policy='lossguide' for a leaf budget, or drop max_leaves. "
            "Silently ignoring it would change model semantics."
        )
    if out.grow_policy == "lossguide":
        for bad, name in (
            (out.colsample_bylevel < 1.0, "colsample_bylevel"),
            (out.colsample_bynode < 1.0, "colsample_bynode"),
            (bool(out.monotone_constraints)
             and any(out.monotone_constraints), "monotone_constraints"),
            (bool(out.interaction_constraints), "interaction_constraints"),
            # the lossguide grower's per-step 2-node histogram is always the
            # one-hot MXU pass; an explicit different impl must not be
            # silently dropped (the repo's no-silent-fallback invariant)
            (out.hist_impl not in ("auto", "onehot"),
             f"hist_impl={out.hist_impl!r}"),
        ):
            if bad:
                raise NotImplementedError(
                    f"{name} is not supported with grow_policy='lossguide' "
                    f"yet (level-wise only); silently ignoring it would "
                    f"change model semantics."
                )
    if out.max_depth < 1:
        raise ValueError("max_depth must be >= 1 for tpu_hist")
    if out.max_depth > 14:
        raise ValueError(
            f"max_depth={out.max_depth} too large for the padded-heap tpu_hist "
            "learner (limit 14)."
        )
    if not 1 < out.max_bin <= 1024:
        raise ValueError("max_bin must be in (1, 1024]")
    if out.objective.startswith("multi:") and out.num_class < 2:
        raise ValueError("multi:* objectives require num_class >= 2")
    if out.booster not in ("gbtree", "dart", "gblinear"):
        raise ValueError(
            f"Unsupported booster: {out.booster!r} (gbtree, dart, or "
            f"gblinear)."
        )
    if out.booster == "gblinear":
        if not had_lambda:
            out.reg_lambda = 0.0  # xgboost LinearTrainParam default
        if updater is not None and str(updater) not in ("shotgun",
                                                        "coord_descent"):
            raise ValueError(
                f"gblinear updater must be 'shotgun' or 'coord_descent'; "
                f"got {updater!r}"
            )
        if feature_selector is not None and str(feature_selector) != "cyclic":
            raise NotImplementedError(
                "gblinear feature_selector other than 'cyclic' is not "
                "supported (both updaters run the deterministic cyclic "
                "pass here)."
            )
        if out.grow_policy == "lossguide" or out.monotone_constraints or \
                out.interaction_constraints:
            raise NotImplementedError(
                "tree growth options (grow_policy/constraints) do not apply "
                "to booster='gblinear'."
            )
    if out.booster == "dart":
        if out.num_parallel_tree != 1:
            raise ValueError("dart does not support num_parallel_tree > 1")
        if out.normalize_type not in ("tree", "forest"):
            raise ValueError("normalize_type must be 'tree' or 'forest'")
        if out.sample_type not in ("uniform", "weighted"):
            raise ValueError("sample_type must be 'uniform' or 'weighted'")
    return out


# --- vmapped-K (vectorized HPO) lane parameters ------------------------------
# A vmapped-K engine traces ONE round program and runs K hyperparameter
# candidates ("lanes") through it under jax.vmap. A param can ride the lane
# axis only if the round body consumes it ARITHMETICALLY (a traced scalar
# works) — anything that changes trace-time structure (shapes, loop extents,
# provider choice, objective kernel) forces a separate compile and is NOT
# lane-vectorizable. The split is enforced loudly here (the repo's
# no-silent-fallback invariant: a lane must never silently train with a
# neighbor's params).

#: Params that may differ per lane inside one vmapped-K program.
#: ``max_depth`` rides as a traced level mask (the program traces
#: ``max(depths)`` levels); ``subsample`` as a traced slot budget over the
#: max-rate buffer; ``seed`` as a per-lane PRNG key fed in at dispatch.
LANE_VECTORIZABLE_KEYS = (
    "learning_rate",
    "reg_lambda",
    "reg_alpha",
    "gamma",
    "min_child_weight",
    "subsample",
    "max_depth",
    "seed",
)


@dataclasses.dataclass(frozen=True)
class LaneParams:
    """K parsed candidate configs packed for one vmapped-K program.

    ``base`` is the trace-time config: lane 0's params with the shape-
    determining fields widened to cover every lane (``max_depth`` = max,
    ``subsample`` = max rate). ``lanes`` keeps each candidate's own parsed
    params for per-lane PRNG seeds, depth/budget arrays, and the per-lane
    boosters' metadata.
    """

    base: TrainParams
    lanes: tuple  # Tuple[TrainParams, ...]

    @property
    def k(self) -> int:
        return len(self.lanes)

    def values(self, name: str) -> list:
        return [getattr(p, name) for p in self.lanes]

    @property
    def depth_varied(self) -> bool:
        return len({p.max_depth for p in self.lanes}) > 1

    @property
    def subsample_varied(self) -> bool:
        return len({float(p.subsample) for p in self.lanes}) > 1


def vectorize_params(configs: Sequence[Dict[str, Any]]) -> LaneParams:
    """Parse K candidate param dicts into a :class:`LaneParams`, or raise
    ``NotImplementedError`` NAMING the first param that cannot ride the
    lane axis (differs across lanes but is not in
    :data:`LANE_VECTORIZABLE_KEYS`)."""
    if not configs:
        raise ValueError("vectorize_params needs at least one config")
    parsed = [parse_params(c) for c in configs]
    base0 = parsed[0]
    for f in dataclasses.fields(TrainParams):
        if f.name in LANE_VECTORIZABLE_KEYS:
            continue
        reprs = {repr(getattr(p, f.name)) for p in parsed}
        if len(reprs) > 1:
            hint = ""
            if f.name in ("top_rate", "other_rate"):
                hint = (
                    " (GOSS budgets are trace-time row counts; under "
                    "sampling_method='gradient_based' every lane must use "
                    "the same rates)"
                )
            raise NotImplementedError(
                f"param {f.name!r} differs across vmapped-K lanes but is "
                f"not lane-vectorizable{hint}; lane-vectorizable params: "
                f"{', '.join(LANE_VECTORIZABLE_KEYS)}. Split these trials "
                f"into separate (sequential) programs instead."
            )
    if base0.booster != "gbtree":
        raise NotImplementedError(
            f"booster={base0.booster!r} is not supported on the vmapped-K "
            f"path (dart re-walks a lane-dependent forest per round; "
            f"gblinear has no round program to vmap). Use booster='gbtree' "
            f"or sequential trials."
        )
    if base0.grow_policy == "lossguide" and \
            len({p.max_depth for p in parsed}) > 1:
        raise NotImplementedError(
            "param 'max_depth' cannot vary across vmapped-K lanes with "
            "grow_policy='lossguide' (the frontier scan has no per-level "
            "structure to mask); use equal depths or sequential trials."
        )
    if base0.sampling_method == "gradient_based" and \
            len({float(p.subsample) for p in parsed}) > 1:
        raise NotImplementedError(
            "param 'subsample' cannot vary across vmapped-K lanes with "
            "sampling_method='gradient_based' (GOSS budgets are trace-time "
            "row counts); use equal rates or sequential trials."
        )
    base = dataclasses.replace(
        base0,
        max_depth=max(p.max_depth for p in parsed),
        subsample=max(float(p.subsample) for p in parsed),
        eval_metric=list(base0.eval_metric),
    )
    return LaneParams(base=base, lanes=tuple(parsed))
