"""The tpu_hist training engine: one JAX program over a device mesh.

This is the TPU-native inversion of the reference's architecture (SURVEY §7.1):
where xgboost_ray runs N OS-process actors each wrapping the xgboost C++ core
and glues them with a Rabit TCP allreduce (``xgboost_ray/main.py:543-815``,
``compat/tracker.py``), here the N "actors" are slots of a
``jax.sharding.Mesh`` axis (named by ``constants.AXIS_ACTORS``) and the
per-round histogram allreduce is ``lax.psum(hist, AXIS_ACTORS)`` inside a
shard_map-ed, jit-compiled round step.
There is no tracker, no rendezvous protocol, no sockets: XLA compiles the
collective onto ICI.

Responsibilities (mapping to reference components):
  * shard rows onto the mesh with padding + validity mask
                       <- per-actor shard dicts (``RayXGBoostActor.load_data``)
  * distributed quantile sketch + device binning (psum-merged)
                       <- xgboost C++ sketch inside ``xgb.DMatrix``
  * jitted round step: grad/hess -> K*T trees -> margin updates -> metrics
                       <- ``xgb.train`` hot loop + Rabit allreduce
  * warm start from a prior forest; forest export to RayXGBoostBooster
                       <- ``xgb_model`` kwarg / checkpoint resume

The driver retry/checkpoint/elastic loop lives in ``main.py`` — mirroring the
reference's split between actor hot loop and driver control flow.
"""

import contextlib
import dataclasses
import logging
import os
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xgboost_ray_tpu import obs
from xgboost_ray_tpu import progreg
from xgboost_ray_tpu.compat import shard_map_compat
from xgboost_ray_tpu.constants import (
    AXIS_ACTORS,
    AXIS_FEATURES,
    SHARD_COLUMN_FILLS,
)
from xgboost_ray_tpu.models.booster import RayXGBoostBooster, stack_trees
from xgboost_ray_tpu.ops import binning
from xgboost_ray_tpu.ops.histogram import (
    AllreduceBytes,
    counting_psum,
    quantized_hist_allreduce,
)
from xgboost_ray_tpu.ops.grow import (
    SALT_BYTREE,
    SALT_GOSS,
    SALT_SR,
    SALT_SUBSAMPLE,
    GrowConfig,
    Tree,
    build_tree,
    predict_tree_binned,
    predict_tree_binned_fsharded,
    sample_feature_mask,
)
from xgboost_ray_tpu.ops.provider import (
    FeatureShard,
    default_hist_impl,
    resolve_hist_provider,
    vmapped_k_impl,
)
from xgboost_ray_tpu.ops import sampling
from xgboost_ray_tpu.ops.metrics import (
    compute_metric,
    device_metric_contrib,
    is_device_metric,
    parse_metric_name,
)
from xgboost_ray_tpu.ops.objectives import (
    CustomObjective,
    get_objective,
    gh_plane_itemsize,
    quantize_gh,
)
from xgboost_ray_tpu.ops.ranking import RankingObjective, build_group_rows
from xgboost_ray_tpu.ops import predict as predict_ops
from xgboost_ray_tpu.ops.split import SplitParams
from xgboost_ray_tpu.params import LaneParams, TrainParams

logger = logging.getLogger(__name__)

shard_map = shard_map_compat  # version-portable, replication check off


def resolve_hist_impl(impl: str) -> str:
    """Resolve 'auto' via the histogram-provider registry's backend policy
    (ops/provider.py — the one string -> strategy point); explicit names
    pass through and are validated at provider resolution."""
    if impl != "auto":
        return impl
    return default_hist_impl()


def resolve_hist_precision(precision: str) -> str:
    """"auto": f32-exact sums on CPU (parity tests), single-pass bf16 on
    accelerators. Measured on TPU v5e (1M x 28 x 256, 16 rounds): "fast"
    shifts final logloss by ~1e-5 and saves 8-12% per histogram build
    (the builds are DMA/step-bound, not MXU-pass-bound, so the saving is
    modest — but never costs accuracy beyond bf16 rounding of gh)."""
    if precision != "auto":
        return precision
    return "highest" if jax.default_backend() == "cpu" else "fast"


@contextlib.contextmanager
def strict_transfer_guard(active: bool = True):
    """Runtime counterpart of rxgblint's SYNC001: under ``RXGB_STRICT=1``,
    steady-state round dispatch runs inside ``jax.transfer_guard("disallow")``
    so ANY hidden implicit host<->device sync (a stray ``.item()``/
    ``float()``/``np.asarray`` smuggled into a round closure) raises instead
    of silently serializing the pipeline.

    The documented host-sync boundaries stay out of scope by construction:
    the guard wraps ONLY the compiled-program dispatch, not the metric
    scalar reads / forest flushes that follow it, and callers pass
    ``active=False`` for a program's first (compiling) dispatch — trace-time
    closure-constant uploads are a legitimate one-off transfer.
    """
    if active and os.environ.get("RXGB_STRICT") == "1":
        with jax.transfer_guard("disallow"):
            yield
    else:
        yield


class _EvalSet:
    """Device-side state for one entry of ``evals`` (binned with train cuts)."""

    def __init__(self, name: str, n_rows: int, group_ptr: Optional[np.ndarray], is_train: bool):
        self.name = name
        self.n_rows = n_rows
        self.group_ptr = group_ptr
        self.is_train = is_train
        self.local_rows = n_rows  # multi-host: set to this process's rows
        self.lower_np = None
        self.upper_np = None
        self.margins_static = None
        # set by engine when not aliased to the train set:
        self.bins = None
        self.label = None
        self.weight = None
        self.valid = None
        self.margins = None
        self.label_np = None
        self.weight_np = None
        self.group_rows_dev = None  # sharded [NG, G] layout for device ndcg/map
        self.bounds_dev = None  # (lower, upper) device rows for device aft-nloglik


class _EvalArrs(NamedTuple):
    """Device arrays of one non-train eval set, as passed into the sharded
    step programs. Optional members hold scalar placeholders (P() specs) when
    absent so the pytree structure is static."""

    bins: Any
    label: Any
    weight: Any
    valid: Any
    margins: Any
    group_rows: Any  # [NG, G] or scalar placeholder
    margins_static: Any  # dart only; scalar placeholder otherwise
    bounds: Any  # (lower, upper) rows or scalar placeholder (survival only)


class TpuEngine:
    def __init__(
        self,
        shards: Sequence[Dict[str, Optional[np.ndarray]]],
        params: TrainParams,
        num_actors: int,
        evals: Sequence[Tuple[Sequence[Dict[str, Optional[np.ndarray]]], str]] = (),
        devices: Optional[Sequence[Any]] = None,
        init_booster: Optional[RayXGBoostBooster] = None,
        feature_names: Optional[List[str]] = None,
        total_rounds: Optional[int] = None,
        feature_weights: Optional[Any] = None,
        feature_types: Optional[List[str]] = None,
        categories: Optional[Dict[int, tuple]] = None,
        stream_donor: Optional["TpuEngine"] = None,
    ):
        # ``stream_donor``: a prior streamed engine of the SAME training run
        # (the elastic driver passes the engine being swapped out). When this
        # load's shard streams overlap the donor's, the new world is seeded
        # from the donor's retained binned rows and frozen cuts — zero
        # re-sketch, zero re-stream of surviving shards (stream/ingest.py's
        # reuse passes). Ignored for materialized loads and incompatible
        # donors (the full pipeline runs instead).
        self.params = params
        self.feature_names = feature_names
        # NOTE on placement: in this SPMD runtime the mesh IS the placement —
        # every actor rank is a physical device slot, so the reference's
        # PACK/SPREAD placement-group strategies reduce to rank NUMBERING.
        # The mesh must stay process-contiguous (the multi-host global row
        # layout and prediction reassembly assume it); real placement
        # decisions live where they have effect: tuner trials run on disjoint
        # contiguous device slices (tuner.py), and get_tune_resources()
        # exports the strategy hint for schedulers above.
        devices = list(devices if devices is not None else jax.devices())
        self.feature_parallel = int(getattr(params, "feature_parallel", 1))
        if self.feature_parallel > 1:
            # 2D row x feature mesh: rows shard over AXIS_ACTORS (R =
            # num_actors slots, the "world"), histogram feature columns over
            # AXIS_FEATURES (C = feature_parallel). C=1 keeps the 1D branch
            # below and traces the exact legacy program.
            if jax.process_count() > 1:
                raise NotImplementedError(
                    "feature_parallel > 1 is single-process only for now "
                    "(the multi-host global row layout assumes the 1D row "
                    "mesh)."
                )
            need = num_actors * self.feature_parallel
            if len(devices) < need:
                raise ValueError(
                    f"feature_parallel={self.feature_parallel} needs "
                    f"num_actors x C = {need} devices; only {len(devices)} "
                    f"available."
                )
            self.n_devices = max(1, num_actors)
            self.mesh = Mesh(
                np.array(devices[:need]).reshape(
                    self.n_devices, self.feature_parallel
                ),
                (AXIS_ACTORS, AXIS_FEATURES),
            )
        else:
            self.n_devices = max(1, min(num_actors, len(devices)))
            if self.n_devices < num_actors:
                logger.info(
                    "num_actors=%d > %d available devices; folding shards onto the mesh.",
                    num_actors,
                    len(devices),
                )
            self.mesh = Mesh(np.array(devices[: self.n_devices]), (AXIS_ACTORS,))
        self.num_actors = num_actors

        self.objective = (
            params.objective
            if isinstance(params.objective, (CustomObjective,))
            else get_objective(
                params.objective,
                params.num_class,
                params.scale_pos_weight,
                tweedie_variance_power=params.tweedie_variance_power,
                aft_loss_distribution=params.aft_loss_distribution,
                aft_loss_distribution_scale=params.aft_loss_distribution_scale,
                huber_slope=params.huber_slope,
                quantile_alpha=params.quantile_alpha,
            )
        )
        self.is_ranking = isinstance(self.objective, RankingObjective)
        from xgboost_ray_tpu.ops.survival import SurvivalObjective

        self.is_survival = isinstance(self.objective, SurvivalObjective)
        if (
            params.gh_precision != "float32"
            and isinstance(self.objective, CustomObjective)
        ):
            # the user's obj callback hands over f32 g/h it computed itself;
            # stochastic-rounding those behind its back would silently train
            # a different objective than the one supplied
            raise NotImplementedError(
                "gh_precision (quantized-gradient training) is not "
                "supported with a custom objective; set "
                "gh_precision='float32' or use a built-in objective."
            )
        self.n_outputs = self.objective.num_outputs
        base_score = (
            params.base_score
            if params.base_score is not None
            else self.objective.default_base_score
        )
        self.base_score = float(base_score)
        self.base_margin0 = float(self.objective.base_score_to_margin(self.base_score))

        # categorical features: bins are category codes, splits one-vs-rest
        from xgboost_ray_tpu.params import cat_feature_indices

        self.feature_types = feature_types
        self.categories = categories
        self._cat_features: tuple = cat_feature_indices(feature_types)

        self.cfg = GrowConfig(
            max_depth=params.max_depth,
            max_bin=params.max_bin,
            split=SplitParams(
                reg_lambda=params.reg_lambda,
                reg_alpha=params.reg_alpha,
                gamma=params.gamma,
                min_child_weight=params.min_child_weight,
                learning_rate=params.learning_rate,
                max_delta_step=params.max_delta_step,
            ),
            hist_impl=resolve_hist_impl(params.hist_impl),
            hist_precision=resolve_hist_precision(params.hist_precision),
            hist_quant=params.hist_quant,
            hist_quant_min_bytes=params.hist_quant_min_bytes,
            hist_quant_block=params.hist_quant_block,
            gh_precision=params.gh_precision,
            hist_chunk=params.hist_chunk,
            sibling_subtract=params.sibling_subtract,
            cat_features=self._cat_features,
            shards_may_skew=self.n_devices > 1 or jax.process_count() > 1,
            grow_policy=params.grow_policy,
            # leaf budget: 0 means depth-bounded only; a budget beyond
            # 2^max_depth is unreachable, so cap it (keeps the frontier
            # table minimal)
            max_leaves=(
                min(params.max_leaves or (1 << params.max_depth),
                    1 << params.max_depth)
                if params.grow_policy == "lossguide" else 0
            ),
        )

        # metrics (device/host split happens after eval sets exist — ndcg/map
        # are device metrics only when every eval set has a group layout)
        names = list(params.eval_metric) or [self.objective.default_metric]
        self.metric_names = names

        # ---- streamed ingestion detection --------------------------------
        # A streamed shard carries {"stream": ShardStream} instead of a raw
        # array. Streams that fit in ONE chunk materialize here and take the
        # standard path below — the engine then traces the EXACT
        # pre-streaming programs, which is the bitwise-parity contract for
        # small streamed loads (the PR 4/PR 10 default-traces-the-old-
        # program discipline).
        from xgboost_ray_tpu.stream import reader as stream_reader

        streams = stream_reader.shard_streams(shards)
        if streams is not None and all(s.n_chunks <= 1 for s in streams):
            materialized = [
                stream_reader.materialize_shard(sh) for sh in shards
            ]
            # eval entries aliasing the train shard list must keep aliasing
            # the materialized one (the is-identity drives the train-set
            # eval fast path); single-chunk streamed eval sets degrade too
            evals = [
                (
                    materialized if eval_shards is shards
                    else self._materialize_if_single_chunk(eval_shards),
                    name,
                )
                for eval_shards, name in evals
            ]
            shards = materialized
            streams = None
        self._streamed = streams is not None
        self._stream_stats: Optional[Dict[str, Any]] = None
        self._stream_cuts_np: Optional[np.ndarray] = None
        if self._streamed:
            from xgboost_ray_tpu.params import validate_streaming_params

            validate_streaming_params(params)
            if jax.process_count() > 1:
                raise NotImplementedError(
                    "streamed ingestion is single-process only for now: the "
                    "multi-host global row layout needs per-process chunk "
                    "streams. Materialize the matrix on multi-host worlds."
                )

        # ---- host data assembly ------------------------------------------
        if self._streamed:
            from xgboost_ray_tpu.stream import ingest as stream_ingest

            # elastic continuation: when a donor engine already holds (a
            # superset of) these shards binned, skip the sketch pipeline
            # entirely — the donor's frozen cuts + binned rows seed this
            # world (shrink keeps every survivor shard; a grow-back onto a
            # NEW replacement actor re-streams only that one shard)
            self._stream_reuse_plan = stream_ingest.plan_stream_reuse(
                streams, stream_donor, max_bin=params.max_bin
            )
            # the FULL budget fail-fast before any byte streams: the
            # N-scaling block-buffer term needs only the declared row
            # counts, the mesh size, and the bin dtype — all known now
            # (the bin passes re-check with measured figures). The reuse
            # variant additionally guards the columns pass from reading a
            # byte of an over-budget re-streamed replacement shard.
            declared = sum(s.n_rows for s in streams)
            _, _, pre_pad_to = self._global_row_layout(declared)
            pre_block = pre_pad_to // self.n_devices
            pre_itemsize = np.dtype(binning.bin_dtype(params.max_bin)).itemsize
            if self._stream_reuse_plan is not None:
                stream_ingest.prevalidate_reuse_budget(
                    streams, self._stream_reuse_plan,
                    block_rows=pre_block,
                    bin_itemsize=pre_itemsize,
                )
                pass1 = stream_ingest.reuse_columns_pass(
                    streams, self._stream_reuse_plan, stream_donor,
                    params.max_bin, cat_features=self._cat_features,
                )
            else:
                stream_ingest.prevalidate_budget(
                    streams,
                    block_rows=pre_block,
                    bin_itemsize=pre_itemsize,
                    n_devices=self.n_devices,
                )
                pass1 = stream_ingest.sketch_pass(
                    streams, params.max_bin, cat_features=self._cat_features
                )
            x = None
            label = (
                pass1.label if pass1.label is not None
                else np.zeros(pass1.n_rows, np.float32)
            )
            weight, base_margin, qid = pass1.weight, pass1.base_margin, pass1.qid
            lo, hi = pass1.lower, pass1.upper
            self.n_rows = pass1.n_rows
            self.n_features = pass1.n_features
        else:
            x, label, weight, base_margin, qid, lo, hi = _concat_shards(shards)
            self.n_rows = x.shape[0]
            self.n_features = x.shape[1]
        if self.is_survival and lo is None and label is None:
            raise ValueError(
                "survival:aft requires label_lower_bound/label_upper_bound "
                "(or a plain label, interpreted as uncensored times)."
            )

        binning.validate_feature_types_count(self._cat_features, self.n_features)
        # streamed loads validate categorical codes per chunk in sketch_pass
        # via the same shared validator (the full column never materializes)
        if not self._streamed:
            binning.validate_categorical_codes(
                x, self._cat_features, params.max_bin
            )

        # monotone / interaction constraints: validated against the real
        # feature count, then attached to the (jit-static) grow config.
        # Reference surface: xgboost_ray/main.py:745-752 forwards both to
        # xgboost's hist updater untouched.
        if params.monotone_constraints or params.interaction_constraints:
            import dataclasses as _dc

            mono = tuple(int(c) for c in params.monotone_constraints)
            if len(mono) > self.n_features:
                raise ValueError(
                    f"monotone_constraints has {len(mono)} entries but the "
                    f"data has {self.n_features} features."
                )
            mono = mono + (0,) * (self.n_features - len(mono))
            for fi in self._cat_features:
                if mono and mono[fi] != 0:
                    raise ValueError(
                        f"monotone constraint on categorical feature {fi} is "
                        f"not supported (one-vs-rest category splits have no "
                        f"order to be monotone in)."
                    )
            ic = params.interaction_constraints
            bad = [i for grp in ic for i in grp if i >= self.n_features]
            if bad:
                raise ValueError(
                    f"interaction_constraints reference feature indices "
                    f"{sorted(set(bad))} but the data has "
                    f"{self.n_features} features."
                )
            self.cfg = _dc.replace(
                self.cfg,
                monotone_constraints=mono if any(mono) else (),
                interaction_constraints=ic,
            )

        # feature_weights bias the colsample_* draws (Gumbel-top-k weighted
        # sampling without replacement; xgboost set_info(feature_weights=...))
        self._log_fw = None
        if feature_weights is not None:
            fw = np.asarray(feature_weights, np.float32).ravel()
            if fw.shape[0] != self.n_features:
                raise ValueError(
                    f"feature_weights has {fw.shape[0]} entries but the data "
                    f"has {self.n_features} features."
                )
            if (fw < 0).any():
                raise ValueError("feature_weights must be non-negative.")
            if fw.sum() <= 0:
                raise ValueError("feature_weights must not be all zero.")
            with np.errstate(divide="ignore"):
                self._log_fw = jnp.asarray(np.log(fw))
        self.label_np = label if label is not None else lo
        self.weight_np = weight
        self.lower_np, self.upper_np = lo, hi
        self.group_ptr = (
            None if qid is None else build_group_rows(qid)[1]
        )
        if (
            getattr(self.objective, "name", "") == "reg:squaredlogerror"
            and label is not None
            and (np.asarray(label) <= -1).any()
        ):
            # xgboost rejects these at data load; clamping would silently
            # train on corrupted targets
            raise ValueError(
                "reg:squaredlogerror requires all labels > -1."
            )

        # Multi-host: `shards` holds only THIS process's ranks (in the order of
        # this process's devices within jax.devices()); row counts are
        # allgathered to agree on the global padded layout. Single-host this
        # degenerates to local == global.
        self._local_rows = self.n_rows
        self.n_rows, self._local_pad, pad_to = self._global_row_layout(
            self._local_rows
        )
        self._row_sharding = NamedSharding(self.mesh, P(AXIS_ACTORS))

        from xgboost_ray_tpu.distributed import put_rows_global

        def put_rows(arr, dtype, fill=0):
            # multi-host: arr holds this process's local rows and is assembled
            # into the global sharded array without cross-host copies
            arr = np.asarray(arr, dtype=dtype)
            if arr.shape[0] < self._local_pad:
                pad_width = [(0, self._local_pad - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad_width, constant_values=fill)
            return put_rows_global(arr, self._row_sharding)

        self._put_rows = put_rows
        self.pad_to = pad_to
        x_dev = None
        if not self._streamed:
            x_dev = put_rows(x, np.float32, fill=np.nan)
        self.valid = put_rows(np.ones(self._local_rows, bool), bool, fill=False)
        self.label_dev = put_rows(label, np.float32)
        self.weight_dev = put_rows(
            weight
            if weight is not None
            else np.ones(self._local_rows, np.float32),
            np.float32,
        )
        if self.is_survival:
            if lo is None:
                lo = label
            if hi is None:
                hi = lo
            self.lower_np, self.upper_np = lo, hi
            self.bounds_dev = (
                put_rows(lo, np.float32, fill=1.0),
                put_rows(hi, np.float32, fill=1.0),
            )
        else:
            self.bounds_dev = None

        # ---- distributed sketch + binning (device, psum-merged) ----------
        # Weight-aware: xgboost's quantile sketch weighs samples (hessian/user
        # weight), so cut points concentrate where the weighted mass is.
        # weight_dev is all-ones when the user passed no weights, which makes
        # the weighted sketch bit-identical to the unweighted one.
        self._stream_init_margins = None
        if self._streamed:
            if self._stream_reuse_plan is not None:
                # elastic continuation: FROZEN donor cuts (retained in
                # memory — bitwise the cuts every reused shard was binned
                # with, so the booster's split_bin routing stays valid) +
                # block assembly from the donor's device binned rows; only
                # a shard the donor never held re-streams, against these
                # same cuts. No sketch pass, no cuts merge.
                cuts_np = stream_donor._stream_cuts_np.copy()
                repl = NamedSharding(self.mesh, P())
                self.cuts = jax.device_put(cuts_np, repl)
                self._feat_has_missing = jax.device_put(
                    stream_donor._stream_fhm_np.copy(), repl
                )
                self._stream_cuts_np = cuts_np
                self.bins, up_stats = stream_ingest.reuse_bin_pass(
                    self, streams, self._stream_reuse_plan, stream_donor,
                    cuts_np,
                )
                self._stream_stats = {
                    "reused_from_donor": True,
                    "chunks": int(pass1.chunks),
                    "pass1_wall_s": round(pass1.wall_s, 4),
                }
            else:
                # streamed: two-pass host sketch -> device cuts merge (the
                # SAME pmin/pmax/psum collective schedule as the
                # materialized sketch program) -> chunked host binning with
                # double-buffered upload. Rows are born binned; the raw f32
                # matrix never exists.
                self.cuts, self._feat_has_missing, cuts_np, sk_err = (
                    stream_ingest.merged_cuts(self, pass1)
                )
                self._stream_cuts_np = cuts_np
                self.bins, up_stats = stream_ingest.bin_upload_pass(
                    self, streams, cuts_np,
                    sketch_bytes=sum(
                        sk.memory_bytes() for sk in pass1.sketches
                    ),
                )
                self._stream_stats = {
                    "chunks": int(pass1.chunks),
                    "sketch_s": round(pass1.sketch_s, 4),
                    "pass1_wall_s": round(pass1.wall_s, 4),
                    "rank_error_bound_max": float(sk_err.max(initial=0.0)),
                }
            for k, v in up_stats.items():
                self._stream_stats[k] = (
                    round(v, 4) if isinstance(v, float) else v
                )
            # elastic-continuation metadata: what a FUTURE shrink/grow needs
            # to seed its world from this engine (``plan_stream_reuse``) and
            # what ``reset_from_booster`` verifies stream identity against
            self._stream_fhm_np = np.asarray(self._feat_has_missing)
            self._stream_shard_fps = [s.fingerprint() for s in streams]
            self._stream_shard_rows = [s.n_rows for s in streams]
            self._stream_cols = {
                "label": pass1.label,
                "weight": pass1.weight,
                "base_margin": pass1.base_margin,
                "label_lower_bound": pass1.lower,
                "label_upper_bound": pass1.upper,
            }
            # warm start has no raw rows to walk: route the init forest over
            # the binned matrix on device, BEFORE any feature-axis sharding
            if init_booster is not None and init_booster.num_trees:
                self._stream_init_margins = self._init_margins_from_bins(
                    init_booster
                )
        else:
            self.bins, self.cuts, self._feat_has_missing = self._sketch_and_bin(
                x_dev, self.valid, self.weight_dev
            )

        # ---- feature-axis sharding (feature_parallel > 1) ----------------
        # Sketch/binning ran at full F (one-off, row-parallel); the binned
        # matrix is then feature-padded to a C-multiple and laid out as
        # [N/R, F_pad/C] tiles. Pad columns bin entirely to the missing
        # bucket, so their split candidates score -inf and can never be
        # elected. cuts / feat_has_missing keep GLOBAL padded copies for the
        # growers (threshold recovery and routing use global feature ids).
        self._f_padded = self.n_features
        self._cuts_grow = self.cuts
        self._fhm_grow = self._feat_has_missing
        if self.feature_parallel > 1:
            c_shards = self.feature_parallel
            self._f_padded = -(-self.n_features // c_shards) * c_shards
            if self._f_padded * (self.params.max_bin - 1) >= (1 << 24):
                # the best-split election ships its flat candidate index as
                # f32 (exact integers below 2^24 only)
                raise NotImplementedError(
                    f"feature_parallel: padded F x (max_bin - 1) = "
                    f"{self._f_padded * (self.params.max_bin - 1)} exceeds "
                    f"the election record's exact-int f32 range (2^24); "
                    f"reduce max_bin or the feature count."
                )
            f_extra = self._f_padded - self.n_features
            if f_extra:
                self._cuts_grow = jnp.pad(self.cuts, ((0, f_extra), (0, 0)))
                # pad columns DO bin to the missing bucket; keeping the
                # flag True leaves their (all-missing) histogram honest
                self._fhm_grow = jnp.pad(
                    self._feat_has_missing, (0, f_extra),
                    constant_values=True,
                )
            if self.cfg.hist_quant != "none":
                # the quantize-vs-exact-f32 fallback (hist_quant_min_bytes)
                # must be decided on the GLOBAL payload, not the F/C local
                # tile — otherwise payloads in the window between the tile
                # size and the full-F size would quantize on (R, 1) but
                # fall back to exact f32 on (R, C), silently training a
                # different model per mesh shape. Scaling the threshold by
                # local/global keeps every decision site (the allreduce
                # fallback AND the growers' exact-node-totals mirrors,
                # which all compare LOCAL payload bytes against this cfg
                # field) exactly equivalent to the 1D decision.
                import dataclasses as _dc

                f_local = self._f_padded // c_shards
                self.cfg = _dc.replace(
                    self.cfg,
                    hist_quant_min_bytes=(
                        self.params.hist_quant_min_bytes
                        * f_local / max(self.n_features, 1)
                    ),
                )
            self.bins = self._feature_shard_bins(self.bins)

        # ---- ranking group structure (per device block) ------------------
        # built whenever qid exists (ranking gradients AND device ndcg/map
        # metrics use the same padded per-shard group layout)
        self.group_rows = (
            self._build_sharded_groups(qid) if qid is not None else None
        )
        if self.is_ranking and self.group_rows is None:
            raise ValueError(f"objective {self.objective.name!r} requires qid")

        # ---- margins ------------------------------------------------------
        margins_static = np.full(
            (self._local_rows, self.n_outputs), self.base_margin0, np.float32
        )
        if base_margin is not None:
            margins_static = margins_static + base_margin.reshape(
                self._local_rows, -1
            ).astype(np.float32)
        margins0 = margins_static
        self._init_trees: List[Tree] = []
        self._init_tree_weights: Optional[np.ndarray] = None
        # propagate the "was saved without per-node stats" marker through
        # continuation so pred_contribs keeps raising instead of silently
        # attributing zero to the init trees
        self._init_has_stats = (
            getattr(init_booster, "_has_node_stats", True)
            if init_booster is not None
            else True
        )
        if init_booster is not None and init_booster.num_trees:
            if not self._streamed:
                margins0 = margins0 + (
                    init_booster.predict_margin_np(x)
                    - init_booster.base_score_margin_np()
                )
            self._init_trees = [init_booster.forest]
            self._init_tree_weights = (
                init_booster.tree_weights
                if init_booster.tree_weights is not None
                else np.ones(init_booster.num_trees, np.float32)
            )
        self.margins = put_rows(margins0, np.float32)
        if self._stream_init_margins is not None:
            # streamed warm start: the device binned-walk contribution
            # (computed against this load's bins before feature sharding)
            self.margins = self.margins + self._stream_init_margins
            self._stream_init_margins = None
        self.dart = params.booster == "dart"
        if self.dart:
            self._margins_static_dev = put_rows(margins_static, np.float32)
            self._dart_total_rounds = int(total_rounds or 0)

        # ---- eval sets ----------------------------------------------------
        self.evals: List[_EvalSet] = []
        for eval_shards, name in evals:
            self._add_eval_set(eval_shards, name, x_id=id(shards), shards_obj=shards,
                               eval_obj=eval_shards, init_booster=init_booster)

        del x_dev  # raw features no longer needed on device

        has_groups = all(
            (self.group_rows is not None)
            if es.is_train
            else (es.group_rows_dev is not None)
            for es in self.evals
        )
        has_bounds = all(
            (self.bounds_dev is not None)
            if es.is_train
            else (es.bounds_dev is not None)
            for es in self.evals
        )
        self._device_metrics = [
            m for m in self.metric_names if is_device_metric(m, has_groups, has_bounds)
        ]
        self._host_metrics = [
            m
            for m in self.metric_names
            if not is_device_metric(m, has_groups, has_bounds)
        ]
        # Host metrics on multi-host meshes are computed per process on its
        # local rows and combined as a weight-/row-weighted mean across
        # processes — the reference's per-worker metric semantics (each actor
        # evaluates its shard, xgboost averages across workers). Exact for
        # per-row-mean metrics; an approximation for order-statistics like a
        # host-fallback AUC (use the device histogram-AUC for exactness).

        self.trees: List[Tree] = []  # host-side forest, one [K*T, heap] entry per round
        # per-round device forests pending host transfer: under the tunneled
        # TPU relay every host read costs ~70-90 ms, so the per-round step
        # path defers the (tiny) forest transfer and flushes in one batched
        # stack per checkpoint/get_booster instead of 9 reads per round
        # (VERDICT r2 #2: per-round np.asarray transfers)
        self._trees_dev: List[Tuple[Tree, Optional[int]]] = []
        # incremental stacked-forest cache (amortized O(1) copies per tree;
        # re-stacking the whole forest per checkpoint interval was O(T^2))
        self._stack_entries = 0  # how many of (_init_trees + trees) are stacked
        self._stack_rows = 0  # filled tree rows in the buffers
        self._stack_buf: Optional[Tree] = None
        self._step_fn = None
        self._step_fn_custom = None
        self._scan_fn = None
        self._dart_fn = None
        # vmapped-K HPO state (enable_lanes): 0 means scalar mode — every
        # existing path traces the exact pre-lanes program
        self._vk = 0
        self._vk_spec_override = None
        # programs that have dispatched at least once: RXGB_STRICT's
        # transfer guard only arms for warm (non-compiling) dispatches
        self._warm_programs: set = set()
        # device-resident payload-byte counter of the latest round's tree
        # allreduces (materialized lazily — see hist_allreduce_bytes_per_round)
        self._ar_bytes_dev = None
        # static attributes attached to every "round" span: world size, row
        # counts, and (when sampling is on) the per-shard compacted budget —
        # the "sampling budgets become span attributes" half of the obs plane
        samp_spec = sampling.spec_from_params(params)
        self._obs_round_attrs = {
            "world": int(self.n_devices),
            "rows": int(self.n_rows),
        }
        if self.feature_parallel > 1:
            self._obs_round_attrs["feature_parallel"] = int(
                self.feature_parallel
            )
        if params.gh_precision != "float32":
            self._obs_round_attrs["gh_precision"] = params.gh_precision
        if self._streamed:
            self._obs_round_attrs["streamed"] = True
        if samp_spec is not None:
            self._obs_round_attrs["sample_rows_per_shard"] = int(
                sampling.row_budget(self.pad_to // self.n_devices, samp_spec)
            )
        if self.dart:
            self._init_dart_forest()
        self.iteration_offset = (
            init_booster.num_boosted_rounds() if init_booster is not None else 0
        )

    # ------------------------------------------------------------------
    def _global_row_layout(self, local_n: int):
        """(global_n, local_pad, pad_to) for the row-sharded device layout.

        Multi-host, row counts are allgathered so every process agrees on the
        global padded extent; each process places exactly ``local_pad`` rows
        (its ranks' rows + tail padding) via put_rows_global.
        """
        pc = jax.process_count()
        if pc == 1:
            pad_to = -(-max(local_n, self.n_devices) // self.n_devices) * self.n_devices
            return local_n, pad_to, pad_to
        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(np.int64(local_n))
        ).ravel()
        global_n = int(counts.sum())
        if self.n_devices % pc:
            raise ValueError(
                f"{self.n_devices} mesh devices do not divide evenly over "
                f"{pc} processes."
            )
        per_proc_devices = self.n_devices // pc
        block = -(-max(global_n, self.n_devices) // self.n_devices)
        # every process must fit its rows in its devices' blocks
        block = max(block, int(-(-counts.max() // per_proc_devices)))
        pad_to = block * self.n_devices
        local_pad = block * per_proc_devices
        return global_n, local_pad, pad_to

    def _fetch_rows(self, arr, valid, n_real: int) -> np.ndarray:
        """Device row-sharded array -> host array of the real data rows.

        Single-host: plain transfer + tail-padding slice. Multi-host: the
        array spans non-addressable devices, so it is allgathered first and
        per-process tail padding dropped via the valid mask.
        """
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)[:n_real]
        from jax.experimental import multihost_utils

        full = np.asarray(multihost_utils.process_allgather(arr, tiled=True))
        mask = np.asarray(
            multihost_utils.process_allgather(valid, tiled=True)
        ).astype(bool)
        return full[mask]

    # ------------------------------------------------------------------
    def _sketch_and_bin(self, x_dev, valid, weight_dev):
        max_bin = self.params.max_bin
        cat_features = self._cat_features

        def fn(x, v, w):
            mn, mx = binning.feature_min_max(x, v)
            mn = jax.lax.pmin(mn, AXIS_ACTORS)
            mx = jax.lax.pmax(mx, AXIS_ACTORS)
            hist = binning.sketch_histogram(x, v, mn, mx, weight=w)
            hist = jax.lax.psum(hist, AXIS_ACTORS)
            cuts = binning.cuts_from_sketch(mn, mx, hist, max_bin)
            if cat_features:
                # categorical columns: cut k sits at k + 0.5, so the bin index
                # IS the category code and one-vs-rest split search applies
                from xgboost_ray_tpu.ops.grow import cat_mask_const

                cat_mask = cat_mask_const(cat_features, x.shape[1])
                code_cuts = jnp.arange(max_bin - 1, dtype=cuts.dtype) + 0.5
                cuts = jnp.where(cat_mask[:, None], code_cuts[None, :], cuts)
            bins = binning.bin_matrix(x, cuts, max_bin)
            # global per-feature "has any missing value" mask (padding rows
            # are excluded — they bin to the missing bucket by construction):
            # lets the tree builder zero phantom missing mass that the
            # subtraction-reconstructed bucket picks up under fast precision
            miss_cnt = jnp.sum(
                ((bins == max_bin) & v[:, None]).astype(jnp.float32), axis=0
            )
            has_missing = jax.lax.psum(miss_cnt, AXIS_ACTORS) > 0
            return bins, cuts, has_missing

        mapped = shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(P(AXIS_ACTORS), P(AXIS_ACTORS), P(AXIS_ACTORS)),
            out_specs=(P(AXIS_ACTORS), P(), P()),
        )
        jit_fn = progreg.register_jit(
            "engine.sketch_cuts",
            mapped,
            example_args=(x_dev, valid, weight_dev),
            meta=self._program_meta(),
        )
        bins, cuts, has_missing = jit_fn(x_dev, valid, weight_dev)
        return bins, cuts, has_missing

    @staticmethod
    def _materialize_if_single_chunk(shard_list):
        """Degrade a single-chunk streamed shard list to materialized
        fields (mirrors the train-set degrade); multi-chunk lists pass
        through untouched (and hit the streamed-eval gate downstream)."""
        from xgboost_ray_tpu.stream import reader as stream_reader

        st = stream_reader.shard_streams(shard_list)
        if st is not None and all(s.n_chunks <= 1 for s in st):
            return [stream_reader.materialize_shard(sh) for sh in shard_list]
        return shard_list

    def _init_margins_from_bins(
        self, init_booster, fsharded: bool = False
    ) -> jnp.ndarray:
        """Warm-start margin contribution of ``init_booster`` over a
        STREAMED load: walk the init forest against the binned device matrix
        (raw features never exist), routing on ``split_bin``.

        split_bin routing is only valid against the cuts the forest was
        grown with. Streamed cuts are deterministic in (data, chunking,
        world) — and FROZEN through elastic shrink/grow — so continuation
        and restart on retained cuts always match bitwise; any cut drift is
        gated loudly instead of silently mis-routing every split.

        ``fsharded=True`` walks ``self.bins`` in its 2D ``[N/R, F_pad/C]``
        tile layout (the ``reset_from_booster`` entry point, where the
        feature sharding already happened) via the fsharded walk's
        owner-broadcast bin columns; at ``__init__`` time the walk runs
        pre-sharding over the full-F row layout.
        """
        booster_cuts = np.asarray(init_booster.cuts, np.float32)
        my_cuts = self._stream_cuts_np
        if booster_cuts.shape != my_cuts.shape or not np.array_equal(
            booster_cuts, my_cuts
        ):
            raise NotImplementedError(
                "streamed warm start requires the checkpoint booster's "
                "sketch cuts to equal this load's (same data, same "
                "chunking, same world): re-binned rows cannot ride the "
                "forest's split_bin routing across cut drift. Materialize "
                "the matrix to warm start across worlds/cut changes."
            )
        forest = init_booster.forest
        weights = (
            init_booster.tree_weights
            if init_booster.tree_weights is not None
            else np.ones(forest.feature.shape[0], np.float32)
        )
        t_cap = forest.feature.shape[0]
        k_out = self.n_outputs
        tp = max(1, int(getattr(init_booster.params, "num_parallel_tree", 1)))
        depth = int(init_booster.max_depth)
        missing_bin = self.params.max_bin
        cats = self.cfg.cat_features
        forest_dev = Tree(*[jnp.asarray(f) for f in forest])
        w_dev = jnp.asarray(np.asarray(weights, np.float32))
        # round-major tree layout: tree t -> class (t // tp) % K (the
        # predict_ops.predict_margin mapping)
        cls_onehot = jax.nn.one_hot(
            (jnp.arange(t_cap) // tp) % k_out, k_out, dtype=jnp.float32
        )

        fshard = None
        if fsharded:
            fshard = FeatureShard(
                AXIS_FEATURES, self.feature_parallel, self._f_padded,
                self.n_features,
            )

        def fn(bins):
            def walk(tr):
                if fshard is None:
                    return predict_tree_binned(
                        tr, bins, depth, missing_bin, cat_features=cats
                    )
                return predict_tree_binned_fsharded(
                    tr, bins, depth, missing_bin, fshard, cat_features=cats
                )

            leaf = jax.vmap(walk)(forest_dev)  # [T, S]
            return jnp.einsum(
                "ts,tk->sk", leaf * w_dev[:, None], cls_onehot
            ) / tp

        mapped = shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(
                P(AXIS_ACTORS, AXIS_FEATURES) if fsharded else P(AXIS_ACTORS),
            ),
            out_specs=P(AXIS_ACTORS),
        )
        jit_fn = progreg.register_jit(
            "stream.init_margins",
            mapped,
            example_args=(self.bins,),
            meta=self._program_meta(),
        )
        return jit_fn(self.bins)

    def _bin_with_cuts(self, x_dev):
        max_bin = self.params.max_bin
        jit_fn = progreg.register_jit(
            "engine.bin_matrix",
            lambda x, c: binning.bin_matrix(x, c, max_bin),
            example_args=(x_dev, self.cuts),
            meta=self._program_meta(),
        )
        return jit_fn(x_dev, self.cuts)

    def _feature_shard_bins(self, bins):
        """Feature-pad a [N, F] binned matrix to ``_f_padded`` columns
        (missing bucket) and lay it out over the 2D mesh as
        [N/R, F_pad/C] tiles."""
        f_extra = self._f_padded - bins.shape[1]
        if f_extra:
            bins = jnp.pad(
                bins, ((0, 0), (0, f_extra)),
                constant_values=np.asarray(
                    self.params.max_bin, bins.dtype
                ),
            )
        return jax.device_put(
            bins, NamedSharding(self.mesh, P(AXIS_ACTORS, AXIS_FEATURES))
        )

    def _bins_spec(self):
        """PartitionSpec of every binned matrix (train + eval sets)."""
        if self.feature_parallel > 1:
            return P(AXIS_ACTORS, AXIS_FEATURES)
        return P(AXIS_ACTORS)

    def _build_sharded_groups(self, qid, n_rows=None, pad_to=None):
        """Per-device-block padded group gather maps, stacked + sharded.

        Multi-host: ``qid`` holds only this process's rows, so each process
        builds the gather maps for its own devices' blocks; the padded
        (n_groups, group_size) extents are allgathered so every process
        materializes the same global array shape, then the per-process slabs
        are assembled without cross-host copies via ``put_rows_global``.
        """
        n_rows = self._local_rows if n_rows is None else n_rows
        pad_to = self.pad_to if pad_to is None else pad_to
        if qid is None:
            raise ValueError(f"objective {self.objective.name!r} requires qid")
        pc = jax.process_count()
        block = pad_to // self.n_devices
        local_devices = self.n_devices // pc
        per_dev = []
        for d in range(local_devices):
            lo, hi = d * block, min((d + 1) * block, n_rows)
            if hi <= lo:
                per_dev.append(None)
                continue
            rows, _ = build_group_rows(qid[lo:hi])
            per_dev.append(rows)
        ng = max([r.shape[0] for r in per_dev if r is not None] or [1])
        gsz = max([r.shape[1] for r in per_dev if r is not None] or [1])
        if pc > 1:
            from jax.experimental import multihost_utils

            dims = np.asarray(
                multihost_utils.process_allgather(
                    np.array([ng, gsz], np.int64)
                )
            ).reshape(-1, 2)
            ng, gsz = int(dims[:, 0].max()), int(dims[:, 1].max())
        stacked = np.full((local_devices, ng, gsz), block, np.int32)
        for d, rows in enumerate(per_dev):
            if rows is None:
                continue
            lo = d * block
            hi = min(lo + block, n_rows)
            # sentinel inside build_group_rows is the local segment length
            # (== hi-lo); remap it to `block`, the padded gather slot every
            # shard treats as invalid
            r = np.where(rows == hi - lo, block, rows)
            stacked[d, : rows.shape[0], : rows.shape[1]] = r
        flat = stacked.reshape(local_devices * ng, gsz)

        from xgboost_ray_tpu.distributed import put_rows_global

        return put_rows_global(flat, self._row_sharding)

    def _add_eval_set(self, eval_shards, name, x_id, shards_obj, eval_obj, init_booster):
        is_train = eval_obj is shards_obj
        if is_train:
            es = _EvalSet(name, self.n_rows, self.group_ptr, True)
            es.label_np = self.label_np
            es.weight_np = self.weight_np
            es.lower_np = getattr(self, "lower_np", None)
            es.upper_np = getattr(self, "upper_np", None)
            self.evals.append(es)
            return
        from xgboost_ray_tpu.stream.reader import is_streamed_shards

        # a single-chunk streamed eval set degrades to materialized fields
        # regardless of how the TRAIN set arrived (the same contract as the
        # train-side single-chunk degrade); only genuinely multi-chunk
        # streams hit the gate
        eval_shards = self._materialize_if_single_chunk(eval_shards)
        if is_streamed_shards(eval_shards):
            raise NotImplementedError(
                f"eval set {name!r} is a streamed matrix: streamed "
                f"ingestion is train-set only (eval margins need per-round "
                f"device residency anyway). Materialize eval sets, or "
                f"evaluate on the train set."
            )
        x, label, weight, base_margin, qid, lo, hi = _concat_shards(eval_shards)
        local_rows = x.shape[0]
        n_global, local_pad, pad_to = self._global_row_layout(local_rows)
        es = _EvalSet(
            name,
            n_global,
            None if qid is None else build_group_rows(qid)[1],
            False,
        )
        es.local_rows = local_rows

        from xgboost_ray_tpu.distributed import put_rows_global

        def put_rows(arr, dtype, fill=0):
            arr = np.asarray(arr, dtype=dtype)
            if arr.shape[0] < local_pad:
                pad_width = [(0, local_pad - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad_width, constant_values=fill)
            return put_rows_global(arr, self._row_sharding)

        x_dev = put_rows(x, np.float32, fill=np.nan)
        es.bins = self._bin_with_cuts(x_dev)
        if self.feature_parallel > 1:
            es.bins = self._feature_shard_bins(es.bins)
        if qid is not None:
            es.group_rows_dev = self._build_sharded_groups(
                qid, n_rows=x.shape[0], pad_to=pad_to
            )
        es.valid = put_rows(np.ones(x.shape[0], bool), bool, fill=False)
        es.label = put_rows(label, np.float32)
        es.weight = put_rows(
            weight if weight is not None else np.ones(x.shape[0], np.float32), np.float32
        )
        es.label_np = label if label is not None else lo
        es.weight_np = weight
        es.lower_np = lo if lo is not None else label
        es.upper_np = hi if hi is not None else es.lower_np
        if self.is_survival and es.lower_np is not None:
            es.bounds_dev = (
                put_rows(es.lower_np, np.float32, fill=1.0),
                put_rows(es.upper_np, np.float32, fill=1.0),
            )
        margins_static = np.full(
            (x.shape[0], self.n_outputs), self.base_margin0, np.float32
        )
        if base_margin is not None:
            margins_static = margins_static + base_margin.reshape(
                x.shape[0], -1
            ).astype(np.float32)
        margins0 = margins_static
        if init_booster is not None and init_booster.num_trees:
            margins0 = margins0 + (
                init_booster.predict_margin_np(x) - init_booster.base_score_margin_np()
            )
        es.margins = put_rows(margins0, np.float32)
        if getattr(self, "dart", False):
            es.margins_static = put_rows(margins_static, np.float32)
        del x_dev
        self.evals.append(es)

    # ------------------------------------------------------------------
    def _round_closures(self, update_evals: bool = True):
        """The shared traced round body used by the per-round step, the
        lax.scan multi-round path, and the dart step — one definition so
        sampling/tree semantics cannot diverge between compiled programs.
        ``update_evals=False`` skips incremental eval-margin updates (dart
        recomputes margins from tree weights instead)."""
        cfg = self.cfg
        params = self.params
        k_out = self.n_outputs
        t_par = params.num_parallel_tree
        obj = self.objective
        is_ranking = self.is_ranking
        missing_bin = params.max_bin
        dev_metrics = list(self._device_metrics)
        n_evals_dev = (
            sum(1 for e in self.evals if not e.is_train) if update_evals else 0
        )
        psum = lambda x: jax.lax.psum(x, AXIS_ACTORS)
        n_actors = self.n_devices

        is_survival = self.is_survival

        # feature-parallel context (trace-time constants; fp_c == 1 takes
        # every legacy branch below, tracing the exact 1D program)
        fp_c = self.feature_parallel
        n_feat_real = self.n_features
        f_padded = self._f_padded
        cuts_grow = self._cuts_grow
        fhm_grow = self._fhm_grow

        # row sampling (ops/sampling.py): None when off — the None path
        # traces the exact pre-sampling program, so default params stay
        # bit-identical to builds that predate the compaction machinery
        samp_spec = sampling.spec_from_params(params)
        if samp_spec is None and \
                getattr(self, "_vk_spec_override", None) is not None:
            # vmapped-K where max(lane subsample) == 1.0 but some lane
            # samples: the base params alone say "sampling off", yet the
            # lanes need the budget-mask machinery — trace the full-budget
            # uniform spec and let per-lane budgets cut it down
            samp_spec = self._vk_spec_override

        # quantize_gh's int32-overflow bound: the global padded row count
        # (trace-time constant; padding rows carry exactly-zero gh but the
        # bound stays safe either way)
        gh_max_rows = int(self.pad_to)

        def tree_round(bins, valid, label, weight, margins, group_rows, gh_in,
                       rng, bounds, eval_bins, eval_margins, lane=None):
            """One boosting round; gh_in is None unless a custom objective
            supplied precomputed gradients. Also returns the round's
            measured tree-path allreduce payload bytes (AllreduceBytes).

            ``lane`` (vmapped-K only) is a dict of TRACED per-lane scalars:
            the lane-vectorizable split params, plus optionally
            ``depth_limit`` (level mask) and ``budget`` (sampling slot
            mask). ``None`` traces the exact scalar program."""
            # fresh per trace: counts the ring-model wire bytes of every
            # tree-path allreduce (histograms + small exact reductions)
            counter = AllreduceBytes(n_actors)
            cfg_t = cfg
            depth_limit = lane_budget = None
            if lane is not None:
                # the growers consume SplitParams arithmetically, so a
                # tracer-carrying replace works; max_delta_step stays the
                # static base value (leaf_weight branches on it in Python)
                cfg_t = dataclasses.replace(
                    cfg,
                    split=dataclasses.replace(
                        cfg.split,
                        learning_rate=lane["learning_rate"],
                        reg_lambda=lane["reg_lambda"],
                        reg_alpha=lane["reg_alpha"],
                        gamma=lane["gamma"],
                        min_child_weight=lane["min_child_weight"],
                    ),
                )
                depth_limit = lane.get("depth_limit")
                lane_budget = lane.get("budget")
            tree_psum = counting_psum(AXIS_ACTORS, counter)
            fshard = None
            counter_f = None
            if fp_c > 1:
                # the feature axis carries only the tiny election gather,
                # the node-total broadcast and the [N] bin-column psums —
                # counted with its own ring extent C
                counter_f = AllreduceBytes(fp_c)
                fshard = FeatureShard(
                    AXIS_FEATURES, fp_c, f_padded, n_feat_real,
                    counter=counter_f,
                )

            def walk(tree_, bins_):
                """Once-per-tree margin walk over a (possibly
                feature-sharded) binned matrix."""
                if fshard is None:
                    return predict_tree_binned(
                        tree_, bins_, cfg.max_depth, missing_bin,
                        cat_features=cfg.cat_features,
                    )
                return predict_tree_binned_fsharded(
                    tree_, bins_, cfg.max_depth, missing_bin, fshard,
                    cat_features=cfg.cat_features,
                )

            def hist_ar(h):
                return quantized_hist_allreduce(
                    h, AXIS_ACTORS, cfg.hist_quant, n_actors, counter,
                    min_bytes=cfg.hist_quant_min_bytes,
                    block=cfg.hist_quant_block,
                )

            w_eff = weight * valid.astype(jnp.float32)
            if gh_in is not None:
                g, h = gh_in
            elif is_ranking:
                g, h = obj.grad_hess_ranked(margins, label, w_eff, group_rows)
            elif is_survival:
                g, h = obj.grad_hess_bounds(margins, bounds[0], bounds[1], w_eff)
            else:
                g, h = obj.grad_hess(margins, label, w_eff)
            new_margins = margins
            new_eval_margins = list(eval_margins)
            trees = []
            for k in range(k_out):
                for t in range(t_par):
                    key = jax.random.fold_in(rng, k * t_par + t)
                    ghk = jnp.stack([g[:, k], h[:, k]], axis=1)
                    ghk_scale = None
                    if cfg.gh_precision != "float32":
                        # quantize g/h AT THE SOURCE (per-tree pmax-shared
                        # scales, stochastic rounding): the narrow buffer is
                        # what compaction gathers and the histogram
                        # accumulates. The SR key folds SALT_SR per (seed,
                        # iteration, tree, actor) — deterministic reruns,
                        # and identical on every feature shard of a 2D mesh
                        # (rows replicate across AXIS_FEATURES).
                        srkey = jax.random.fold_in(
                            jax.random.fold_in(key, SALT_SR),
                            jax.lax.axis_index(AXIS_ACTORS),
                        )
                        ghk, ghk_scale = quantize_gh(
                            ghk, cfg.gh_precision, srkey,
                            axis_name=AXIS_ACTORS, counter=counter,
                            max_rows=gh_max_rows,
                        )
                    bins_t = bins
                    if samp_spec is not None:
                        # compact the round's rows to the fixed M-row budget
                        # so EVERY level's histogram build / partition update
                        # runs over M rows, not N (the tree walk below is
                        # then the only full-row work per tree). Per-actor
                        # key fold: same stream structure as the old
                        # Bernoulli mask, so selections are deterministic in
                        # (seed, iteration, actor) and replay identically
                        # after a checkpoint resume.
                        salt = (
                            SALT_GOSS
                            if samp_spec.policy == "gradient_based"
                            else SALT_SUBSAMPLE
                        )
                        skey = jax.random.fold_in(
                            jax.random.fold_in(key, salt),
                            jax.lax.axis_index(AXIS_ACTORS),
                        )
                        rows_sel, ghk = sampling.sample_rows(
                            ghk, valid, skey, samp_spec, scale=ghk_scale,
                            lane_budget=lane_budget,
                        )
                        bins_t = bins[rows_sel]
                    fmask = None
                    if params.colsample_bytree < 1.0:
                        fkey = jax.random.fold_in(key, SALT_BYTREE)
                        # drawn over the REAL global feature count (same
                        # stream/semantics on every mesh shape), padded out
                        # to the sharded layout's width when 2D
                        fmask = sample_feature_mask(
                            fkey, n_feat_real, params.colsample_bytree,
                            self._log_fw,
                        )
                        if fshard is not None and f_padded != n_feat_real:
                            fmask = jnp.pad(
                                fmask, (0, f_padded - n_feat_real)
                            )
                    need_level_rng = (
                        params.colsample_bylevel < 1.0
                        or params.colsample_bynode < 1.0
                    )
                    tree, row_value = build_tree(
                        bins_t,
                        ghk,
                        cuts_grow,
                        cfg_t,
                        depth_limit=depth_limit,
                        feature_mask=fmask,
                        level_rng=key if need_level_rng else None,
                        colsample_bylevel=params.colsample_bylevel,
                        colsample_bynode=params.colsample_bynode,
                        allreduce=tree_psum,
                        feature_log_weights=self._log_fw,
                        feat_has_missing=fhm_grow,
                        hist_allreduce=hist_ar,
                        ar_counter=counter,
                        fshard=fshard,
                        # GOSS compaction dequantizes its small [M, 2]
                        # buffer (amplification is real-valued); the grower
                        # then takes the f32 path over quantized-grid values
                        gh_scale=(
                            ghk_scale
                            if ghk_scale is not None
                            and jnp.issubdtype(ghk.dtype, jnp.integer)
                            else None
                        ),
                    )
                    trees.append(tree)
                    if samp_spec is not None:
                        # the compacted build only knows the sampled rows'
                        # leaf values; ALL rows need their margin update (the
                        # next round's gradients cover every row), so walk
                        # the finished tree over the full binned matrix —
                        # the same once-per-tree device walk eval sets use.
                        row_value = walk(tree, bins)
                    new_margins = new_margins.at[:, k].add(row_value / t_par)
                    for e in range(n_evals_dev):
                        upd = walk(tree, eval_bins[e])
                        new_eval_margins[e] = (
                            new_eval_margins[e].at[:, k].add(upd / t_par)
                        )
            forest = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
            # total per-chip wire bytes of the round: actors-axis traffic
            # (histogram merges + exact reductions) plus, on a 2D mesh, the
            # feature-axis election/broadcast traffic
            counter.absorb(counter_f)
            return (new_margins, tuple(new_eval_margins), forest,
                    counter.as_scalar())

        def metric_contribs(new_margins, new_eval_margins, label, w_eff,
                            train_group_rows, eval_data, bounds=None):
            """Post-update psum'd (num, den) pairs per eval set x metric."""
            contribs = []
            ei = 0
            for es in self.evals:
                if es.is_train:
                    m, lab, w = new_margins, label, w_eff
                    gr, bnd = train_group_rows, bounds
                else:
                    ed = eval_data[ei]
                    m, lab, w = (
                        new_eval_margins[ei],
                        ed.label,
                        ed.weight * ed.valid.astype(jnp.float32),
                    )
                    gr, bnd = ed.group_rows, ed.bounds
                    ei += 1
                set_contribs = []
                for name in dev_metrics:
                    set_contribs.append(
                        device_metric_contrib(
                            name, m, lab, w, gr, psum,
                            huber_slope=params.huber_slope,
                            quantile_alpha=tuple(
                                params.quantile_alpha
                                if isinstance(params.quantile_alpha, (list, tuple))
                                else [params.quantile_alpha]
                            ),
                            bounds=bnd,
                            aft_distribution=params.aft_loss_distribution,
                            aft_sigma=params.aft_loss_distribution_scale,
                        )
                    )
                contribs.append(tuple(set_contribs))
            return tuple(contribs)

        return tree_round, metric_contribs

    def _eval_arrs(self) -> tuple:
        """Non-train eval sets as _EvalArrs (scalar placeholders for absent
        members so the pytree structure is static across programs)."""
        out = []
        for es in self.evals:
            if es.is_train:
                continue
            out.append(_EvalArrs(
                es.bins, es.label, es.weight, es.valid, es.margins,
                es.group_rows_dev
                if es.group_rows_dev is not None
                else jnp.zeros((), jnp.int32),
                es.margins_static
                if es.margins_static is not None
                else jnp.zeros((), jnp.float32),
                es.bounds_dev
                if es.bounds_dev is not None
                else jnp.zeros((), jnp.float32),
            ))
        return tuple(out)

    def _eval_arr_specs(self) -> tuple:
        # vmapped-K: eval margins carry a leading (replicated) lane axis;
        # every other eval member is lane-shared
        m_spec = P(None, AXIS_ACTORS) if self._vk else P(AXIS_ACTORS)
        specs = []
        for es in self.evals:
            if es.is_train:
                continue
            specs.append(_EvalArrs(
                self._bins_spec(), P(AXIS_ACTORS), P(AXIS_ACTORS), P(AXIS_ACTORS), m_spec,
                P(AXIS_ACTORS) if es.group_rows_dev is not None else P(),
                P(AXIS_ACTORS) if es.margins_static is not None else P(),
                (P(AXIS_ACTORS), P(AXIS_ACTORS)) if es.bounds_dev is not None else P(),
            ))
        return tuple(specs)

    # ------------------------------------------------------------------
    # Program registry (tools/rxgbverify): abstract signatures of every
    # compiled program, so the verifier can re-trace them without running.
    # ------------------------------------------------------------------
    def _program_meta(self) -> Dict[str, Any]:
        """Config coordinates the jaxpr verifier groups programs by. The
        cross-world schedule-identity check compares records that agree on
        everything here except ``world``."""
        samp = sampling.spec_from_params(self.params)
        if samp is None and \
                getattr(self, "_vk_spec_override", None) is not None:
            samp = self._vk_spec_override
        # derived from params, not self.dart: the sketch program registers
        # during __init__ before the dart attribute exists
        is_dart = self.params.booster == "dart"
        meta = {
            "world": int(self.n_devices),
            "grower": "dart" if is_dart else self.params.grow_policy,
            "hist_quant": self.cfg.hist_quant,
            # block-scale wire granularity: a different block size traces a
            # different ring payload layout, so it is part of the identity
            "hist_quant_block": int(self.cfg.hist_quant_block),
            # on-chip gh precision: int8/int16 programs trace integer
            # accumulation + int32 (or quantized) histogram wires — a
            # legitimately different schedule from float32, so it is an
            # identity-group coordinate (and VER004's precision-flow key)
            "gh_precision": str(self.cfg.gh_precision),
            "sampling": samp.policy if samp is not None else "none",
            # feature-axis mesh extent: (R, C) programs are legitimately
            # different from (R, 1) ones and must not share a cross-world
            # identity group; 2D programs group with each other across R
            "feature_parallel": int(self.feature_parallel),
            "n_outputs": int(self.n_outputs),
            # program-shape coordinates: two engines differing here trace
            # legitimately different programs and must not share a
            # cross-world identity group
            "max_depth": int(self.cfg.max_depth),
            "max_leaves": int(self.cfg.max_leaves),
            # ingestion mode: like "world", a WITHIN-group variant axis —
            # rxgbverify's VER001 requires streamed and materialized
            # programs of one config to execute the identical collective
            # schedule (the streamed sketch merge must not change any
            # round-step program)
            "ingest": "streamed" if getattr(self, "_streamed", False)
            else "materialized",
        }
        if getattr(self, "_vk", 0):
            # candidate-lane extent: a K-lane program's collectives carry a
            # leading lane axis (rank grows by one, schedule identical), so
            # K is a program-shape coordinate — k=2 and k=4 must not share
            # a cross-world identity group
            meta["k"] = int(self._vk)
        return meta

    def _default_group_rows(self):
        """The ``group_rows`` dispatch argument (scalar sentinel when the
        data is ungrouped) — shared by the real dispatch sites and the
        ``*_example_args`` signature capture, so the registered abstract
        program cannot drift from the dispatched one."""
        if self.group_rows is not None:
            return self.group_rows
        return jnp.zeros((), jnp.int32)

    def _default_bounds(self):
        """The label-bounds dispatch argument (scalar sentinel when not
        survival training) — shared like :meth:`_default_group_rows`."""
        if self.bounds_dev is not None:
            return self.bounds_dev
        return jnp.zeros((), jnp.float32)

    def _step_example_args(self, custom: bool) -> tuple:
        """The ``step()`` call site's argument tuple, for signature capture.
        Must mirror :meth:`step` exactly — the registered abstract trace IS
        the program the verifier certifies."""
        group_rows = self._default_group_rows()
        gh_in = (
            (self.margins, self.margins) if custom
            else jnp.zeros((), jnp.float32)
        )
        bounds = self._default_bounds()
        rng = jax.random.PRNGKey(self.params.seed)
        return (self.bins, self.valid, self.label_dev, self.weight_dev,
                self.margins, group_rows, gh_in, rng, bounds,
                self._eval_arrs())

    def _scan_example_args(self) -> tuple:
        """``step_many``'s signature at a representative 2-round chunk (the
        collective schedule inside the scan body is chunk-length blind)."""
        group_rows = self._default_group_rows()
        bounds = self._default_bounds()
        return (self.bins, self.valid, self.label_dev, self.weight_dev,
                self.margins, group_rows, jnp.arange(2), bounds,
                self._eval_arrs())

    def _dart_example_args(self) -> tuple:
        group_rows = self._default_group_rows()
        bounds = self._default_bounds()
        return (self.bins, self.valid, self.label_dev, self.weight_dev,
                self._margins_static_dev, group_rows, bounds,
                self.dart_forest_dev, jnp.asarray(self.dart_weights),
                jnp.asarray(self.dart_weights), jnp.float32(1.0),
                jnp.int32(0), jax.random.PRNGKey(self.params.seed),
                self._eval_arrs())

    def build_programs(self) -> None:
        """Force-build every round program this engine configuration can
        dispatch (without compiling or executing any of them — ``jax.jit``
        is lazy). Under :func:`progreg.capture` this is how the verifier
        populates the registry for a config without running a round."""
        if self._vk:
            if self._vk not in self._vk_fns:
                self._vk_fns[self._vk] = self._make_vmapped_step(self._vk)
            return
        if self.dart:
            if self._dart_fn is None:
                self._dart_fn = self._make_dart_step()
            return
        if self._step_fn is None:
            self._step_fn = self._make_step(custom=False)
        if self._step_fn_custom is None:
            # the custom-objective variant dispatches the same collectives
            # from externally-supplied g/h; it must be certified too (a
            # user's obj callback can reach every grower/hist_quant config)
            self._step_fn_custom = self._make_step(custom=True)
        if self.can_batch_rounds() and self._scan_fn is None:
            self._scan_fn = self._make_scan_step()

    def _make_step(self, custom: bool):
        tree_round, metric_contribs = self._round_closures()

        def step(bins, valid, label, weight, margins, group_rows, gh_in, rng,
                 bounds, eval_data):
            eval_bins = tuple(d.bins for d in eval_data)
            eval_margins = tuple(d.margins for d in eval_data)
            new_margins, new_eval_margins, forest, ar_bytes = tree_round(
                bins, valid, label, weight, margins, group_rows,
                gh_in if custom else None, rng, bounds, eval_bins, eval_margins,
            )
            contribs = metric_contribs(
                new_margins, new_eval_margins, label,
                weight * valid.astype(jnp.float32), group_rows, eval_data,
                bounds=bounds,
            )
            return new_margins, new_eval_margins, forest, contribs, ar_bytes

        eval_specs = self._eval_arr_specs()
        mapped = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(
                self._bins_spec(),  # bins
                P(AXIS_ACTORS),  # valid
                P(AXIS_ACTORS),  # label
                P(AXIS_ACTORS),  # weight
                P(AXIS_ACTORS),  # margins
                P(AXIS_ACTORS) if self.group_rows is not None else P(),
                (P(AXIS_ACTORS), P(AXIS_ACTORS)) if custom else P(),
                P(),  # rng
                (P(AXIS_ACTORS), P(AXIS_ACTORS)) if self.bounds_dev is not None else P(),
                eval_specs,
            ),
            out_specs=(
                P(AXIS_ACTORS),
                tuple(P(AXIS_ACTORS) for _ in eval_specs),
                P(),
                tuple(
                    tuple((P(), P()) for _ in self._device_metrics)
                    for _ in self.evals
                ),
                P(),  # allreduce payload bytes (identical on every shard)
            ),
        )
        return progreg.register_jit(
            "engine.step_custom" if custom else "engine.step",
            mapped,
            donate_argnums=(4,),
            example_args=lambda: self._step_example_args(custom),
            meta=self._program_meta(),
        )

    # ------------------------------------------------------------------
    def _make_scan_step(self):
        """Multi-round variant: lax.scan over the round body inside one
        shard_map program. Removes per-round host dispatch — the TPU analog
        of the reference keeping its hot loop inside ``xgb.train``
        (``xgboost_ray/main.py:745-752``) instead of stepping from Python.
        Only built when no per-round host interaction is needed (no custom
        objective, no host-side metrics)."""
        tree_round, metric_contribs = self._round_closures()
        seed_key = jax.random.PRNGKey(self.params.seed)

        def run(bins, valid, label, weight, margins, group_rows, iterations,
                bounds, eval_data):
            eval_bins = tuple(d.bins for d in eval_data)
            eval_margins0 = tuple(d.margins for d in eval_data)

            def scan_body(carry, iteration):
                margins_c, eval_margins_c = carry
                rng = jax.random.fold_in(seed_key, iteration)
                new_margins, new_eval_margins, forest, ar_bytes = tree_round(
                    bins, valid, label, weight, margins_c, group_rows, None,
                    rng, bounds, eval_bins, eval_margins_c,
                )
                contribs = metric_contribs(
                    new_margins, new_eval_margins, label,
                    weight * valid.astype(jnp.float32), group_rows, eval_data,
                    bounds=bounds,
                )
                return (new_margins, new_eval_margins), (forest, contribs, ar_bytes)

            (margins_out, eval_margins_out), (forests, contribs, ar_bytes) = (
                jax.lax.scan(scan_body, (margins, eval_margins0), iterations)
            )
            return margins_out, eval_margins_out, forests, contribs, ar_bytes

        eval_specs = self._eval_arr_specs()
        mapped = shard_map(
            run,
            mesh=self.mesh,
            in_specs=(
                self._bins_spec(),
                P(AXIS_ACTORS),
                P(AXIS_ACTORS),
                P(AXIS_ACTORS),
                P(AXIS_ACTORS),
                P(AXIS_ACTORS) if self.group_rows is not None else P(),
                P(),  # iterations
                (P(AXIS_ACTORS), P(AXIS_ACTORS)) if self.bounds_dev is not None else P(),
                eval_specs,
            ),
            out_specs=(
                P(AXIS_ACTORS),
                tuple(P(AXIS_ACTORS) for _ in eval_specs),
                P(),
                tuple(tuple((P(), P()) for _ in self._device_metrics) for _ in self.evals),
                P(),  # per-round allreduce payload bytes [n_rounds]
            ),
        )
        return progreg.register_jit(
            "engine.step_many",
            mapped,
            donate_argnums=(4,),
            example_args=self._scan_example_args,
            meta=self._program_meta(),
        )

    def can_batch_rounds(self) -> bool:
        return not self._host_metrics and not self.dart

    def _emit_round_spans(self, ts, t0, round0: int, n_rounds: int = 1) -> None:
        """Record per-round spans on the current tracer, fenced by the same
        host-side sync the step paths already perform (no extra device round
        trips). Fused-scan chunks amortize the chunk duration evenly and mark
        each span with ``fused_chunk`` so consumers know the granularity."""
        tracer = obs.get_tracer()
        if not tracer.enabled:
            return
        dur = (time.perf_counter() - t0) / max(n_rounds, 1)
        attrs = self._obs_round_attrs
        if n_rounds > 1:
            attrs = dict(attrs, fused_chunk=n_rounds)
        for r in range(n_rounds):
            tracer.add_span(
                "round", ts + r * dur, dur, round=round0 + r, attrs=attrs
            )

    def step_many(self, iteration0: int, n_rounds: int) -> List[Dict[str, Dict[str, float]]]:
        """Run ``n_rounds`` boosting rounds in one compiled program.

        Returns the per-round metrics list (same schema as ``step``).
        Programs are cached per n_rounds; callers should use a fixed chunk
        size (the driver uses ENV.SCAN_MAX_CHUNK, clamped to checkpoint
        boundaries) to avoid recompiles.
        """
        if self._vk:
            raise RuntimeError(
                "engine is in vmapped-K mode; use step_vmapped()"
            )
        if not self.can_batch_rounds():
            raise RuntimeError("host-side metrics require per-round stepping")
        span_ts, span_t0 = time.time(), time.perf_counter()
        if self._scan_fn is None:
            self._scan_fn = self._make_scan_step()
        iterations = jnp.arange(
            self.iteration_offset + iteration0,
            self.iteration_offset + iteration0 + n_rounds,
        )
        eval_data = self._eval_arrs()
        group_rows = self._default_group_rows()
        bounds = self._default_bounds()
        # the scan program compiles once per distinct chunk length; the
        # strict guard arms only for chunk lengths already dispatched
        prog = ("scan", n_rounds)
        with strict_transfer_guard(active=prog in self._warm_programs):
            new_margins, new_eval_margins, forests, contribs, ar_bytes = self._scan_fn(
                self.bins,
                self.valid,
                self.label_dev,
                self.weight_dev,
                self.margins,
                group_rows,
                iterations,
                bounds,
                eval_data,
            )
        self._warm_programs.add(prog)
        # keep the device scalar; materialized lazily by the accessor so the
        # steady-state step path adds NO host reads (transfer-count contract)
        self._ar_bytes_dev = ar_bytes[0]
        self.margins = new_margins
        ei = 0
        for es in self.evals:
            if not es.is_train:
                es.margins = new_eval_margins[ei]
                ei += 1
        # defer forest transfer: keep the whole stacked chunk on device
        # (order-safe alongside per-round step()s) and materialize it in ONE
        # batched read per Tree field at the next checkpoint/get_booster —
        # under the tunneled relay every host read costs ~70-90 ms, so the
        # previous eager 9-field read per chunk was ~0.07 s/round of latency
        self._trees_dev.append((forests, n_rounds))

        # metrics: one stacked transfer for ALL (num, den) scalars of the
        # whole chunk instead of a device read per (eval, metric, row)
        flat_scalars = [
            c
            for si in range(len(self.evals))
            for mi in range(len(self._device_metrics))
            for c in contribs[si][mi]
        ]
        if flat_scalars:
            flat_vals = np.asarray(jnp.stack(flat_scalars))
        else:
            flat_vals = np.zeros((0, n_rounds))
            # with no eval sets, the metric read above is skipped and (with
            # forest transfer deferred) nothing else syncs — force one tiny
            # host read so returning means "chunk computed", keeping
            # round_times_s and the overhead ablation honest (under the
            # tunneled relay block_until_ready does not reliably block)
            shard0 = new_margins.addressable_shards[0].data
            np.asarray(shard0[:1, :1])
        self._emit_round_spans(
            span_ts, span_t0, self.iteration_offset + iteration0, n_rounds
        )
        results: List[Dict[str, Dict[str, float]]] = []
        for r in range(n_rounds):
            round_res: Dict[str, Dict[str, float]] = {}
            fi = 0
            for si, es in enumerate(self.evals):
                row: Dict[str, float] = {}
                for mi, name in enumerate(self._device_metrics):
                    num = float(flat_vals[fi][r])
                    den = float(flat_vals[fi + 1][r])
                    fi += 2
                    val = num / max(den, 1e-12)
                    base, _ = parse_metric_name(name)
                    row[name] = float(np.sqrt(val)) if base in ("rmse", "rmsle") else val
                round_res[es.name] = row
            results.append(round_res)
        return results

    def step(self, iteration: int, gh_custom=None) -> Dict[str, Dict[str, float]]:
        """Run one boosting round; returns {eval_name: {metric: value}}."""
        if self._vk:
            raise RuntimeError(
                "engine is in vmapped-K mode; use step_vmapped()"
            )
        if self.dart:
            if gh_custom is not None:
                raise ValueError("custom objectives are not supported with dart")
            return self.step_dart(iteration)
        span_ts, span_t0 = time.time(), time.perf_counter()
        custom = gh_custom is not None
        if custom:
            if self._step_fn_custom is None:
                self._step_fn_custom = self._make_step(custom=True)
            fn = self._step_fn_custom
        else:
            if self._step_fn is None:
                self._step_fn = self._make_step(custom=False)
            fn = self._step_fn
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.params.seed), self.iteration_offset + iteration
        )
        eval_data = self._eval_arrs()
        group_rows = self._default_group_rows()
        if custom:
            # g/h hold THIS process's rows (the driver computes the custom
            # objective from get_margins_local + process-local labels — the
            # reference's per-actor local computation, ``main.py:745-752``);
            # _put_rows assembles them into the global sharded layout.
            g, h = gh_custom
            gh_in = (
                self._put_rows(
                    np.asarray(g, np.float32).reshape(self._local_rows, -1),
                    np.float32,
                ),
                self._put_rows(
                    np.asarray(h, np.float32).reshape(self._local_rows, -1),
                    np.float32,
                ),
            )
        else:
            gh_in = jnp.zeros((), jnp.float32)
        bounds = self._default_bounds()
        prog = "step_custom" if custom else "step"
        with strict_transfer_guard(active=prog in self._warm_programs):
            new_margins, new_eval_margins, forest, contribs, ar_bytes = fn(
                self.bins,
                self.valid,
                self.label_dev,
                self.weight_dev,
                self.margins,
                group_rows,
                gh_in,
                rng,
                bounds,
                eval_data,
            )
        self._warm_programs.add(prog)
        self._ar_bytes_dev = ar_bytes
        self.margins = new_margins
        ei = 0
        for es in self.evals:
            if not es.is_train:
                es.margins = new_eval_margins[ei]
                ei += 1
        self._trees_dev.append((forest, None))

        # metrics: one stacked transfer for all (num, den) scalars instead of
        # a blocking host read per scalar (each read is a relay round trip)
        flat_scalars = [
            c
            for si in range(len(self.evals))
            for mi in range(len(self._device_metrics))
            for c in contribs[si][mi]
        ]
        flat_vals = (
            np.asarray(jnp.stack(flat_scalars)) if flat_scalars else np.zeros(0)
        )
        results: Dict[str, Dict[str, float]] = {}
        fi = 0
        for si, es in enumerate(self.evals):
            row: Dict[str, float] = {}
            for mi, name in enumerate(self._device_metrics):
                num, den = float(flat_vals[fi]), float(flat_vals[fi + 1])
                fi += 2
                val = num / max(den, 1e-12)
                base, _ = parse_metric_name(name)
                row[name] = float(np.sqrt(val)) if base in ("rmse", "rmsle") else val
            if self._host_metrics:
                margin = self.get_margins_local(es)
                for name in self._host_metrics:
                    row[name] = self.combine_host_scalar(
                        self._host_metric_value(name, margin, es), es,
                        metric=name,
                    )
            results[es.name] = row
        self._emit_round_spans(
            span_ts, span_t0, self.iteration_offset + iteration
        )
        return results

    def _host_metric_value(self, name: str, margin: np.ndarray, es) -> float:
        """One host-side metric value, including the aft-nloglik special case
        (which consumes label *bounds* rather than labels). Shared by the
        regular ``step()`` and the dart ``step_dart()`` results paths."""
        if name == "aft-nloglik":
            from xgboost_ray_tpu.ops import survival as survival_mod

            return survival_mod.aft_nloglik_np(
                margin,
                es.lower_np if es.lower_np is not None else self.lower_np,
                es.upper_np if es.upper_np is not None else self.upper_np,
                es.weight_np,
                distribution=self.params.aft_loss_distribution,
                sigma=self.params.aft_loss_distribution_scale,
            )
        return compute_metric(
            name,
            margin,
            es.label_np if es.label_np is not None else self.label_np,
            es.weight_np,
            group_ptr=es.group_ptr,
            huber_slope=self.params.huber_slope,
            quantile_alpha=self.params.quantile_alpha,
        )

    def get_margins(self, es: Optional[_EvalSet] = None) -> np.ndarray:
        """Gather (unpadded) margins for the train set or an eval set.

        Works on multi-host meshes: non-addressable sharded margins are
        allgathered before the padding rows are dropped.
        """
        if es is None or es.is_train:
            return self._fetch_rows(self.margins, self.valid, self.n_rows)
        return self._fetch_rows(es.margins, es.valid, es.n_rows)

    def get_margins_local(self, es: Optional[_EvalSet] = None) -> np.ndarray:
        """This process's rows' (unpadded) margins — the per-actor local view
        the reference computes custom obj/feval on (``main.py:745-752``).
        Pairs with the process-local ``label_np``/``weight_np`` arrays.
        Single-host this IS the global view."""
        if jax.process_count() == 1:
            return self.get_margins(es)
        if es is None or es.is_train:
            arr, local_n = self.margins, self._local_rows
        else:
            arr, local_n = es.margins, es.local_rows
        shards = sorted(
            arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        slab = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        return slab[:local_n]

    def combine_host_scalar(
        self, value: float, es: Optional[_EvalSet] = None,
        metric: Optional[str] = None,
    ) -> float:
        """Combine a process-locally computed scalar metric into the global
        value: weighted mean across processes. The weight matches the
        metric's own averaging unit — GROUP count for per-group metrics
        (ndcg/map/pre are means over query groups), otherwise weight sum
        (weighted eval set) or row count. Identity on single-host meshes.
        Deterministic and identical on every process (allgather-based), so
        evals_result stays replica-consistent."""
        if jax.process_count() == 1:
            return float(value)
        from jax.experimental import multihost_utils

        base = parse_metric_name(metric)[0] if metric else None
        if base in ("ndcg", "map", "pre") and es is not None and es.group_ptr is not None:
            wt = float(len(es.group_ptr) - 1)
        elif es is not None and es.weight_np is not None:
            wt = float(np.sum(es.weight_np))
        elif es is not None and es.label_np is not None:
            wt = float(len(es.label_np))
        else:
            wt = float(self._local_rows)
        arr = np.asarray(
            multihost_utils.process_allgather(
                np.array([float(value) * wt, wt], np.float64)
            )
        ).reshape(-1, 2).sum(axis=0)
        return float(arr[0] / max(arr[1], 1e-12))

    def _stacked_forest(self) -> Tree:
        """Stacked [T, heap] forest with incremental appends: only rounds added
        since the last call are copied into capacity-doubling buffers, so T/k
        checkpoints over T rounds cost O(T) total tree copies, not O(T^2)."""
        self._flush_trees()
        all_trees = self._init_trees + self.trees
        if not all_trees:
            raise ValueError("empty forest")
        if self._stack_entries == len(all_trees):
            return Tree(*[f[: self._stack_rows] for f in self._stack_buf])
        add = stack_trees(all_trees[self._stack_entries :])
        rows = add.feature.shape[0]
        need = self._stack_rows + rows
        if self._stack_buf is None or need > self._stack_buf.feature.shape[0]:
            cap = max(need, 2 * (self._stack_buf.feature.shape[0] if self._stack_buf is not None else 0))
            grown = []
            for i, f in enumerate(add):
                buf = np.empty((cap,) + f.shape[1:], f.dtype)
                if self._stack_rows:
                    buf[: self._stack_rows] = self._stack_buf[i][: self._stack_rows]
                grown.append(buf)
            self._stack_buf = Tree(*grown)
        for i, f in enumerate(add):
            self._stack_buf[i][self._stack_rows : need] = f
        self._stack_rows = need
        self._stack_entries = len(all_trees)
        return Tree(*[f[: self._stack_rows] for f in self._stack_buf])

    def _flush_trees(self) -> None:
        """Transfer pending device forests to host with batched reads.

        Entries are ``(tree, None)`` for one round (per-round step paths) or
        ``(stacked_tree, n_rounds)`` for a whole scan chunk. ALL pending
        entries are concatenated on device first (per-round trees expand to a
        length-1 leading axis; forest shapes are constant within a run), so a
        flush costs exactly one host read per Tree field no matter how many
        rounds or chunks are pending — one round trip per field under the
        tunneled relay."""
        entries = self._trees_dev
        if not entries:
            return
        total = sum(1 if n is None else n for _, n in entries)
        if len(entries) == 1 and entries[0][1] is None:
            self.trees.append(jax.tree.map(np.asarray, entries[0][0]))
            self._trees_dev.clear()
            return
        expanded = [
            jax.tree.map(lambda a: a[None], t) if n is None else t
            for t, n in entries
        ]
        stacked = jax.tree.map(
            lambda *xs: np.asarray(jnp.concatenate(xs, axis=0)), *expanded
        )
        for r in range(total):
            self.trees.append(jax.tree.map(lambda a, _r=r: a[_r], stacked))
        self._trees_dev.clear()

    def hist_allreduce_bytes_per_round(self) -> Optional[int]:
        """Measured collective payload bytes of one boosting round's tree
        path (histogram merges + small exact reductions), from the
        device-side counter threaded through the compiled step. ``None``
        before the first round. This is the ``hist_quant`` traffic metric:
        int8 cuts it ~4x vs the f32 psum. Reading it costs one device->host
        transfer, so callers (bench/driver) fetch it once after training,
        never per round."""
        if self._ar_bytes_dev is None:
            return None
        return int(np.asarray(self._ar_bytes_dev))

    def gh_plane_bytes_per_shard(self) -> int:
        """Static per-shard bytes of one tree's (grad, hess) plane — the
        buffer the objective kernel emits, compaction gathers from, and the
        histogram accumulates: ``rows_per_shard * 2 * itemsize`` of the
        ``gh_precision`` storage dtype. This is the memory metric the
        quantized-gradient mode is bought for (int8 = 4x smaller shards per
        chip); rxgbverify's VER004 pass certifies the traced programs really
        carry this dtype into the accumulation."""
        n_local = self.pad_to // self.n_devices
        return n_local * 2 * gh_plane_itemsize(self.cfg.gh_precision)

    @property
    def num_round_trees(self) -> int:
        """Rounds recorded so far (host-resident + pending device forests)."""
        return len(self.trees) + sum(
            1 if n is None else n for _, n in self._trees_dev
        )

    def get_booster(self) -> RayXGBoostBooster:
        if self._vk:
            raise RuntimeError(
                "engine is in vmapped-K mode; use get_booster_lane(lane)"
            )
        forest = self._stacked_forest()
        tree_weights = None
        if self.dart:
            tree_weights = self.dart_weights[: self.dart_t].copy()
        booster = RayXGBoostBooster(
            forest,
            np.asarray(self.cuts),
            self.params,
            self.base_score,
            feature_names=self.feature_names,
            feature_types=self.feature_types,
            tree_weights=tree_weights,
        )
        booster._has_node_stats = self._init_has_stats
        booster.categories = self.categories
        return booster

    # ------------------------------------------------------------------
    # Vmapped-K HPO: train K candidate boosters in ONE XLA program.
    #
    # ``enable_lanes`` switches a freshly-built engine into lane mode: the
    # whole boosting round (objective -> sampling -> histogram build ->
    # allreduce -> split election -> partition) is vmapped over a leading
    # candidate axis on the SAME binned data, with each lane's params
    # carried as traced scalars. Collectives batch under vmap — every
    # psum/pmax payload gains a leading K axis but the schedule (count,
    # order, reduction op) is identical to the scalar program, which is
    # exactly the property rxgbverify's VER001 certifies via the ``k``
    # program-meta coordinate. One compile covers all K candidates; ASHA
    # pruning re-packs survivors into a smaller K' program (one more
    # compile per distinct K', cached in ``_vk_fns``).
    # ------------------------------------------------------------------

    def enable_lanes(
        self, lane_params: LaneParams, *, force_masks: bool = False
    ) -> None:
        """Switch this engine into vmapped-K mode for ``lane_params.k``
        candidate lanes. The engine must have been constructed with
        ``lane_params.base`` (the trace-shape config: max depth, max
        subsample rate) and must be fresh — no rounds stepped yet.

        ``force_masks`` traces the per-lane depth and subsample planes even
        when this pack's lanes don't vary them — the sequential-HPO dedupe
        mode: a later ``reset_lanes`` pack may then vary depth/subsample
        (within the base caps) without retracing.

        Raises ``NotImplementedError`` for configurations whose round
        program cannot ride a lane axis; never silently degrades a lane.
        """
        if self._vk:
            raise RuntimeError("lanes already enabled on this engine")
        if self.trees or self._trees_dev or self.iteration_offset:
            raise RuntimeError(
                "enable_lanes requires a fresh engine (no boosted rounds)"
            )
        if jax.process_count() > 1:
            raise NotImplementedError(
                "vmapped-K HPO is single-process only (the multi-host "
                "dispatch path does not carry the lane axis)"
            )
        if self.dart:
            raise NotImplementedError(
                "booster='dart' is not supported on the vmapped-K path"
            )
        if self._streamed:
            raise NotImplementedError(
                "streamed ingestion is not supported on the vmapped-K "
                "path; materialize the matrix for vectorized HPO"
            )
        if self.feature_parallel > 1:
            raise NotImplementedError(
                "feature_parallel > 1 is not supported on the vmapped-K "
                "path (2D-mesh programs are per-trial compiles)"
            )
        if self._host_metrics:
            raise NotImplementedError(
                "host-side eval metrics "
                f"({', '.join(self._host_metrics)}) need per-round host "
                "margins and cannot ride the vmapped-K path; use device "
                "metrics (or sequential trials)"
            )
        if self._init_trees:
            raise NotImplementedError(
                "warm-starting from an init booster is not supported on "
                "the vmapped-K path (lanes share no forest)"
            )
        lanes = lane_params.lanes
        k = lane_params.k
        lane_depth_max = max(p.max_depth for p in lanes)
        if lane_depth_max > self.cfg.max_depth or (
            lane_depth_max != self.cfg.max_depth and not force_masks
        ):
            # without the depth plane the program's level count IS the lane
            # depth; with force_masks any depth <= the traced cap is fine
            raise ValueError(
                "engine was not built with lane_params.base: lane depths "
                f"{[p.max_depth for p in lanes]} vs cfg.max_depth="
                f"{self.cfg.max_depth}"
            )
        # histogram-provider seam: the lane build must go through an
        # order-free provider (presorted-row-order providers carry state
        # the lane axis cannot batch) — route cfg.hist_impl through the
        # registry's vmapped_k wrapper, which validates and delegates
        base_impl = self.cfg.hist_impl
        prov = resolve_hist_provider(
            base_impl, self.cfg.hist_precision, self.cfg.hist_chunk
        )
        if prov.wants_order:
            if self.params.hist_impl == "auto":
                # auto resolves per backend; under lanes the order-free
                # scatter build is the auto choice
                base_impl = "scatter"
            else:
                raise NotImplementedError(
                    f"hist_impl {self.params.hist_impl!r} maintains a "
                    f"presorted row order and cannot back the vmapped-K "
                    f"build; use hist_impl='auto' or an order-free "
                    f"implementation (scatter, onehot)"
                )
        self.cfg = dataclasses.replace(
            self.cfg, hist_impl=vmapped_k_impl(base_impl)
        )
        # per-lane param planes: f32 split params always; depth/budget
        # masks only when they actually vary (uniform lanes keep the
        # scalar program's exact arithmetic — the bitwise-parity contract)
        # or when force_masks pre-arms them for later reset_lanes packs
        planes = ["learning_rate", "reg_lambda", "reg_alpha", "gamma",
                  "min_child_weight"]
        if lane_params.depth_varied or force_masks:
            planes.append("depth_limit")
        if lane_params.subsample_varied or force_masks:
            planes.append("budget")
            if sampling.spec_from_params(self.params) is None:
                # base (max) rate is 1.0 yet some lane samples (or
                # force_masks pre-arms sampling): trace the full-budget
                # uniform machinery and let lane budgets mask
                self._vk_spec_override = sampling.SamplingSpec(
                    "uniform", rate=1.0
                )
        self._vk_plane_names = tuple(planes)
        arrs = self._vk_build_planes(lanes)
        # K-stack the margin state: [K, rows, n_outputs], lane axis
        # replicated across the mesh, row axis sharded as before. The
        # pristine pre-stack margins are stashed so reset_lanes can re-arm
        # the engine for a fresh pack without rebuilding.
        self._vk_sharding = NamedSharding(self.mesh, P(None, AXIS_ACTORS))
        self._vk_margins0 = np.asarray(self.margins)
        self._vk_eval_margins0 = [
            np.asarray(es.margins) for es in self.evals if not es.is_train
        ]
        self.margins = self._vk_stack(self._vk_margins0, k)
        ei = 0
        for es in self.evals:
            if not es.is_train:
                es.margins = self._vk_stack(self._vk_eval_margins0[ei], k)
                ei += 1
        self._vk = k
        self._vk_lane_params = list(lanes)
        self._vk_lane_ids = list(range(k))
        self._vk_seeds = [int(p.seed) for p in lanes]
        self._vk_lane_np = arrs
        self._vk_lane_arrays = {
            name: jnp.asarray(v) for name, v in arrs.items()
        }
        self._vk_fns: Dict[int, Any] = {}
        self._vk_trees: List[List[Tree]] = [[] for _ in range(k)]
        self._vk_trees_dev: List[Tree] = []
        self._obs_round_attrs = dict(self._obs_round_attrs, k=k)

    def _vk_stack(self, arr_np: np.ndarray, k: int):
        return jax.device_put(
            np.broadcast_to(arr_np, (k,) + arr_np.shape).copy(),
            self._vk_sharding,
        )

    def _vk_build_planes(self, lanes) -> Dict[str, np.ndarray]:
        """The per-lane param planes of ``self._vk_plane_names`` for a lane
        pack (shared by enable_lanes / reset_lanes / repack slicing)."""
        arrs: Dict[str, np.ndarray] = {
            "learning_rate": np.array(
                [p.learning_rate for p in lanes], np.float32
            ),
            "reg_lambda": np.array([p.reg_lambda for p in lanes], np.float32),
            "reg_alpha": np.array([p.reg_alpha for p in lanes], np.float32),
            "gamma": np.array([p.gamma for p in lanes], np.float32),
            "min_child_weight": np.array(
                [p.min_child_weight for p in lanes], np.float32
            ),
        }
        if "depth_limit" in self._vk_plane_names:
            arrs["depth_limit"] = np.array(
                [p.max_depth for p in lanes], np.int32
            )
        if "budget" in self._vk_plane_names:
            block = self.pad_to // self.n_devices
            arrs["budget"] = np.array(
                [
                    sampling.row_budget(
                        block,
                        sampling.SamplingSpec(
                            "uniform", rate=float(p.subsample)
                        ),
                    )
                    for p in lanes
                ],
                np.int32,
            )
        return arrs

    def reset_lanes(self, lane_params: LaneParams) -> None:
        """Re-arm a lane-enabled engine for a fresh candidate pack WITHOUT
        retracing: margin state rewinds to the pristine pre-training
        margins, per-lane planes and seeds are replaced, and the compiled
        K-lane programs in ``_vk_fns`` are reused when the pack's K was
        dispatched before (a new K compiles lazily).

        This is the sequential-HPO compile-dedupe primitive: the Tuner
        routes same-shaped trials through ONE engine, resetting between
        trials, so trials differing only in lane-vectorizable params share
        a single compile. The pack must be sliced from the SAME group pack
        the engine was built with (``lane_params.base == self.params``) so
        every static coordinate — padded shapes, max depth cap, max
        subsample budget — is already covered by the traced program.
        """
        if not self._vk:
            raise RuntimeError("enable_lanes() first")
        if lane_params.base != self.params:
            raise ValueError(
                "reset_lanes pack was built against different base params; "
                "slice the pack from the engine's own group LaneParams"
            )
        lanes = lane_params.lanes
        k = lane_params.k
        if "depth_limit" not in self._vk_plane_names and any(
            p.max_depth != self.cfg.max_depth for p in lanes
        ):
            raise NotImplementedError(
                "param 'max_depth' varies in this pack but the engine's "
                "lane programs traced no depth plane; enable_lanes with "
                "force_masks=True to pre-arm it"
            )
        if "budget" not in self._vk_plane_names and any(
            float(p.subsample) != float(self.params.subsample) for p in lanes
        ):
            raise NotImplementedError(
                "param 'subsample' varies in this pack but the engine's "
                "lane programs traced no budget plane; enable_lanes with "
                "force_masks=True to pre-arm it"
            )
        self._vk_trees_dev.clear()
        self.margins = self._vk_stack(self._vk_margins0, k)
        ei = 0
        for es in self.evals:
            if not es.is_train:
                es.margins = self._vk_stack(self._vk_eval_margins0[ei], k)
                ei += 1
        self._vk = k
        self._vk_lane_params = list(lanes)
        self._vk_lane_ids = list(range(k))
        self._vk_seeds = [int(p.seed) for p in lanes]
        self._vk_lane_np = self._vk_build_planes(lanes)
        self._vk_lane_arrays = {
            name: jnp.asarray(v) for name, v in self._vk_lane_np.items()
        }
        self._vk_trees = [[] for _ in range(k)]
        self._obs_round_attrs = dict(self._obs_round_attrs, k=k)

    def _make_vmapped_step(self, k: int):
        """The K-lane round program: ``jax.vmap`` of the shared round body
        over the lane axis, inside one shard_map. Per-round collectives
        stay per-lane-batched — payload rank grows by one, the collective
        schedule is identical to the scalar step."""
        tree_round, metric_contribs = self._round_closures()

        def step(bins, valid, label, weight, margins_k, group_rows,
                 lane_arrs, rngs, bounds, eval_data):
            eval_bins = tuple(d.bins for d in eval_data)
            eval_margins_k = tuple(d.margins for d in eval_data)

            def one_lane(margins, eval_margins, lane, rng):
                new_margins, new_eval_margins, forest, ar_bytes = tree_round(
                    bins, valid, label, weight, margins, group_rows, None,
                    rng, bounds, eval_bins, eval_margins, lane=lane,
                )
                contribs = metric_contribs(
                    new_margins, new_eval_margins, label,
                    weight * valid.astype(jnp.float32), group_rows,
                    eval_data, bounds=bounds,
                )
                return new_margins, new_eval_margins, forest, contribs, ar_bytes

            return jax.vmap(one_lane, in_axes=(0, 0, 0, 0))(
                margins_k, eval_margins_k, lane_arrs, rngs
            )

        eval_specs = self._eval_arr_specs()
        mapped = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(
                self._bins_spec(),  # bins (lane-shared)
                P(AXIS_ACTORS),  # valid
                P(AXIS_ACTORS),  # label
                P(AXIS_ACTORS),  # weight
                P(None, AXIS_ACTORS),  # margins [K, rows, n_out]
                P(AXIS_ACTORS) if self.group_rows is not None else P(),
                {name: P() for name in self._vk_lane_arrays},  # lane planes
                P(),  # per-lane rng keys [K, 2]
                (P(AXIS_ACTORS), P(AXIS_ACTORS))
                if self.bounds_dev is not None else P(),
                eval_specs,
            ),
            out_specs=(
                P(None, AXIS_ACTORS),
                tuple(P(None, AXIS_ACTORS) for _ in eval_specs),
                P(),  # forests [K, T, heap]
                tuple(
                    tuple((P(), P()) for _ in self._device_metrics)
                    for _ in self.evals
                ),
                P(),  # allreduce payload bytes [K]
            ),
        )
        return progreg.register_jit(
            "engine.step_vmapped",
            mapped,
            donate_argnums=(4,),
            example_args=lambda: self._vmapped_example_args(),
            meta=self._program_meta(),
        )

    def _vmapped_example_args(self) -> tuple:
        group_rows = self._default_group_rows()
        bounds = self._default_bounds()
        return (self.bins, self.valid, self.label_dev, self.weight_dev,
                self.margins, group_rows, self._vk_lane_arrays,
                self._vk_rngs(0), bounds, self._eval_arrs())

    def _vk_rngs(self, iteration: int) -> jnp.ndarray:
        """[K, 2] per-lane round keys: each lane folds ITS OWN seed with
        the global round index, so a lane whose seed equals a sequential
        trial's seed replays that trial's exact PRNG stream."""
        it = self.iteration_offset + iteration
        return jnp.stack([
            jax.random.fold_in(jax.random.PRNGKey(lane_seed), it)
            for lane_seed in self._vk_seeds
        ])

    def step_vmapped(self, iteration: int) -> List[Dict[str, Dict[str, float]]]:
        """Run one boosting round for ALL live lanes; returns a per-lane
        list of ``{eval_name: {metric: value}}`` (index = live-lane slot;
        map through ``lane_ids()`` for original candidate identity)."""
        if not self._vk:
            raise RuntimeError("enable_lanes() first")
        span_ts, span_t0 = time.time(), time.perf_counter()
        k = self._vk
        fn = self._vk_fns.get(k)
        if fn is None:
            fn = self._vk_fns[k] = self._make_vmapped_step(k)
        eval_data = self._eval_arrs()
        group_rows = self._default_group_rows()
        bounds = self._default_bounds()
        rngs = self._vk_rngs(iteration)
        prog = ("vmapped", k)
        with strict_transfer_guard(active=prog in self._warm_programs):
            new_margins, new_eval_margins, forests, contribs, ar_bytes = fn(
                self.bins,
                self.valid,
                self.label_dev,
                self.weight_dev,
                self.margins,
                group_rows,
                self._vk_lane_arrays,
                rngs,
                bounds,
                eval_data,
            )
        self._warm_programs.add(prog)
        self._ar_bytes_dev = ar_bytes[0]
        self.margins = new_margins
        ei = 0
        for es in self.evals:
            if not es.is_train:
                es.margins = new_eval_margins[ei]
                ei += 1
        # defer the [K, T, heap] forest transfer like the scalar path
        self._vk_trees_dev.append(forests)

        # metrics: one stacked [2*n_metrics*n_evals, K] transfer
        flat_scalars = [
            c
            for si in range(len(self.evals))
            for mi in range(len(self._device_metrics))
            for c in contribs[si][mi]
        ]
        flat_vals = (
            np.asarray(jnp.stack(flat_scalars))
            if flat_scalars else np.zeros((0, k))
        )
        results: List[Dict[str, Dict[str, float]]] = []
        for j in range(k):
            lane_res: Dict[str, Dict[str, float]] = {}
            fi = 0
            for si, es in enumerate(self.evals):
                row: Dict[str, float] = {}
                for mi, name in enumerate(self._device_metrics):
                    num = float(flat_vals[fi][j])
                    den = float(flat_vals[fi + 1][j])
                    fi += 2
                    val = num / max(den, 1e-12)
                    base, _ = parse_metric_name(name)
                    row[name] = (
                        float(np.sqrt(val)) if base in ("rmse", "rmsle")
                        else val
                    )
                lane_res[es.name] = row
            results.append(lane_res)
        self._emit_round_spans(
            span_ts, span_t0, self.iteration_offset + iteration
        )
        return results

    def lane_ids(self) -> List[int]:
        """Original candidate index of each live lane slot."""
        return list(self._vk_lane_ids)

    def _vk_flush(self) -> None:
        """Transfer pending [K, T, heap] device forests to per-lane host
        tree lists. All pending entries share the CURRENT lane packing
        (``repack_lanes`` flushes before slicing)."""
        entries = self._vk_trees_dev
        if not entries:
            return
        for entry in entries:
            ent = jax.tree.map(np.asarray, entry)
            for j in range(len(self._vk_trees)):
                self._vk_trees[j].append(
                    jax.tree.map(lambda a, _j=j: a[_j], ent)
                )
        self._vk_trees_dev.clear()

    def repack_lanes(self, keep: Sequence[int]) -> None:
        """Drop pruned lanes and re-pack survivors into a K' = len(keep)
        program (ASHA's successive-halving primitive). Margin state and
        lane planes are sliced on host and re-placed; the K' round program
        compiles lazily at the next ``step_vmapped`` (cached per K', so a
        later group pruning to the same K' reuses it)."""
        keep = list(keep)
        if not keep:
            raise ValueError("repack_lanes needs at least one survivor")
        if sorted(set(keep)) != sorted(keep) or \
                not all(0 <= j < self._vk for j in keep):
            raise ValueError(f"invalid lane indices {keep!r}")
        self._vk_flush()
        idx = np.asarray(keep, np.int64)

        def take(arr):
            return jax.device_put(np.asarray(arr)[idx], self._vk_sharding)

        self.margins = take(self.margins)
        for es in self.evals:
            if not es.is_train:
                es.margins = take(es.margins)
        self._vk_lane_np = {
            name: v[idx] for name, v in self._vk_lane_np.items()
        }
        self._vk_lane_arrays = {
            name: jnp.asarray(v) for name, v in self._vk_lane_np.items()
        }
        self._vk_seeds = [self._vk_seeds[j] for j in keep]
        self._vk_lane_params = [self._vk_lane_params[j] for j in keep]
        self._vk_trees = [self._vk_trees[j] for j in keep]
        self._vk_lane_ids = [self._vk_lane_ids[j] for j in keep]
        self._vk = len(keep)
        self._obs_round_attrs = dict(self._obs_round_attrs, k=self._vk)

    def get_booster_lane(self, lane: int) -> RayXGBoostBooster:
        """The finished booster of live-lane slot ``lane``, carrying that
        lane's OWN parsed params (eta, lambda, depth, ...) — not the
        widened base config the program traced with."""
        if not self._vk:
            raise RuntimeError("enable_lanes() first")
        self._vk_flush()
        if not self._vk_trees[lane]:
            raise ValueError("empty forest")
        forest = stack_trees(self._vk_trees[lane])
        booster = RayXGBoostBooster(
            forest,
            np.asarray(self.cuts),
            self._vk_lane_params[lane],
            self.base_score,
            feature_names=self.feature_names,
            feature_types=self.feature_types,
        )
        booster._has_node_stats = self._init_has_stats
        booster.categories = self.categories
        return booster

    # ------------------------------------------------------------------
    # In-flight elastic continuation (zero-replay shrink/grow): the driver
    # swaps worlds mid-attempt without restarting from a checkpoint. A
    # cached engine for a previously-seen world signature is revived via
    # ``reset_from_booster`` — its compiled step programs, sketch cuts and
    # binned device matrix are reused, so growing back to a known world
    # costs one host forest walk instead of a retrace + re-sketch.
    # ------------------------------------------------------------------

    def can_reshard(self) -> bool:
        """Whether this engine supports the zero-replay re-shard path.

        True for EVERY gbtree configuration this engine can train: the 1D
        row mesh (PR 5), 2D row x feature meshes (a shrink rebuilds the
        mesh as ``(R', C)`` with feature tiles fixed; a grow-back into a
        previously-compiled ``(R, C)`` world hits the driver's engine
        cache), streamed matrices (survivor shards' binned blocks and
        frozen cuts are reused in memory — no re-stream, no re-sketch; see
        ``stream/ingest.py``'s reuse passes), and dart (the
        capacity-padded device forest and tree weights rebuild from the
        in-memory booster via ``reset_from_booster``; the per-round drop
        RNG is a pure function of (seed, global round), so it needs no
        carried state). gblinear is no longer the asterisk: ``LinearEngine``
        ships its own ``can_reshard``/``reset_from_booster`` (the weight
        vector re-derives from the in-memory booster on any survivor mesh),
        so every built-in booster continues in flight."""
        return True

    def reset_from_booster(self, shards, evals, init_booster) -> None:
        """Re-shard entry point: reuse this engine (compiled step programs,
        binned device matrix, sketch cuts, eval-set device state) for a
        continuation segment starting from ``init_booster``.

        The caller guarantees ``shards``/``evals`` hold the SAME rows this
        engine was built over (``shard_layout_fingerprint`` at the driver's
        world cache; shapes — or stream identities — re-checked here): the
        device-resident data never moves, only the margin state and forest
        bookkeeping are re-derived from the booster. Cost: one forest walk
        per data set — a host walk over raw rows for materialized loads, a
        compiled binned-matrix walk (``stream.init_margins``, fsharded on
        2D meshes) for streamed loads whose raw rows never existed. dart
        additionally rebuilds its capacity-padded device forest + weights
        from the booster inside the engine's compiled capacity. No round
        program retraces, no re-bin, no re-sketch.
        """
        base_margin = None
        x = None
        if self._streamed:
            # streamed: raw rows never existed — verify stream identity
            # (the same fingerprints the driver's cache matched on), then
            # re-derive margins from the retained binned matrix below
            from xgboost_ray_tpu.stream import reader as stream_reader

            streams = stream_reader.shard_streams(shards)
            if streams is None or [
                s.fingerprint() for s in streams
            ] != self._stream_shard_fps:
                raise ValueError(
                    "reshard: streamed shard identity changed; a fresh "
                    "engine build is required."
                )
            base_margin = self._stream_cols.get("base_margin")
        else:
            x, _label, _weight, base_margin, _qid, _lo, _hi = _concat_shards(
                shards
            )
            if x.shape[0] != self._local_rows or x.shape[1] != self.n_features:
                raise ValueError(
                    f"reshard: shard layout changed ({x.shape} vs "
                    f"({self._local_rows}, {self.n_features})); a fresh "
                    f"engine build is required."
                )
        self._init_has_stats = (
            getattr(init_booster, "_has_node_stats", True)
            if init_booster is not None
            else True
        )
        have_init = init_booster is not None and init_booster.num_trees

        def static_margins(n_rows, bm):
            ms = np.full((n_rows, self.n_outputs), self.base_margin0,
                         np.float32)
            if bm is not None:
                ms = ms + bm.reshape(n_rows, -1).astype(np.float32)
            return ms

        def margins_for(xv, bm):
            ms = static_margins(xv.shape[0], bm)
            if have_init:
                ms = ms + (
                    init_booster.predict_margin_np(xv)
                    - init_booster.base_score_margin_np()
                )
            return ms

        self._init_trees = []
        self._init_tree_weights = None
        if have_init:
            self._init_trees = [init_booster.forest]
            self._init_tree_weights = (
                init_booster.tree_weights
                if init_booster.tree_weights is not None
                else np.ones(init_booster.num_trees, np.float32)
            )
        if self.dart:
            # margins are recomputed from the device forest at every dart
            # step (static + weighted forest walk), so only the static part
            # is staged here; the forest/weights rebuild below is the state
            # the next step actually consumes
            self.margins = self._put_rows(
                static_margins(self._local_rows, base_margin), np.float32
            )
            self._reset_dart_state(init_booster)
        elif self._streamed:
            self.margins = self._put_rows(
                static_margins(self._local_rows, base_margin), np.float32
            )
            if have_init:
                # the PR 14 warm-start walk, gated on bitwise cut equality
                # — which holds trivially here: the cuts are retained in
                # memory and the booster was grown on this engine's cuts
                self.margins = self.margins + self._init_margins_from_bins(
                    init_booster, fsharded=self.feature_parallel > 1
                )
        else:
            self.margins = self._put_rows(
                margins_for(x, base_margin), np.float32
            )

        from xgboost_ray_tpu.distributed import put_rows_global

        if len(evals) != len(self.evals):
            raise ValueError("reshard: eval-set count changed")
        for (eval_shards, _name), es in zip(evals, self.evals):
            if es.is_train:
                continue
            # eval sets are materialized by construction (streamed evals
            # are gated at _add_eval_set), so the host walk always applies
            ex, _, _, ebm, _, _, _ = _concat_shards(eval_shards)
            if ex.shape[0] != es.local_rows:
                raise ValueError(
                    f"reshard: eval set {es.name!r} layout changed"
                )
            _, local_pad, _ = self._global_row_layout(ex.shape[0])
            # dart recomputes eval margins from the device forest per step
            # against margins_static, which is already device-resident
            arr = (
                static_margins(ex.shape[0], ebm) if self.dart
                else margins_for(ex, ebm)
            )
            if arr.shape[0] < local_pad:
                arr = np.pad(arr, [(0, local_pad - arr.shape[0]), (0, 0)])
            es.margins = put_rows_global(arr, self._row_sharding)

        # forest bookkeeping restarts at the booster's round count; the
        # compiled programs themselves carry no forest state (the margins
        # and per-round trees are program inputs/outputs)
        self.trees = []
        self._trees_dev = []
        self._stack_entries = 0
        self._stack_rows = 0
        self._stack_buf = None
        self._ar_bytes_dev = None
        self.iteration_offset = (
            init_booster.num_boosted_rounds() if init_booster is not None else 0
        )

    def _reset_dart_state(self, init_booster) -> None:
        """Rebuild dart's capacity-padded device forest, tree weights and
        slot cursor from ``init_booster`` WITHOUT changing ``_dart_t_cap``
        — the capacity is a static shape of the compiled dart step, so a
        reset that resized it would force a retrace (and the cached
        program would dispatch against stale shapes). The per-round drop
        RNG carries no state: ``_dart_sample_drops`` is a pure function of
        (seed, iteration_offset + round, weights), and both offset and
        weights are restored here."""
        n_init = (
            init_booster.num_trees
            if init_booster is not None and init_booster.num_trees
            else 0
        )
        if n_init > self._dart_t_cap:
            raise ValueError(
                f"reshard: booster carries {n_init} trees but this dart "
                f"engine's compiled forest capacity is {self._dart_t_cap}; "
                f"a fresh engine build is required."
            )
        self._init_dart_forest(t_cap=self._dart_t_cap)


    # ------------------------------------------------------------------
    # DART (dropout) booster: per-round dropout over the forest built so
    # far, with tree/forest normalization — the analog of xgboost's
    # ``booster="dart"`` which reference users pass straight through.
    # Margins are recomputed from the (capacity-padded, device-resident)
    # forest each round via a vmapped binned walk, so dropping trees is a
    # weight-vector edit, not a cache invalidation problem.
    # ------------------------------------------------------------------

    def _init_dart_forest(self, t_cap: Optional[int] = None):
        """Allocate (or, with an explicit ``t_cap``, re-fill at the pinned
        compiled capacity — the ``reset_from_booster`` path) the
        capacity-padded device forest from ``_init_trees``/weights."""
        k_out = self.n_outputs
        heap = self.cfg.heap_size
        n_init = self._init_trees[0].feature.shape[0] if self._init_trees else 0
        if t_cap is None:
            t_cap = n_init + max(1, self._dart_total_rounds) * k_out

        def empty(dtype, fill):
            return np.full((t_cap, heap), fill, dtype)

        fills = {"feature": (np.int32, -1), "split_bin": (np.int32, 0),
                 "threshold": (np.float32, 0.0), "default_left": (bool, False),
                 "is_leaf": (bool, False), "value": (np.float32, 0.0),
                 "gain": (np.float32, 0.0), "cover": (np.float32, 0.0),
                 "base_weight": (np.float32, 0.0)}
        bufs = {name: empty(dtype, fill) for name, (dtype, fill) in fills.items()}
        bufs["is_leaf"][:, 0] = True  # empty slots predict 0 from a root leaf
        if n_init:
            init = self._init_trees[0]
            for name in Tree._fields:
                bufs[name][:n_init] = getattr(init, name)
        self.dart_forest_dev = Tree(
            **{name: jnp.asarray(bufs[name]) for name in Tree._fields}
        )
        self.dart_weights = np.zeros(t_cap, np.float32)
        if n_init:
            self.dart_weights[:n_init] = self._init_tree_weights
        self.dart_t = n_init
        self._dart_t_cap = t_cap

    def _make_dart_step(self):
        tree_round, metric_contribs = self._round_closures(update_evals=False)
        cfg = self.cfg
        k_out = self.n_outputs
        missing_bin = self.params.max_bin
        t_cap = self._dart_t_cap
        cls_onehot = jax.nn.one_hot(
            jnp.arange(t_cap) % k_out, k_out, dtype=jnp.float32
        )  # [t_cap, K]

        def forest_margin(forest, bins_local, static, weights):
            leaf = jax.vmap(
                lambda tr: predict_tree_binned(
                    tr, bins_local, cfg.max_depth, missing_bin,
                    cat_features=cfg.cat_features,
                )
            )(forest)  # [t_cap, S]
            contrib = jnp.einsum(
                "ts,tk->sk", leaf * weights[:, None], cls_onehot,
                precision=jax.lax.Precision.HIGHEST,
            )
            return static + contrib

        def dart_step(bins, valid, label, weight, static_margins, group_rows,
                      bounds, forest, w_eff, w_post, new_w, slot, rng, eval_data):
            m_eff = forest_margin(forest, bins, static_margins, w_eff)
            eval_bins = tuple(d.bins for d in eval_data)
            new_margins, _, round_forest, ar_bytes = tree_round(
                bins, valid, label, weight, m_eff, group_rows, None, rng,
                bounds, (), (),
            )
            del new_margins  # dart recomputes margins from weights instead
            # insert the K new trees at [slot, slot+K)
            forest = jax.tree.map(
                lambda fa, ta: jax.lax.dynamic_update_slice(
                    fa, ta.astype(fa.dtype), (slot,) + (0,) * (fa.ndim - 1)
                ),
                forest,
                round_forest,
            )
            # post-round weights: dropped rescaled + new trees at new_w
            slots = jnp.arange(t_cap)
            w_full = jnp.where(
                (slots >= slot) & (slots < slot + k_out), new_w, w_post
            )
            m_full = forest_margin(forest, bins, static_margins, w_full)
            new_eval_margins = []
            for e, d in enumerate(eval_data):
                m_e = forest_margin(forest, eval_bins[e], d.margins_static, w_full)
                new_eval_margins.append(m_e)
            contribs = metric_contribs(
                m_full, new_eval_margins, label,
                weight * valid.astype(jnp.float32), group_rows, eval_data,
                bounds=bounds,
            )
            return (m_full, tuple(new_eval_margins), forest, round_forest,
                    contribs, ar_bytes)

        eval_specs = self._eval_arr_specs()
        mapped = shard_map(
            dart_step,
            mesh=self.mesh,
            in_specs=(
                P(AXIS_ACTORS),  # bins
                P(AXIS_ACTORS),  # valid
                P(AXIS_ACTORS),  # label
                P(AXIS_ACTORS),  # weight
                P(AXIS_ACTORS),  # static margins
                P(AXIS_ACTORS) if self.group_rows is not None else P(),
                (P(AXIS_ACTORS), P(AXIS_ACTORS)) if self.bounds_dev is not None else P(),
                P(),  # forest (replicated)
                P(),  # w_eff
                P(),  # w_post
                P(),  # new_w
                P(),  # slot
                P(),  # rng
                eval_specs,
            ),
            out_specs=(
                P(AXIS_ACTORS),
                tuple(P(AXIS_ACTORS) for _ in eval_specs),
                P(),
                P(),
                tuple(
                    tuple((P(), P()) for _ in self._device_metrics)
                    for _ in self.evals
                ),
                P(),  # allreduce payload bytes
            ),
        )
        return progreg.register_jit(
            "engine.step_dart",
            mapped,
            donate_argnums=(7,),
            example_args=self._dart_example_args,
            meta=self._program_meta(),
        )

    def _dart_sample_drops(self, iteration: int):
        """Host-side dropout sampling; deterministic in (seed, iteration)."""
        params = self.params
        t = self.dart_t
        rng = np.random.RandomState(
            (params.seed * 1_000_003 + self.iteration_offset + iteration) % (2 ** 31)
        )
        drop = np.zeros(self._dart_t_cap, bool)
        if t == 0 or (params.skip_drop > 0 and rng.rand() < params.skip_drop):
            return drop
        weights = np.maximum(self.dart_weights[:t], 0.0)
        if params.sample_type == "weighted":
            probs = weights / max(weights.sum(), 1e-12)
            drop[:t] = rng.rand(t) < np.minimum(probs * t * params.rate_drop, 1.0)
        else:
            drop[:t] = rng.rand(t) < params.rate_drop
        if params.one_drop and not drop.any():
            if params.sample_type == "weighted" and weights.sum() > 0:
                idx = rng.choice(t, p=weights / weights.sum())
            else:
                idx = rng.randint(t)
            drop[idx] = True
        return drop

    def step_dart(self, iteration: int) -> Dict[str, Dict[str, float]]:
        params = self.params
        span_ts, span_t0 = time.time(), time.perf_counter()
        if self.dart_t + self.n_outputs > self._dart_t_cap:
            # the in-program dynamic_update_slice CLAMPS an out-of-range
            # slot, which would silently overwrite the newest trees —
            # unreachable under the driver's round arithmetic (capacity
            # covers init + total_rounds, resets keep the invariant), so
            # tripping it means a bookkeeping bug, not a user error
            raise RuntimeError(
                f"dart forest capacity exhausted: slot {self.dart_t} + "
                f"{self.n_outputs} trees > t_cap {self._dart_t_cap}"
            )
        if self._dart_fn is None:
            self._dart_fn = self._make_dart_step()
        lr = params.learning_rate
        drop = self._dart_sample_drops(iteration)
        k_dropped = int(drop.sum())
        if k_dropped:
            if params.normalize_type == "forest":
                new_w, drop_scale = 1.0 / (1.0 + lr), 1.0 / (1.0 + lr)
            else:  # "tree"
                new_w = 1.0 / (k_dropped + lr)
                drop_scale = k_dropped / (k_dropped + lr)
        else:
            new_w, drop_scale = 1.0, 1.0
        w_eff = self.dart_weights.copy()
        w_eff[drop] = 0.0
        w_post = self.dart_weights.copy()
        w_post[drop] *= drop_scale

        rng = jax.random.fold_in(
            jax.random.PRNGKey(params.seed), self.iteration_offset + iteration
        )
        eval_data = self._eval_arrs()
        group_rows = self._default_group_rows()
        bounds = self._default_bounds()
        # the per-round drop weights / tree index are legitimate host
        # inputs of the dart program: place them explicitly (replicated)
        # BEFORE entering the strict guard, which rejects the implicit
        # upload-and-reshard the bare jnp conversions would trigger
        repl = NamedSharding(self.mesh, P())
        w_eff_dev = jax.device_put(np.asarray(w_eff), repl)
        w_post_dev = jax.device_put(np.asarray(w_post), repl)
        new_w_dev = jax.device_put(np.float32(new_w), repl)
        dart_t_dev = jax.device_put(np.int32(self.dart_t), repl)
        with strict_transfer_guard(active="dart" in self._warm_programs):
            m_full, new_eval_margins, forest, round_forest, contribs, ar_bytes = self._dart_fn(
                self.bins,
                self.valid,
                self.label_dev,
                self.weight_dev,
                self._margins_static_dev,
                group_rows,
                bounds,
                self.dart_forest_dev,
                w_eff_dev,
                w_post_dev,
                new_w_dev,
                dart_t_dev,
                rng,
                eval_data,
            )
        self._warm_programs.add("dart")
        self.margins = m_full
        self._ar_bytes_dev = ar_bytes
        self.dart_forest_dev = forest
        ei = 0
        for es in self.evals:
            if not es.is_train:
                es.margins = new_eval_margins[ei]
                ei += 1
        self._trees_dev.append((round_forest, None))
        w_new_vec = w_post
        w_new_vec[self.dart_t : self.dart_t + self.n_outputs] = new_w
        self.dart_weights = w_new_vec
        self.dart_t += self.n_outputs

        results: Dict[str, Dict[str, float]] = {}
        for si, es in enumerate(self.evals):
            row: Dict[str, float] = {}
            for mi, name in enumerate(self._device_metrics):
                num, den = contribs[si][mi]
                num, den = float(num), float(den)
                val = num / max(den, 1e-12)
                base, _ = parse_metric_name(name)
                row[name] = float(np.sqrt(val)) if base in ("rmse", "rmsle") else val
            if self._host_metrics:
                margin = self.get_margins_local(es)
                for name in self._host_metrics:
                    row[name] = self.combine_host_scalar(
                        self._host_metric_value(name, margin, es), es,
                        metric=name,
                    )
            results[es.name] = row
        self._emit_round_spans(
            span_ts, span_t0, self.iteration_offset + iteration
        )
        return results

    # ------------------------------------------------------------------
    # Fenced per-phase profiling (the obs plane's runtime replacement for
    # bench.py's former standalone phase timers).
    # ------------------------------------------------------------------

    def profile_phases(self, tracer=None, iters: int = 3) -> Dict[str, Any]:
        """Micro-time each round phase (``sample`` / ``hist`` / ``split`` /
        ``partition`` / ``margin`` / ``allreduce``) standalone at THIS
        engine's true per-shard shapes, emitting one span per phase on the
        current tracer with compile-vs-execute separated via
        ``jax.block_until_ready`` and rows/bytes attributes attached.

        The compiled round step fuses these phases (XLA may overlap them),
        so this is a phase-share approximation, not an in-program trace —
        but it runs against the engine's real shard block size, sampling
        budget, resolved hist impl and split params, so the breakdown
        reflects the program that actually trains. Returns the
        ``phase_profile`` dict that ``train()`` surfaces under
        ``additional_results["obs"]`` when ``RXGB_TRACE_PHASES=1``."""
        import functools

        from xgboost_ray_tpu.ops.grow import empty_tree, route_right_binned
        from xgboost_ray_tpu.ops.split import find_splits

        tracer = tracer if tracer is not None else obs.get_tracer()
        n_local = self.pad_to // self.n_devices  # one shard's row block
        # per-chip feature tile width (== F on the 1D mesh)
        n_feat = (
            self._f_padded // self.feature_parallel
            if self.feature_parallel > 1
            else self.n_features
        )
        depth = self.cfg.max_depth
        max_bin = self.params.max_bin
        nbt = max_bin + 1
        provider = self.cfg.hist_provider()
        impl = provider.name
        spec = sampling.spec_from_params(self.params)
        m = n_local if spec is None else sampling.row_budget(n_local, spec)

        rng = np.random.RandomState(0)
        bins = jnp.asarray(
            rng.randint(0, max_bin, size=(n_local, n_feat)), jnp.uint8
        )
        gh = jnp.asarray(
            np.stack(
                [rng.standard_normal(n_local),
                 np.abs(rng.standard_normal(n_local))],
                axis=1,
            ),
            jnp.float32,
        )
        valid = jnp.ones((n_local,), bool)
        key = jax.random.PRNGKey(0)
        gh_scale = None
        if self.cfg.gh_precision != "float32":
            # profile the int path the real round runs: quantized gh buffer
            # feeding the builders (no mesh here, so no pmax — the scales
            # only affect values, not shapes/dtypes)
            gh, gh_scale = jax.jit(
                lambda g, k, _m=self.cfg.gh_precision, _r=int(self.pad_to):
                quantize_gh(g, _m, k, max_rows=_r)
            )(gh, key)

        def fenced(fn, *args):
            """(compile_s, execute_s): the first call carries compile; the
            steady mean over ``iters`` further calls is execute — every
            timing fenced by block_until_ready."""
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            first = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            execute = (time.perf_counter() - t0) / iters
            return max(first - execute, 0.0), execute

        phases: Dict[str, Dict[str, Any]] = {}

        def emit(name, compile_s, execute_s, rows, **extra):
            attrs = {"compile_s": round(compile_s, 6), "rows": int(rows)}
            attrs.update(extra)
            tracer.add_span(name, time.time(), execute_s, attrs=attrs)
            phases[name] = {
                "compile_ms": round(1e3 * compile_s, 3),
                "execute_ms": round(1e3 * execute_s, 3),
                "rows": int(rows),
                **extra,
            }

        # -- sample: budget selection + row gather (absent for full rows)
        if spec is None:
            emit("sample", 0.0, 0.0, n_local)
            bins_m, gh_m = bins, gh
        else:
            sample_fn = jax.jit(
                lambda g, v, k, _s=spec, _sc=gh_scale: sampling.sample_rows(
                    g, v, k, _s, scale=_sc
                )
            )
            gather_fn = jax.jit(lambda r: bins[r])
            rows_sel, gh_m = sample_fn(gh, valid, key)
            c1, e1 = fenced(sample_fn, gh, valid, key)
            c2, e2 = fenced(gather_fn, rows_sel)
            bins_m = gather_fn(rows_sel)
            emit("sample", c1 + c2, e1 + e2, m)

        # -- hist + partition, per level (sibling subtraction halves the
        # built fan-out beyond the root, exactly as the real builds do)
        hist_c = hist_e = part_c = part_e = 0.0
        split_c = split_e = 0.0
        for d in range(depth):
            n_nodes = 1 << d
            build_nodes = max(1, n_nodes // 2) if d > 0 else 1
            pos = jnp.asarray(
                rng.randint(0, build_nodes, size=(m,)), jnp.int32
            )
            hist_fn = jax.jit(
                functools.partial(
                    provider.build,
                    n_nodes=build_nodes,
                    n_bins_total=nbt,
                )
            )
            c, e = fenced(hist_fn, bins_m, gh_m, pos)
            hist_c, hist_e = hist_c + c, hist_e + e

            hist = jnp.asarray(
                rng.standard_normal((n_nodes, n_feat, nbt, 2)), jnp.float32
            )
            node_gh = hist[:, 0, :, :].sum(axis=1)
            split_fn = jax.jit(
                lambda h, ng, _p=self.cfg.split: find_splits(h, ng, _p)
            )
            c, e = fenced(split_fn, hist, node_gh)
            split_c, split_e = split_c + c, split_e + e

            pos_lvl = jnp.asarray(
                rng.randint(0, n_nodes, size=(m,)), jnp.int32
            )
            sbin = jnp.asarray(
                rng.randint(0, max_bin - 1, size=(n_nodes,)), jnp.int32
            )

            def part_fn(b, p, sb):
                bv = b[:, 0].astype(jnp.int32)
                go_right = route_right_binned(
                    bv, sb[p], jnp.zeros_like(sb, bool)[p], None, max_bin
                )
                return p * 2 + go_right.astype(jnp.int32)

            c, e = fenced(jax.jit(part_fn), bins_m, pos_lvl, sbin)
            part_c, part_e = part_c + c, part_e + e
        emit("hist", hist_c, hist_e, m, impl=impl)
        emit("split", split_c, split_e, m)
        emit("partition", part_c, part_e, m)

        # -- margin: the once-per-tree full-row walk sampled builds pay
        # (full-row builds fuse the margin update into the build itself)
        if spec is None:
            emit("margin", 0.0, 0.0, n_local, fused_into_build=True)
        else:
            tree = empty_tree((1 << (depth + 1)) - 1)
            tree = tree._replace(
                feature=jnp.asarray(
                    rng.randint(0, n_feat, tree.feature.shape), jnp.int32
                ),
                split_bin=jnp.asarray(
                    rng.randint(0, max_bin - 1, tree.split_bin.shape),
                    jnp.int32,
                ),
            )
            walk_fn = jax.jit(
                lambda t, b: predict_tree_binned(t, b, depth, max_bin)
            )
            c, e = fenced(walk_fn, tree, bins)
            emit("margin", c, e, n_local)

        # -- allreduce: one psum of the deepest built level's histogram over
        # the real mesh, with the whole round's ring-model payload attached
        # (measured from the trained program when a round has run)
        last_level = depth - 1
        last_nodes = (
            max(1, (1 << last_level) // 2) if last_level > 0 else 1
        )
        arr = jnp.zeros((last_nodes, n_feat, nbt, 2), jnp.float32)
        ar_fn = jax.jit(
            shard_map(
                lambda a: jax.lax.psum(a, AXIS_ACTORS),
                mesh=self.mesh,
                in_specs=(P(),),
                out_specs=P(),
            )
        )
        c, e = fenced(ar_fn, arr)
        measured = self.hist_allreduce_bytes_per_round()
        if measured is None:
            counter = AllreduceBytes(self.n_devices)
            for d in range(depth):
                bn = max(1, (1 << d) // 2) if d > 0 else 1
                counter.add_allreduce(
                    np.zeros((bn, n_feat, nbt, 2), np.float32)
                )
            measured = counter.total
        emit("allreduce", c, e, m, bytes_per_round=int(measured))

        total_ms = round(sum(p["execute_ms"] for p in phases.values()), 3)
        return {
            "rows_per_shard": int(n_local),
            "sample_rows": int(m),
            "phases": phases,
            "total_execute_ms": total_ms,
            "config": {
                "features": int(n_feat),
                "depth": int(depth),
                "max_bin": int(max_bin),
                "impl": impl,
                "world": int(self.n_devices),
                "note": (
                    "standalone jitted phases fenced with block_until_ready; "
                    "compile-vs-execute separated; phase-share approximation "
                    "— the compiled round fuses phases"
                ),
            },
        }


def shard_layout_fingerprint(shards) -> tuple:
    """Cheap deterministic fingerprint of a shard list: per-shard shape plus
    strided value samples of data and label. The driver's world cache uses
    it to decide whether a cached engine's binned device data is still valid
    for the actors now holding these ranks — shard loads are deterministic
    in (rank, num_actors), so a matching fingerprint means matching rows
    without an O(N) comparison."""
    parts = []
    for sh in shards:
        stream = sh.get("stream")
        if stream is not None:
            # streamed shards: loaders are deterministic in (source, rank,
            # chunking), so the stream's declared identity stands in for
            # value samples (no rows exist to sample)
            parts.append(stream.fingerprint())
            continue
        d = np.asarray(sh["data"])
        flat = d.ravel()
        stride = max(1, flat.size // 256)
        dsum = float(np.nansum(flat[::stride].astype(np.float64)))
        lab = sh.get("label")
        lsum = 0.0
        if lab is not None:
            la = np.asarray(lab, np.float64).ravel()
            lsum = float(np.nansum(la[:: max(1, la.size // 64)]))
        parts.append((tuple(d.shape), dsum, lsum))
    return tuple(parts)


def _concat_shards(shards):
    """Merge per-actor shard dicts (rank order) into global host arrays.

    Absent-column fills come from ``constants.SHARD_COLUMN_FILLS`` — the
    same table the streamed ingest synthesizes from."""
    fills = SHARD_COLUMN_FILLS
    xs, ys, ws, bs, qs = [], [], [], [], []
    has_w = has_b = has_q = False
    for sh in shards:
        xs.append(np.asarray(sh["data"], np.float32))
        lab = sh.get("label")
        ys.append(
            np.asarray(lab, np.float32)
            if lab is not None
            else np.full(xs[-1].shape[0], fills["label"], np.float32)
        )
        w = sh.get("weight")
        if w is not None:
            has_w = True
        ws.append(
            np.asarray(w, np.float32) if w is not None
            else np.full(xs[-1].shape[0], fills["weight"], np.float32)
        )
        b = sh.get("base_margin")
        if b is not None:
            has_b = True
            bs.append(np.asarray(b, np.float32))
        else:
            bs.append(None)
        q = sh.get("qid")
        if q is not None:
            has_q = True
            qs.append(np.asarray(q))
        else:
            qs.append(None)
    lls, lus = [], []
    has_ll = has_lu = False
    for sh in shards:
        ll = sh.get("label_lower_bound")
        lu = sh.get("label_upper_bound")
        if ll is not None:
            has_ll = True
        if lu is not None:
            has_lu = True
        lls.append(None if ll is None else np.asarray(ll, np.float32).ravel())
        lus.append(None if lu is None else np.asarray(lu, np.float32).ravel())
    x = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
    y = np.concatenate(ys, axis=0) if len(ys) > 1 else ys[0]
    w = (np.concatenate(ws, axis=0) if len(ws) > 1 else ws[0]) if has_w else None
    if has_b:
        bs = [
            b if b is not None
            else np.full(xi.shape[0], fills["base_margin"], np.float32)
            for b, xi in zip(bs, xs)
        ]
        b = np.concatenate(bs, axis=0) if len(bs) > 1 else bs[0]
    else:
        b = None
    if has_q:
        qs = [
            q if q is not None else np.full(xi.shape[0], -1)
            for q, xi in zip(qs, xs)
        ]
        q = np.concatenate(qs, axis=0) if len(qs) > 1 else qs[0]
    else:
        q = None
    if has_ll:
        lls = [
            l if l is not None
            else np.full(xi.shape[0], fills["label_lower_bound"], np.float32)
            for l, xi in zip(lls, xs)
        ]
        ll = np.concatenate(lls, axis=0) if len(lls) > 1 else lls[0]
    else:
        ll = None
    if has_lu:
        lus = [
            l if l is not None
            else np.full(xi.shape[0], fills["label_upper_bound"], np.float32)
            for l, xi in zip(lus, xs)
        ]
        lu = np.concatenate(lus, axis=0) if len(lus) > 1 else lus[0]
    else:
        lu = None
    return x, y, w, b, q, ll, lu
