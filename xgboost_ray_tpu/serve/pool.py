"""Replica pool + router: N predictors per host behind one front door.

One ``MicroBatcher`` over one ``CompiledPredictor`` serializes every batch
through a single flusher thread; under concurrent load the queue — not the
device — becomes the p99. This module scales that out *within* the
process: N :class:`Replica`\\ s, each its own batcher + predictor instance,
behind a :class:`Router` that admission-controls at the front door and
dispatches each request to the live replica with the least queued rows.

Design points, mirroring the elastic trainer's shrink semantics (PRs 5/15:
capacity degrades, availability never):

* **Shared compiled-program cache.** Every replica builds its own
  ``CompiledPredictor``, but the program cache is module-level and keyed by
  ``(model signature, devices, kind)`` — N replicas of one model cost ONE
  compile per program, and a replica spun up after warmup serves its first
  request with zero compiles.
* **One registry, per-replica predictors.** Replicas share the
  :class:`~xgboost_ray_tpu.serve.registry.ModelRegistry` (so a hot-swap
  drains and flips exactly once) through a :class:`_ReplicaRegistryView`
  that substitutes a replica-private predictor per model version — the
  shared entry's predictor never becomes a cross-replica contention point.
* **Failure sheds capacity, never availability.** ``kill()`` removes the
  replica from the table, then shuts its batcher down: its queued requests
  fail internally with ``ShuttingDownError`` and the router *re-dispatches
  them to survivors* — a replica loss mid-load completes every in-flight
  request (chaos-pinned by ``tests/test_serve_pool.py``). Mid-execution
  batches finish normally on the dying replica.
* **Observable.** Every dispatch fires the ``serve.route`` fault site and
  emits a ``serve.route`` trace event; every pool membership change emits
  ``serve.replica_up`` / ``serve.replica_down`` — the whole
  route → death → shed → rejoin story is reconstructible from the obs
  timeline alone.

The router exposes the batcher's duck-typed surface (``submit``,
``queue_depth``, ``drain``, ``shutdown``, ``breaker_open``, ...), so
``ServeHandle`` plugs it in wherever a ``MicroBatcher`` went.
"""

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import numpy as np

from xgboost_ray_tpu import faults, obs
from xgboost_ray_tpu.serve.batcher import (
    MicroBatcher,
    OverloadedError,
    ShuttingDownError,
)
from xgboost_ray_tpu.serve.predictor import CompiledPredictor
from xgboost_ray_tpu.serve.registry import ModelEntry, ModelRegistry


class NoReplicasError(RuntimeError):
    """Every replica is gone (killed or scaled to zero); HTTP 503."""


class _ReplicaRegistryView:
    """Per-replica view of the shared registry: same lease/drain semantics
    and model versions, but predictions run on this replica's OWN
    ``CompiledPredictor`` (built lazily per version; programs come from the
    shared module-level cache, so the build costs device_puts, not
    compiles)."""

    def __init__(self, registry: ModelRegistry, layout: str = "heap",
                 devices=None, min_bucket: int = 8):
        self._registry = registry
        self._layout = layout
        self._devices = devices
        self._min_bucket = min_bucket
        self._lock = threading.Lock()
        self._entry: Optional[ModelEntry] = None

    @contextmanager
    def lease(self):
        # the shared lease pins the version (and participates in the
        # registry's drain); the yielded entry swaps in this replica's
        # predictor for that exact version
        with self._registry.lease() as shared:
            yield self._entry_for(shared)

    def _entry_for(self, shared: ModelEntry) -> ModelEntry:
        with self._lock:
            entry = self._entry
        if entry is not None and entry.version == shared.version:
            return entry
        predictor = CompiledPredictor(
            shared.booster, devices=self._devices,
            min_bucket=self._min_bucket, layout=self._layout,
        )
        entry = ModelEntry(
            shared.version, shared.booster, predictor, name=shared.name
        )
        with self._lock:
            # two racing rebuilds of one version produce equivalent
            # entries; last writer wins and the loser's is garbage
            self._entry = entry
        return entry


class Replica:
    """One serving replica: a private batcher + predictor view over the
    shared registry. Shedding is centralized at the router, so the
    replica's own queue is uncapped."""

    def __init__(self, index: int, registry: ModelRegistry, metrics=None,
                 max_batch: int = 256, max_delay_ms: float = 2.0,
                 breaker_threshold: int = 5, layout: str = "heap",
                 devices=None, min_bucket: int = 8):
        self.index = index
        self.view = _ReplicaRegistryView(
            registry, layout=layout, devices=devices, min_bucket=min_bucket
        )
        self.batcher = MicroBatcher(
            self.view,
            max_batch=max_batch,
            max_delay_ms=max_delay_ms,
            metrics=metrics,
            max_queue_rows=0,
            breaker_threshold=breaker_threshold,
        )


class Router:
    """Least-queue-depth dispatcher over a replica table, with per-model
    admission control at the front door. Duck-types the ``MicroBatcher``
    surface so it drops into ``ServeHandle``."""

    def __init__(self, registry: ModelRegistry, n_replicas: int = 2,
                 metrics=None, max_batch: int = 256,
                 max_delay_ms: float = 2.0, max_queue_rows: int = 0,
                 breaker_threshold: int = 5, layout: str = "heap",
                 devices=None, min_bucket: int = 8):
        self.registry = registry
        self.metrics = metrics
        # admission control: reject (429) once this many rows are queued
        # across the whole pool (0 = unbounded)
        self.max_queue_rows = int(max_queue_rows)
        self._replica_kwargs = dict(
            registry=registry, metrics=metrics, max_batch=max_batch,
            max_delay_ms=max_delay_ms, breaker_threshold=breaker_threshold,
            layout=layout, devices=devices, min_bucket=min_bucket,
        )
        self._lock = threading.Lock()
        self._replicas: Dict[int, Replica] = {}
        self._next_slot = 0
        self._closed = False
        self.scale_to(max(int(n_replicas), 1), reason="startup")

    # -- pool membership ---------------------------------------------------

    def live_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def replica_slots(self) -> List[int]:
        with self._lock:
            return sorted(self._replicas)

    def _snapshot(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def scale_to(self, n: int, reason: str = "scale") -> int:
        """Grow or shrink the pool to ``n`` replicas; returns the live
        count. Shrinking drains the youngest replica before stopping it,
        so a scale-down drops no accepted request."""
        n = max(int(n), 0)
        added: List[Replica] = []
        removed: List[Replica] = []
        with self._lock:
            if self._closed:
                raise ShuttingDownError("router is shut down")
            while len(self._replicas) < n:
                slot = self._next_slot
                self._next_slot += 1
                replica = Replica(slot, **self._replica_kwargs)
                self._replicas[slot] = replica
                added.append(replica)
            while len(self._replicas) > n:
                slot = max(self._replicas)
                removed.append(self._replicas.pop(slot))
            live = len(self._replicas)
        tracer = obs.get_tracer()
        for replica in added:
            tracer.event(
                "serve.replica_up",
                replica=replica.index, reason=reason, live=live,
            )
        for replica in removed:
            tracer.event(
                "serve.replica_down",
                replica=replica.index, reason=reason, live=live,
            )
            # graceful: finish what it accepted, then stop; anything the
            # drain misses fails with ShuttingDownError and is re-dispatched
            replica.batcher.drain(timeout=5.0)
            replica.batcher.shutdown()
        return live

    def kill(self, slot: int) -> None:
        """Chaos hook: hard-stop one replica. Its queued requests fail
        internally and the router re-dispatches them to survivors; its
        mid-execution batch completes. Capacity drops, availability
        doesn't."""
        with self._lock:
            replica = self._replicas.pop(slot, None)
            live = len(self._replicas)
        if replica is None:
            raise KeyError(f"no live replica in slot {slot}")
        obs.get_tracer().event(
            "serve.replica_down", replica=slot, reason="killed", live=live,
        )
        replica.batcher.shutdown()

    def rejoin(self) -> int:
        """Bring one replica's worth of capacity back after a loss (the
        recover leg of the chaos story); returns the new slot."""
        with self._lock:
            if self._closed:
                raise ShuttingDownError("router is shut down")
            slot = self._next_slot
            self._next_slot += 1
            self._replicas[slot] = Replica(slot, **self._replica_kwargs)
            live = len(self._replicas)
        obs.get_tracer().event(
            "serve.replica_up", replica=slot, reason="rejoin", live=live,
        )
        return slot

    # -- request path ------------------------------------------------------

    def submit(
        self, x: np.ndarray, kind: str = "value",
        timeout: Optional[float] = 30.0,
    ) -> Tuple[np.ndarray, int]:
        """Admission-check, pick the least-loaded live replica, dispatch.
        A replica dying with this request queued sheds it back here and it
        is re-dispatched to a survivor — the caller never sees the death."""
        x = np.asarray(x, np.float32)
        n_rows = int(x.shape[0])
        if (
            self.max_queue_rows
            and self.queued_rows() + n_rows > self.max_queue_rows
        ):
            if self.metrics is not None:
                self.metrics.observe_admission_reject()
            raise OverloadedError(
                f"pool queue is full ({self.queued_rows()} rows queued, "
                f"cap {self.max_queue_rows}); request rejected at admission"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            replica = self._pick()
            if replica is None:
                with self._lock:
                    closed = self._closed
                if closed:
                    raise ShuttingDownError("router is shut down")
                raise NoReplicasError(
                    "no live replicas; scale_to()/rejoin() to restore "
                    "capacity"
                )
            faults.fire(
                "serve.route", replica=replica.index, kind=kind, rows=n_rows
            )
            obs.get_tracer().event(
                "serve.route", replica=replica.index, kind=kind, rows=n_rows,
            )
            if deadline is None:
                remaining = None
            else:
                remaining = max(deadline - time.monotonic(), 0.001)
            try:
                return replica.batcher.submit(x, kind, timeout=remaining)
            except ShuttingDownError:
                with self._lock:
                    closed = self._closed
                    still_live = replica.index in self._replicas
                if closed:
                    raise
                if still_live:
                    # the replica shut down without being removed (not a
                    # router action) — drop it from the table so the retry
                    # loop cannot spin on it
                    with self._lock:
                        self._replicas.pop(replica.index, None)
                        live = len(self._replicas)
                    obs.get_tracer().event(
                        "serve.replica_down",
                        replica=replica.index, reason="shutdown", live=live,
                    )
                continue  # re-dispatch to a survivor

    def _pick(self) -> Optional[Replica]:
        replicas = self._snapshot()
        if not replicas:
            return None
        # least queued ROWS (not requests): rows are what occupy the
        # device; ties break toward the lowest slot for determinism
        return min(
            replicas, key=lambda r: (r.batcher.queued_rows(), r.index)
        )

    # -- batcher-compatible surface ---------------------------------------

    def queue_depth(self) -> int:
        return sum(r.batcher.queue_depth() for r in self._snapshot())

    def queued_rows(self) -> int:
        return sum(r.batcher.queued_rows() for r in self._snapshot())

    def executing_batches(self) -> int:
        return sum(r.batcher.executing_batches() for r in self._snapshot())

    def consecutive_failures(self) -> int:
        return max(
            (r.batcher.consecutive_failures() for r in self._snapshot()),
            default=0,
        )

    @property
    def breaker_open(self) -> bool:
        """Degraded only when EVERY live replica's breaker is open — one
        healthy replica keeps the endpoint in rotation."""
        replicas = self._snapshot()
        return bool(replicas) and all(
            r.batcher.breaker_open for r in replicas
        )

    def drain(self, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        ok = True
        for replica in self._snapshot():
            ok = replica.batcher.drain(
                max(deadline - time.monotonic(), 0.0)
            ) and ok
        return ok

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            replicas = list(self._replicas.values())
            self._replicas = {}
        tracer = obs.get_tracer()
        for replica in replicas:
            tracer.event(
                "serve.replica_down",
                replica=replica.index, reason="shutdown", live=0,
            )
            replica.batcher.shutdown(timeout)
