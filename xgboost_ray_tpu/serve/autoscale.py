"""Replica autoscaler: p99/queue-driven scale decisions with hysteresis.

The serving metrics already carry everything a scaler needs — the
log-bucket latency histogram's p50/p95/p99 and the router's live queue
depth — so the scaler is a thin control loop over
``ServeMetrics.snapshot()`` + ``Router``: no new measurement plane.

Policy (deliberately boring — the interesting property is hysteresis):

* **hot** when p99 exceeds ``p99_high_ms`` OR the pool's queued requests
  reach ``queue_high``; after ``up_after`` *consecutive* hot evaluations,
  add one replica (bounded by ``max_replicas``).
* **cold** when p99 is under ``p99_low_ms`` AND the queue is empty; after
  ``down_after`` consecutive cold evaluations, remove one replica
  (bounded by ``min_replicas``).
* anything else resets both streaks — a single calm tick forgives a hot
  streak, so the scaler never flaps on a noisy boundary.

Every decision emits a ``serve.scale`` event carrying the direction, the
from/to replica counts, and the evidence (p99, queue depth, reason) — the
scale-up → scale-down cycle is reconstructible from the obs timeline
alone (pinned by ``tests/test_serve_pool.py``).

Drive it manually (``tick()`` per evaluation — what the tests and the
bench do) or start the background thread (``start()`` / ``stop()``).
"""

import threading
from typing import Optional

from xgboost_ray_tpu import obs


class AutoScaler:
    """Hysteresis scaler over a :class:`~xgboost_ray_tpu.serve.pool.Router`
    and a :class:`~xgboost_ray_tpu.serve.metrics.ServeMetrics`."""

    def __init__(
        self,
        router,
        metrics,
        min_replicas: int = 1,
        max_replicas: int = 4,
        p99_high_ms: float = 50.0,
        p99_low_ms: float = 5.0,
        queue_high: int = 0,
        up_after: int = 2,
        down_after: int = 3,
        interval_s: float = 1.0,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas; got "
                f"{min_replicas}..{max_replicas}"
            )
        self.router = router
        self.metrics = metrics
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.p99_high_ms = float(p99_high_ms)
        self.p99_low_ms = float(p99_low_ms)
        self.queue_high = int(queue_high)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._over = 0
        self._under = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self) -> int:
        """One evaluation of the control loop. Returns -1/0/+1 — the scale
        decision taken (and already applied to the router)."""
        snap = self.metrics.snapshot()
        p99 = float(snap.get("latency_p99_ms", 0.0))
        depth = int(self.router.queue_depth())
        live = int(self.router.live_replicas())
        queue_hot = self.queue_high > 0 and depth >= self.queue_high
        hot = p99 > self.p99_high_ms or queue_hot
        cold = p99 < self.p99_low_ms and depth == 0
        decision = 0
        reason = ""
        with self._lock:
            if hot:
                self._over += 1
                self._under = 0
            elif cold:
                self._under += 1
                self._over = 0
            else:
                self._over = 0
                self._under = 0
            if self._over >= self.up_after and live < self.max_replicas:
                decision = 1
                reason = "queue_depth" if queue_hot else "p99_high"
                self._over = 0
            elif self._under >= self.down_after and live > self.min_replicas:
                decision = -1
                reason = "idle"
                self._under = 0
        if decision:
            target = live + decision
            obs.get_tracer().event(
                "serve.scale",
                direction="up" if decision > 0 else "down",
                from_replicas=live,
                to_replicas=target,
                reason=reason,
                p99_ms=round(p99, 3),
                queue_depth=depth,
            )
            self.router.scale_to(
                target, reason="scale_up" if decision > 0 else "scale_down"
            )
        return decision

    # -- background loop ---------------------------------------------------

    def start(self) -> "AutoScaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
        self._thread = None

    def _loop(self) -> None:
        from xgboost_ray_tpu.serve.batcher import ShuttingDownError

        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except ShuttingDownError:
                return  # a racing endpoint shutdown ends the loop
            except Exception:  # noqa: BLE001 - retry next interval
                continue
