"""Serving-side observability, on the shared ``obs`` metrics plane.

Until PR 6 every gauge here was a hand-rolled Python counter and the
latency histogram was a private type; both now come from
``xgboost_ray_tpu.obs.metrics`` — the same registry/counter/histogram
primitives the training side uses — so the serving layer gains Prometheus
text exposition (``/metrics?format=prometheus``) for free and the
log-bucket :class:`LatencyHistogram` has one implementation repo-wide.

``snapshot()`` keeps its original flat-dict schema (the payload of the
HTTP ``/metrics`` JSON endpoint and of the bench ``serve`` section):
derived rates (qps, rows/s, padding waste, percentiles) are computed at
read time from the underlying counters. Each endpoint owns its own
:class:`~xgboost_ray_tpu.obs.metrics.MetricsRegistry` by default so
multiple endpoints in one process never share counters; pass
``registry=obs.get_registry()`` to publish into the process-wide one —
but at most ONE endpoint per registry: counters are name-keyed (no
per-endpoint label), so a second ServeMetrics on the same registry would
merge counts, rebind the live gauges to itself, and let either
endpoint's ``reset()`` zero the other's window.

Latency percentiles come from the fixed log-spaced histogram (60 buckets,
0.05 ms .. ~170 s at ~1.26x spacing): constant memory, O(1) record, and
the p50/p95/p99 read is a cumulative walk with linear interpolation
inside the bucket — the same resolution/overhead trade Prometheus client
histograms make.
"""

import threading
import time
from typing import Callable, Dict, List, Optional

from xgboost_ray_tpu.obs.metrics import (
    BUCKET_BOUNDS_MS as _BOUNDS_MS,  # noqa: F401 - back-compat re-export
    LatencyHistogram,
    MetricsRegistry,
)

__all__ = ["LatencyHistogram", "ServeMetrics"]

_COUNTER_NAMES = (
    "requests",
    "rows",
    "errors",
    "shed",
    "batches",
    "batch_rows",
    "padded_rows",
    "model_swaps",
    "admission_rejects",
    "canary_promotions",
    "canary_rollbacks",
)


class ServeMetrics:
    """Thread-safe counters for one serving endpoint.

    ``queue_depth_fn`` is injected by the batcher so the gauge reads the
    live queue without a reverse dependency; ``recompile_count_fn`` reads
    the predictor layer's trace counter the same way; ``breaker_fn`` the
    front-end's degradation breaker. All three are also exported as live
    gauges in the Prometheus exposition.
    """

    def __init__(
        self,
        queue_depth_fn: Optional[Callable[[], int]] = None,
        recompile_count_fn: Optional[Callable[[], int]] = None,
        breaker_fn: Optional[Callable[[], Dict[str, int]]] = None,
        replica_count_fn: Optional[Callable[[], int]] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        # outer lock restoring the pre-obs single-lock guarantee for
        # MULTI-counter operations: observe_batch's three increments,
        # reset()'s zeroing sweep, and snapshot()'s cross-counter read are
        # each atomic relative to one another (individual counters keep
        # their own locks for the Prometheus export path)
        self._lock = threading.Lock()
        self._started = time.monotonic()
        self._c = {
            name: self.registry.counter(f"rxgb_serve_{name}_total")
            for name in _COUNTER_NAMES
        }
        self._hist = self.registry.histogram(
            "rxgb_serve_latency_ms", "request latency (ms)"
        )
        self.queue_depth_fn = queue_depth_fn
        self.recompile_count_fn = recompile_count_fn
        # injected by the front-end: live degradation-breaker state
        # {"breaker_open": 0|1, "consecutive_predictor_failures": n}
        self.breaker_fn = breaker_fn
        # injected by the router: live replica count (None = unreplicated)
        self.replica_count_fn = replica_count_fn
        # the compile counter is process-global (the program cache is shared
        # so hot-swaps reuse programs); report compiles SINCE this endpoint
        # came up (re-baselined by reset()), not the process total
        self._recompile_base = int(recompile_count_fn()) if recompile_count_fn else 0
        # live gauges for the Prometheus exposition (the JSON snapshot reads
        # the fns directly); closures read the CURRENT fn so late injection
        # (http.py assigns queue_depth_fn after construction) just works
        self.registry.gauge(
            "rxgb_serve_uptime_seconds",
            fn=lambda: round(time.monotonic() - self._started, 3),
        )
        self.registry.gauge(
            "rxgb_serve_queue_depth",
            fn=lambda: int(self.queue_depth_fn()) if self.queue_depth_fn else 0,
        )
        self.registry.gauge(
            "rxgb_serve_breaker_open",
            fn=lambda: int((self.breaker_fn() or {}).get("breaker_open", 0))
            if self.breaker_fn
            else 0,
        )
        self.registry.gauge(
            "rxgb_serve_replicas",
            fn=lambda: (
                int(self.replica_count_fn()) if self.replica_count_fn else 1
            ),
        )
        self.registry.gauge(
            "rxgb_serve_recompile_count",
            fn=lambda: (
                int(self.recompile_count_fn()) - self._recompile_base
                if self.recompile_count_fn
                else 0
            ),
        )

    # back-compat attribute access (the counters used to be plain ints)
    @property
    def requests(self) -> int:
        return self._c["requests"].value

    @property
    def rows(self) -> int:
        return self._c["rows"].value

    @property
    def errors(self) -> int:
        return self._c["errors"].value

    @property
    def shed(self) -> int:
        return self._c["shed"].value

    @property
    def batches(self) -> int:
        return self._c["batches"].value

    @property
    def batch_rows(self) -> int:
        return self._c["batch_rows"].value

    @property
    def padded_rows(self) -> int:
        return self._c["padded_rows"].value

    @property
    def model_swaps(self) -> int:
        return self._c["model_swaps"].value

    @property
    def admission_rejects(self) -> int:
        return self._c["admission_rejects"].value

    @property
    def canary_promotions(self) -> int:
        return self._c["canary_promotions"].value

    @property
    def canary_rollbacks(self) -> int:
        return self._c["canary_rollbacks"].value

    def reset(self) -> None:
        """Zero every counter and restart the clock — used by the closed-loop
        bench to exclude its warmup traffic from the measured window."""
        with self._lock:
            self._started = time.monotonic()
            for c in self._c.values():
                c.reset()
            self._hist.reset()
            if self.recompile_count_fn is not None:
                self._recompile_base = int(self.recompile_count_fn())

    def observe_request(self, latency_s: float, n_rows: int) -> None:
        with self._lock:
            self._c["requests"].inc()
            self._c["rows"].inc(n_rows)
            self._hist.record(latency_s * 1000.0)

    def observe_error(self) -> None:
        self._c["errors"].inc()

    def observe_shed(self) -> None:
        self._c["shed"].inc()

    def observe_batch(self, n_rows: int, bucket: int) -> None:
        with self._lock:
            self._c["batches"].inc()
            self._c["batch_rows"].inc(n_rows)
            self._c["padded_rows"].inc(max(bucket - n_rows, 0))

    def observe_swap(self) -> None:
        self._c["model_swaps"].inc()

    def observe_admission_reject(self) -> None:
        """The router refused a request at the door (per-model admission
        control): the pool's queued rows would exceed the configured cap."""
        self._c["admission_rejects"].inc()

    def observe_canary(self, promoted: bool) -> None:
        """A canary publish concluded: the candidate was promoted (flip)
        or rolled back (old version kept serving)."""
        if promoted:
            self._c["canary_promotions"].inc()
        else:
            self._c["canary_rollbacks"].inc()

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            elapsed = max(time.monotonic() - self._started, 1e-9)
            hist = self._hist.snapshot()  # consistent cut under both locks
            requests = self.requests
            rows = self.rows
            batches = self.batches
            batch_rows = self.batch_rows
            padded = self.padded_rows
            # reset() rebaselines this under the same lock; reading it
            # outside the cut could pair a new baseline with old counters
            recompile_base = self._recompile_base
        issued = batch_rows + padded
        snap = {
            "uptime_s": round(elapsed, 3),
            "requests": requests,
            "rows": rows,
            "errors": self.errors,
            "shed": self.shed,
            "qps": round(requests / elapsed, 3),
            "rows_per_s": round(rows / elapsed, 3),
            "batches": batches,
            "mean_batch_rows": round(batch_rows / max(batches, 1), 3),
            "padding_waste": round(padded / max(issued, 1), 5),
            "latency_p50_ms": round(hist["p50_ms"], 4),
            "latency_p95_ms": round(hist["p95_ms"], 4),
            "latency_p99_ms": round(hist["p99_ms"], 4),
            "latency_mean_ms": round(hist["mean_ms"], 4),
            "model_swaps": self.model_swaps,
            "admission_rejects": self.admission_rejects,
            "canary_promotions": self.canary_promotions,
            "canary_rollbacks": self.canary_rollbacks,
        }
        if self.queue_depth_fn is not None:
            snap["queue_depth"] = int(self.queue_depth_fn())
        if self.replica_count_fn is not None:
            snap["replicas"] = int(self.replica_count_fn())
        if self.breaker_fn is not None:
            snap.update(self.breaker_fn())
        if self.recompile_count_fn is not None:
            snap["recompile_count"] = (
                int(self.recompile_count_fn()) - recompile_base
            )
        return snap

    def latency_buckets(self) -> List[int]:
        return list(self._hist.snapshot()["counts"])

    def prometheus_text(self) -> str:
        """Prometheus 0.0.4 text exposition of this endpoint's registry
        (counters, live gauges, and the latency histogram with cumulative
        ``le`` buckets) — the ``/metrics?format=prometheus`` payload."""
        return self.registry.prometheus_text()
