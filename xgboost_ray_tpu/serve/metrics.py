"""Serving-side observability counters.

The training path surfaces its one wire counter (``AllreduceBytes``) as a
plain number threaded through ``additional_results`` (PR 1); the serving
path follows the same pattern — every gauge here is a host-side Python
counter, updated under one lock on the request completion path and exported
as a flat dict by ``snapshot()`` (the payload of the HTTP ``/metrics``
endpoint and of the bench ``serve`` section). Nothing touches the device.

Latency percentiles come from a fixed log-spaced histogram (60 buckets,
0.05 ms .. ~170 s at ~1.26x spacing) rather than a reservoir: constant
memory, O(1) record, and the p50/p95/p99 read is a cumulative walk with
linear interpolation inside the bucket — the same resolution/overhead
trade Prometheus client histograms make.
"""

import math
import threading
import time
from typing import Callable, Dict, List, Optional

# log-spaced latency bucket upper bounds (ms)
_BUCKET_BASE_MS = 0.05
_BUCKET_FACTOR = 1.26
_N_BUCKETS = 60
_BOUNDS_MS = [
    _BUCKET_BASE_MS * _BUCKET_FACTOR ** i for i in range(_N_BUCKETS)
]


class LatencyHistogram:
    """Fixed log-bucket latency histogram with interpolated percentiles."""

    def __init__(self):
        self.counts = [0] * (_N_BUCKETS + 1)  # +1 overflow bucket
        self.total = 0
        self.sum_ms = 0.0

    def record(self, ms: float) -> None:
        if ms <= _BOUNDS_MS[0]:
            idx = 0
        elif ms > _BOUNDS_MS[-1]:
            idx = _N_BUCKETS
        else:
            idx = int(
                math.ceil(math.log(ms / _BUCKET_BASE_MS) / math.log(_BUCKET_FACTOR))
            )
            idx = min(max(idx, 0), _N_BUCKETS)
        self.counts[idx] += 1
        self.total += 1
        self.sum_ms += ms

    def percentile(self, q: float) -> float:
        """Interpolated latency at quantile ``q`` in [0, 1]; 0.0 when empty."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                hi = _BOUNDS_MS[i] if i < _N_BUCKETS else _BOUNDS_MS[-1] * _BUCKET_FACTOR
                lo = _BOUNDS_MS[i - 1] if 0 < i <= _N_BUCKETS else 0.0
                frac = (target - seen) / c
                return lo + frac * (hi - lo)
            seen += c
        return _BOUNDS_MS[-1]


class ServeMetrics:
    """Thread-safe counters for one serving endpoint.

    ``queue_depth_fn`` is injected by the batcher so the gauge reads the
    live queue without a reverse dependency; ``recompile_count_fn`` reads
    the predictor layer's trace counter the same way.
    """

    def __init__(
        self,
        queue_depth_fn: Optional[Callable[[], int]] = None,
        recompile_count_fn: Optional[Callable[[], int]] = None,
        breaker_fn: Optional[Callable[[], Dict[str, int]]] = None,
    ):
        self._lock = threading.Lock()
        self._started = time.monotonic()
        self._hist = LatencyHistogram()
        self.requests = 0
        self.rows = 0
        self.errors = 0
        self.shed = 0  # requests rejected at the max_queue_rows cap (429)
        self.batches = 0
        self.batch_rows = 0
        self.padded_rows = 0  # padding rows added on top of batch_rows
        self.model_swaps = 0
        self.queue_depth_fn = queue_depth_fn
        self.recompile_count_fn = recompile_count_fn
        # injected by the front-end: live degradation-breaker state
        # {"breaker_open": 0|1, "consecutive_predictor_failures": n}
        self.breaker_fn = breaker_fn
        # the compile counter is process-global (the program cache is shared
        # so hot-swaps reuse programs); report compiles SINCE this endpoint
        # came up (re-baselined by reset()), not the process total
        self._recompile_base = int(recompile_count_fn()) if recompile_count_fn else 0

    def reset(self) -> None:
        """Zero every counter and restart the clock — used by the closed-loop
        bench to exclude its warmup traffic from the measured window."""
        with self._lock:
            self._started = time.monotonic()
            self._hist = LatencyHistogram()
            self.requests = 0
            self.rows = 0
            self.errors = 0
            self.shed = 0
            self.batches = 0
            self.batch_rows = 0
            self.padded_rows = 0
            self.model_swaps = 0
            if self.recompile_count_fn is not None:
                self._recompile_base = int(self.recompile_count_fn())

    def observe_request(self, latency_s: float, n_rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += n_rows
            self._hist.record(latency_s * 1000.0)

    def observe_error(self) -> None:
        with self._lock:
            self.errors += 1

    def observe_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def observe_batch(self, n_rows: int, bucket: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += n_rows
            self.padded_rows += max(bucket - n_rows, 0)

    def observe_swap(self) -> None:
        with self._lock:
            self.model_swaps += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            elapsed = max(time.monotonic() - self._started, 1e-9)
            issued = self.batch_rows + self.padded_rows
            snap = {
                "uptime_s": round(elapsed, 3),
                "requests": self.requests,
                "rows": self.rows,
                "errors": self.errors,
                "shed": self.shed,
                "qps": round(self.requests / elapsed, 3),
                "rows_per_s": round(self.rows / elapsed, 3),
                "batches": self.batches,
                "mean_batch_rows": round(
                    self.batch_rows / max(self.batches, 1), 3
                ),
                "padding_waste": round(
                    self.padded_rows / max(issued, 1), 5
                ),
                "latency_p50_ms": round(self._hist.percentile(0.50), 4),
                "latency_p95_ms": round(self._hist.percentile(0.95), 4),
                "latency_p99_ms": round(self._hist.percentile(0.99), 4),
                "latency_mean_ms": round(
                    self._hist.sum_ms / max(self._hist.total, 1), 4
                ),
                "model_swaps": self.model_swaps,
            }
        if self.queue_depth_fn is not None:
            snap["queue_depth"] = int(self.queue_depth_fn())
        if self.breaker_fn is not None:
            snap.update(self.breaker_fn())
        if self.recompile_count_fn is not None:
            snap["recompile_count"] = (
                int(self.recompile_count_fn()) - self._recompile_base
            )
        return snap

    def latency_buckets(self) -> List[int]:
        with self._lock:
            return list(self._hist.counts)
