"""Compiled-predictor cache: zero-recompile steady-state inference.

The batch path (``ops/predict.py``) jits one program per input shape; online
traffic has arbitrary batch sizes, so naively each new size would trigger a
fresh XLA compile — seconds of tail latency. This layer makes the shape
space finite: every batch is padded up to a power-of-two **bucket** (rounded
to a mesh multiple), and the compiled program for a given
``(model signature, bucket, output kind, mesh)`` key is built exactly once
and cached process-wide. The tree walk is row-independent, so the padding
rows change nothing about the real rows' outputs — served results are
bit-identical to the batch ``predict()`` path (pinned by
``tests/test_serve.py``).

Programs are keyed by the booster's *structural* signature
(``RayXGBoostBooster.signature()``), not its identity: hot-swapping to a
same-shaped model (the common retrain-and-swap loop) reuses every compiled
program, so a swap costs zero recompiles. The forest rides in as a plain
jit argument.

Compile tracking: each program body bumps a module counter at Python trace
time (the body only executes when jax traces, i.e. compiles) — the counter
the ``/metrics`` ``recompile_count`` field and the zero-recompile test read.
"""

import threading
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from xgboost_ray_tpu import progreg
from xgboost_ray_tpu.constants import AXIS_ACTORS
from xgboost_ray_tpu.ops import node_array as node_array_ops
from xgboost_ray_tpu.ops import predict as predict_ops
from xgboost_ray_tpu.ops.grow import Tree

#: output kinds this layer can serve, mapped to the batch-path flag they
#: must stay bit-identical to
KINDS = ("value", "margin", "leaf", "contribs")

#: forest layouts the predictor can walk: the padded heap (per-tree
#: depth-first walk, the batch path's layout) and the FIL-style breadth-
#: first node-array (level-synchronous gathers; see ops/node_array.py).
#: Both serve bitwise-identical outputs; node_array targets lower p99.
LAYOUTS = ("heap", "node_array")

_lock = threading.Lock()
_COMPILE_COUNT = 0
# program cache: (signature, dev ids, kind) -> jitted callable; jax's own
# jit cache then holds one executable per bucket shape underneath it.
# Bounded FIFO like booster._SPMD_MARGIN_FNS; old models' programs age out.
_PROGRAMS: Dict[tuple, callable] = {}
_PROGRAMS_MAX = 128


def compile_count() -> int:
    """Total serve-program traces (== XLA compiles) in this process."""
    return _COMPILE_COUNT


def _count_trace() -> None:
    global _COMPILE_COUNT
    with _lock:
        _COMPILE_COUNT += 1


def bucket_rows(n: int, min_bucket: int, n_dev: int) -> int:
    """Smallest bucket >= max(n, min_bucket) from the ladder of powers of
    two rounded up to a multiple of ``n_dev`` (so the row shard divides
    evenly over the mesh). IDEMPOTENT — ``bucket_rows(bucket_rows(n)) ==
    bucket_rows(n)`` — which is what makes the warmup able to enumerate
    exactly the buckets live requests will hit on non-power-of-two device
    counts."""
    n_dev = max(int(n_dev), 1)
    rows = max(int(n), int(min_bucket), n_dev, 1)
    # start one power of two below rows: its n_dev-rounded value may
    # already cover rows (e.g. rows=17, n_dev=3 -> 16 rounds to 18)
    p = 1 << max((rows - 1).bit_length() - 1, 0)
    while True:
        b = -(-p // n_dev) * n_dev
        if b >= rows:
            return b
        p *= 2


def _cached_program(key, build):
    with _lock:
        fn = _PROGRAMS.get(key)
    if fn is not None:
        return fn
    fn = build()
    with _lock:
        if len(_PROGRAMS) >= _PROGRAMS_MAX:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
        _PROGRAMS[key] = fn
    return fn


class CompiledPredictor:
    """Padded-bucket inference facade over one booster + device set.

    Thin and stateless apart from device-resident model arrays: the
    program cache is module-level (shared across instances, so hot-swaps
    between same-shaped models hit warm programs), and every ``predict``
    call pads to a bucket, runs the cached program, and slices the real
    rows back out.
    """

    def __init__(self, booster, devices=None, min_bucket: int = 8,
                 layout: str = "heap"):
        sig = getattr(booster, "signature", None)
        if sig is None:
            raise TypeError(
                f"serving requires a tree booster (RayXGBoostBooster); got "
                f"{type(booster).__name__} — gblinear models have no padded "
                f"forest walk to compile."
            )
        if layout not in LAYOUTS:
            raise ValueError(
                f"unknown forest layout {layout!r}; one of {LAYOUTS}"
            )
        self.booster = booster
        self.devices = list(devices) if devices else [jax.devices()[0]]
        self.min_bucket = int(min_bucket)
        self.layout = layout
        self.signature = booster.signature()
        self._key_base = (
            self.signature,
            tuple(getattr(d, "id", i) for i, d in enumerate(self.devices)),
        )
        self.m0 = booster.base_score_margin_np()
        n_dev = len(self.devices)
        if n_dev > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            self._mesh = Mesh(np.asarray(self.devices), (AXIS_ACTORS,))
            self._repl = NamedSharding(self._mesh, P())
            self._rows = NamedSharding(self._mesh, P(AXIS_ACTORS))
            put = lambda a: jax.device_put(a, self._repl)  # noqa: E731
        else:
            dev = self.devices[0]
            put = lambda a: jax.device_put(a, dev)  # noqa: E731
        self.forest_dev = Tree(*[put(np.asarray(f)) for f in booster.forest])
        if layout == "node_array":
            # the level-major permutation of the same heap; forest_dev is
            # kept alongside because contribs stays on the heap program
            na_host = node_array_ops.forest_to_node_array(
                booster.forest, booster.max_depth
            )
            self.na_dev = node_array_ops.NodeForest(*[put(f) for f in na_host])
        else:
            self.na_dev = None
        self.has_tw = booster.tree_weights is not None
        self.tw_dev = put(
            np.asarray(booster.tree_weights, np.float32)
            if self.has_tw else np.zeros(0, np.float32)
        )

    # -- program builders --------------------------------------------------

    def _kernel_kwargs(self):
        b = self.booster
        return dict(
            max_depth=b.max_depth,
            num_outputs=b.num_outputs,
            num_parallel_tree=b.params.num_parallel_tree,
            ntree_limit=0,
            cat_features=b.cat_features,
        )

    def _uses_node_array(self, kind: str) -> bool:
        # contribs needs base_weight/cover path statistics the node array
        # does not carry — it routes to the (shared) heap program, so a
        # node-array predictor's contribs hit the same cache entry a heap
        # predictor's do and stay trivially bitwise-identical
        return self.layout == "node_array" and kind != "contribs"

    def _program(self, kind: str):
        # "value" and "margin" trace the identical program (they differ only
        # in host-side _finalize) — share one cache entry so warming either
        # warms both and neither ever compiles twice
        prog_kind = "margin" if kind == "value" else kind
        if self._uses_node_array(kind):
            key = self._key_base + (prog_kind, "node_array")
            return _cached_program(
                key, lambda: self._build_program_na(prog_kind)
            )
        key = self._key_base + (prog_kind,)
        return _cached_program(key, lambda: self._build_program(prog_kind))

    def _build_program(self, kind: str):
        kw = self._kernel_kwargs()
        has_tw = self.has_tw
        n_dev = len(self.devices)

        if kind in ("value", "margin"):
            def body(forest, tw, x, base):
                _count_trace()
                return predict_ops.predict_margin(
                    forest, x, base, tree_weights=tw if has_tw else None, **kw
                )

            if n_dev > 1:
                from jax.sharding import PartitionSpec as P

                from xgboost_ray_tpu.compat import shard_map_compat as shard_map

                return jax.jit(
                    shard_map(
                        body, mesh=self._mesh,
                        in_specs=(P(), P(), P(AXIS_ACTORS), P(AXIS_ACTORS)),
                        out_specs=P(AXIS_ACTORS),
                    )
                )
            return jax.jit(body)

        if kind == "leaf":
            max_depth = kw["max_depth"]
            cat_features = kw["cat_features"]

            def body(forest, tw, x, base):
                _count_trace()
                return predict_ops.predict_leaf_index(
                    forest, x, max_depth, cat_features=cat_features
                )

            # row sharding propagates through the vmap'd walk (GSPMD); no
            # manual shard_map needed for an int gather with no collectives
            return jax.jit(body)

        if kind == "contribs":
            def body(forest, tw, x, base):
                _count_trace()
                return predict_ops.predict_contribs_exact(
                    forest, x, tree_weights=tw if has_tw else None, **kw
                )

            # like booster.predict_special_spmd: the scan-carrying SHAP
            # kernel parallelizes over rows via sharding propagation from
            # the device_put inputs, not an explicit shard_map
            return jax.jit(body)

        raise ValueError(f"unknown serve output kind {kind!r}; one of {KINDS}")

    def _build_program_na(self, kind: str):
        """Node-array twin of :meth:`_build_program`: same calling
        convention (model, tw, x, base) with the flat :class:`NodeForest`
        in the model slot, same sharding story as the heap programs."""
        kw = self._kernel_kwargs()
        has_tw = self.has_tw
        n_dev = len(self.devices)

        if kind == "margin":
            def body(na, tw, x, base):
                _count_trace()
                return node_array_ops.predict_margin_na(
                    na, x, base, tree_weights=tw if has_tw else None, **kw
                )

            if n_dev > 1:
                from jax.sharding import PartitionSpec as P

                from xgboost_ray_tpu.compat import shard_map_compat as shard_map

                return jax.jit(
                    shard_map(
                        body, mesh=self._mesh,
                        in_specs=(P(), P(), P(AXIS_ACTORS), P(AXIS_ACTORS)),
                        out_specs=P(AXIS_ACTORS),
                    )
                )
            return jax.jit(body)

        if kind == "leaf":
            max_depth = kw["max_depth"]
            cat_features = kw["cat_features"]

            def body(na, tw, x, base):
                _count_trace()
                return node_array_ops.predict_leaf_index_na(
                    na, x, max_depth, cat_features=cat_features
                )

            return jax.jit(body)

        raise ValueError(
            f"no node-array program for kind {kind!r} (contribs routes to "
            f"the heap program)"
        )

    # -- execution ---------------------------------------------------------

    def predict(self, x: np.ndarray, kind: str = "value") -> np.ndarray:
        """Serve one already-coerced [N, F] float32 batch. Pads to the
        bucket, runs the cached program, slices the N real rows back out and
        applies the same host-side finalization as the batch path."""
        out, _ = self.predict_with_bucket(x, kind)
        return out

    def predict_with_bucket(
        self, x: np.ndarray, kind: str = "value"
    ) -> Tuple[np.ndarray, int]:
        if kind not in KINDS:
            raise ValueError(
                f"unknown serve output kind {kind!r}; one of {KINDS}"
            )
        b = self.booster
        if kind == "contribs":
            # same guard as the batch path: a pre-node-stats model would
            # serve all-zero SHAP values with a 200 instead of erroring
            b._assert_node_stats()
        n = int(x.shape[0])
        n_dev = len(self.devices)
        bucket = bucket_rows(n, self.min_bucket, n_dev)
        xb = np.zeros((bucket, b.num_features), np.float32)
        xb[:n] = x
        base = np.full((bucket, b.num_outputs), self.m0, np.float32)
        if n_dev > 1:
            xb_dev = jax.device_put(xb, self._rows)
            base_dev = jax.device_put(base, self._rows)
        else:
            xb_dev = jax.device_put(xb, self.devices[0])
            base_dev = jax.device_put(base, self.devices[0])
        prog = self._program(kind)
        self._note_program(kind, bucket, prog, (xb_dev, base_dev))
        model_dev = (
            self.na_dev if self._uses_node_array(kind) else self.forest_dev
        )
        res = prog(model_dev, self.tw_dev, xb_dev, base_dev)
        out = np.asarray(res)[:n]
        return self._finalize(out, kind), bucket

    def _note_program(self, kind: str, bucket: int, prog, row_args) -> None:
        """Register the bucket's program signature with the progreg registry
        (no-op unless capture is on — the serve hot path pays one early
        return). ``row_args`` are the (x, base) batch arrays; only shapes
        and dtypes are read, so host arrays work as well as device ones."""
        if not progreg.enabled():
            return
        prog_kind = "margin" if kind == "value" else kind
        meta = {
            "world": len(self.devices),
            "bucket": int(bucket),
            "grower": "serve",
            "hist_quant": "none",
            "sampling": "none",
        }
        if self._uses_node_array(kind):
            # own meta coordinate: node-array programs form their own
            # verify identity groups instead of colliding with the heap
            # walk's (same name, different jaxpr)
            meta["layout"] = "node_array"
            model_dev = self.na_dev
        else:
            model_dev = self.forest_dev
        progreg.note_jit_call(
            f"serve.predict_{prog_kind}",
            prog,
            (model_dev, self.tw_dev) + tuple(row_args),
            meta=meta,
        )

    def register_programs(self, kinds=KINDS, batch: int = 8) -> None:
        """Build + register the bucket programs for ``batch`` rows WITHOUT
        executing (jit stays lazy): the jaxpr verifier's entry point. Uses
        the exact argument assembly of :meth:`predict_with_bucket`."""
        b = self.booster
        n_dev = len(self.devices)
        bucket = bucket_rows(batch, self.min_bucket, n_dev)
        xb = np.zeros((bucket, b.num_features), np.float32)
        base = np.full((bucket, b.num_outputs), self.m0, np.float32)
        for kind in kinds:
            self._note_program(kind, bucket, self._program(kind), (xb, base))

    def _finalize(self, out: np.ndarray, kind: str) -> np.ndarray:
        b = self.booster
        if kind == "margin":
            return out[:, 0] if b.num_outputs == 1 else out
        if kind == "value":
            # the batch path transforms eagerly on host (outside the jitted
            # walk) — do exactly the same so values stay bit-identical
            return b._margin_to_prediction(out, output_margin=False)
        if kind == "leaf":
            return out
        # contribs: bias column carries the base-score margin, class axis
        # squeezed for single-output models (shared batch-path helper, which
        # mutates in place — the device view is read-only, so copy)
        return b._finalize_contribs(np.array(out), "contribs", None)

    def warmup(self, kinds=("value",), max_batch: int = 256) -> int:
        """Compile every bucket in [min_bucket, bucket(max_batch)] for the
        given kinds; returns the number of programs compiled now. After
        warmup, requests up to ``max_batch`` rows never compile."""
        before = compile_count()
        n_dev = len(self.devices)
        top = bucket_rows(max_batch, self.min_bucket, n_dev)
        dummy_cols = self.booster.num_features
        n = 1
        while True:
            # enumerate successive distinct buckets: bucket_rows is an
            # idempotent monotone step function, so bucket+1 jumps to the
            # next rung of the ladder
            bucket = bucket_rows(n, self.min_bucket, n_dev)
            x = np.zeros((bucket, dummy_cols), np.float32)
            for kind in kinds:
                self.predict(x, kind)
            if bucket >= top:
                break
            n = bucket + 1
        return compile_count() - before
