"""The train → refresh → serve loop: warm-start refresh, shadow + canary
publish, automatic rollback.

Closes the loop between the trainer and the serving plane:

* :func:`refresh` — continual refresh: boost additional rounds on fresh
  data *warm-started from the live booster* (``train(xgb_model=live)``).
  With a streamed ``RayDMatrix`` the ingestion plane's mergeable quantile
  sketch (``stream/sketch.py``) folds the fresh chunks' summaries onto the
  existing cut structure, so refreshing is an incremental pass over the
  new data, not a re-read of history.
* :class:`CanaryController` — gated publish on top of the registry's
  drain-then-flip hot-swap. The candidate is evaluated *before* the flip:

  1. **shadow traffic** — the candidate predicts the mirrored request
     sample next to the live model; the divergence is recorded as a
     ``serve.shadow`` event (evidence, not a gate);
  2. **canary gate** — candidate vs live metric (default: binary logloss)
     on a labeled canary set, through each model's compiled predictor;
  3. **verdict** — a regression past the gate emits ``serve.rollback``
     and leaves the registry untouched: the old version never stops
     serving, bit-identically, for even one request (the rollback is
     automatic because the bad model is never flipped in). A pass runs
     ``registry.load`` — full warm (all four kinds), drain, flip — and
     emits ``serve.promote``.

Every publish fires the ``serve.canary`` fault site before the verdict,
so chaos plans can fail the evaluation itself; ``tests/test_serve.py``
hammers the gate under concurrent load and ``tests/test_serve_pool.py``
runs the refresh → publish loop end-to-end.
"""

from typing import Any, Callable, Dict, Optional

import numpy as np

from xgboost_ray_tpu import faults, obs
from xgboost_ray_tpu.serve.predictor import CompiledPredictor
from xgboost_ray_tpu.serve.registry import ModelRegistry, coerce_model


def binary_logloss(y_true: np.ndarray, y_prob: np.ndarray) -> float:
    """Mean negative log-likelihood of binary labels under predicted
    probabilities (the default canary metric; lower is better)."""
    y = np.asarray(y_true, np.float64).reshape(-1)
    p = np.clip(np.asarray(y_prob, np.float64).reshape(-1), 1e-7, 1 - 1e-7)
    return float(-np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))


def refresh(live_booster, params: Dict, dtrain, num_boost_round: int,
            ray_params=None, **train_kwargs):
    """Warm-start ``num_boost_round`` additional rounds from the live
    booster on fresh data; returns the refreshed booster (publish it with
    :meth:`CanaryController.publish`)."""
    from xgboost_ray_tpu.main import train  # lazy: main imports serve

    return train(
        params, dtrain, num_boost_round,
        ray_params=ray_params, xgb_model=live_booster, **train_kwargs,
    )


class CanaryController:
    """Shadow + canary gate in front of a registry's hot-swap."""

    def __init__(
        self,
        registry: ModelRegistry,
        metric_fn: Callable[[np.ndarray, np.ndarray], float] = binary_logloss,
        rel_tol: float = 0.02,
        abs_tol: float = 1e-6,
        metrics=None,
    ):
        self.registry = registry
        self.metric_fn = metric_fn
        # gate: candidate_metric <= live_metric * (1 + rel_tol) + abs_tol
        self.rel_tol = float(rel_tol)
        self.abs_tol = float(abs_tol)
        self.metrics = metrics

    def _candidate_predictor(self, booster) -> CompiledPredictor:
        return CompiledPredictor(
            booster,
            devices=self.registry.devices,
            min_bucket=self.registry.min_bucket,
            layout=getattr(self.registry, "layout", "heap"),
        )

    def publish(
        self,
        candidate: Any,
        canary_x: np.ndarray,
        canary_y: np.ndarray,
        shadow_x: Optional[np.ndarray] = None,
        name: str = "",
    ) -> Dict[str, Any]:
        """Evaluate ``candidate`` against the live model and flip only on a
        pass. Returns the verdict dict (``promoted``, both metric values,
        the serving version, and the shadow divergence when measured)."""
        booster = coerce_model(candidate)
        canary_x = np.asarray(canary_x, np.float32)
        canary_y = np.asarray(canary_y)
        if not self.registry.has_model:
            # cold start: nothing to canary against — publish directly
            version = self.registry.load(booster, name=name)
            obs.get_tracer().event(
                "serve.promote", version=version, reason="cold_start",
            )
            if self.metrics is not None:
                self.metrics.observe_canary(promoted=True)
            return {"promoted": True, "version": version,
                    "reason": "cold_start"}

        cand_pred = self._candidate_predictor(booster)
        with self.registry.lease() as live:
            live_version = live.version
            shadow_delta = None
            if shadow_x is not None:
                shadow_x = np.asarray(shadow_x, np.float32)
                live_out = live.predictor.predict(shadow_x, "value")
                cand_out = cand_pred.predict(shadow_x, "value")
                shadow_delta = float(
                    np.mean(np.abs(
                        np.asarray(cand_out, np.float64)
                        - np.asarray(live_out, np.float64)
                    ))
                )
                obs.get_tracer().event(
                    "serve.shadow",
                    live_version=live_version,
                    rows=int(shadow_x.shape[0]),
                    mean_abs_delta=round(shadow_delta, 6),
                )
            faults.fire(
                "serve.canary",
                live_version=live_version, rows=int(canary_x.shape[0]),
            )
            live_metric = self.metric_fn(
                canary_y, live.predictor.predict(canary_x, "value")
            )
        cand_metric = self.metric_fn(
            canary_y, cand_pred.predict(canary_x, "value")
        )
        gate = live_metric * (1.0 + self.rel_tol) + self.abs_tol
        verdict: Dict[str, Any] = {
            "live_version": live_version,
            "live_metric": live_metric,
            "candidate_metric": cand_metric,
            "gate": gate,
        }
        if shadow_delta is not None:
            verdict["shadow_mean_abs_delta"] = shadow_delta
        if cand_metric > gate:
            # regression: never flip — the live version keeps serving
            # bit-identically; this IS the automatic rollback
            obs.get_tracer().event(
                "serve.rollback",
                live_version=live_version,
                live_metric=round(live_metric, 6),
                candidate_metric=round(cand_metric, 6),
            )
            if self.metrics is not None:
                self.metrics.observe_canary(promoted=False)
            verdict.update(promoted=False, version=live_version,
                           reason="metric_regression")
            return verdict
        version = self.registry.load(booster, name=name)
        obs.get_tracer().event(
            "serve.promote",
            version=version,
            live_metric=round(live_metric, 6),
            candidate_metric=round(cand_metric, 6),
        )
        if self.metrics is not None:
            self.metrics.observe_canary(promoted=True)
        verdict.update(promoted=True, version=version, reason="gate_pass")
        return verdict
