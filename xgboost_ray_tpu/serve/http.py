"""Threaded stdlib HTTP front-end for online inference.

Endpoints (JSON in/out, loopback-friendly, no extra dependencies):

* ``POST /predict`` — body ``{"data": [[...], ...], "kind": "value"}``;
  responds ``{"predictions": [...], "model_version": v, "latency_ms": t}``.
  ``kind`` is one of ``value | margin | leaf | contribs`` (default value).
* ``POST /models`` — hot-swap: body ``{"path": "..."}`` (saved native or
  xgboost JSON model) or ``{"model_json": {...}}``; drains in-flight
  batches, responds ``{"model_version": v}``.
* ``GET /healthz`` — 200 ``{"status": "ok", "model_version": v}`` when
  serving; 503 with ``status`` ``no_model`` / ``draining`` (graceful
  shutdown) / ``degraded`` (consecutive-predictor-failure breaker open).
* ``GET /metrics`` — the ``ServeMetrics.snapshot()`` dict: qps, queue
  depth, p50/p95/p99 latency, padding-waste fraction, recompile count.
  ``GET /metrics?format=prometheus`` returns the same endpoint's counters,
  live gauges and latency histogram as Prometheus 0.0.4 text exposition
  (stable name ordering, cumulative ``le`` buckets) — scrape-ready, from
  the shared ``obs`` metrics registry.

Each HTTP request runs on its own thread (``ThreadingHTTPServer``); the
threads rendezvous in the microbatcher, which is where concurrency turns
into padded-bucket batches.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from xgboost_ray_tpu.serve.batcher import (
    MicroBatcher,
    OverloadedError,
    ShuttingDownError,
)
from xgboost_ray_tpu.serve.metrics import ServeMetrics
from xgboost_ray_tpu.serve.pool import NoReplicasError, Router
from xgboost_ray_tpu.serve.predictor import KINDS, compile_count
from xgboost_ray_tpu.serve.registry import ModelRegistry, NoModelError


class _Handler(BaseHTTPRequestHandler):
    # set by the server factory
    serve_handle: "ServeHandle" = None

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw.decode("utf-8"))

    def _reply_text(self, code: int, body: str, content_type: str) -> None:
        raw = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def do_GET(self):  # noqa: N802 - http.server API
        from urllib.parse import parse_qs, urlparse

        h = self.serve_handle
        parsed = urlparse(self.path)
        if parsed.path == "/metrics":
            fmt = parse_qs(parsed.query).get("format", ["json"])[0]
            if fmt == "prometheus":
                self._reply_text(
                    200, h.metrics.prometheus_text(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif fmt == "json":
                self._reply(200, h.metrics.snapshot())
            else:
                self._reply(400, {"error": f"unknown format {fmt!r}; "
                                           f"one of json|prometheus"})
            return
        if self.path == "/healthz":
            # 503 is reserved for the take-me-out-of-rotation states:
            # draining (graceful shutdown), no model yet, and degraded
            # (consecutive-predictor-failure breaker open). Requests still
            # flow while degraded so one success can close the breaker.
            if h.draining:
                self._reply(503, {"status": "draining"})
            elif not h.registry.has_model:
                self._reply(503, {"status": "no_model"})
            elif h.batcher.breaker_open:
                self._reply(503, {
                    "status": "degraded",
                    "consecutive_predictor_failures":
                        h.batcher.consecutive_failures(),
                    "model_version": h.registry.version,
                })
            else:
                self._reply(200, {
                    "status": "ok", "model_version": h.registry.version,
                })
            return
        self._reply(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):  # noqa: N802 - http.server API
        h = self.serve_handle
        try:
            doc = self._read_json()
        except (ValueError, UnicodeDecodeError) as exc:
            self._reply(400, {"error": f"bad JSON body: {exc}"})
            return
        if self.path == "/predict":
            self._do_predict(h, doc)
            return
        if self.path == "/models":
            self._do_models(h, doc)
            return
        self._reply(404, {"error": f"unknown path {self.path!r}"})

    def _do_predict(self, h: "ServeHandle", doc: dict) -> None:
        t0 = time.monotonic()
        if h.draining:
            # graceful shutdown step 1: stop ACCEPTING before draining
            self._reply(503, {"error": "endpoint is draining"})
            return
        data = doc.get("data")
        if data is None:
            self._reply(400, {"error": "missing 'data'"})
            return
        kind = doc.get("kind", "value")
        try:
            x = np.asarray(data, np.float32)
            if x.ndim == 1:
                x = x[None, :]
            if x.ndim != 2:
                raise ValueError(f"'data' must be [rows, features]; got "
                                 f"ndim={x.ndim}")
            # feature-count validation happens in the batcher against the
            # LEASED model (hot-swap safe); its ValueError maps to 400 below
            result, version = h.batcher.submit(x, kind)
        except OverloadedError as exc:
            # shed counted once, in the batcher, when the cap rejected it
            self._reply(429, {"error": str(exc)})
            return
        except (NoModelError, NoReplicasError, ShuttingDownError) as exc:
            self._reply(503, {"error": str(exc)})
            return
        except (ValueError, TypeError) as exc:
            h.metrics.observe_error()
            self._reply(400, {"error": str(exc)})
            return
        except TimeoutError as exc:
            h.metrics.observe_error()
            self._reply(504, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - XLA/runtime failures etc.
            # anything marshalled out of the batch (device runtime errors,
            # a racing shutdown) must still produce a structured response,
            # not a dropped connection
            h.metrics.observe_error()
            self._reply(500, {"error": f"{type(exc).__name__}: {exc}"})
            return
        self._reply(200, {
            "predictions": np.asarray(result).tolist(),
            "model_version": version,
            "kind": kind,
            "latency_ms": round((time.monotonic() - t0) * 1000.0, 3),
        })

    def _do_models(self, h: "ServeHandle", doc: dict) -> None:
        model = doc.get("path") or doc.get("model_json")
        if model is None:
            self._reply(400, {"error": "body must carry 'path' or "
                                       "'model_json'"})
            return
        try:
            version = h.registry.load(model)
        except (OSError, ValueError, TypeError, KeyError) as exc:
            self._reply(400, {"error": f"{type(exc).__name__}: {exc}"})
            return
        except Exception as exc:  # noqa: BLE001 - compile/warmup failures
            # an XLA compile error (or an injected registry.swap fault) must
            # produce a structured 500, not a dropped connection
            self._reply(500, {"error": f"{type(exc).__name__}: {exc}"})
            return
        self._reply(200, {"model_version": version})


class ServeHandle:
    """One serving endpoint: registry + batcher + metrics + HTTP server."""

    def __init__(
        self,
        model=None,
        host: str = "127.0.0.1",
        port: int = 0,
        devices=None,
        max_batch: int = 256,
        max_delay_ms: float = 2.0,
        min_bucket: int = 8,
        warm_kinds: tuple = KINDS,
        max_queue_rows: int = 0,
        breaker_threshold: int = 5,
        n_replicas: int = 1,
        layout: str = "heap",
    ):
        self._draining = False
        self.metrics = ServeMetrics(recompile_count_fn=compile_count)
        self.registry = ModelRegistry(
            devices=devices,
            min_bucket=min_bucket,
            layout=layout,
            warm_kinds=warm_kinds,
            warm_max_batch=max_batch,
            metrics=self.metrics,
        )
        # the two steps that can fail (port bind, bad model) run BEFORE the
        # batcher spawns its flusher thread, so a raising __init__ leaks no
        # thread the caller has no handle to shut down
        handler = type("_BoundHandler", (_Handler,), {"serve_handle": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._server_thread: Optional[threading.Thread] = None
        try:
            if model is not None:
                self.registry.load(model)
            if n_replicas > 1:
                # Router duck-types the batcher surface (submit/drain/
                # shutdown/queue_depth/breaker_open), so everything below
                # — and every handler — is replica-count agnostic
                self.batcher = Router(
                    self.registry,
                    n_replicas=n_replicas,
                    metrics=self.metrics,
                    max_batch=max_batch,
                    max_delay_ms=max_delay_ms,
                    max_queue_rows=max_queue_rows,
                    breaker_threshold=breaker_threshold,
                    layout=layout,
                    devices=devices,
                    min_bucket=min_bucket,
                )
                self.metrics.replica_count_fn = self.batcher.live_replicas
            else:
                self.batcher = MicroBatcher(
                    self.registry,
                    max_batch=max_batch,
                    max_delay_ms=max_delay_ms,
                    metrics=self.metrics,
                    max_queue_rows=max_queue_rows,
                    breaker_threshold=breaker_threshold,
                )
        except BaseException:
            self._httpd.server_close()
            raise
        self.metrics.queue_depth_fn = self.batcher.queue_depth
        self.metrics.breaker_fn = lambda: {
            "breaker_open": int(self.batcher.breaker_open),
            "consecutive_predictor_failures":
                self.batcher.consecutive_failures(),
        }

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "ServeHandle":
        self._server_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._server_thread.start()
        return self

    def shutdown(self, drain_timeout_s: float = 5.0) -> None:
        """Graceful: stop accepting (503 on new /predict), drain queued and
        in-flight batches, then close the server and the batcher."""
        self._draining = True
        self.batcher.drain(drain_timeout_s)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._server_thread is not None:
            self._server_thread.join(5.0)
        self.batcher.shutdown()


def create_server(model=None, host: str = "127.0.0.1", port: int = 0,
                  **config) -> ServeHandle:
    """Build and start a serving endpoint; returns its ``ServeHandle``
    (``.url`` for clients, ``.registry.load()`` for hot-swaps,
    ``.shutdown()`` when done)."""
    return ServeHandle(model=model, host=host, port=port, **config).start()
