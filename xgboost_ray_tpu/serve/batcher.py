"""Microbatching queue: coalesce concurrent requests into padded batches.

Dedicated GBDT inference engines get their throughput from batched,
layout-specialized tree traversal (Booster, arXiv:2011.02022; the GPU
prediction kernel of arXiv:1806.11248); on TPU the analog is feeding the
jit-compiled padded-bucket walk batches as large as latency allows. This
queue implements the standard two-knob policy:

* ``max_batch`` — flush as soon as the pending rows for one output kind
  reach this many (throughput bound);
* ``max_delay_ms`` — flush when the OLDEST pending request has waited this
  long (latency bound), even if the batch is small.

Requests of different output kinds never share a batch (their programs
differ); within a kind, rows are concatenated in arrival order, executed
against one leased model snapshot, and sliced back per request — so every
response is wholly from one model version, with that version reported back.
"""

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from xgboost_ray_tpu import faults
from xgboost_ray_tpu.serve.predictor import KINDS
from xgboost_ray_tpu.serve.registry import ModelRegistry, NoModelError


class OverloadedError(RuntimeError):
    """The queue is at its ``max_queue_rows`` cap: the request is shed
    (HTTP 429) instead of queueing unboundedly behind a slow predictor."""


class ShuttingDownError(RuntimeError):
    """The batcher is shut down / shutting down; no new requests (HTTP 503)."""


class _Pending:
    __slots__ = ("x", "kind", "event", "result", "version", "error", "t_in")

    def __init__(self, x: np.ndarray, kind: str):
        self.x = x
        self.kind = kind
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.version: int = 0
        self.error: Optional[BaseException] = None
        self.t_in = time.monotonic()


class MicroBatcher:
    """Request queue + background flusher over a ``ModelRegistry``."""

    def __init__(
        self,
        registry: ModelRegistry,
        max_batch: int = 256,
        max_delay_ms: float = 2.0,
        metrics=None,
        max_queue_rows: int = 0,
        breaker_threshold: int = 5,
    ):
        self.registry = registry
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.metrics = metrics
        # load shedding: reject (429) once this many rows are queued
        # (0 = unbounded, the pre-hardening behavior)
        self.max_queue_rows = int(max_queue_rows)
        # degradation breaker: this many consecutive failed batches flips
        # /healthz to "degraded" (a success closes it again)
        self.breaker_threshold = int(breaker_threshold)
        self._cond = threading.Condition(threading.Lock())
        self._queues: Dict[str, List[_Pending]] = {k: [] for k in KINDS}
        self._depth = 0  # pending requests across kinds (queue_depth gauge)
        self._queued_rows = 0  # pending ROWS across kinds (shedding cap)
        self._executing = 0  # batches currently running on the device
        self._consecutive_failures = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._flusher, name="serve-flusher", daemon=True
        )
        self._thread.start()

    # -- client side -------------------------------------------------------

    def submit(
        self, x: np.ndarray, kind: str = "value", timeout: float = 30.0
    ) -> Tuple[np.ndarray, int]:
        """Enqueue one [N, F] request; block until its batch executes.
        Returns ``(result, model_version)``."""
        if kind not in KINDS:
            raise ValueError(
                f"unknown serve output kind {kind!r}; one of {KINDS}"
            )
        req = _Pending(np.asarray(x, np.float32), kind)
        n_rows = int(req.x.shape[0])
        with self._cond:
            # the closed check and the append are one atomic block: a
            # request can never slip in between shutdown's closed-flip and
            # its straggler sweep and then sit out its full client timeout
            if self._closed:
                raise ShuttingDownError("batcher is shut down")
            if (
                self.max_queue_rows
                and self._queued_rows + n_rows > self.max_queue_rows
            ):
                if self.metrics is not None:
                    self.metrics.observe_shed()
                raise OverloadedError(
                    f"serve queue is full ({self._queued_rows} rows queued, "
                    f"cap {self.max_queue_rows}); request shed"
                )
            self._queues[kind].append(req)
            self._depth += 1
            self._queued_rows += n_rows
            self._cond.notify_all()
        if not req.event.wait(timeout):
            # shed the request if it is still queued, so an abandoned
            # client's rows don't occupy device time later and deepen the
            # overload (mid-execution requests can't be recalled)
            with self._cond:
                q = self._queues[kind]
                if req in q:
                    q.remove(req)
                    self._depth -= 1
                    self._queued_rows -= n_rows
                closed = self._closed
            if closed:
                # a shutdown racing this wait is a drain, not a timeout
                raise ShuttingDownError("batcher shut down while waiting")
            raise TimeoutError(
                f"serve request did not complete within {timeout}s"
            )
        if req.error is not None:
            raise req.error
        if self.metrics is not None:
            self.metrics.observe_request(
                time.monotonic() - req.t_in, int(req.x.shape[0])
            )
        return req.result, req.version

    def queue_depth(self) -> int:
        with self._cond:
            return self._depth

    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def executing_batches(self) -> int:
        """Batches currently running on the device (drain barometer)."""
        with self._cond:
            return self._executing

    def consecutive_failures(self) -> int:
        with self._cond:
            return self._consecutive_failures

    @property
    def breaker_open(self) -> bool:
        """True once ``breaker_threshold`` batches failed in a row — the
        endpoint reports itself ``degraded`` (requests still flow, so one
        success can close the breaker again)."""
        with self._cond:
            return (
                self.breaker_threshold > 0
                and self._consecutive_failures >= self.breaker_threshold
            )

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until nothing is queued or executing (graceful-shutdown
        step 2); True when fully drained within ``timeout``."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if self._depth == 0 and self._executing == 0:
                    return True
            time.sleep(0.005)
        with self._cond:
            return self._depth == 0 and self._executing == 0

    def shutdown(self, timeout: float = 5.0) -> None:
        # closed-flip and the straggler sweep are one atomic block, so a
        # queued request is failed promptly instead of waiting out its
        # client timeout (mid-execution batches still complete normally)
        with self._cond:
            self._closed = True
            for q in self._queues.values():
                for req in q:
                    req.error = ShuttingDownError("batcher shut down")
                    req.event.set()
                q.clear()
            self._depth = 0
            self._queued_rows = 0
            self._cond.notify_all()
        self._thread.join(timeout)

    # -- flusher side ------------------------------------------------------

    def _ready_kind(self) -> Tuple[Optional[str], float]:
        """(kind to flush now, seconds until the next deadline). Called
        under the lock. A kind is ready when it has ``max_batch`` rows
        pending or its oldest request is past the delay deadline; among
        ready kinds the one with the OLDEST waiter wins, so sustained
        max_batch traffic of one kind cannot starve another past its
        deadline."""
        now = time.monotonic()
        ready_kind, ready_oldest = None, float("inf")
        next_wait = float("inf")
        for kind, q in self._queues.items():
            if not q:
                continue
            rows = sum(r.x.shape[0] for r in q)
            deadline = q[0].t_in + self.max_delay_s
            if rows >= self.max_batch or now >= deadline:
                if q[0].t_in < ready_oldest:
                    ready_kind, ready_oldest = kind, q[0].t_in
            else:
                next_wait = min(next_wait, deadline - now)
        if ready_kind is not None:
            return ready_kind, 0.0
        return None, next_wait

    def _flusher(self) -> None:
        while True:
            with self._cond:
                kind, wait = self._ready_kind()
                while kind is None and not self._closed:
                    self._cond.wait(None if wait == float("inf") else wait)
                    kind, wait = self._ready_kind()
                if self._closed:
                    return
                batch: List[_Pending] = []
                rows = 0
                q = self._queues[kind]
                # take whole requests up to max_batch rows (never split a
                # request; a single oversized request flushes alone)
                while q and (not batch or rows + q[0].x.shape[0] <= self.max_batch):
                    r = q.pop(0)
                    batch.append(r)
                    rows += int(r.x.shape[0])
                self._depth -= len(batch)
                self._queued_rows -= rows
                self._executing += 1
            try:
                self._execute(kind, batch)
            finally:
                with self._cond:
                    self._executing -= 1

    def _execute(self, kind: str, batch: List[_Pending]) -> None:
        try:
            faults.fire(
                "serve.predict",
                kind=kind,
                rows=sum(int(r.x.shape[0]) for r in batch),
            )
            with self.registry.lease() as entry:
                # per-request feature validation against the LEASED model:
                # a hot-swap between an HTTP-level check and batch
                # execution may change num_features; fail only the
                # mismatched requests, not the whole batch
                f = entry.booster.num_features
                bad = [r for r in batch if r.x.shape[1] != f]
                for r in bad:
                    r.error = ValueError(
                        f"feature shape mismatch: model v{entry.version} "
                        f"expects {f}, got {r.x.shape[1]}"
                    )
                    r.event.set()
                batch = [r for r in batch if r.x.shape[1] == f]
                if not batch:
                    return
                x = (
                    np.concatenate([r.x for r in batch], axis=0)
                    if len(batch) > 1 else batch[0].x
                )
                out, bucket = entry.predictor.predict_with_bucket(x, kind)
                version = entry.version
            if self.metrics is not None:
                self.metrics.observe_batch(int(x.shape[0]), bucket)
            lo = 0
            for r in batch:
                hi = lo + int(r.x.shape[0])
                r.result = out[lo:hi]
                r.version = version
                lo = hi
            with self._cond:
                self._consecutive_failures = 0  # breaker half-open -> closed
        except BaseException as exc:  # noqa: BLE001 - marshal to waiters
            # not counted here: the error surfaces from submit() and is
            # counted once per failed request by the front-end (a batch
            # observe here would double-count every failure)
            if not isinstance(exc, NoModelError):
                # NoModelError is an empty endpoint, not a broken predictor
                with self._cond:
                    self._consecutive_failures += 1
            for r in batch:
                r.error = exc
        finally:
            for r in batch:
                r.event.set()
