"""Model registry: the serving layer's single mutable cell.

Holds the current ``(version, booster, CompiledPredictor)`` triple and
swaps it atomically: a swap first builds (and optionally warms) the new
model's predictor entirely OUTSIDE the lock — compiles happen before the
swap is visible — then blocks new leases, **drains in-flight batches**, and
flips the pointer. Every batch executes against the entry its ``lease()``
snapshotted, so a response is always wholly from one model version; the
drain guarantees the swap returns only once no batch is still running on
the old model (the reference semantics of replacing a Ray Serve replica's
model object).

Models load from any of the shapes the driver produces: a trained
``RayXGBoostBooster`` (the ``train()`` result / checkpoint payload), a
pickled checkpoint ``bytes`` blob, a saved native-JSON path, or an xgboost
JSON document/path (``import_xgboost_json`` interop surface).
"""

import json
import pickle
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

from xgboost_ray_tpu import faults
from xgboost_ray_tpu.models.booster import RayXGBoostBooster
from xgboost_ray_tpu.serve.predictor import KINDS, CompiledPredictor


class NoModelError(RuntimeError):
    """A request arrived before any model was registered."""


@dataclass
class ModelEntry:
    version: int
    booster: RayXGBoostBooster
    predictor: CompiledPredictor
    name: str = ""


def coerce_model(model: Any) -> RayXGBoostBooster:
    """Accept the model shapes the driver hands around (see module doc)."""
    if isinstance(model, RayXGBoostBooster):
        return model
    if isinstance(model, bytes):
        return pickle.loads(model)
    if isinstance(model, dict):
        doc = model
    elif isinstance(model, str):
        # explicit path-existence dispatch (not brace-sniffing, which
        # misreads BOM-prefixed documents — same fix as linear.py's import)
        import os

        if os.path.exists(model):
            with open(model) as f:
                doc = json.load(f)
        else:
            try:
                doc = json.loads(model)
            except ValueError as exc:
                raise ValueError(
                    f"serve model string is neither an existing file path "
                    f"nor valid JSON: {model[:80]!r}"
                ) from exc
    else:
        raise TypeError(
            f"cannot serve a model of type {type(model).__name__} (gblinear "
            f"boosters have no padded forest walk to compile); pass a tree "
            f"RayXGBoostBooster, checkpoint bytes, a saved model path, or "
            f"an xgboost JSON document."
        )
    if doc.get("format") == "xgboost_ray_tpu.booster":
        return RayXGBoostBooster._from_dict(doc)
    return RayXGBoostBooster.import_xgboost_json(doc)


@dataclass
class ModelRegistry:
    """Thread-safe current-model cell with drain-before-swap semantics."""

    devices: Optional[list] = None
    min_bucket: int = 8
    #: forest layout compiled into the predictor ("heap" or "node_array")
    layout: str = "heap"
    #: kinds precompiled on load (before the swap becomes visible); all
    #: four by default so the first request of ANY kind — value, margin,
    #: leaf, contribs — hits a warm program after a publish
    warm_kinds: tuple = KINDS
    #: largest batch the warmup covers; align with the batcher's max_batch
    warm_max_batch: int = 256
    metrics: Optional[Any] = None  # ServeMetrics, for the swap counter

    _cond: threading.Condition = field(
        default_factory=lambda: threading.Condition(threading.Lock()),
        repr=False,
    )
    #: serializes whole load() calls (not just the flip): two concurrent
    #: loads otherwise both predict version N+1 before either commits, so
    #: the fault-injection key and the committed version could disagree
    _load_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )
    _current: Optional[ModelEntry] = field(default=None, repr=False)
    _inflight: int = field(default=0, repr=False)
    _swapping: bool = field(default=False, repr=False)
    _version: int = field(default=0, repr=False)

    def load(self, model: Any, name: str = "", warm: bool = True) -> int:
        """Register ``model`` and atomically make it current; returns the
        new version. Compiles (warmup) happen before the old model stops
        serving, and in-flight batches drain before the flip. Whole loads
        serialize (leases do NOT — the old model keeps serving while the
        new one compiles): with only the flip serialized, two concurrent
        loads would both predict version N+1 at fire time and the
        fault-injection key would disagree with the committed version."""
        with self._load_lock:
            # exact under _load_lock: no other load can commit in between,
            # and a failed load (fault fired, bad model) consumes nothing
            with self._cond:
                next_version = self._version + 1
            faults.fire("registry.swap", version=next_version)
            booster = coerce_model(model)
            predictor = CompiledPredictor(
                booster,
                devices=self.devices,
                min_bucket=self.min_bucket,
                layout=self.layout,
            )
            if warm and self.warm_kinds:
                kinds = [k for k in self.warm_kinds if k in KINDS]
                if not getattr(booster, "_has_node_stats", True):
                    # imported-JSON boosters without per-node stats cannot
                    # run exact TreeSHAP; warming contribs would raise
                    kinds = [k for k in kinds if k != "contribs"]
                predictor.warmup(kinds=kinds, max_batch=self.warm_max_batch)
            with self._cond:
                # serialize vs the drain; leases block only during the flip
                while self._swapping:
                    self._cond.wait()
                self._swapping = True
                while self._inflight:
                    self._cond.wait()
                self._version = next_version
                entry = ModelEntry(next_version, booster, predictor, name=name)
                was_live = self._current is not None
                self._current = entry
                self._swapping = False
                self._cond.notify_all()
        if was_live and self.metrics is not None:
            self.metrics.observe_swap()
        return entry.version

    @contextmanager
    def lease(self):
        """Snapshot the current entry and hold it in-flight for the scope.
        Blocks briefly while a swap is draining (so the drain terminates),
        then yields a consistent entry the swap cannot mutate."""
        with self._cond:
            while self._swapping:
                self._cond.wait()
            if self._current is None:
                raise NoModelError(
                    "no model registered; POST /models or call "
                    "ModelRegistry.load() first."
                )
            entry = self._current
            self._inflight += 1
        try:
            yield entry
        finally:
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()

    @property
    def version(self) -> int:
        with self._cond:
            return self._current.version if self._current else 0

    @property
    def has_model(self) -> bool:
        with self._cond:
            return self._current is not None
