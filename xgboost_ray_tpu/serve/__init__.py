"""Online inference serving for TPU-native GBDT models.

The batch path (``xgboost_ray_tpu.predict``) walks a whole RayDMatrix once;
this subsystem serves *online* traffic: a compiled-predictor cache with
power-of-two padded batch buckets (zero recompiles in steady state), a
microbatching queue coalescing concurrent requests under a latency
deadline, a model registry with drain-then-flip hot-swap, and a threaded
stdlib HTTP front-end with /predict, /healthz and /metrics. On top of the
single-batcher path: a replica pool behind a least-queue router with
admission control (``Router``, ``n_replicas=`` on ``create_server``), a
p99/queue-driven ``AutoScaler`` with hysteresis, an optional FIL-style
breadth-first ``node_array`` forest layout (``layout=``), and the
train → refresh → serve loop (``refresh`` + ``CanaryController`` —
shadow traffic, canary gate, automatic rollback).

Typical use::

    from xgboost_ray_tpu import serve

    bst = train(params, dtrain, ray_params=RayParams(num_actors=8))
    handle = serve.create_server(bst, port=8000, max_batch=256,
                                 max_delay_ms=2.0)
    ...
    handle.registry.load(new_bst)   # atomic hot-swap, drains in-flight
    handle.shutdown()

or publish straight from training::

    reg = serve.ModelRegistry()
    train(params, dtrain, ray_params=rp, serve_registry=reg)
"""

from xgboost_ray_tpu.serve.autoscale import AutoScaler
from xgboost_ray_tpu.serve.batcher import (
    MicroBatcher,
    OverloadedError,
    ShuttingDownError,
)
from xgboost_ray_tpu.serve.canary import CanaryController, refresh
from xgboost_ray_tpu.serve.http import ServeHandle, create_server
from xgboost_ray_tpu.serve.metrics import ServeMetrics
from xgboost_ray_tpu.serve.pool import NoReplicasError, Replica, Router
from xgboost_ray_tpu.serve.predictor import (
    KINDS,
    LAYOUTS,
    CompiledPredictor,
    bucket_rows,
    compile_count,
)
from xgboost_ray_tpu.serve.registry import (
    ModelRegistry,
    NoModelError,
    coerce_model,
)

__all__ = [
    "KINDS",
    "LAYOUTS",
    "AutoScaler",
    "CanaryController",
    "CompiledPredictor",
    "MicroBatcher",
    "ModelRegistry",
    "NoModelError",
    "NoReplicasError",
    "OverloadedError",
    "Replica",
    "Router",
    "ServeHandle",
    "ShuttingDownError",
    "ServeMetrics",
    "bucket_rows",
    "coerce_model",
    "compile_count",
    "create_server",
    "refresh",
]
