"""Hyperparameter-tuning integration.

Mirror of ``xgboost_ray/tune.py``: a report/checkpoint callback that is
auto-injected when training runs inside a tuning session
(``tune.py:27-104``), trial resource computation (``tune.py:107-126``), and a
checkpoint-aware ``load_model`` (``tune.py:130-156``).

Two backends:
  * If ``ray.tune`` happens to be importable, its ``session.report`` is used.
  * Otherwise a standalone session (``xgboost_ray_tpu.hpo``) provides the
    same report/checkpoint surface, so HPO sweeps work on a bare TPU VM.
"""

import dataclasses
import json
import logging
import os
import tempfile
from typing import Any, Dict, List, Optional

from xgboost_ray_tpu.callback import TrainingCallback
from xgboost_ray_tpu.models.booster import RayXGBoostBooster

logger = logging.getLogger(__name__)

try:  # pragma: no cover - not installed in the TPU image
    from ray import tune as _ray_tune
    from ray.tune.integration import xgboost as _  # noqa: F401

    RAY_TUNE_INSTALLED = True
except Exception:
    _ray_tune = None
    RAY_TUNE_INSTALLED = False


# --- standalone tuning session ---------------------------------------------
#
# Thread-local so the Tuner can run trials concurrently (task parallelism
# across trials, SURVEY §2.3): each trial thread owns its session and,
# optionally, its own slice of the device mesh.

import threading as _threading

_session_tls = _threading.local()


class TuneSession:
    """Trial-side context collecting reported results and checkpoints."""

    def __init__(self, trial_dir: Optional[str] = None, devices=None):
        self.trial_dir = trial_dir or tempfile.mkdtemp(prefix="rxgb_trial_")
        self.results: List[Dict[str, Any]] = []
        self.last_checkpoint_path: Optional[str] = None
        # device subset this trial trains on (None = all local devices);
        # the driver hands it to TpuEngine so concurrent trials map onto
        # disjoint mesh slices
        self.devices = list(devices) if devices is not None else None
        # trial scheduler hook (tuner.ASHAScheduler / MedianStoppingRule):
        # consulted on every report; True stops the trial's training loop
        # (the Ray Tune scheduler role, which the reference delegates to Ray)
        self.scheduler = None
        self.trial_id: Optional[str] = None
        self.stopped_by_scheduler = False

    def report(self, metrics: Dict[str, Any], checkpoint_path: Optional[str] = None) -> bool:
        """Record a result; returns True when the attached scheduler decides
        the trial should stop early."""
        self.results.append(dict(metrics))
        if checkpoint_path:
            self.last_checkpoint_path = checkpoint_path
        if self.scheduler is not None:
            stop = bool(
                self.scheduler.on_report(
                    self.trial_id or "trial",
                    int(metrics.get("training_iteration", len(self.results))),
                    metrics,
                )
            )
            if stop:
                self.stopped_by_scheduler = True
            return stop
        return False


def init_session(trial_dir: Optional[str] = None, devices=None) -> TuneSession:
    _session_tls.value = TuneSession(trial_dir, devices=devices)
    return _session_tls.value


def shutdown_session():
    _session_tls.value = None


def get_session() -> Optional[TuneSession]:
    return getattr(_session_tls, "value", None)


def is_session_enabled() -> bool:
    """Are we inside a tuning trial? (mirror of ``tune.py:61-64``)."""
    if get_session() is not None:
        return True
    if RAY_TUNE_INSTALLED:  # pragma: no cover
        try:
            from ray.tune import is_session_enabled as _ise

            return _ise()
        except Exception:
            return False
    return False


# --- report/checkpoint callback --------------------------------------------


class TuneReportCheckpointCallback(TrainingCallback):
    """Per-iteration metric report + periodic checkpoint to the trial dir.

    Mirror of the reference's Tune callback (``tune.py:26-48``), which runs
    its hooks on the driver. ``metrics`` maps reported names to eval-result
    keys ("{set}-{metric}"); default reports every recorded metric.
    """

    def __init__(
        self,
        metrics: Optional[Any] = None,
        filename: str = "checkpoint.json",
        frequency: int = 5,
    ):
        if isinstance(metrics, str):
            metrics = [metrics]
        self._metrics = metrics
        self._filename = filename
        self._frequency = max(1, int(frequency))

    @staticmethod
    def _flatten(evals_log: Dict) -> Dict[str, float]:
        flat = {}
        for set_name, metric_dict in (evals_log or {}).items():
            for metric_name, values in metric_dict.items():
                if values:
                    flat[f"{set_name}-{metric_name}"] = values[-1]
        return flat

    def after_iteration(self, model, epoch: int, evals_log: Dict) -> bool:
        session = get_session()
        if session is None:
            return False
        flat = self._flatten(evals_log)
        if self._metrics is None:
            report = dict(flat)
        elif isinstance(self._metrics, dict):
            report = {out: flat.get(src) for out, src in self._metrics.items()}
        else:
            report = {m: flat.get(m) for m in self._metrics}
        report["training_iteration"] = epoch + 1

        checkpoint_path = None
        if (epoch + 1) % self._frequency == 0:
            checkpoint_path = os.path.join(
                session.trial_dir, f"checkpoint_{epoch + 1:06d}"
            )
            os.makedirs(checkpoint_path, exist_ok=True)
            model.save_model(os.path.join(checkpoint_path, self._filename))
        # the report's return is the scheduler's stop decision: returning
        # True from after_iteration stops this trial's training loop
        return session.report(report, checkpoint_path=checkpoint_path)


# legacy alias (reference exports TuneReportCallback too)
class TuneReportCallback(TuneReportCheckpointCallback):
    def __init__(self, metrics: Optional[Any] = None):
        super().__init__(metrics=metrics, frequency=1 << 30)


def _try_add_tune_callback(callbacks: List) -> List:
    """Inject/replace the tune callback inside a tuning session
    (mirror of ``tune.py:60-104``)."""
    if not is_session_enabled():
        return callbacks
    has = any(isinstance(cb, TuneReportCheckpointCallback) for cb in callbacks)
    if not has:
        callbacks = list(callbacks) + [TuneReportCheckpointCallback()]
    return callbacks


# --- trial resources --------------------------------------------------------


@dataclasses.dataclass
class PlacementGroupFactory:
    """Standalone stand-in for Tune's PlacementGroupFactory: a head bundle
    plus one bundle per actor, PACK strategy (mirror ``tune.py:107-126``)."""

    bundles: List[Dict[str, float]]
    strategy: str = "PACK"
    # extra placement options (e.g. _max_cpu_fraction_per_node) carried
    # through verbatim, matching ray.tune's permissive PlacementGroupFactory
    options: dict = dataclasses.field(default_factory=dict)

    def required_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for bundle in self.bundles:
            for key, val in bundle.items():
                total[key] = total.get(key, 0.0) + val
        return total


def _get_tune_resources(
    num_actors: int,
    cpus_per_actor: int,
    gpus_per_actor: int,
    tpus_per_actor: int,
    resources_per_actor: Optional[Dict],
    placement_options: Optional[Dict],
) -> PlacementGroupFactory:
    head = {"CPU": 1.0}
    child: Dict[str, float] = {"CPU": float(cpus_per_actor)}
    if gpus_per_actor:
        child["GPU"] = float(gpus_per_actor)
    if tpus_per_actor:
        child["TPU"] = float(tpus_per_actor)
    if resources_per_actor:
        child.update({k: float(v) for k, v in resources_per_actor.items()})
    options = dict(placement_options or {})
    strategy = options.pop("strategy", "PACK")
    return PlacementGroupFactory(
        bundles=[head] + [dict(child) for _ in range(num_actors)],
        strategy=strategy,
        options=options,
    )


def load_model(model_path: str) -> RayXGBoostBooster:
    """Load a model saved by the tune callback (mirror ``tune.py:130-156``)."""
    if os.path.isdir(model_path):
        for name in sorted(os.listdir(model_path)):
            if name.endswith(".json"):
                model_path = os.path.join(model_path, name)
                break
    return RayXGBoostBooster.load_model(model_path)
