"""Native (C++) host-side components, bound via ctypes.

The reference leans on native code for host data paths (pandas C parsers,
xgboost's C++ DMatrix ingestion); this package holds our equivalents.
Currently: ``fast_csv`` — a multithreaded CSV -> float32 parser used by the
CSV data source when available. Built lazily with g++ on first use; every
entry point degrades gracefully to the pandas path if the toolchain or the
build is unavailable.
"""

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fast_csv.cpp")
_LIB_PATH = os.path.join(_HERE, "libfastcsv.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", _LIB_PATH, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as exc:  # noqa: BLE001 - fall back to pandas
        logger.debug("fast_csv build failed: %s", exc)
        return False


def _load():
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("RXGB_DISABLE_NATIVE_CSV"):
            _load_failed = True
            return None
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as exc:
            logger.debug("fast_csv load failed: %s", exc)
            _load_failed = True
            return None
        lib.fcsv_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.fcsv_open.restype = ctypes.c_int64
        lib.fcsv_rows.argtypes = [ctypes.c_int64]
        lib.fcsv_rows.restype = ctypes.c_int64
        lib.fcsv_cols.argtypes = [ctypes.c_int64]
        lib.fcsv_cols.restype = ctypes.c_int64
        lib.fcsv_header.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        lib.fcsv_header.restype = ctypes.c_int64
        lib.fcsv_parse.argtypes = [
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
        ]
        lib.fcsv_parse.restype = ctypes.c_int
        lib.fcsv_close.argtypes = [ctypes.c_int64]
        lib.fcsv_close.restype = None
        _lib = lib
        return _lib


def native_csv_available() -> bool:
    return _load() is not None


def read_csv_numpy(
    path: str, n_threads: int = 0
) -> Optional[Tuple[np.ndarray, List[str]]]:
    """Parse a (numeric, comma-separated, headered) CSV into float32.

    Returns (matrix [rows, cols], column names), or None when the native
    parser is unavailable or the file isn't eligible (e.g. gzip) — callers
    fall back to pandas.
    """
    if path.endswith(".gz"):
        return None
    lib = _load()
    if lib is None:
        return None
    handle = lib.fcsv_open(path.encode(), 1)
    if handle == 0:
        return None
    try:
        rows = lib.fcsv_rows(handle)
        cols = lib.fcsv_cols(handle)
        if rows < 0 or cols <= 0:
            return None
        buf = ctypes.create_string_buffer(1 << 16)
        n = lib.fcsv_header(handle, buf, len(buf))
        if n < 0:
            return None
        names = buf.value.decode("utf-8", errors="replace").split("\n") if n else []
        if len(names) != cols:
            return None
        # header must be non-numeric, otherwise this was a headerless file
        # and pandas semantics differ — fall back
        for name in names:
            try:
                float(name)
                return None
            except ValueError:
                pass
        out = np.empty((rows, cols), dtype=np.float32)
        rc = lib.fcsv_parse(
            handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n_threads
        )
        if rc != 0:
            return None
        return out, names
    finally:
        lib.fcsv_close(handle)
