// Multithreaded CSV -> float32 parser for the TPU data-ingestion path.
//
// Native analog of the host-side loading the reference delegates to
// pandas.read_csv inside its actors (xgboost_ray/data_sources/csv.py:26-43)
// and, transitively, to the xgboost C++ DMatrix parser. Parsing the HIGGS-
// class CSVs (11M rows) is a host bottleneck before device binning, so this
// runs chunked std::from_chars parsing across hardware threads.
//
// Layout: two-pass. Pass 1 (single scan) counts rows/columns and records
// per-thread chunk boundaries at newline alignment. Pass 2 parses chunks in
// parallel straight into the caller's float32 buffer (row-major).
// Empty fields, "na"/"nan"/"null" (any case) and parse failures become NaN.
//
// C ABI (ctypes-friendly):
//   fcsv_open(path, skip_header)        -> handle (>0) or 0 on failure
//   fcsv_rows(h) / fcsv_cols(h)         -> dimensions
//   fcsv_header(h, buf, cap)            -> '\n'-joined header into buf
//   fcsv_parse(h, out, n_threads)       -> 0 on success (out: rows*cols f32)
//   fcsv_close(h)

#include <atomic>
#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <limits>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct CsvFile {
  std::string data;
  std::string header;
  int64_t rows = 0;
  int64_t cols = 0;
  size_t body_offset = 0;  // first byte after the header row
};

std::mutex g_mutex;
std::map<int64_t, CsvFile*> g_files;
int64_t g_next_handle = 1;

bool is_na_token(const char* begin, const char* end) {
  size_t len = static_cast<size_t>(end - begin);
  if (len == 0) return true;
  if (len > 4) return false;
  char low[5] = {0, 0, 0, 0, 0};
  for (size_t i = 0; i < len; ++i)
    low[i] = static_cast<char>(std::tolower(static_cast<unsigned char>(begin[i])));
  return !std::strncmp(low, "na", 5) || !std::strncmp(low, "nan", 5) ||
         !std::strncmp(low, "null", 5);
}

// A line in data[begin, end) counts as a row iff this is false. Must be the
// single source of truth for both the row counters and the parser, or the
// parser writes a different number of rows than fcsv_rows() promised.
bool is_blank_line(const char* data, size_t begin, size_t end) {
  if (end <= begin) return true;                          // empty (LF only)
  return end - begin == 1 && data[begin] == '\r';         // bare CR from CRLF
}

float parse_field(const char* begin, const char* end) {
  while (begin < end && (*begin == ' ' || *begin == '\t')) ++begin;
  while (end > begin && (end[-1] == ' ' || end[-1] == '\t' || end[-1] == '\r')) --end;
  if (begin >= end || is_na_token(begin, end))
    return std::numeric_limits<float>::quiet_NaN();
  float value;
  auto result = std::from_chars(begin, end, value);
  if (result.ec != std::errc()) return std::numeric_limits<float>::quiet_NaN();
  return value;
}

// Parse rows in data[begin, end) into out, starting at row_index `row0`.
void parse_span(const CsvFile& file, size_t begin, size_t end, int64_t row0,
                float* out) {
  const char* data = file.data.data();
  int64_t row = row0;
  size_t pos = begin;
  while (pos < end) {
    size_t line_end = pos;
    while (line_end < end && data[line_end] != '\n') ++line_end;
    if (!is_blank_line(data, pos, line_end)) {
      float* out_row = out + row * file.cols;
      size_t field_start = pos;
      int64_t col = 0;
      for (size_t i = pos; i <= line_end; ++i) {
        if (i == line_end || data[i] == ',') {
          if (col < file.cols)
            out_row[col] = parse_field(data + field_start, data + i);
          ++col;
          field_start = i + 1;
        }
      }
      for (; col < file.cols; ++col)
        out_row[col] = std::numeric_limits<float>::quiet_NaN();
      ++row;
    }
    pos = line_end + 1;
  }
}

}  // namespace

extern "C" {

int64_t fcsv_open(const char* path, int skip_header) {
  std::ifstream in(path, std::ios::binary | std::ios::ate);
  if (!in) return 0;
  auto size = in.tellg();
  in.seekg(0);
  auto* file = new CsvFile();
  file->data.resize(static_cast<size_t>(size));
  if (!in.read(file->data.data(), size)) {
    delete file;
    return 0;
  }

  const std::string& s = file->data;
  size_t pos = 0;
  if (skip_header && !s.empty()) {
    size_t eol = s.find('\n');
    if (eol == std::string::npos) eol = s.size();
    file->header = s.substr(0, eol);
    while (!file->header.empty() && file->header.back() == '\r')
      file->header.pop_back();
    pos = eol + 1 < s.size() ? eol + 1 : s.size();
  }
  file->body_offset = pos;

  // count columns from the first body line, rows from newline count
  size_t first_eol = s.find('\n', pos);
  if (first_eol == std::string::npos) first_eol = s.size();
  if (first_eol > pos) {
    file->cols = 1;
    for (size_t i = pos; i < first_eol; ++i)
      if (s[i] == ',') ++file->cols;
  }
  int64_t rows = 0;
  size_t scan = pos;
  while (scan < s.size()) {
    size_t eol = s.find('\n', scan);
    if (eol == std::string::npos) eol = s.size();
    if (!is_blank_line(s.data(), scan, eol)) ++rows;
    scan = eol + 1;
  }
  file->rows = rows;

  std::lock_guard<std::mutex> lock(g_mutex);
  int64_t handle = g_next_handle++;
  g_files[handle] = file;
  return handle;
}

int64_t fcsv_rows(int64_t handle) {
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_files.find(handle);
  return it == g_files.end() ? -1 : it->second->rows;
}

int64_t fcsv_cols(int64_t handle) {
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_files.find(handle);
  return it == g_files.end() ? -1 : it->second->cols;
}

int64_t fcsv_header(int64_t handle, char* buf, int64_t cap) {
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_files.find(handle);
  if (it == g_files.end()) return -1;
  std::string header = it->second->header;
  for (char& c : header)
    if (c == ',') c = '\n';
  int64_t n = static_cast<int64_t>(header.size());
  if (n + 1 > cap) return -(n + 1);
  std::memcpy(buf, header.data(), static_cast<size_t>(n));
  buf[n] = '\0';
  return n;
}

int fcsv_parse(int64_t handle, float* out, int n_threads) {
  CsvFile* file;
  {
    std::lock_guard<std::mutex> lock(g_mutex);
    auto it = g_files.find(handle);
    if (it == g_files.end()) return 1;
    file = it->second;
  }
  if (file->rows == 0 || file->cols == 0) return 0;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  n_threads = std::max(1, std::min<int>(n_threads, 64));

  const std::string& s = file->data;
  // chunk boundaries aligned to newlines, with the starting row of each chunk
  std::vector<size_t> starts{file->body_offset};
  size_t target = std::max<size_t>(1, (s.size() - file->body_offset) / n_threads);
  for (int t = 1; t < n_threads; ++t) {
    size_t probe = std::min(file->body_offset + t * target, s.size());
    size_t eol = s.find('\n', probe);
    starts.push_back(eol == std::string::npos ? s.size() : eol + 1);
  }
  starts.push_back(s.size());

  // rows before each chunk (serial newline count per chunk, then prefix sum)
  std::vector<int64_t> chunk_rows(n_threads, 0);
  {
    std::vector<std::thread> counters;
    for (int t = 0; t < n_threads; ++t) {
      counters.emplace_back([&, t] {
        int64_t rows = 0;
        size_t scan = starts[t];
        while (scan < starts[t + 1]) {
          size_t eol = s.find('\n', scan);
          if (eol == std::string::npos || eol >= starts[t + 1])
            eol = starts[t + 1];
          if (!is_blank_line(s.data(), scan, eol)) ++rows;
          scan = eol + 1;
        }
        chunk_rows[t] = rows;
      });
    }
    for (auto& th : counters) th.join();
  }
  std::vector<int64_t> row0(n_threads, 0);
  for (int t = 1; t < n_threads; ++t) row0[t] = row0[t - 1] + chunk_rows[t - 1];

  std::vector<std::thread> workers;
  for (int t = 0; t < n_threads; ++t) {
    workers.emplace_back(
        [&, t] { parse_span(*file, starts[t], starts[t + 1], row0[t], out); });
  }
  for (auto& th : workers) th.join();
  return 0;
}

void fcsv_close(int64_t handle) {
  std::lock_guard<std::mutex> lock(g_mutex);
  auto it = g_files.find(handle);
  if (it != g_files.end()) {
    delete it->second;
    g_files.erase(it);
  }
}

}  // extern "C"
