"""Elastic fault tolerance: background reintegration of failed ranks.

Semantics mirror of ``xgboost_ray/elastic.py``: while training continues with
survivors, failed ranks are re-scheduled every
``RXGB_ELASTIC_RESTART_RESOURCE_CHECK_S`` seconds, staged through data
loading, and after ``RXGB_ELASTIC_RESTART_GRACE_PERIOD_S`` of readiness a
``RayXGBoostActorAvailable`` is raised so the driver restarts from the last
checkpoint with the restored world — a restart that does not consume a retry
(``xgboost_ray/main.py:1661-1673``).

TPU difference: "scheduling" a worker is creating a virtual worker and
reloading its shard (the mesh is recompiled for the new world size on
restart, SURVEY §5.8); resource waits are therefore instantaneous, but the
check/grace cadence is preserved so the driver-visible timeline — and the
reference's orchestrated-timeline tests — behave the same.
"""

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from xgboost_ray_tpu import obs
from xgboost_ray_tpu.exceptions import RayActorError, RayXGBoostActorAvailable

logger = logging.getLogger(__name__)

# how long _maybe_schedule_new_actors waits synchronously for a rescheduled
# rank's data load before letting it continue in the background (the
# reference stages loading in background actor tasks, elastic.py:63-87 —
# a slow shard must not stall the surviving workers' training loop)
_LOAD_FAST_PATH_S = 1.0


class PendingActor:
    """A rescheduled rank staged through (possibly background) data loading.

    ``ready_at``/``error`` are written by the background ``elastic-load-*``
    thread and polled by the driver's round loop whenever the 1 s fast-path
    join times out (the documented slow-load path) — a cross-thread
    check-then-act with no happens-before edge, surfaced as RACE001 by
    ``tools/rxgbrace``'s elastic scenario. Both fields now live behind a
    lock with one-shot ``mark_ready``/``mark_error`` writers, so the driver
    can never observe a torn (ready AND errored) worker."""

    def __init__(self, actor, created_at: float):
        self.actor = actor
        self.created_at = created_at
        self.thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._ready_at: Optional[float] = None
        self._error: Optional[BaseException] = None

    def mark_ready(self) -> None:
        with self._lock:
            if self._error is None:
                self._ready_at = time.time()

    def mark_error(self, exc: BaseException) -> None:
        with self._lock:
            self._error = exc

    @property
    def ready_at(self) -> Optional[float]:
        with self._lock:
            return self._ready_at

    @property
    def error(self) -> Optional[BaseException]:
        with self._lock:
            return self._error

    @property
    def ready(self) -> bool:
        with self._lock:
            return self._ready_at is not None


def _maybe_schedule_new_actors(
    training_state,
    num_cpus_per_actor: int,
    num_gpus_per_actor: int,
    resources_per_actor: Optional[Dict],
    ray_params,
    load_data: Sequence,
) -> bool:
    """Try to re-create failed workers in the background (elastic.py:19-95)."""
    from xgboost_ray_tpu.main import ENV, _create_actor

    now = time.time()
    if now - training_state.last_resource_check_at < float(
        ENV.ELASTIC_RESTART_RESOURCE_CHECK_S
    ):
        return False
    training_state.last_resource_check_at = now

    if training_state.pending_actors is None:
        training_state.pending_actors = {}

    scheduled = False
    dead_ranks = set(training_state.elastic_dead_ranks) | set(
        training_state.failed_actor_ranks
    )
    started: List[Tuple[int, PendingActor]] = []
    for rank in sorted(dead_ranks):
        if rank in training_state.pending_actors:
            continue
        actor = _create_actor(
            rank,
            ray_params.num_actors,
            training_state.queue,
            training_state.stop_event,
            ray_params.distributed_callbacks,
        )
        pending = PendingActor(actor, now)

        def _load(pending=pending, actor=actor):
            try:
                for matrix in load_data:
                    actor.load_data(matrix)
                pending.mark_ready()
            except BaseException as exc:  # noqa: BLE001 - surfaced by updater
                pending.mark_error(exc)

        pending.thread = threading.Thread(
            target=_load, name=f"elastic-load-rank-{rank}", daemon=True
        )
        pending.thread.start()
        started.append((rank, pending))

    # fast path: tiny/central loads finish within one SHARED deadline; slow
    # distributed loads continue in the background without stalling the round
    # loop (no per-rank serial join — N dead ranks still cost <= 1s total)
    deadline = time.time() + _LOAD_FAST_PATH_S
    for rank, pending in started:
        pending.thread.join(max(0.0, deadline - time.time()))
        err = pending.error  # one locked read; the load thread may still run
        if err is not None:
            logger.warning(
                f"[RayXGBoost] Could not load data for rescheduled rank "
                f"{rank}: {err}"
            )
            continue
        training_state.pending_actors[rank] = pending
        scheduled = True
        logger.debug(f"[RayXGBoost] Re-scheduled worker with rank {rank}.")
    if started:
        # recovery observability: how often the elastic scheduler had to act
        rob = training_state.additional_results.setdefault("robustness", {})
        rob["elastic_reschedules"] = (
            rob.get("elastic_reschedules", 0) + len(started)
        )
        obs.get_tracer().event(
            "elastic.reschedule",
            attrs={"ranks": [r for r, _ in started]},
        )
    return scheduled


def _update_scheduled_actor_states(training_state, raise_on_ready: bool = True):
    """Reintegration state machine for pending workers (elastic.py:98-142),
    now per fault domain.

    Returns True when reintegration is due for at least one COMPLETE domain:
    every dead rank of the domain has a READY pending worker and that
    domain's grace period has expired. The due domains land in
    ``training_state.domains_due`` so the driver's round-boundary grow path
    (``raise_on_ready=False``) re-admits them atomically — a half-staged
    domain waits, it never half-grows. With ``raise_on_ready`` (the legacy
    restart-from-checkpoint mode for engines without a ``can_reshard``
    probe) a due reintegration raises ``RayXGBoostActorAvailable`` instead
    of returning.

    Workers whose background data load failed are dropped (and re-tried on
    the next resource check). Each domain's grace clock arms only once ALL
    of its dead ranks have FINISHED loading, and is DISARMED again whenever
    that completeness regresses — a freshly-complete domain must earn its
    own grace period, and one flapping domain never resets the clocks of
    healthy domains. Without a domain map every rank is its own domain,
    which reproduces the pre-domain per-rank semantics."""
    from xgboost_ray_tpu.main import ENV

    clocks = getattr(training_state, "domain_restart_at", None)
    if clocks is None:
        clocks = {}
        training_state.domain_restart_at = clocks
    training_state.domains_due = []
    if not training_state.pending_actors:
        clocks.clear()
        training_state.restart_training_at = None
        return False
    for rank, pending in list(training_state.pending_actors.items()):
        err = pending.error  # one locked read vs the background load thread
        if err is not None:
            logger.warning(
                f"[RayXGBoost] Background data load failed for rescheduled "
                f"rank {rank}: {err}"
            )
            del training_state.pending_actors[rank]

    domain_map = getattr(training_state, "domain_map", None)

    def _dom(rank: int) -> int:
        return domain_map.domain_of(rank) if domain_map is not None else rank

    # a domain's required set = every rank it has in flight: dead ranks not
    # yet rescheduled AND staged pendings — completeness over that set is
    # the atomic-grow contract
    dead = set(getattr(training_state, "elastic_dead_ranks", ()) or ())
    dead |= set(getattr(training_state, "failed_actor_ranks", ()) or ())
    required: Dict[int, set] = {}
    for rank in set(training_state.pending_actors) | dead:
        required.setdefault(_dom(rank), set()).add(rank)

    now = time.time()
    due_domains: List[int] = []
    for dom in sorted(required):
        complete = all(
            (p := training_state.pending_actors.get(r)) is not None and p.ready
            for r in required[dom]
        )
        if not complete:
            clocks.pop(dom, None)
            continue
        if dom not in clocks:
            clocks[dom] = now + float(ENV.ELASTIC_RESTART_GRACE_PERIOD_S)
        elif now >= clocks[dom]:
            due_domains.append(dom)
    for dom in list(clocks):  # drop clocks of domains no longer in flight
        if dom not in required:
            del clocks[dom]
    for dom in due_domains:
        clocks.pop(dom, None)
    # legacy mirror: earliest armed clock (tests and the resume path read it)
    training_state.restart_training_at = min(clocks.values()) if clocks else None
    if not due_domains:
        return False
    training_state.domains_due = due_domains
    obs.get_tracer().event(
        "elastic.ready",
        attrs={
            "ranks": sorted(
                r for dom in due_domains for r in required[dom]
            ),
            "domains": due_domains,
            "mode": "restart" if raise_on_ready else "grow",
        },
    )
    if raise_on_ready:
        raise RayXGBoostActorAvailable(
            "A new worker became available for training. Restarting from "
            "the latest checkpoint with the restored world size."
        )
    return True


def _get_actor_alive_status(actors: List, callback) -> int:
    """Probe worker liveness (elastic.py:145-178); invoke callback for dead
    ranks. Returns the number of dead actors."""
    dead = 0
    for rank, actor in enumerate(actors):
        if actor is None:
            dead += 1
            callback(rank)
            continue
        try:
            actor.pid()
        except RayActorError:
            dead += 1
            callback(rank)
    return dead
