"""Elastic fault tolerance: background reintegration of failed ranks.

Semantics mirror of ``xgboost_ray/elastic.py``: while training continues with
survivors, failed ranks are re-scheduled every
``RXGB_ELASTIC_RESTART_RESOURCE_CHECK_S`` seconds, staged through data
loading, and after ``RXGB_ELASTIC_RESTART_GRACE_PERIOD_S`` of readiness a
``RayXGBoostActorAvailable`` is raised so the driver restarts from the last
checkpoint with the restored world — a restart that does not consume a retry
(``xgboost_ray/main.py:1661-1673``).

TPU difference: "scheduling" a worker is creating a virtual worker and
reloading its shard (the mesh is recompiled for the new world size on
restart, SURVEY §5.8); resource waits are therefore instantaneous, but the
check/grace cadence is preserved so the driver-visible timeline — and the
reference's orchestrated-timeline tests — behave the same.
"""

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

from xgboost_ray_tpu.exceptions import RayActorError, RayXGBoostActorAvailable

logger = logging.getLogger(__name__)


def _maybe_schedule_new_actors(
    training_state,
    num_cpus_per_actor: int,
    num_gpus_per_actor: int,
    resources_per_actor: Optional[Dict],
    ray_params,
    load_data: Sequence,
) -> bool:
    """Try to re-create failed workers in the background (elastic.py:19-95)."""
    from xgboost_ray_tpu.main import ENV, _create_actor

    now = time.time()
    if now - training_state.last_resource_check_at < float(
        ENV.ELASTIC_RESTART_RESOURCE_CHECK_S
    ):
        return False
    training_state.last_resource_check_at = now

    if training_state.pending_actors is None:
        training_state.pending_actors = {}

    scheduled = False
    dead_ranks = set(training_state.elastic_dead_ranks) | set(
        training_state.failed_actor_ranks
    )
    for rank in sorted(dead_ranks):
        if rank in training_state.pending_actors:
            continue
        actor = _create_actor(
            rank,
            ray_params.num_actors,
            training_state.queue,
            training_state.stop_event,
            ray_params.distributed_callbacks,
        )
        try:
            for matrix in load_data:
                actor.load_data(matrix)
        except Exception as exc:  # noqa: BLE001 - stay elastic on load failure
            logger.warning(
                f"[RayXGBoost] Could not load data for rescheduled rank "
                f"{rank}: {exc}"
            )
            continue
        training_state.pending_actors[rank] = (actor, now)
        scheduled = True
        logger.debug(f"[RayXGBoost] Re-scheduled worker with rank {rank}.")
    return scheduled


def _update_scheduled_actor_states(training_state):
    """Promote ready pending workers; after the grace period force a restart
    from checkpoint by raising RayXGBoostActorAvailable (elastic.py:98-142)."""
    from xgboost_ray_tpu.main import ENV

    if not training_state.pending_actors:
        return
    now = time.time()
    if training_state.restart_training_at is None:
        training_state.restart_training_at = now + float(
            ENV.ELASTIC_RESTART_GRACE_PERIOD_S
        )
        return
    if now >= training_state.restart_training_at:
        training_state.restart_training_at = None
        raise RayXGBoostActorAvailable(
            "A new worker became available for training. Restarting from the "
            "latest checkpoint with the restored world size."
        )


def _get_actor_alive_status(actors: List, callback) -> int:
    """Probe worker liveness (elastic.py:145-178); invoke callback for dead
    ranks. Returns the number of dead actors."""
    dead = 0
    for rank, actor in enumerate(actors):
        if actor is None:
            dead += 1
            callback(rank)
            continue
        try:
            actor.pid()
        except RayActorError:
            dead += 1
            callback(rank)
    return dead
