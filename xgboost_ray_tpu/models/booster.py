"""The trained-model object: a TPU-native ``xgboost.Booster`` analog.

The reference hands xgboost ``Booster`` objects across its whole API surface
(return value of ``train`` at ``xgboost_ray/main.py:1747``, checkpoint payload
at ``main.py:507-510``, prediction input at ``main.py:795-810``). This class
fills that role: it owns the forest (padded-heap tree arrays, see
``ops/grow.py``), the binning cuts, and the objective envelope, and provides
predict / save / load / dump.
"""

import base64
import io
import json
import warnings
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

import jax.numpy as jnp

from xgboost_ray_tpu import progreg
from xgboost_ray_tpu.constants import AXIS_ACTORS
from xgboost_ray_tpu.ops.grow import Tree
from xgboost_ray_tpu.ops.objectives import get_objective
from xgboost_ray_tpu.ops import predict as predict_ops
from xgboost_ray_tpu.params import TrainParams

_PREDICT_CHUNK = 1 << 16
# exact TreeSHAP materializes [2^depth, chunk, F] slot contributions: smaller
_SHAP_CHUNK = 1 << 12

# jitted SPMD margin programs, keyed on everything that changes the traced
# function (jit's own cache then handles shape polymorphism). Without this a
# fresh closure per predict() call would defeat jit caching and recompile
# every time — seconds per call on TPU.
_SPMD_MARGIN_FNS: Dict[tuple, Any] = {}


def _spmd_margin_fn(devices, k, max_depth, npt, ntree_limit, has_tw,
                    cat_features):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from xgboost_ray_tpu.compat import shard_map_compat as shard_map

    key = (
        tuple(getattr(d, "id", i) for i, d in enumerate(devices)),
        k, max_depth, npt, int(ntree_limit), has_tw, tuple(cat_features),
    )
    mapped = _SPMD_MARGIN_FNS.get(key)
    if mapped is not None:
        return mapped
    mesh = Mesh(np.asarray(devices), (AXIS_ACTORS,))

    def fn(forest, tw, xb, bb):
        return predict_ops.predict_margin(
            forest, xb, bb,
            max_depth=max_depth, num_outputs=k,
            num_parallel_tree=npt, ntree_limit=int(ntree_limit),
            tree_weights=tw if has_tw else None,
            cat_features=tuple(cat_features),
        )

    mapped = jax.jit(
        shard_map(
            fn, mesh=mesh,
            in_specs=(P(), P(), P(AXIS_ACTORS), P(AXIS_ACTORS)),
            out_specs=P(AXIS_ACTORS),
        )
    )
    if len(_SPMD_MARGIN_FNS) > 16:  # bound retained programs; evict oldest
        _SPMD_MARGIN_FNS.pop(next(iter(_SPMD_MARGIN_FNS)))
    _SPMD_MARGIN_FNS[key] = mapped
    return mapped


def _forest_to_np(forest: Tree) -> Tree:
    return Tree(*[np.asarray(f) for f in forest])


def stack_trees(trees: List[Tree]) -> Tree:
    """Stack per-round Tree pytrees ([k, heap] each) into one [T, heap] forest."""
    if not trees:
        raise ValueError("empty forest")
    fields = []
    for i in range(len(trees[0])):
        fields.append(np.concatenate([np.asarray(t[i]) for t in trees], axis=0))
    return Tree(*fields)


class RayXGBoostBooster:
    """Trained GBDT ensemble.

    Mirrors the parts of ``xgboost.Booster`` the reference ecosystem relies
    on: ``predict``, ``save_model``/``load_model``, ``get_dump`` (used by the
    reference's structural model-equality test helpers,
    ``xgboost_ray/tests/utils.py:182-226``), ``num_boosted_rounds``, and
    pickling (checkpoints pickle the booster, ``xgboost_ray/main.py:616``).
    """

    def __init__(
        self,
        forest: Tree,
        cuts: np.ndarray,
        params: TrainParams,
        base_score: float,
        feature_names: Optional[List[str]] = None,
        feature_types: Optional[List[str]] = None,
        tree_weights: Optional[np.ndarray] = None,
    ):
        self.forest = _forest_to_np(forest)
        self.cuts = np.asarray(cuts, dtype=np.float32)
        self.params = params
        self.base_score = float(base_score)
        # per-tree output scales (DART dropout normalization); None == all 1.0
        self.tree_weights = (
            None if tree_weights is None else np.asarray(tree_weights, np.float32)
        )
        self.feature_names = feature_names
        self.feature_types = feature_types
        # col index -> category values for auto-encoded categorical columns;
        # used to encode predict-time DataFrames with the TRAINING mapping
        self.categories: Optional[Dict[int, tuple]] = None
        self.best_iteration: Optional[int] = None
        self.best_score: Optional[float] = None
        self._attributes: Dict[str, str] = {}
        # False only for models loaded from pre-stats serializations, whose
        # cover/base_weight were zero-filled (contributions would be garbage)
        self._has_node_stats: bool = True

    # -- introspection -----------------------------------------------------

    @property
    def num_features(self) -> int:
        return int(self.cuts.shape[0])

    @property
    def num_outputs(self) -> int:
        if self.params.objective == "reg:quantileerror":
            qa = self.params.quantile_alpha
            return len(qa) if isinstance(qa, (list, tuple)) else 1
        return max(self.params.num_class, 1)

    @property
    def cat_features(self) -> tuple:
        """Indices of categorical features ('c' in feature_types)."""
        from xgboost_ray_tpu.params import cat_feature_indices

        return cat_feature_indices(self.feature_types)

    @property
    def max_depth(self) -> int:
        heap = self.forest.feature.shape[1]
        return int(np.log2(heap + 1)) - 1

    def signature(self) -> tuple:
        """Structural identity for compiled-program caching (the serve
        layer's cache key): everything that changes the traced prediction
        program — forest/feature shapes, static walk parameters, and the
        objective envelope that drives the margin transform — but NOT the
        array contents, so a hot-swap to a same-shaped retrain reuses every
        compiled program."""
        p = self.params
        return (
            "gbtree",
            int(self.forest.feature.shape[0]),  # trees
            int(self.forest.feature.shape[1]),  # heap slots
            self.num_features,
            self.num_outputs,
            self.max_depth,
            p.num_parallel_tree,
            self.tree_weights is not None,
            self.cat_features,
            p.objective,
            p.num_class,
            float(p.scale_pos_weight),
            tuple(p.quantile_alpha) if isinstance(
                p.quantile_alpha, (list, tuple)) else p.quantile_alpha,
        )

    def num_boosted_rounds(self) -> int:
        per_round = self.num_outputs * self.params.num_parallel_tree
        return int(self.forest.feature.shape[0] // per_round)

    @property
    def num_trees(self) -> int:
        return int(self.forest.feature.shape[0])

    def attributes(self) -> Dict[str, str]:
        return dict(self._attributes)

    def attr(self, key: str) -> Optional[str]:
        return self._attributes.get(key)

    def set_attr(self, **kwargs) -> None:
        for k, v in kwargs.items():
            if v is None:
                self._attributes.pop(k, None)
            else:
                self._attributes[k] = str(v)

    # -- prediction --------------------------------------------------------

    def _coerce_features(self, data) -> np.ndarray:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            if self.feature_names and list(data.columns) != list(self.feature_names):
                cols = [c for c in self.feature_names if c in data.columns]
                if len(cols) == len(self.feature_names):
                    data = data[self.feature_names]
            non_numeric = [
                c
                for c in data.columns
                if not pd.api.types.is_numeric_dtype(data[c].dtype)
            ]
            if non_numeric:
                # category/string columns -> codes using the TRAINING
                # category mapping (a frame's own category set can differ,
                # which would silently re-route equality splits); unseen
                # categories become NaN like xgboost
                data = data.copy()
                col_pos = {c: i for i, c in enumerate(data.columns)}
                for c in non_numeric:
                    cats = (self.categories or {}).get(col_pos[c])
                    if cats is not None:
                        codes = pd.Categorical(
                            data[c], categories=list(cats)
                        ).codes.astype(np.float32)
                        codes = pd.Series(codes, index=data.index)
                    elif col_pos[c] in self.cat_features:
                        raise ValueError(
                            f"column {c!r} is categorical in the model but no "
                            f"category mapping was recorded (the model was "
                            f"trained on integer codes); pass codes encoded "
                            f"the same way as training."
                        )
                    else:
                        codes = data[c].astype("category").cat.codes.astype(
                            np.float32
                        )
                    data[c] = codes.where(codes >= 0, np.nan)
            data = data.to_numpy()
        x = np.asarray(data, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        if x.shape[1] != self.num_features:
            raise ValueError(
                f"Feature shape mismatch: model expects {self.num_features}, "
                f"got {x.shape[1]}"
            )
        return x

    def slice_rounds(self, begin: int, end: int) -> "RayXGBoostBooster":
        """Sub-forest covering boosting rounds [begin, end)."""
        per_round = self.num_outputs * self.params.num_parallel_tree
        sl = slice(begin * per_round, end * per_round)
        sub = Tree(*[f[sl] for f in self.forest])
        out = RayXGBoostBooster(
            sub, self.cuts, self.params, self.base_score, self.feature_names,
            self.feature_types,
            tree_weights=None if self.tree_weights is None else self.tree_weights[sl],
        )
        out._has_node_stats = self._has_node_stats
        out.categories = self.categories
        return out

    def base_score_margin_np(self) -> float:
        """The margin-space offset implied by this booster's base_score."""
        obj = get_objective(
            self.params.objective, self.params.num_class,
            self.params.scale_pos_weight,
            quantile_alpha=self.params.quantile_alpha,
        )
        return float(obj.base_score_to_margin(self.base_score))

    def predict_margin_np(
        self, x: np.ndarray, ntree_limit: int = 0, base_margin: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """Raw margin [N, K]."""
        n = x.shape[0]
        k = self.num_outputs
        obj = get_objective(
            self.params.objective, self.params.num_class,
            self.params.scale_pos_weight,
            quantile_alpha=self.params.quantile_alpha,
        )
        m0 = obj.base_score_to_margin(self.base_score)
        out = np.empty((n, k), np.float32)
        forest_dev = Tree(*[jnp.asarray(f) for f in self.forest])
        for lo in range(0, n, _PREDICT_CHUNK):
            hi = min(lo + _PREDICT_CHUNK, n)
            base = jnp.full((hi - lo, k), m0, jnp.float32)
            if base_margin is not None:
                bm = np.asarray(base_margin[lo:hi], np.float32)
                base = base + jnp.asarray(bm.reshape(hi - lo, -1))
            margin = predict_ops.predict_margin(
                forest_dev,
                jnp.asarray(x[lo:hi]),
                base,
                max_depth=self.max_depth,
                num_outputs=k,
                num_parallel_tree=self.params.num_parallel_tree,
                ntree_limit=int(ntree_limit),
                tree_weights=(
                    None if self.tree_weights is None else jnp.asarray(self.tree_weights)
                ),
                cat_features=self.cat_features,
            )
            out[lo:hi] = np.asarray(margin)
        return out

    def predict_margin_spmd(
        self,
        x: np.ndarray,
        devices,
        ntree_limit: int = 0,
        base_margin: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Raw margin [N, K], row-sharded over an explicit device mesh.

        The tree walk is embarrassingly parallel over rows, so each device
        walks its row block against the replicated forest inside ONE compiled
        shard_map program — the SPMD replacement for the reference's
        per-actor host loop (``xgboost_ray/main.py:1750-1896``), where every
        actor calls ``model.predict`` on its local shard.

        Multi-process worlds (``jax.process_count() > 1``): ``x`` is this
        process's LOCAL rows, ``devices`` must span every process
        (process-contiguous), and the local rows' margins come back — the
        same process-local contract as training (VERDICT r4 #4 lifts the
        single-process restriction).
        """
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if jax.process_count() > 1:
            return self._predict_margin_spmd_multiproc(
                x, devices, ntree_limit, base_margin
            )
        n_dev = len(devices)
        if n_dev <= 1:
            return self.predict_margin_np(
                x, ntree_limit=ntree_limit, base_margin=base_margin
            )
        n = x.shape[0]
        k = self.num_outputs
        obj = get_objective(
            self.params.objective, self.params.num_class,
            self.params.scale_pos_weight,
            quantile_alpha=self.params.quantile_alpha,
        )
        m0 = obj.base_score_to_margin(self.base_score)
        mesh = Mesh(np.asarray(devices), (AXIS_ACTORS,))
        repl = NamedSharding(mesh, P())
        rows = NamedSharding(mesh, P(AXIS_ACTORS))
        forest_dev = Tree(*[jax.device_put(np.asarray(f), repl) for f in self.forest])
        has_tw = self.tree_weights is not None
        tw_dev = jax.device_put(
            np.asarray(self.tree_weights, np.float32)
            if has_tw else np.zeros(0, np.float32),
            repl,
        )
        mapped = _spmd_margin_fn(
            devices, k, self.max_depth, self.params.num_parallel_tree,
            ntree_limit, has_tw, self.cat_features,
        )
        chunk = _PREDICT_CHUNK * n_dev
        out = np.empty((n, k), np.float32)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            rows_n = hi - lo
            pad = (-rows_n) % n_dev
            xb = np.asarray(x[lo:hi], np.float32)
            if pad:
                xb = np.concatenate([xb, np.zeros((pad, xb.shape[1]), np.float32)])
            base = np.full((rows_n + pad, k), m0, np.float32)
            if base_margin is not None:
                base[:rows_n] += np.asarray(
                    base_margin[lo:hi], np.float32
                ).reshape(rows_n, -1)
            progreg.note_jit_call(
                "booster.margin_spmd", mapped, (forest_dev, tw_dev, xb, base),
                meta={"world": n_dev, "grower": "predict",
                      "hist_quant": "none", "sampling": "none"},
            )
            margin = mapped(
                forest_dev, tw_dev,
                jax.device_put(xb, rows), jax.device_put(base, rows),
            )
            out[lo:hi] = np.asarray(margin)[:rows_n]
        return out

    def _predict_margin_spmd_multiproc(
        self,
        x: np.ndarray,
        devices,
        ntree_limit: int = 0,
        base_margin: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Multi-process SPMD margin walk: every process dispatches the SAME
        jitted program over the global mesh in lockstep, feeding its local
        rows via ``make_array_from_process_local_data`` (the layout training
        uses, ``engine.py _global_row_layout``) and reading its own rows'
        margins back from the addressable output shards. Row counts are
        allgathered so all processes agree on the padded block extent and
        the chunk schedule."""
        import jax
        from jax.experimental import multihost_utils
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        pc = jax.process_count()
        n_dev = len(devices)
        if n_dev % pc:
            raise ValueError(
                f"{n_dev} mesh devices do not divide evenly over {pc} "
                f"processes."
            )
        per_proc = n_dev // pc
        n_local = int(x.shape[0])
        f = int(x.shape[1])
        k = self.num_outputs
        obj = get_objective(
            self.params.objective, self.params.num_class,
            self.params.scale_pos_weight,
            quantile_alpha=self.params.quantile_alpha,
        )
        m0 = obj.base_score_to_margin(self.base_score)

        counts = np.asarray(
            multihost_utils.process_allgather(np.int64(n_local))
        ).ravel()
        block = max(1, int(-(-int(counts.max()) // per_proc)))

        mesh = Mesh(np.asarray(devices), (AXIS_ACTORS,))
        repl = NamedSharding(mesh, P())
        rows_sh = NamedSharding(mesh, P(AXIS_ACTORS))

        def put_repl(arr):
            # replicated multi-host placement: every process holds the same
            # host value, each fills its addressable shards locally
            return jax.make_array_from_callback(
                arr.shape, repl, lambda idx: arr[idx]
            )

        forest_dev = Tree(*[put_repl(np.asarray(f_)) for f_ in self.forest])
        has_tw = self.tree_weights is not None
        tw_dev = put_repl(
            np.asarray(self.tree_weights, np.float32)
            if has_tw else np.zeros(0, np.float32)
        )
        mapped = _spmd_margin_fn(
            devices, k, self.max_depth, self.params.num_parallel_tree,
            ntree_limit, has_tw, self.cat_features,
        )

        # local rows laid out as per-device consecutive blocks
        x_pad = np.zeros((per_proc * block, f), np.float32)
        x_pad[:n_local] = np.asarray(x, np.float32)
        base_pad = np.full((per_proc * block, k), m0, np.float32)
        if base_margin is not None:
            base_pad[:n_local] += np.asarray(
                base_margin, np.float32
            ).reshape(n_local, -1)
        x_blocks = x_pad.reshape(per_proc, block, f)
        b_blocks = base_pad.reshape(per_proc, block, k)

        dev_pos = {d: i for i, d in enumerate(devices)}
        out_blocks = np.empty((per_proc, block, k), np.float32)
        cb = _PREDICT_CHUNK
        for lo in range(0, block, cb):
            hi = min(lo + cb, block)
            w = hi - lo
            xb = np.ascontiguousarray(
                x_blocks[:, lo:hi].reshape(per_proc * w, f)
            )
            bb = np.ascontiguousarray(
                b_blocks[:, lo:hi].reshape(per_proc * w, k)
            )
            margin = mapped(
                forest_dev, tw_dev,
                jax.make_array_from_process_local_data(
                    rows_sh, xb, (n_dev * w, f)
                ),
                jax.make_array_from_process_local_data(
                    rows_sh, bb, (n_dev * w, k)
                ),
            )
            shards_ = sorted(
                margin.addressable_shards, key=lambda s: dev_pos[s.device]
            )
            loc = np.concatenate([np.asarray(s.data) for s in shards_], axis=0)
            out_blocks[:, lo:hi] = loc.reshape(per_proc, w, k)
        return out_blocks.reshape(per_proc * block, k)[:n_local]

    def predict_special_spmd(
        self,
        x: np.ndarray,
        devices,
        kind: str,  # "contribs" | "contribs_approx" | "interactions" | "leaf"
        ntree_limit: int = 0,
        base_margin: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """SHAP contributions / interactions / leaf indices with rows
        sharded over the mesh — the SPMD analog of the ``*_np`` host
        methods (VERDICT r4 weak #3: the SPMD fast path used to exclude
        exactly these outputs). Unlike the margin walk (hand shard_map'd),
        these kernels carry internal scans, so the row parallelism is
        expressed the GSPMD way: rows placed with a P(AXIS_ACTORS) sharding
        into the ALREADY-jitted kernels and XLA's sharding propagation
        partitions the row-parallel walk — no manual axes to fight.
        Single-process meshes only; the driver falls back to the host loop
        elsewhere."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if kind != "leaf":
            self._assert_node_stats()
        n_dev = len(devices)
        n = x.shape[0]
        k = self.num_outputs
        f1 = self.num_features + 1
        t = int(np.asarray(self.forest.feature).shape[0])
        mesh = Mesh(np.asarray(devices), (AXIS_ACTORS,))
        repl = NamedSharding(mesh, P())
        rows = NamedSharding(mesh, P(AXIS_ACTORS))
        forest_dev = Tree(*[jax.device_put(np.asarray(f), repl)
                            for f in self.forest])
        tw_dev = (
            None if self.tree_weights is None
            else jax.device_put(np.asarray(self.tree_weights, np.float32),
                                repl)
        )
        kw = dict(
            max_depth=self.max_depth, num_outputs=k,
            num_parallel_tree=self.params.num_parallel_tree,
            ntree_limit=int(ntree_limit), tree_weights=tw_dev,
            cat_features=self.cat_features,
        )
        kernels = {
            "leaf": lambda xb: predict_ops.predict_leaf_index(
                forest_dev, xb, self.max_depth,
                cat_features=self.cat_features),
            "contribs": lambda xb: predict_ops.predict_contribs_exact(
                forest_dev, xb, **kw),
            "contribs_approx": lambda xb: predict_ops.predict_contribs(
                forest_dev, xb, **kw),
            "interactions": lambda xb: predict_ops.predict_interactions(
                forest_dev, xb, **kw),
        }
        shapes = {
            "leaf": ((t,), np.int32),
            "contribs": ((k, f1), np.float32),
            "contribs_approx": ((k, f1), np.float32),
            "interactions": ((k, f1, f1), np.float32),
        }
        tail, dtype = shapes[kind]
        # only exact SHAP has the [2^depth, chunk, F] working-set blowup;
        # Saabas and leaf walks take the large chunk (host-path rule)
        per_dev = (_SHAP_CHUNK if kind in ("contribs", "interactions")
                   else _PREDICT_CHUNK)
        chunk = per_dev * n_dev
        out = np.empty((n,) + tail, dtype)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            rows_n = hi - lo
            pad = (-rows_n) % n_dev
            xb = np.asarray(x[lo:hi], np.float32)
            if pad:
                xb = np.concatenate(
                    [xb, np.zeros((pad, xb.shape[1]), np.float32)])
            res = kernels[kind](jax.device_put(xb, rows))
            out[lo:hi] = np.asarray(res)[:rows_n]
        if kind == "leaf":
            return out
        return self._finalize_contribs(out, kind, base_margin)

    def _finalize_contribs(self, out: np.ndarray, kind: str,
                           base_margin: Optional[np.ndarray]) -> np.ndarray:
        """Shared contribs/interactions postprocessing for the host AND
        SPMD paths (single source so their bias-column conventions cannot
        diverge): add the base-score margin (+ user base_margin) to the
        bias slot and squeeze the class axis for single-output models."""
        n = out.shape[0]
        k = out.shape[1]
        m0 = self.base_score_margin_np()
        if kind == "interactions":
            out[:, :, -1, -1] += m0
            if base_margin is not None:
                out[:, :, -1, -1] += np.asarray(
                    base_margin, np.float32).reshape(n, -1)
            return out[:, 0] if k == 1 else out
        out[:, :, -1] += m0
        if base_margin is not None:
            out[:, :, -1] += np.asarray(
                base_margin, np.float32).reshape(n, -1)
        return out[:, 0, :] if k == 1 else out

    def _assert_node_stats(self):
        if not self._has_node_stats:
            raise ValueError(
                "This model was saved by a version without per-node statistics "
                "(cover/base_weight); prediction contributions would be "
                "all-zero. Re-train or re-save the model with this version."
            )

    def predict_contribs_np(
        self, x: np.ndarray, ntree_limit: int = 0,
        base_margin: Optional[np.ndarray] = None,
        approx: bool = False,
    ) -> np.ndarray:
        """Per-feature contributions [N, F+1] (binary/regression) or
        [N, K, F+1] (multiclass), bias last; rows sum to the margin.
        Exact TreeSHAP by default; ``approx=True`` selects the cheaper Saabas
        path attribution (xgboost ``approx_contribs=True``)."""
        self._assert_node_stats()
        n = x.shape[0]
        k = self.num_outputs
        forest_dev = Tree(*[jnp.asarray(f) for f in self.forest])
        kernel = (
            predict_ops.predict_contribs
            if approx
            else predict_ops.predict_contribs_exact
        )
        chunk = _PREDICT_CHUNK if approx else _SHAP_CHUNK
        out = np.empty((n, k, self.num_features + 1), np.float32)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            out[lo:hi] = np.asarray(
                kernel(
                    forest_dev,
                    jnp.asarray(x[lo:hi]),
                    max_depth=self.max_depth,
                    num_outputs=k,
                    num_parallel_tree=self.params.num_parallel_tree,
                    ntree_limit=int(ntree_limit),
                    tree_weights=(
                        None
                        if self.tree_weights is None
                        else jnp.asarray(self.tree_weights)
                    ),
                    cat_features=self.cat_features,
                )
            )
        return self._finalize_contribs(out, "contribs", base_margin)

    def predict_interactions_np(
        self, x: np.ndarray, ntree_limit: int = 0,
        base_margin: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """SHAP interaction values [N, F+1, F+1] (or [N, K, F+1, F+1]);
        each feature row sums to that feature's plain contribution and the
        grand total equals the margin (xgboost ``pred_interactions``)."""
        self._assert_node_stats()
        n = x.shape[0]
        k = self.num_outputs
        f1 = self.num_features + 1
        forest_dev = Tree(*[jnp.asarray(f) for f in self.forest])
        out = np.empty((n, k, f1, f1), np.float32)
        for lo in range(0, n, _SHAP_CHUNK):
            hi = min(lo + _SHAP_CHUNK, n)
            out[lo:hi] = np.asarray(
                predict_ops.predict_interactions(
                    forest_dev,
                    jnp.asarray(x[lo:hi]),
                    max_depth=self.max_depth,
                    num_outputs=k,
                    num_parallel_tree=self.params.num_parallel_tree,
                    ntree_limit=int(ntree_limit),
                    tree_weights=(
                        None
                        if self.tree_weights is None
                        else jnp.asarray(self.tree_weights)
                    ),
                    cat_features=self.cat_features,
                )
            )
        return self._finalize_contribs(out, "interactions", base_margin)

    def predict(
        self,
        data,
        output_margin: bool = False,
        pred_leaf: bool = False,
        pred_contribs: bool = False,
        pred_interactions: bool = False,
        ntree_limit: int = 0,
        iteration_range: Optional[Tuple[int, int]] = None,
        validate_features: bool = True,
        base_margin: Optional[np.ndarray] = None,
        approx_contribs: bool = False,
        **_ignored,
    ) -> np.ndarray:
        x = self._coerce_features(data)
        if pred_contribs or pred_interactions:
            booster = self
            if iteration_range is not None and iteration_range != (0, 0):
                booster = self.slice_rounds(iteration_range[0], iteration_range[1])
            if pred_interactions:
                if approx_contribs:
                    warnings.warn(
                        "approx_contribs=True is ignored with "
                        "pred_interactions: only the exact "
                        "O(2^depth * depth^2) interactions kernel is "
                        "implemented (xgboost's approximate interactions "
                        "path has no TPU equivalent here)."
                    )
                return booster.predict_interactions_np(
                    x, ntree_limit=ntree_limit, base_margin=base_margin
                )
            return booster.predict_contribs_np(
                x, ntree_limit=ntree_limit, base_margin=base_margin,
                approx=approx_contribs,
            )
        if pred_leaf:
            booster = self
            if iteration_range is not None and iteration_range != (0, 0):
                booster = self.slice_rounds(iteration_range[0], iteration_range[1])
            forest_dev = Tree(*[jnp.asarray(f) for f in booster.forest])
            return np.asarray(
                predict_ops.predict_leaf_index(
                    forest_dev, jnp.asarray(x), booster.max_depth,
                    cat_features=booster.cat_features,
                )
            )
        booster = self
        if iteration_range is not None and iteration_range != (0, 0):
            booster = self.slice_rounds(iteration_range[0], iteration_range[1])
        margin = booster.predict_margin_np(x, ntree_limit=ntree_limit, base_margin=base_margin)
        return booster._margin_to_prediction(margin, output_margin)

    def _margin_to_prediction(self, margin: np.ndarray, output_margin: bool) -> np.ndarray:
        """Shared margin→prediction transform — used by this host predict
        path AND main's SPMD predict path so the two cannot diverge."""
        if output_margin:
            return margin[:, 0] if self.num_outputs == 1 else margin
        obj = get_objective(
            self.params.objective, self.params.num_class,
            self.params.scale_pos_weight,
            quantile_alpha=self.params.quantile_alpha,
        )
        return np.asarray(obj.transform(jnp.asarray(margin)))

    def export_xgboost_json(self, fname: Optional[str] = None) -> str:
        """Serialize in the xgboost JSON model schema (loadable by any
        xgboost runtime — the interop property reference users have)."""
        from xgboost_ray_tpu.models.xgb_export import export_xgboost_json

        return export_xgboost_json(self, fname)

    @classmethod
    def import_xgboost_json(cls, data) -> "RayXGBoostBooster":
        """Load an xgboost JSON model (ours or real xgboost's)."""
        from xgboost_ray_tpu.models.xgb_export import import_xgboost_json

        return import_xgboost_json(data)

    # -- serialization -----------------------------------------------------

    def _to_dict(self) -> Dict[str, Any]:
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            cuts=self.cuts,
            tree_weights=(
                self.tree_weights
                if self.tree_weights is not None
                else np.zeros((0,), np.float32)
            ),
            **{name: getattr(self.forest, name) for name in Tree._fields},
        )
        import dataclasses as dc

        return {
            "format": "xgboost_ray_tpu.booster",
            "version": 1,
            "params": dc.asdict(self.params),
            "base_score": self.base_score,
            "feature_names": self.feature_names,
            "feature_types": self.feature_types,
            "best_iteration": self.best_iteration,
            "best_score": self.best_score,
            "attributes": self._attributes,
            "has_node_stats": self._has_node_stats,
            "categories": (
                None
                if self.categories is None
                else {str(k): list(v) for k, v in self.categories.items()}
            ),
            "arrays_npz_b64": base64.b64encode(buf.getvalue()).decode("ascii"),
        }

    @classmethod
    def _from_dict(cls, d: Dict[str, Any]) -> "RayXGBoostBooster":
        raw = base64.b64decode(d["arrays_npz_b64"])
        with np.load(io.BytesIO(raw)) as z:
            # stats fields default to zeros for models saved before they
            # existed; such models cannot produce contributions (see
            # _has_node_stats guard) but predict/resume normally
            has_stats = bool(d.get("has_node_stats", "base_weight" in z))
            forest = Tree(
                **{
                    name: (z[name] if name in z else np.zeros_like(z["value"]))
                    for name in Tree._fields
                }
            )
            cuts = z["cuts"]
            tw = z["tree_weights"] if "tree_weights" in z else np.zeros((0,), np.float32)
        params = TrainParams(**d["params"])
        out = cls(
            forest,
            cuts,
            params,
            d["base_score"],
            d.get("feature_names"),
            d.get("feature_types"),
            tree_weights=tw if tw.size else None,
        )
        out.best_iteration = d.get("best_iteration")
        out.best_score = d.get("best_score")
        out._attributes = dict(d.get("attributes") or {})
        out._has_node_stats = has_stats
        cats = d.get("categories")
        if cats is not None:
            out.categories = {int(k): tuple(v) for k, v in cats.items()}
        return out

    def save_model(self, fname: str) -> None:
        with open(fname, "w") as f:
            json.dump(self._to_dict(), f)

    @classmethod
    def load_model(cls, fname: str) -> "RayXGBoostBooster":
        with open(fname) as f:
            return cls._from_dict(json.load(f))

    def save_raw(self) -> bytes:
        return json.dumps(self._to_dict()).encode("utf-8")

    @classmethod
    def load_raw(cls, raw: bytes) -> "RayXGBoostBooster":
        return cls._from_dict(json.loads(raw.decode("utf-8")))

    # -- model dump (structural comparison; reference tests/utils.py) ------

    def get_dump(self, with_stats: bool = False, dump_format: str = "text") -> List[str]:
        if dump_format == "json":
            return self._get_dump_json(with_stats)
        if dump_format != "text":
            raise ValueError(
                f"Unsupported dump_format {dump_format!r} (text or json)."
            )
        dumps = []
        heap = self.forest.feature.shape[1]
        for t in range(self.num_trees):
            lines = []

            def rec(idx: int, depth: int):
                if idx >= heap:
                    return
                indent = "\t" * depth
                if self.forest.is_leaf[t, idx]:
                    stats = (
                        f",cover={self.forest.cover[t, idx]:.6g}" if with_stats else ""
                    )
                    lines.append(
                        f"{indent}{idx}:leaf={self.forest.value[t, idx]:.6g}{stats}"
                    )
                    return
                f = self.forest.feature[t, idx]
                if f < 0:
                    return  # unused slot
                thr = self.forest.threshold[t, idx]
                miss = 2 * idx + 1 if self.forest.default_left[t, idx] else 2 * idx + 2
                stats = (
                    f",gain={self.forest.gain[t, idx]:.6g}"
                    f",cover={self.forest.cover[t, idx]:.6g}"
                    if with_stats
                    else ""
                )
                lines.append(
                    f"{indent}{idx}:[f{f}<{thr:.6g}] "
                    f"yes={2*idx+1},no={2*idx+2},missing={miss}{stats}"
                )
                rec(2 * idx + 1, depth + 1)
                rec(2 * idx + 2, depth + 1)

            rec(0, 0)
            dumps.append("\n".join(lines) + "\n")
        return dumps

    def _get_dump_json(self, with_stats: bool) -> List[str]:
        """xgboost ``dump_format="json"``: one nested node-dict JSON string
        per tree (``nodeid/depth/split/split_condition/yes/no/missing/
        children`` for internal nodes, ``nodeid/leaf`` for leaves)."""
        heap = self.forest.feature.shape[1]
        dumps = []
        for t in range(self.num_trees):

            def rec(idx: int, depth: int):
                if bool(self.forest.is_leaf[t, idx]):
                    node = {"nodeid": idx, "leaf": float(self.forest.value[t, idx])}
                    if with_stats:
                        node["cover"] = float(self.forest.cover[t, idx])
                    return node
                f = int(self.forest.feature[t, idx])
                if f < 0:
                    return None  # unused slot
                miss = 2 * idx + 1 if bool(self.forest.default_left[t, idx]) else 2 * idx + 2
                node = {
                    "nodeid": idx,
                    "depth": depth,
                    "split": f"f{f}",
                    "split_condition": float(self.forest.threshold[t, idx]),
                    "yes": 2 * idx + 1,
                    "no": 2 * idx + 2,
                    "missing": miss,
                }
                if with_stats:
                    node["gain"] = float(self.forest.gain[t, idx])
                    node["cover"] = float(self.forest.cover[t, idx])
                children = [
                    rec(2 * idx + 1, depth + 1), rec(2 * idx + 2, depth + 1)
                ]
                node["children"] = [c for c in children if c is not None]
                return node

            root = rec(0, 0)
            dumps.append(json.dumps(root if root is not None else {}))
        return dumps

    def trees_to_dataframe(self):
        """Flat per-node table of the forest (xgboost analog); columns:
        Tree, Node, ID, Feature, Split, Yes, No, Missing, Gain, IsLeaf, Value."""
        import pandas as pd

        rows = []
        heap = self.forest.feature.shape[1]
        for t in range(self.num_trees):
            for idx in range(heap):
                is_leaf = bool(self.forest.is_leaf[t, idx])
                feat = int(self.forest.feature[t, idx])
                if not is_leaf and feat < 0:
                    continue  # unused slot
                rows.append({
                    "Tree": t,
                    "Node": idx,
                    "ID": f"{t}-{idx}",
                    "Feature": "Leaf" if is_leaf else (
                        self.feature_names[feat]
                        if self.feature_names
                        else f"f{feat}"
                    ),
                    "Split": None if is_leaf else float(self.forest.threshold[t, idx]),
                    "Yes": None if is_leaf else f"{t}-{2 * idx + 1}",
                    "No": None if is_leaf else f"{t}-{2 * idx + 2}",
                    "Missing": None if is_leaf else (
                        f"{t}-{2 * idx + 1}"
                        if self.forest.default_left[t, idx]
                        else f"{t}-{2 * idx + 2}"
                    ),
                    "Gain": float(self.forest.gain[t, idx]),
                    "IsLeaf": is_leaf,
                    "Value": float(self.forest.value[t, idx]),
                })
        return pd.DataFrame(rows)

    def get_score(self, importance_type: str = "weight") -> Dict[str, float]:
        """Per-feature importance (xgboost ``Booster.get_score`` analog):
        weight (split counts), gain (mean split gain), total_gain."""
        feat = self.forest.feature
        leaf = self.forest.is_leaf
        internal = (feat >= 0) & (~leaf)
        used = feat[internal]
        names = self.feature_names or [f"f{i}" for i in range(self.num_features)]
        counts = np.bincount(used, minlength=self.num_features).astype(np.float64)
        if importance_type == "weight":
            vals = counts
        elif importance_type in ("gain", "total_gain"):
            gains = self.forest.gain[internal]
            total = np.zeros(self.num_features, np.float64)
            np.add.at(total, used, gains)
            vals = total if importance_type == "total_gain" else (
                np.divide(total, counts, out=np.zeros_like(total),
                          where=counts > 0)
            )
        else:
            raise ValueError(
                f"Unsupported importance_type: {importance_type!r} "
                f"(weight, gain, total_gain)"
            )
        return {names[i]: float(v) for i, v in enumerate(vals) if v > 0}

    def get_fscore(self) -> Dict[str, float]:
        """xgboost ``Booster.get_fscore`` alias: split counts per feature."""
        return self.get_score(importance_type="weight")

    def __getstate__(self):
        return self._to_dict()

    def __setstate__(self, state):
        other = self._from_dict(state)
        self.__dict__.update(other.__dict__)


# Short alias mirroring `xgboost.Booster` usage in user code.
Booster = RayXGBoostBooster
