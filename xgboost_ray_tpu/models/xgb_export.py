"""xgboost-schema model interop: export/import the native JSON format.

The reference's boosters ARE xgboost boosters, so its users can hand a saved
model to any xgboost runtime (serving, SHAP tooling, other bindings). This
module gives the TPU booster the same property: ``export_xgboost_json``
writes the xgboost >= 1.7 JSON model schema (``learner.gradient_booster.
model.trees[*]`` node arrays), and ``import_xgboost_json`` loads such a file
— whether written by us or by real xgboost — back into a
``RayXGBoostBooster`` (split semantics are identical: go left iff
``x < split_condition``, missing follows ``default_left``; leaf values are
post-learning-rate in both).

Reference tooling this mirrors: ``xgboost_ray`` checkpoints/``save_model``
(``xgboost_ray/main.py:507-510, 616``) which delegate to xgboost's native
serialization.
"""

import json
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_INT_MAX = 2147483647


def _tree_to_xgb(tree_np, t_id: int, num_feature: int,
                 learning_rate: float = 1.0,
                 leaf_scale: float = 1.0) -> Dict[str, Any]:
    """One padded-heap tree -> xgboost compact node-array dict (BFS ids).

    ``base_weights`` convention: xgboost stores PRE-learning-rate node
    weights (leaf value = eta * base_weight); this repo's Tree.base_weight is
    lr-scaled, so export divides by ``learning_rate``.

    ``leaf_scale`` folds the num_parallel_tree averaging into the stored
    values: xgboost core SUMS every tree's leaf, while this repo's predictor
    averages the ``num_parallel_tree`` trees of a round
    (``ops/predict.py``), so export writes ``value / npt`` (and import
    multiplies back). Scaling value and base_weight together keeps the
    leaf value/weight ratio — and hence the importer's eta recovery —
    intact."""
    feature = np.asarray(tree_np.feature)
    threshold = np.asarray(tree_np.threshold)
    default_left = np.asarray(tree_np.default_left)
    is_leaf = np.asarray(tree_np.is_leaf)
    value = np.asarray(tree_np.value)
    gain = np.asarray(tree_np.gain)
    cover = np.asarray(tree_np.cover)
    base_weight = np.asarray(tree_np.base_weight)

    heap = len(feature)

    def _internal(i):
        return (not bool(is_leaf[i])) and int(feature[i]) >= 0 and 2 * i + 2 < heap

    # BFS over reachable heap slots; compact ids in visit order (root = 0)
    ids: Dict[int, int] = {}
    order: List[int] = []
    queue = deque([0])
    while queue:
        h = queue.popleft()
        ids[h] = len(order)
        order.append(h)
        if _internal(h):
            queue.append(2 * h + 1)
            queue.append(2 * h + 2)

    n = len(order)
    left, right, parents = [], [], []
    split_idx, split_cond, dleft, losses, hess, bw = [], [], [], [], [], []
    for cid, h in enumerate(order):
        if _internal(h):
            left.append(ids[2 * h + 1])
            right.append(ids[2 * h + 2])
            split_idx.append(int(feature[h]))
            split_cond.append(float(threshold[h]))
            dleft.append(1 if bool(default_left[h]) else 0)
            losses.append(float(gain[h]))
        else:
            left.append(-1)
            right.append(-1)
            split_idx.append(0)
            split_cond.append(float(value[h]) * leaf_scale)  # leaf value lives here
            dleft.append(0)
            losses.append(0.0)
        hess.append(float(cover[h]))
        bw.append(float(base_weight[h]) * leaf_scale / max(learning_rate, 1e-12))
        if h == 0:
            parents.append(_INT_MAX)
        else:
            parents.append(ids[(h - 1) // 2])

    return {
        "base_weights": bw,
        "categories": [],
        "categories_nodes": [],
        "categories_segments": [],
        "categories_sizes": [],
        "default_left": dleft,
        "id": t_id,
        "left_children": left,
        "loss_changes": losses,
        "parents": parents,
        "right_children": right,
        "split_conditions": split_cond,
        "split_indices": split_idx,
        "split_type": [0] * n,
        "sum_hessian": hess,
        "tree_param": {
            "num_deleted": "0",
            "num_feature": str(num_feature),
            "num_nodes": str(n),
            "size_leaf_vector": "1",
        },
    }


_OBJECTIVE_PARAM_KEYS = {
    "reg:squarederror": ("reg_loss_param", {"scale_pos_weight": "1"}),
    "reg:squaredlogerror": ("reg_loss_param", {"scale_pos_weight": "1"}),
    "binary:logistic": ("reg_loss_param", {"scale_pos_weight": "1"}),
    "reg:logistic": ("reg_loss_param", {"scale_pos_weight": "1"}),
    "count:poisson": ("poisson_regression_param", {"max_delta_step": "0.7"}),
    "multi:softmax": ("softmax_multiclass_param", {"num_class": "0"}),
    "multi:softprob": ("softmax_multiclass_param", {"num_class": "0"}),
    "rank:pairwise": ("lambdarank_param", {}),
    "rank:ndcg": ("lambdarank_param", {}),
    "rank:map": ("lambdarank_param", {}),
    "survival:aft": ("aft_loss_param", {"aft_loss_distribution": "normal",
                                        "aft_loss_distribution_scale": "1"}),
    "reg:gamma": ("reg_loss_param", {"scale_pos_weight": "1"}),
    "reg:tweedie": ("tweedie_regression_param", {"tweedie_variance_power": "1.5"}),
}


def objective_param_entry(params) -> Tuple[str, str, Dict[str, str]]:
    """``(objective_name, param_key, param_dict)`` for the xgboost JSON
    schema's ``learner.objective`` block.

    Real xgboost's objective loader expects a DIFFERENT param key per
    objective family (``softmax_multiclass_param`` with ``num_class``,
    ``poisson_regression_param``, ...); hardcoding ``reg_loss_param``
    produces files that misload for anything beyond plain regression.
    Shared by the tree exporter and ``RayLinearBooster.export_xgboost_json``
    (ADVICE r5) so the mapping cannot diverge again."""
    obj_name = str(params.objective)
    pkey, pdefault = _OBJECTIVE_PARAM_KEYS.get(
        obj_name, ("reg_loss_param", {"scale_pos_weight": "1"})
    )
    pval = dict(pdefault)
    if pkey == "softmax_multiclass_param":
        pval["num_class"] = str(int(params.num_class or 0))
    if pkey == "aft_loss_param":
        pval["aft_loss_distribution"] = str(params.aft_loss_distribution)
        pval["aft_loss_distribution_scale"] = str(
            params.aft_loss_distribution_scale
        )
    return obj_name, pkey, pval


def export_xgboost_json(booster, fname: Optional[str] = None) -> str:
    """Serialize ``booster`` in the xgboost JSON model schema. Returns the
    JSON string; also writes it to ``fname`` when given."""
    booster._assert_node_stats()
    forest = booster.forest
    num_feature = booster.num_features
    k = max(1, int(booster.params.num_class or 0)) if str(
        booster.params.objective).startswith("multi:") else 1
    npt = int(booster.params.num_parallel_tree or 1)
    per_round = k * npt

    n_trees = int(np.asarray(forest.feature).shape[0])
    lr = float(getattr(booster.params, "learning_rate", 1.0) or 1.0)
    trees = []
    tree_info = []
    for t in range(n_trees):
        tree_np = type(forest)(*[np.asarray(f)[t] for f in forest])
        trees.append(_tree_to_xgb(tree_np, t, num_feature, learning_rate=lr,
                                  leaf_scale=1.0 / npt))
        tree_info.append((t % per_round) // npt if k > 1 else 0)

    rounds = max(1, n_trees // per_round)
    iteration_indptr = [r * per_round for r in range(rounds + 1)]

    obj_name, pkey, pval = objective_param_entry(booster.params)

    gbtree_model = {
        "gbtree_model_param": {
            "num_parallel_tree": str(npt),
            "num_trees": str(n_trees),
        },
        "iteration_indptr": iteration_indptr,
        "tree_info": tree_info,
        "trees": trees,
    }
    if booster.tree_weights is not None:  # dart
        gradient_booster = {
            "name": "dart",
            "gbtree": {"model": gbtree_model},
            "weight_drop": [float(w) for w in np.asarray(booster.tree_weights)],
        }
    else:
        gradient_booster = {"name": "gbtree", "model": gbtree_model}

    doc = {
        "learner": {
            "attributes": {
                str(a): str(b) for a, b in booster.attributes().items()
            },
            "feature_names": list(booster.feature_names or []),
            "feature_types": [],
            "gradient_booster": gradient_booster,
            "learner_model_param": {
                "base_score": str(float(booster.base_score)),
                "boost_from_average": "1",
                "num_class": str(int(booster.params.num_class or 0)),
                "num_feature": str(num_feature),
                "num_target": "1",
            },
            "objective": {"name": obj_name, pkey: pval},
        },
        "version": [2, 0, 0],
    }
    out = json.dumps(doc)
    if fname:
        with open(fname, "w") as f:
            f.write(out)
    return out


def _xgb_tree_to_heap(t: Dict[str, Any],
                      leaf_scale: float = 1.0) -> Tuple[Dict[str, np.ndarray], int]:
    """One xgboost node-array tree -> padded-heap field dict + depth.

    ``leaf_scale`` is ``num_parallel_tree`` on import: xgboost files store
    sum-convention leaves (core sums all trees), while this repo's predictor
    divides each round's trees by npt — multiplying the stored values back
    up makes both conventions produce the same margin."""
    left = t["left_children"]
    right = t["right_children"]
    n = len(left)

    # depth of the compact tree: node order in xgboost dumps is not
    # guaranteed parent-before-child, so walk from the root
    max_depth = 0
    stack = [(0, 0)]
    while stack:
        nid, d = stack.pop()
        max_depth = max(max_depth, d)
        if left[nid] != -1:
            stack.append((left[nid], d + 1))
            stack.append((right[nid], d + 1))
    if max_depth > 16:
        # the padded heap is 2^(depth+1) slots per tree: a lossguide-grown
        # xgboost model with depth 25-60 would allocate GBs/TBs — fail with
        # the reason instead of a MemoryError deep in the allocator
        raise ValueError(
            f"imported tree has depth {max_depth}; the padded-heap layout "
            f"supports depth <= 16 (2^(d+1) slots/tree). Re-train with "
            f"bounded depth (e.g. grow_policy='depthwise', max_depth<=16)."
        )
    heap = (1 << (max_depth + 1)) - 1

    fields = {
        "feature": np.full(heap, -1, np.int32),
        "split_bin": np.zeros(heap, np.int32),
        "threshold": np.zeros(heap, np.float32),
        "default_left": np.zeros(heap, bool),
        "is_leaf": np.zeros(heap, bool),
        "value": np.zeros(heap, np.float32),
        "gain": np.zeros(heap, np.float32),
        "cover": np.zeros(heap, np.float32),
        "base_weight": np.zeros(heap, np.float32),
    }
    sc = t["split_conditions"]
    si = t["split_indices"]
    dl = t["default_left"]
    lc = t.get("loss_changes", [0.0] * n)
    sh = t.get("sum_hessian", [0.0] * n)
    bw = t.get("base_weights", [0.0] * n)

    # xgboost base_weights are PRE-learning-rate (leaf value = eta * weight);
    # this repo's convention is lr-scaled (base_weight == value at leaves).
    # The schema does not store eta, so recover the scale from the leaves'
    # value/weight ratios (median for robustness; 1.0 when degenerate, e.g.
    # our own exports round-tripped or an all-zero-weight tree).
    ratios = [
        sc[i] / bw[i]
        for i in range(n)
        if left[i] == -1 and abs(bw[i]) > 1e-12
    ]
    eta_scale = float(np.median(ratios)) if ratios else 1.0
    if not np.isfinite(eta_scale) or eta_scale <= 0:
        eta_scale = 1.0

    stack = [(0, 0)]  # (compact id, heap slot)
    while stack:
        nid, h = stack.pop()
        fields["cover"][h] = sh[nid]
        fields["base_weight"][h] = bw[nid] * eta_scale * leaf_scale
        if left[nid] == -1:
            fields["is_leaf"][h] = True
            fields["value"][h] = sc[nid] * leaf_scale
            # exact convention: base_weight equals the leaf value at leaves
            fields["base_weight"][h] = sc[nid] * leaf_scale
        else:
            fields["feature"][h] = si[nid]
            fields["threshold"][h] = sc[nid]
            fields["default_left"][h] = bool(dl[nid])
            fields["gain"][h] = lc[nid]
            stack.append((left[nid], 2 * h + 1))
            stack.append((right[nid], 2 * h + 2))
    return fields, max_depth


def import_xgboost_json(data) -> "RayXGBoostBooster":
    """Load an xgboost JSON model (path, JSON string, or parsed dict) into a
    RayXGBoostBooster. Works for models written by ``export_xgboost_json``
    AND by real xgboost (gbtree/dart, numeric splits)."""
    from xgboost_ray_tpu.models.booster import RayXGBoostBooster
    from xgboost_ray_tpu.ops.grow import Tree
    from xgboost_ray_tpu.params import TrainParams

    if isinstance(data, dict):
        doc = data
    else:
        text = data
        if isinstance(data, str) and not data.lstrip().startswith("{"):
            with open(data) as f:
                text = f.read()
        doc = json.loads(text)

    learner = doc["learner"]
    gb = learner["gradient_booster"]
    weight_drop = None
    if gb.get("name") == "dart":
        weight_drop = np.asarray(gb["weight_drop"], np.float32)
        model = gb["gbtree"]["model"]
    else:
        model = gb["model"]
    trees_json = model["trees"]
    if any(any(t.get("split_type", [])) for t in trees_json):
        raise ValueError(
            "model contains categorical (partition) splits; only numeric "
            "splits are supported by the importer."
        )

    npt = max(1, int(
        model.get("gbtree_model_param", {}).get("num_parallel_tree", "1") or 1))
    per_tree = [_xgb_tree_to_heap(t, leaf_scale=float(npt)) for t in trees_json]
    max_depth = max((d for _, d in per_tree), default=1)
    max_depth = max(max_depth, 1)
    heap = (1 << (max_depth + 1)) - 1

    def _pad(fields):
        out = {}
        for k, v in fields.items():
            if len(v) < heap:
                pad_val = -1 if k == "feature" else 0
                padded = np.full(heap, pad_val, v.dtype)
                # heap layout is depth-contiguous: smaller heaps are prefixes
                padded[: len(v)] = v
                out[k] = padded
            else:
                out[k] = v
        return out

    padded = [_pad(f) for f, _ in per_tree]
    stacked = {
        k: np.stack([p[k] for p in padded])
        for k in per_tree[0][0]
    } if per_tree else {
        k: np.zeros((0, heap), np.float32) for k in (
            "feature", "split_bin", "threshold", "default_left", "is_leaf",
            "value", "gain", "cover", "base_weight")
    }
    forest = Tree(**{k: stacked[k] for k in Tree._fields})

    lmp = learner["learner_model_param"]
    obj = learner.get("objective", {}).get("name", "reg:squarederror")
    params = TrainParams()
    params.objective = obj
    params.num_class = int(lmp.get("num_class", "0") or 0)
    params.max_depth = max_depth
    params.num_parallel_tree = npt
    if weight_drop is not None:
        params.booster = "dart"
    num_feature = int(lmp.get("num_feature", "0") or 0)

    booster = RayXGBoostBooster(
        forest=forest,
        cuts=np.zeros((max(num_feature, 1), 1), np.float32),
        params=params,
        base_score=float(lmp.get("base_score", "0.5") or 0.5),
        feature_names=list(learner.get("feature_names") or []) or None,
        tree_weights=weight_drop,
    )
    for key, val in (learner.get("attributes") or {}).items():
        booster.set_attr(**{key: val})
    return booster
