"""Span tracer: bounded ring buffer, JSONL export, and timeline helpers.

One trace record per finished span or instantaneous event, as a plain JSON
dict (the schema the whole repo shares — drivers, tests, CI and ``bench.py``
all validate against :func:`validate_trace_records`):

``{"kind": "span" | "event", "name": str, "ts": float (epoch seconds),
"seq": int (monotonic per tracer), "dur_s": float (spans only),
"parent": int | None (enclosing span's seq, spans only),
"round": int (optional — global boosting round), "attrs": dict (optional)}``

Design points:

* **Bounded and never silent.** Records live in a fixed-capacity ring
  buffer (``RXGB_TRACE_CAPACITY``, default 8192); when a record would
  overflow, the OLDEST record is dropped and the tracer's ``dropped``
  counter advances — the count is exported in ``snapshot()`` and in
  ``additional_results["obs"]["dropped_spans"]``, so truncation is always
  accounted, never invisible.
* **Nesting via a thread-local stack.** ``span()`` records its enclosing
  span's ``seq`` as ``parent``; children finish (and are appended) before
  their parents, so the record list is end-time ordered while ``seq``
  preserves start order.
* **Streaming.** With ``RXGB_TRACE_DIR`` set (or ``trace_dir=`` passed),
  every record is also appended as one JSON line to
  ``<dir>/trace-rank<k>.jsonl`` (k = the JAX process index when available)
  at emission time — a crash loses at most the last unflushed line, and
  multi-host runs produce one stream per rank.
* **Import-light.** Stdlib only: the launcher worker (and ``faults.py``)
  touch this module before any jax import.

This module is process-global-aware: :func:`get_tracer` returns the
thread's installed tracer (``use_tracer``) or a lazily-created process
default, so instrumentation sites never need plumbing.
"""

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "TRACE_NAMES",
    "Tracer",
    "get_tracer",
    "set_default_tracer",
    "use_tracer",
    "validate_trace_records",
    "recovery_time_s",
]

_DEFAULT_CAPACITY = 8192

#: The declared catalog of every span/event name the runtime emits — the
#: single source of truth rxgblint's OBS001 checks emission sites against
#: (both directions: an uncatalogued emission and a never-emitted catalog
#: entry are each findings), and the optional ``known_names`` vocabulary
#: for :func:`validate_trace_records`. Grouped by emitting layer.
TRACE_NAMES = frozenset({
    # engine round/phase spans (engine.py; phase spans via profile_phases)
    "round", "sample", "hist", "split", "partition", "margin", "allreduce",
    # streamed ingestion (stream/ingest.py + stream/upload.py): one fenced
    # span per sketch/bin chunk and per H2D transfer, one per cuts merge —
    # a streamed load is reconstructible from the timeline alone
    "data.sketch_chunk", "data.bin_chunk", "data.h2d", "data.cuts_merge",
    # elastic continuation of a streamed world (stream/ingest.py): donor
    # binned-row reuse — one summary event per reuse pass plus one fenced
    # span per donor block fetch; a shrink that re-used every survivor
    # shard shows bin_reuse spans and NO sketch_chunk/bin_chunk after the
    # kill (the zero-re-stream contract, asserted from the timeline)
    "data.bin_reuse",
    # driver lifecycle (main.py)
    "attempt", "failure.detected", "recovered", "backoff",
    "world.shrink", "world.grow", "world.resume", "world.restart",
    "checkpoint.commit", "allreduce.bytes",
    # failure domains (main.py): domain_down when a failure takes a whole
    # domain's last alive rank (one per lost domain, beside the single
    # coalesced world.shrink), deaths_coalesced when one shrink absorbed
    # multiple near-simultaneous deaths (ranks + how many were folded),
    # domain_up when an atomic domain grow-back makes the domain whole —
    # a host-loss incident reads domain_down -> deaths_coalesced ->
    # world.shrink -> elastic.ready -> world.grow -> domain_up
    "world.domain_down", "world.domain_up", "world.deaths_coalesced",
    # elastic scheduler (elastic.py)
    "elastic.reschedule", "elastic.ready",
    # launcher (launcher.py)
    "launcher.spawn", "launcher.hung", "launcher.attempt_failed",
    "checkpoint.load",
    # fault injection (faults.py)
    "fault.injected",
    # vectorized HPO (tuner.py): ASHA lane pruning inside a vmapped-K
    # program — one lane_prune event per pruned lane (original candidate
    # id + the rung metric that lost), one repack event per successive-
    # halving re-pack (k_before -> k_after)
    "hpo.lane_prune", "hpo.repack",
    # serving scale-out (serve/pool.py, serve/autoscale.py,
    # serve/canary.py): one route event per replica dispatch, one
    # replica_up/replica_down per pool membership change (reason:
    # scale_up | scale_down | rejoin | killed | shutdown), one scale
    # event per autoscaler decision (direction + from/to replica counts
    # + the p99/queue evidence), and the canary verdict pair — shadow
    # (candidate-vs-live divergence on mirrored traffic) then either
    # rollback (gate regression, old model keeps serving) or promote
    # (drain-then-flip committed). A scale-up -> scale-down cycle and a
    # replica-loss chaos run are each reconstructible from these alone
    # (asserted by tests/test_serve_pool.py).
    "serve.route", "serve.replica_up", "serve.replica_down", "serve.scale",
    "serve.shadow", "serve.rollback", "serve.promote",
})


def _process_rank() -> int:
    """This process's rank for trace-file naming; 0 when jax is absent or
    uninitialized (single-host)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 - tracing must never fail the caller
        return 0


class Tracer:
    """Span/event recorder with a bounded ring buffer.

    ``enabled`` defaults from ``RXGB_TRACE`` (on unless ``"0"``);
    ``capacity`` from ``RXGB_TRACE_CAPACITY``; ``trace_dir`` from
    ``RXGB_TRACE_DIR`` (empty = no streaming). A disabled tracer's
    ``span()``/``event()`` are near-free no-ops.
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        enabled: Optional[bool] = None,
        trace_dir: Optional[str] = None,
        rank: Optional[int] = None,
    ):
        if enabled is None:
            enabled = os.environ.get("RXGB_TRACE", "1") != "0"
        if capacity is None:
            capacity = int(
                os.environ.get("RXGB_TRACE_CAPACITY", str(_DEFAULT_CAPACITY))
            )
        if trace_dir is None:
            trace_dir = os.environ.get("RXGB_TRACE_DIR", "")
        self.enabled = bool(enabled)
        self.capacity = max(1, int(capacity))
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._seq = 0
        self._dropped = 0
        self._trace_dir = trace_dir or ""
        self._rank = rank
        self._stream_file = None
        self._stream_failed = False

    # -- recording ----------------------------------------------------------

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def _next_seq_locked(self) -> int:
        # _locked suffix = caller holds self._lock (enforced by rxgblint
        # LOCK001 on both ends: this method may touch shared state bare,
        # and every call site must sit inside `with self._lock`)
        self._seq += 1
        return self._seq

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(rec)
            self._stream_locked(rec)

    def _stream_locked(self, rec: Dict[str, Any]) -> None:
        """Append one JSON line to the per-rank trace file (best-effort;
        caller holds the lock)."""
        if not self._trace_dir or self._stream_failed:
            return
        try:
            if self._stream_file is None:
                rank = self._rank if self._rank is not None else _process_rank()
                self._rank = rank
                os.makedirs(self._trace_dir, exist_ok=True)
                path = os.path.join(self._trace_dir, f"trace-rank{rank}.jsonl")
                self._stream_file = open(path, "a", buffering=1)
            # default=str: attrs are caller-supplied (span() hands out the
            # mutable dict) — a numpy scalar or exotic value must degrade to
            # its string form, never raise out of the instrumented code
            self._stream_file.write(json.dumps(rec, default=str) + "\n")
        except Exception:  # noqa: BLE001 - tracing must never fail the caller
            # a dead disk must not take training down; the in-memory ring
            # still has the records
            self._stream_failed = True

    @contextlib.contextmanager
    def span(self, name: str, round: Optional[int] = None, **attrs):
        """Context manager recording one fenced span; yields the (mutable)
        attrs dict so callers can attach results measured inside."""
        if not self.enabled:
            yield attrs
            return
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        with self._lock:
            seq = self._next_seq_locked()
        parent = stack[-1] if stack else None
        stack.append(seq)
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield attrs
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            self._finish_span(name, ts, dur, seq, parent, round, attrs)

    def add_span(
        self,
        name: str,
        ts: float,
        dur_s: float,
        round: Optional[int] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record an externally-timed span (no nesting bookkeeping)."""
        if not self.enabled:
            return
        with self._lock:
            seq = self._next_seq_locked()
        stack = getattr(self._tls, "stack", None)
        parent = stack[-1] if stack else None
        self._finish_span(name, ts, dur_s, seq, parent, round, attrs)

    def _finish_span(self, name, ts, dur_s, seq, parent, round, attrs):
        rec: Dict[str, Any] = {
            "kind": "span",
            "name": name,
            "ts": ts,
            "seq": seq,
            "dur_s": float(dur_s),
            "parent": parent,
        }
        if round is not None:
            rec["round"] = int(round)
        if attrs:
            rec["attrs"] = dict(attrs)
        self._append(rec)

    def event(
        self,
        name: str,
        round: Optional[int] = None,
        attrs: Optional[Dict[str, Any]] = None,
        **kw,
    ) -> None:
        """Record one instantaneous event; attributes may come as an
        ``attrs`` dict, keyword arguments, or both (merged, kwargs win)."""
        if not self.enabled:
            return
        merged = dict(attrs) if attrs else {}
        merged.update(kw)
        with self._lock:
            seq = self._next_seq_locked()
        rec: Dict[str, Any] = {
            "kind": "event",
            "name": name,
            "ts": time.time(),
            "seq": seq,
        }
        if round is not None:
            rec["round"] = int(round)
        if merged:
            rec["attrs"] = merged
        self._append(rec)

    # -- reading / export ---------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """Snapshot of the ring buffer (oldest first)."""
        with self._lock:
            return list(self._buf)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "records": len(self._buf),
                "dropped_spans": self._dropped,
                "capacity": self.capacity,
            }

    def export_jsonl(self, path: str) -> int:
        """Write the buffered records as JSON lines; returns record count.
        Non-JSON-serializable attr values degrade to their string form."""
        recs = self.records()
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec, default=str) + "\n")
        return len(recs)

    def close(self) -> None:
        with self._lock:
            if self._stream_file is not None:
                try:
                    self._stream_file.close()
                except OSError:  # pragma: no cover - close is best-effort
                    pass
                self._stream_file = None


# ---------------------------------------------------------------------------
# current-tracer plumbing: thread-local install (train() scopes a fresh
# tracer per run) over a lazily-created process default (launcher-level
# spans outside any train() land there).
# ---------------------------------------------------------------------------

_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()
_tls = threading.local()


def get_tracer() -> Tracer:
    """The thread's installed tracer, else the process-default tracer."""
    current = getattr(_tls, "current", None)
    if current is not None:
        return current
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def set_default_tracer(tracer: Optional[Tracer]) -> None:
    """Replace the process-default tracer (None resets to lazy re-create)."""
    global _default_tracer
    _default_tracer = tracer


@contextlib.contextmanager
def use_tracer(tracer: Tracer):
    """Install ``tracer`` as this thread's current tracer for the scope."""
    prev = getattr(_tls, "current", None)
    _tls.current = tracer
    try:
        yield tracer
    finally:
        _tls.current = prev


# ---------------------------------------------------------------------------
# schema validation + timeline queries (shared by tests, CI and bench.py)
# ---------------------------------------------------------------------------

_ALLOWED_KEYS = {"kind", "name", "ts", "seq", "dur_s", "parent", "round", "attrs"}


def validate_trace_records(
    records: Iterable[Dict[str, Any]],
    known_names: Optional[Iterable[str]] = None,
) -> List[str]:
    """Validate records against the trace schema; returns a list of problem
    strings (empty = valid). Exported at package top level so tests and the
    CI example (``examples/trace_run.py``) share one checker.

    ``known_names`` opts into vocabulary checking: pass :data:`TRACE_NAMES`
    (or any custom set) and a record whose ``name`` is outside it becomes a
    problem — the runtime counterpart of rxgblint's static OBS001 check.
    The default (``None``) keeps the historical schema-only behavior."""
    problems: List[str] = []
    seen_seq = set()
    name_vocab = None if known_names is None else set(known_names)
    for i, rec in enumerate(records):
        where = f"record {i}"
        if not isinstance(rec, dict):
            problems.append(f"{where}: not a dict")
            continue
        unknown = set(rec) - _ALLOWED_KEYS
        if unknown:
            problems.append(f"{where}: unknown keys {sorted(unknown)}")
        kind = rec.get("kind")
        if kind not in ("span", "event"):
            problems.append(f"{where}: bad kind {kind!r}")
        name = rec.get("name")
        if not isinstance(name, str) or not name:
            problems.append(f"{where}: bad name {name!r}")
        elif name_vocab is not None and name not in name_vocab:
            problems.append(f"{where}: unknown name {name!r}")
        if not isinstance(rec.get("ts"), (int, float)):
            problems.append(f"{where}: bad ts {rec.get('ts')!r}")
        seq = rec.get("seq")
        if not isinstance(seq, int):
            problems.append(f"{where}: bad seq {seq!r}")
        elif seq in seen_seq:
            problems.append(f"{where}: duplicate seq {seq}")
        else:
            seen_seq.add(seq)
        if kind == "span":
            dur = rec.get("dur_s")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: bad dur_s {dur!r}")
            parent = rec.get("parent")
            if parent is not None and not isinstance(parent, int):
                problems.append(f"{where}: bad parent {parent!r}")
        if "round" in rec and not isinstance(rec["round"], int):
            problems.append(f"{where}: bad round {rec['round']!r}")
        if "attrs" in rec and not isinstance(rec["attrs"], dict):
            problems.append(f"{where}: bad attrs {rec['attrs']!r}")
        if kind == "event" and "dur_s" in rec:
            problems.append(f"{where}: event carries dur_s")
    return problems


def recovery_time_s(records: Iterable[Dict[str, Any]]) -> float:
    """Total failure→first-forward-progress time reconstructed from the
    timeline: each ``recovered`` event closes the clock opened by the most
    recent ``failure.detected`` event (matching the driver's
    ``time_to_recover_s`` accounting, which restarts the clock on repeated
    failures before progress)."""
    total = 0.0
    last_failure: Optional[float] = None
    for rec in records:
        if rec.get("kind") != "event":
            continue
        if rec.get("name") == "failure.detected":
            last_failure = float(rec["ts"])
        elif rec.get("name") == "recovered" and last_failure is not None:
            total += max(0.0, float(rec["ts"]) - last_failure)
            last_failure = None
    return total
