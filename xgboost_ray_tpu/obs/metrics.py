"""Process-wide metrics primitives: counters, gauges, latency histograms,
and a registry with Prometheus text exposition.

The log-bucket :class:`LatencyHistogram` is the one that used to live in
``serve/metrics.py`` (fixed log-spaced buckets, O(1) record, interpolated
percentiles — the Prometheus-client trade), promoted here so the serving
layer and any future hot path share one implementation. Two edge cases are
hardened in the move:

* ``record()`` of a non-finite ms (NaN/±inf) no longer corrupts bucket
  indexing (``math.ceil(nan)`` raised; ±inf poisoned ``sum_ms``) — such
  samples are counted in a separate ``invalid`` counter and excluded from
  buckets and the sum; a negative ms clamps to 0 (bucket 0, zero sum
  contribution).
* ``snapshot()`` takes every field under the histogram's own lock, so
  counts/total/``sum_ms`` are a consistent cut even while ``record()``
  runs on other threads.

Stdlib only — importable before jax (launcher workers, faults layer).
"""

import math
import re
import threading
from typing import Callable, Dict, List, Optional, Union

__all__ = [
    "BUCKET_BOUNDS_MS",
    "Counter",
    "Gauge",
    "LatencyHistogram",
    "MetricsRegistry",
    "get_registry",
]

# log-spaced latency bucket upper bounds (ms): 0.05 ms .. ~170 s at ~1.26x
_BUCKET_BASE_MS = 0.05
_BUCKET_FACTOR = 1.26
_N_BUCKETS = 60
BUCKET_BOUNDS_MS = [
    _BUCKET_BASE_MS * _BUCKET_FACTOR ** i for i in range(_N_BUCKETS)
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _fmt(v: Union[int, float]) -> str:
    """Prometheus sample value: ints bare, floats via repr (stable),
    non-finite as the exposition format's canonical NaN/+Inf/-Inf tokens
    (a dead live-gauge probe reads as NaN — it must not kill the scrape)."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Counter:
    """Monotonic counter (thread-safe)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Point-in-time value: ``set()`` a number or ``set_fn()`` a live
    callable (queue depth, breaker state) read at export time."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value: float = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            self._fn = None

    def set_fn(self, fn: Optional[Callable[[], float]]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return fn()
        except Exception:  # noqa: BLE001 - a dead probe must not kill export
            return float("nan")

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class LatencyHistogram:
    """Fixed log-bucket latency histogram with interpolated percentiles."""

    kind = "histogram"

    def __init__(self, name: str = "latency_ms", help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self.counts = [0] * (_N_BUCKETS + 1)  # +1 overflow bucket
        self.total = 0
        self.sum_ms = 0.0
        self.invalid = 0  # non-finite samples, counted but never bucketed

    def record(self, ms: float) -> None:
        ms = float(ms)
        if not math.isfinite(ms):
            with self._lock:
                self.invalid += 1
            return
        if ms < 0.0:
            ms = 0.0
        if ms <= BUCKET_BOUNDS_MS[0]:
            idx = 0
        elif ms > BUCKET_BOUNDS_MS[-1]:
            idx = _N_BUCKETS
        else:
            idx = int(
                math.ceil(math.log(ms / _BUCKET_BASE_MS) / math.log(_BUCKET_FACTOR))
            )
            idx = min(max(idx, 0), _N_BUCKETS)
        with self._lock:
            self.counts[idx] += 1
            self.total += 1
            self.sum_ms += ms

    def percentile(self, q: float) -> float:
        """Interpolated latency at quantile ``q`` in [0, 1]; 0.0 when empty."""
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float:
        if self.total == 0:
            return 0.0
        target = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                hi = (
                    BUCKET_BOUNDS_MS[i]
                    if i < _N_BUCKETS
                    else BUCKET_BOUNDS_MS[-1] * _BUCKET_FACTOR
                )
                lo = BUCKET_BOUNDS_MS[i - 1] if 0 < i <= _N_BUCKETS else 0.0
                frac = (target - seen) / c
                return lo + frac * (hi - lo)
            seen += c
        return BUCKET_BOUNDS_MS[-1]

    def snapshot(self) -> Dict[str, object]:
        """Consistent cut of every field plus the standard percentiles."""
        with self._lock:
            return {
                "counts": list(self.counts),
                "total": self.total,
                "sum_ms": self.sum_ms,
                "invalid": self.invalid,
                "mean_ms": self.sum_ms / max(self.total, 1),
                "p50_ms": self._percentile_locked(0.50),
                "p95_ms": self._percentile_locked(0.95),
                "p99_ms": self._percentile_locked(0.99),
            }

    def reset(self) -> None:
        with self._lock:
            self.counts = [0] * (_N_BUCKETS + 1)
            self.total = 0
            self.sum_ms = 0.0
            self.invalid = 0


class MetricsRegistry:
    """Named metric namespace with get-or-create accessors and Prometheus
    text exposition. One process-wide default instance (``get_registry()``)
    plus per-endpoint instances where isolation matters (each serve
    endpoint owns its own by default)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}, not {cls.__name__}"
                    )
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get_or_create(Gauge, name, help, fn=fn)
        if fn is not None:
            g.set_fn(fn)
        return g

    def histogram(self, name: str, help: str = "") -> LatencyHistogram:
        return self._get_or_create(LatencyHistogram, name, help)

    def snapshot(self) -> Dict[str, object]:
        """Flat name→value dict (histograms as their snapshot sub-dict,
        minus the raw bucket counts)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, object] = {}
        for m in metrics:
            if m.kind == "histogram":
                snap = m.snapshot()
                snap.pop("counts")
                out[m.name] = snap
            else:
                out[m.name] = m.value
        return out

    def reset(self) -> None:
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    def prometheus_text(self) -> str:
        """Prometheus 0.0.4 text exposition, deterministically ordered:
        metrics sorted by name, histogram buckets by ascending ``le``."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if m.kind == "histogram":
                snap = m.snapshot()
                counts = snap["counts"]
                cum = 0
                for bound, c in zip(BUCKET_BOUNDS_MS, counts[:-1]):
                    cum += c
                    lines.append(
                        f'{m.name}_bucket{{le="{_fmt(round(bound, 6))}"}} {cum}'
                    )
                cum += counts[-1]
                lines.append(f'{m.name}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{m.name}_sum {_fmt(snap['sum_ms'])}")
                lines.append(f"{m.name}_count {snap['total']}")
            else:
                lines.append(f"{m.name} {_fmt(m.value)}")
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (training-side counters live
    here; serve endpoints default to their own instances)."""
    return _default_registry
