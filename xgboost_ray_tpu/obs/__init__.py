"""Unified observability plane: metrics registry + span tracer.

Every subsystem used to invent its own telemetry (the ``AllreduceBytes``
number threaded through ``additional_results``, the hand-rolled
``robustness`` dict, ``bench.py``'s private phase timers, the serve layer's
lock-guarded metrics island). This package is the one plane they now share:

* :mod:`xgboost_ray_tpu.obs.metrics` — process-wide ``MetricsRegistry``
  with counters, gauges and the log-bucket ``LatencyHistogram`` (promoted
  out of ``serve/metrics.py``), plus Prometheus text exposition.
* :mod:`xgboost_ray_tpu.obs.trace` — span/event ``Tracer`` with a bounded
  ring buffer (dropped-record accounting, never silent), JSONL export and
  per-rank ``RXGB_TRACE_DIR`` streaming; ``validate_trace_records`` is the
  shared schema checker; ``recovery_time_s`` reconstructs
  failure→recovery timing from the event timeline.

``train()`` scopes a fresh tracer per run and returns its timeline under
``additional_results["obs"]``. Environment knobs: ``RXGB_TRACE`` (0
disables), ``RXGB_TRACE_CAPACITY`` (ring size), ``RXGB_TRACE_DIR``
(per-rank JSONL streaming), ``RXGB_TRACE_PHASES=1`` (fenced per-phase
engine profiling at the end of training).

Stdlib-only imports: safe to touch before jax comes up.
"""

from xgboost_ray_tpu.obs.metrics import (
    BUCKET_BOUNDS_MS,
    Counter,
    Gauge,
    LatencyHistogram,
    MetricsRegistry,
    get_registry,
)
from xgboost_ray_tpu.obs.trace import (
    TRACE_NAMES,
    Tracer,
    get_tracer,
    recovery_time_s,
    set_default_tracer,
    use_tracer,
    validate_trace_records,
)

__all__ = [
    "BUCKET_BOUNDS_MS",
    "Counter",
    "Gauge",
    "LatencyHistogram",
    "MetricsRegistry",
    "TRACE_NAMES",
    "Tracer",
    "get_registry",
    "get_tracer",
    "recovery_time_s",
    "set_default_tracer",
    "use_tracer",
    "validate_trace_records",
]


def phase_profiling_enabled() -> bool:
    """Whether end-of-training fenced phase profiling is requested
    (``RXGB_TRACE_PHASES=1``)."""
    import os

    return os.environ.get("RXGB_TRACE_PHASES", "0") == "1"
