"""DataSource interface: polymorphic ingestion for RayDMatrix.

Mirrors the reference's static-method DataSource ABC
(``xgboost_ray/data_sources/data_source.py:22-155``) so every ingestion path
(numpy, pandas, csv, parquet, object refs, partitioned frames) plugs into the
same loader machinery. TPU-specific difference: shard payloads end up as
host numpy dicts that the engine device_puts onto the mesh as quantile-binned
blocks, instead of Ray object-store references.
"""

import enum
from typing import Any, List, Optional, Sequence, Union

import numpy as np
import pandas as pd


class RayFileType(enum.Enum):
    """File formats supported by distributed/central file loading.

    Mirrors ``xgboost_ray/data_sources/data_source.py:13-19``.
    """

    CSV = 1
    PARQUET = 2
    PETASTORM = 3


class DataSource:
    """Interface for a supported data input type.

    All methods are static; sources are registered (ordered) in
    ``data_sources/__init__.py`` and probed with ``is_data_type``.
    """

    supports_central_loading: bool = True
    supports_distributed_loading: bool = False
    needs_partitions: bool = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        return False

    @staticmethod
    def get_filetype(data: Any) -> Optional[RayFileType]:
        return None

    @staticmethod
    def load_data(
        data: Any,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Union[Sequence[int], Sequence[Any]]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        raise NotImplementedError

    @staticmethod
    def update_feature_names(
        x: pd.DataFrame, feature_names: Optional[List[str]]
    ) -> pd.DataFrame:
        if feature_names:
            x.columns = feature_names
        return x

    @staticmethod
    def convert_to_series(data: Any) -> pd.Series:
        if isinstance(data, pd.DataFrame):
            return pd.Series(data.squeeze())
        if isinstance(data, pd.Series):
            return data
        return pd.Series(np.asarray(data).ravel())

    @classmethod
    def get_column(
        cls, data: pd.DataFrame, column: Any
    ) -> tuple:
        """Resolve a label/weight/etc. reference to a series.

        Returns (series, column_name_to_exclude_or_None); a string selects a
        column of ``data`` (and excludes it from the features), anything else
        is converted to a standalone series.
        """
        if isinstance(column, str):
            return data[column], column
        if column is not None:
            return cls.convert_to_series(column), None
        return None, None

    @staticmethod
    def get_n(data: Any) -> int:
        return len(data)

    @staticmethod
    def get_actor_shards(data: Any, actors: Sequence[Any]) -> tuple:
        """Distributed sources: (possibly transformed data, {rank: partitions})."""
        return data, None
