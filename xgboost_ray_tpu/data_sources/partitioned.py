"""``__partitioned__`` protocol data source.

Mirrors ``xgboost_ray/data_sources/partitioned.py`` (Intel DPPY distributed
dataframe protocol): an object exposing ``__partitioned__`` with a
``partitions`` dict ({pos: {"start": ..., "shape": ..., "data": obj_or_ref}})
and a ``get`` callable resolving references.
"""

from typing import Any, Optional, Sequence

import numpy as np
import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType
from xgboost_ray_tpu.data_sources.object_store import _materialize


class Partitioned(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        return hasattr(data, "__partitioned__")

    @staticmethod
    def load_data(
        data: Any,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[Any]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        meta = data.__partitioned__
        getter = meta.get("get", lambda x: x)
        parts = meta["partitions"]
        # order partitions by their start offset for deterministic row order
        items = sorted(parts.items(), key=lambda kv: tuple(np.ravel(kv[1].get("start", kv[0]))))
        keys = [k for k, _ in items]
        if indices is not None:
            keys = [keys[i] for i in indices]
        frames = [_materialize(getter(parts[k]["data"])) for k in keys]
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        if ignore:
            keep = [c for c in df.columns if c not in set(ignore)]
            df = df[keep]
        return df

    @staticmethod
    def get_n(data: Any) -> int:
        return len(data.__partitioned__["partitions"])
