"""Modin DataFrame data source (mirrors ``xgboost_ray/data_sources/modin.py``).

Gated on modin being importable; partitions are unwrapped and assigned with
host locality, same flow as the reference (``modin.py:114-135``) minus the
Ray-object-ref indirection.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType
from xgboost_ray_tpu.data_sources._distributed import (
    assign_partitions_to_actors,
    get_actor_rank_hosts,
)


def _modin_installed() -> bool:
    try:
        import modin  # noqa: F401

        return True
    except ImportError:
        return False


class Modin(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if not _modin_installed():
            return False
        from modin.pandas import DataFrame as ModinDataFrame
        from modin.pandas import Series as ModinSeries

        return isinstance(data, (ModinDataFrame, ModinSeries))

    @staticmethod
    def load_data(
        data: Any,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[Any]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        local_df = data
        if indices is not None:
            # indices are partition objects assigned via get_actor_shards
            frames = [p if isinstance(p, pd.DataFrame) else p._to_pandas()
                      for p in indices]
            df = pd.concat(frames, ignore_index=True)
        else:
            df = local_df._to_pandas() if hasattr(local_df, "_to_pandas") else (
                local_df.to_pandas() if hasattr(local_df, "to_pandas") else local_df
            )
        if isinstance(df, pd.Series):
            df = pd.DataFrame(df)
        if ignore:
            df = df[[c for c in df.columns if c not in set(ignore)]]
        return df

    @staticmethod
    def get_actor_shards(data: Any, actors: Sequence[Any]) -> Tuple[Any, Dict[int, List[Any]]]:
        """Unwrap partitions and assign them to ranks with locality."""
        from modin.distributed.dataframe.pandas import unwrap_partitions

        parts = unwrap_partitions(data, axis=0)
        hosts = get_actor_rank_hosts(len(actors))
        assignment = assign_partitions_to_actors({"localhost": list(parts)}, hosts)
        return data, assignment

    @staticmethod
    def get_n(data: Any) -> int:
        return len(data)
