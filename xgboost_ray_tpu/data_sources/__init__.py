"""Ordered data-source registry (mirrors ``xgboost_ray/data_sources/__init__.py``).

Probe order matters: specific path-based sources before generic containers.
"""

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType
from xgboost_ray_tpu.data_sources.numpy import Numpy
from xgboost_ray_tpu.data_sources.pandas import Pandas
from xgboost_ray_tpu.data_sources.csv import CSV
from xgboost_ray_tpu.data_sources.parquet import Parquet
from xgboost_ray_tpu.data_sources.object_store import ObjectStore
from xgboost_ray_tpu.data_sources.partitioned import Partitioned
from xgboost_ray_tpu.data_sources.modin import Modin
from xgboost_ray_tpu.data_sources.dask import Dask
from xgboost_ray_tpu.data_sources.ray_dataset import RayDataset
from xgboost_ray_tpu.data_sources.petastorm import Petastorm

data_sources = [
    Numpy,
    Pandas,
    Modin,
    Dask,
    RayDataset,
    Partitioned,
    Petastorm,
    CSV,
    Parquet,
    ObjectStore,
]

__all__ = [
    "DataSource",
    "RayFileType",
    "Numpy",
    "Pandas",
    "CSV",
    "Parquet",
    "ObjectStore",
    "Partitioned",
    "Modin",
    "Dask",
    "RayDataset",
    "Petastorm",
    "data_sources",
]
