"""Ordered data-source registry (mirrors ``xgboost_ray/data_sources/__init__.py``).

Probe order matters: specific path-based sources before generic containers.
"""

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType
from xgboost_ray_tpu.data_sources.numpy import Numpy
from xgboost_ray_tpu.data_sources.pandas import Pandas
from xgboost_ray_tpu.data_sources.csv import CSV
from xgboost_ray_tpu.data_sources.parquet import Parquet
from xgboost_ray_tpu.data_sources.object_store import ObjectStore
from xgboost_ray_tpu.data_sources.partitioned import Partitioned

data_sources = [
    Numpy,
    Pandas,
    Partitioned,
    CSV,
    Parquet,
    ObjectStore,
]

__all__ = [
    "DataSource",
    "RayFileType",
    "Numpy",
    "Pandas",
    "CSV",
    "Parquet",
    "ObjectStore",
    "Partitioned",
    "data_sources",
]
