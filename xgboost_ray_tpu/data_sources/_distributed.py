"""Locality-aware partition-to-actor assignment.

Re-implements the semantics of ``xgboost_ray/data_sources/_distributed.py``:
a greedy assigner that first hands each actor partitions co-located on its
host (round-robin, bounded by even min/max shares), then spills the remainder
round-robin. On a TPU pod, "host" is the process/worker owning a mesh slot
(``jax.process_index``-keyed); on one host all partitions are local and the
algorithm degenerates to an even round-robin — same even/uneven guarantees as
the reference tests (``tests/test_data_source.py:38-166``) expect.
"""

import math
from collections import defaultdict
from typing import Any, Dict, List, Sequence


def get_actor_rank_hosts(num_actors: int) -> Dict[int, str]:
    """Host key per actor rank. Single-process: all "localhost"."""
    try:
        import jax

        # map mesh slots round-robin onto jax processes
        n_proc = jax.process_count()
        return {rank: f"process-{rank % n_proc}" for rank in range(num_actors)}
    except Exception:  # pragma: no cover
        return {rank: "localhost" for rank in range(num_actors)}


def assign_partitions_to_actors(
    host_to_parts: Dict[str, Sequence[Any]],
    actor_rank_hosts: Dict[int, str],
) -> Dict[int, List[Any]]:
    """Greedy co-located assignment with even min/max per-actor bounds."""
    num_parts = sum(len(p) for p in host_to_parts.values())
    num_actors = len(actor_rank_hosts)
    min_parts = num_parts // num_actors
    max_parts = math.ceil(num_parts / num_actors)

    host_to_parts = {h: list(p) for h, p in host_to_parts.items()}
    assignment: Dict[int, List[Any]] = defaultdict(list)
    ranks = sorted(actor_rank_hosts)

    def deficit() -> int:
        """Partitions still owed to actors below their min share."""
        return sum(max(0, min_parts - len(assignment[r])) for r in ranks)

    # 1) co-located pass up to the min share, round-robin
    progress = True
    while progress:
        progress = False
        for rank in ranks:
            if len(assignment[rank]) >= min_parts:
                continue
            local = host_to_parts.get(actor_rank_hosts[rank])
            if local:
                assignment[rank].append(local.pop(0))
                progress = True

    # 2) co-located pass beyond min up to max — but an actor may only take an
    #    extra local partition if enough partitions remain for every actor
    #    still below min (the reference's expected maps encode exactly this
    #    reservation, tests/test_data_source.py:128-166)
    progress = True
    while progress:
        progress = False
        remaining = sum(len(p) for p in host_to_parts.values())
        for rank in ranks:
            if len(assignment[rank]) >= max_parts:
                continue
            local = host_to_parts.get(actor_rank_hosts[rank])
            if not local:
                continue
            if remaining - 1 < deficit():
                continue  # reserved for a starving (non-co-located) actor
            assignment[rank].append(local.pop(0))
            remaining -= 1
            progress = True

    # 3) spill the remainder: fill everyone to min first, then to max
    rest = [p for parts in host_to_parts.values() for p in parts]
    while rest:
        under_min = [r for r in ranks if len(assignment[r]) < min_parts]
        targets = under_min or [r for r in ranks if len(assignment[r]) < max_parts]
        if not targets:  # all at max; shouldn't happen, but don't loop forever
            assignment[ranks[0]].append(rest.pop(0))
            continue
        for rank in targets:
            if rest:
                assignment[rank].append(rest.pop(0))

    return dict(assignment)
