"""Locality-aware partition-to-actor assignment.

Re-implements the semantics of ``xgboost_ray/data_sources/_distributed.py``:
a greedy assigner that first hands each actor partitions co-located on its
host (round-robin, bounded by even min/max shares), then spills the remainder
round-robin. On a TPU pod, "host" is the process/worker owning a mesh slot
(``jax.process_index``-keyed); on one host all partitions are local and the
algorithm degenerates to an even round-robin — same even/uneven guarantees as
the reference tests (``tests/test_data_source.py:38-166``) expect.
"""

import math
from collections import defaultdict
from typing import Any, Dict, List, Sequence


def get_actor_rank_hosts(num_actors: int) -> Dict[int, str]:
    """Host key per actor rank. Single-process: all "localhost"."""
    try:
        import jax

        # map mesh slots round-robin onto jax processes
        n_proc = jax.process_count()
        return {rank: f"process-{rank % n_proc}" for rank in range(num_actors)}
    except Exception:  # pragma: no cover
        return {rank: "localhost" for rank in range(num_actors)}


def assign_partitions_to_actors(
    host_to_parts: Dict[str, Sequence[Any]],
    actor_rank_hosts: Dict[int, str],
) -> Dict[int, List[Any]]:
    """Greedy co-located assignment with even min/max per-actor bounds."""
    num_parts = sum(len(p) for p in host_to_parts.values())
    num_actors = len(actor_rank_hosts)
    min_parts = num_parts // num_actors
    max_parts = math.ceil(num_parts / num_actors)

    host_to_parts = {h: list(p) for h, p in host_to_parts.items()}
    assignment: Dict[int, List[Any]] = defaultdict(list)

    # 1) co-located pass: actors take local partitions round-robin up to max
    progress = True
    while progress:
        progress = False
        for rank, host in actor_rank_hosts.items():
            if len(assignment[rank]) >= max_parts:
                continue
            local = host_to_parts.get(host)
            if local:
                assignment[rank].append(local.pop(0))
                progress = True

    # 2) spill: remaining partitions round-robin to actors below min/max
    rest = [p for parts in host_to_parts.values() for p in parts]
    ranks = sorted(actor_rank_hosts)
    while rest:
        placed = False
        for bound in (min_parts, max_parts):
            for rank in ranks:
                if not rest:
                    break
                if len(assignment[rank]) < bound:
                    assignment[rank].append(rest.pop(0))
                    placed = True
            if not rest:
                break
        if not placed:  # all at max; shouldn't happen, but don't loop forever
            assignment[ranks[0]].append(rest.pop(0))

    return dict(assignment)
