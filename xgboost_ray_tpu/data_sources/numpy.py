"""Numpy ndarray data source (mirrors ``xgboost_ray/data_sources/numpy.py``)."""

from typing import Any, Optional, Sequence

import numpy as np
import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType
from xgboost_ray_tpu.data_sources.pandas import Pandas


class Numpy(DataSource):
    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        return isinstance(data, np.ndarray)

    @staticmethod
    def load_data(
        data: np.ndarray,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        arr = data if data.ndim == 2 else data.reshape(data.shape[0], -1)
        # column naming parity: f0, f1, ... (reference numpy.py:26-33)
        frame = pd.DataFrame(arr, columns=[f"f{i}" for i in range(arr.shape[1])])
        return Pandas.load_data(frame, ignore=ignore, indices=indices)
