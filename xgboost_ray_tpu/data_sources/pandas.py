"""Pandas DataFrame/Series data source (mirrors ``xgboost_ray/data_sources/pandas.py``)."""

from typing import Any, Optional, Sequence

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


class Pandas(DataSource):
    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        return isinstance(data, (pd.DataFrame, pd.Series))

    @staticmethod
    def load_data(
        data: Any,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        if isinstance(data, pd.Series):
            data = pd.DataFrame(data)
        if indices is not None:
            data = data.iloc[list(indices)]
        if ignore:
            keep = [c for c in data.columns if c not in set(ignore)]
            data = data[keep]
        return data
