"""CSV file data source (mirrors ``xgboost_ray/data_sources/csv.py``).

Single path or list of paths; with a list, distributed loading shards on the
*file* level (indices select files, reference csv.py:26-43).

Numeric CSVs take the native multithreaded C++ parser
(``xgboost_ray_tpu/native/fast_csv.cpp``) when it is built and no
pandas-specific kwargs are requested; anything else falls back to
``pandas.read_csv``.
"""

from typing import Any, List, Optional, Sequence, Union

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


def _read_one(path: str, **kwargs) -> pd.DataFrame:
    if not kwargs:
        try:
            from xgboost_ray_tpu import native

            result = native.read_csv_numpy(path)
        except Exception:  # noqa: BLE001 - native path is best-effort
            result = None
        if result is not None:
            matrix, names = result
            return pd.DataFrame(matrix, columns=names, copy=False)
    return pd.read_csv(path, **kwargs)


def _is_csv_path(p: Any) -> bool:
    return isinstance(p, str) and (p.endswith(".csv") or p.endswith(".csv.gz"))


class CSV(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if filetype == RayFileType.CSV:
            return True
        if isinstance(data, str):
            return _is_csv_path(data)
        if isinstance(data, Sequence) and not isinstance(data, str):
            return len(data) > 0 and all(_is_csv_path(p) for p in data)
        return False

    @staticmethod
    def get_filetype(data: Any) -> Optional[RayFileType]:
        probe = data[0] if isinstance(data, (list, tuple)) and data else data
        return RayFileType.CSV if _is_csv_path(probe) else None

    @staticmethod
    def load_data(
        data: Union[str, Sequence[str]],
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        if isinstance(data, (list, tuple)):
            files = list(data)
            if indices is not None:
                files = [files[i] for i in indices]
            frames = [_read_one(f, **kwargs) for f in files]
            df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        else:
            df = _read_one(data, **kwargs)
            if indices is not None:
                df = df.iloc[list(indices)]
        if ignore:
            keep = [c for c in df.columns if c not in set(ignore)]
            df = df[keep]
        return df
