"""CSV file data source (mirrors ``xgboost_ray/data_sources/csv.py``).

Single path or list of paths; with a list, distributed loading shards on the
*file* level (indices select files, reference csv.py:26-43).
"""

from typing import Any, List, Optional, Sequence, Union

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


def _is_csv_path(p: Any) -> bool:
    return isinstance(p, str) and (p.endswith(".csv") or p.endswith(".csv.gz"))


class CSV(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if filetype == RayFileType.CSV:
            return True
        if isinstance(data, str):
            return _is_csv_path(data)
        if isinstance(data, Sequence) and not isinstance(data, str):
            return len(data) > 0 and all(_is_csv_path(p) for p in data)
        return False

    @staticmethod
    def get_filetype(data: Any) -> Optional[RayFileType]:
        probe = data[0] if isinstance(data, (list, tuple)) and data else data
        return RayFileType.CSV if _is_csv_path(probe) else None

    @staticmethod
    def load_data(
        data: Union[str, Sequence[str]],
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        if isinstance(data, (list, tuple)):
            files = list(data)
            if indices is not None:
                files = [files[i] for i in indices]
            frames = [pd.read_csv(f, **kwargs) for f in files]
            df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        else:
            df = pd.read_csv(data, **kwargs)
            if indices is not None:
                df = df.iloc[list(indices)]
        if ignore:
            keep = [c for c in df.columns if c not in set(ignore)]
            df = df[keep]
        return df
