"""Pre-partitioned in-memory data source.

The reference's ObjectStore source consumes ``List[ray.ObjectRef]``
(``xgboost_ray/data_sources/object_store.py:15-32``). Standalone TPU analog:
a list of already-materialized partitions (pandas DataFrames, numpy arrays,
or zero-arg callables producing either). Distributed loading shards on the
partition level, like the reference does on refs.
"""

from typing import Any, Callable, Optional, Sequence

import numpy as np
import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


def _is_partition(p: Any) -> bool:
    return isinstance(p, (pd.DataFrame, pd.Series, np.ndarray)) or callable(p)


def _materialize(p: Any) -> pd.DataFrame:
    if callable(p):
        p = p()
    if isinstance(p, np.ndarray):
        arr = p if p.ndim == 2 else p.reshape(p.shape[0], -1)
        return pd.DataFrame(arr, columns=[f"f{i}" for i in range(arr.shape[1])])
    if isinstance(p, pd.Series):
        return pd.DataFrame(p)
    return p


class ObjectStore(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        return (
            isinstance(data, (list, tuple))
            and len(data) > 0
            and all(_is_partition(p) for p in data)
            and not isinstance(data[0], str)
        )

    @staticmethod
    def load_data(
        data: Sequence[Any],
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        parts = list(data)
        if indices is not None:
            parts = [parts[i] for i in indices]
        frames = [_materialize(p) for p in parts]
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        if ignore:
            keep = [c for c in df.columns if c not in set(ignore)]
            df = df[keep]
        return df

    @staticmethod
    def get_n(data: Any) -> int:
        return len(data)
