"""Parquet file data source (mirrors ``xgboost_ray/data_sources/parquet.py``)."""

from typing import Any, Optional, Sequence, Union

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


def _is_parquet_path(p: Any) -> bool:
    return isinstance(p, str) and p.endswith(".parquet")


class Parquet(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if filetype == RayFileType.PARQUET:
            return True
        if isinstance(data, str):
            return _is_parquet_path(data)
        if isinstance(data, Sequence) and not isinstance(data, str):
            return len(data) > 0 and all(_is_parquet_path(p) for p in data)
        return False

    @staticmethod
    def get_filetype(data: Any) -> Optional[RayFileType]:
        probe = data[0] if isinstance(data, (list, tuple)) and data else data
        return RayFileType.PARQUET if _is_parquet_path(probe) else None

    @staticmethod
    def load_data(
        data: Union[str, Sequence[str]],
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        if isinstance(data, (list, tuple)):
            files = list(data)
            if indices is not None:
                files = [files[i] for i in indices]
            frames = [pd.read_parquet(f, **kwargs) for f in files]
            df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        else:
            df = pd.read_parquet(data, **kwargs)
            if indices is not None:
                df = df.iloc[list(indices)]
        if ignore:
            keep = [c for c in df.columns if c not in set(ignore)]
            df = df[keep]
        return df
