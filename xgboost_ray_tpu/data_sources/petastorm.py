"""Petastorm data source (mirrors ``xgboost_ray/data_sources/petastorm.py``).

Gated on petastorm being importable; reads s3/gs/hdfs/file parquet URLs via
``make_batch_reader`` (``petastorm.py:45-85``).
"""

from typing import Any, Optional, Sequence, Union

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


def _petastorm_installed() -> bool:
    try:
        import petastorm  # noqa: F401

        return True
    except ImportError:
        return False


_SCHEMES = ("s3://", "gs://", "hdfs://", "file://")


class Petastorm(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if not _petastorm_installed():
            return False
        if filetype == RayFileType.PETASTORM:
            return True
        if isinstance(data, str):
            return data.startswith(_SCHEMES) and data.endswith(".parquet")
        if isinstance(data, Sequence) and not isinstance(data, str):
            return len(data) > 0 and all(
                isinstance(p, str) and p.startswith(_SCHEMES) and p.endswith(".parquet")
                for p in data
            )
        return False

    @staticmethod
    def get_filetype(data: Any) -> Optional[RayFileType]:
        probe = data[0] if isinstance(data, (list, tuple)) and data else data
        if isinstance(probe, str) and probe.startswith(_SCHEMES) and probe.endswith(".parquet"):
            return RayFileType.PETASTORM
        return None

    @staticmethod
    def load_data(
        data: Union[str, Sequence[str]],
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[int]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        from petastorm import make_batch_reader

        urls = [data] if isinstance(data, str) else list(data)
        if indices is not None:
            urls = [urls[i] for i in indices]
        frames = []
        with make_batch_reader(urls if len(urls) > 1 else urls[0]) as reader:
            for batch in reader:
                frames.append(pd.DataFrame(batch._asdict()))
        df = pd.concat(frames, ignore_index=True)
        if ignore:
            df = df[[c for c in df.columns if c not in set(ignore)]]
        return df
