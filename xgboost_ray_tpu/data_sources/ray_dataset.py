"""Ray Dataset data source (mirrors ``xgboost_ray/data_sources/ray_dataset.py``).

Gated on ray.data being importable; splits the dataset into one sub-dataset
per rank (``ray_dataset.py:87-103``).
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType


def _ray_data_installed() -> bool:
    try:
        import ray.data  # noqa: F401

        return True
    except ImportError:
        return False


class RayDataset(DataSource):
    supports_distributed_loading = True
    needs_partitions = False

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if not _ray_data_installed():
            return False
        import ray.data

        return isinstance(data, ray.data.Dataset)

    @staticmethod
    def load_data(
        data: Any,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[Any]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        if indices is not None:
            frames = [shard.to_pandas() for shard in indices]
            df = pd.concat(frames, ignore_index=True)
        else:
            df = data.to_pandas()
        if ignore:
            df = df[[c for c in df.columns if c not in set(ignore)]]
        return df

    @staticmethod
    def get_actor_shards(data: Any, actors: Sequence[Any]) -> Tuple[Any, Dict[int, List[Any]]]:
        splits = data.split(len(actors), equal=True)
        return data, {rank: [splits[rank]] for rank in range(len(actors))}

    @staticmethod
    def get_n(data: Any) -> int:
        return int(data.num_blocks())
