"""Dask DataFrame data source (mirrors ``xgboost_ray/data_sources/dask.py``).

Gated on dask being importable. Partitions (delayed frames) are computed
per-rank; locality discovery, which the reference does through a
map_partitions node-IP probe (``dask.py:137-161``), degenerates to even
round-robin in the single-host TPU runtime.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import pandas as pd

from xgboost_ray_tpu.data_sources.data_source import DataSource, RayFileType
from xgboost_ray_tpu.data_sources._distributed import (
    assign_partitions_to_actors,
    get_actor_rank_hosts,
)


def _dask_installed() -> bool:
    try:
        import dask  # noqa: F401

        return True
    except ImportError:
        return False


class Dask(DataSource):
    supports_distributed_loading = True

    @staticmethod
    def is_data_type(data: Any, filetype: Optional[RayFileType] = None) -> bool:
        if not _dask_installed():
            return False
        import dask.dataframe as dd

        return isinstance(data, (dd.DataFrame, dd.Series))

    @staticmethod
    def load_data(
        data: Any,
        ignore: Optional[Sequence[str]] = None,
        indices: Optional[Sequence[Any]] = None,
        **kwargs,
    ) -> pd.DataFrame:
        if indices is not None:
            import dask

            frames = list(dask.compute(*indices))
            df = pd.concat(frames, ignore_index=True)
        else:
            df = data.compute()
        if isinstance(df, pd.Series):
            df = pd.DataFrame(df)
        if ignore:
            df = df[[c for c in df.columns if c not in set(ignore)]]
        return df

    @staticmethod
    def get_actor_shards(data: Any, actors: Sequence[Any]) -> Tuple[Any, Dict[int, List[Any]]]:
        parts = data.to_delayed()
        hosts = get_actor_rank_hosts(len(actors))
        assignment = assign_partitions_to_actors({"localhost": list(parts)}, hosts)
        return data, assignment

    @staticmethod
    def get_n(data: Any) -> int:
        return int(data.npartitions)
