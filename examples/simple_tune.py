"""Hyperparameter sweep over the mesh trainer (parity with
``examples/simple_tune.py``, using the standalone Tuner instead of Ray Tune)."""

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu.tuner import Tuner, grid_search, loguniform


def train_model(config):
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    train_set = RayDMatrix(data.astype(np.float32), labels.astype(np.float32))
    params = {
        "objective": "binary:logistic",
        "eval_metric": ["logloss", "error"],
        "eta": config["eta"],
        "subsample": config["subsample"],
        "max_depth": config["max_depth"],
    }
    train(
        params,
        train_set,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(num_actors=2),
    )


def main():
    search_space = {
        "eta": loguniform(1e-4, 1e-1),
        "subsample": 0.8,
        "max_depth": grid_search([3, 4, 5]),
    }
    from xgboost_ray_tpu.tuner import ASHAScheduler

    tuner = Tuner(
        train_model,
        search_space,
        metric="train-error",
        mode="min",
        num_samples=2,
        # terminate unpromising trials at successive-halving rungs (the Ray
        # Tune ASHAScheduler role, standalone)
        scheduler=ASHAScheduler(metric="train-error", mode="min",
                                grace_rounds=4),
    )
    result = tuner.fit()
    best = result.get_best_trial()
    print("Best hyperparameters", best.config)
    print("Best error", best.last_result["train-error"])


if __name__ == "__main__":
    main()
