"""Train from pre-materialized partitions (parity with
``examples/simple_objectstore.py`` — Ray object refs become in-memory
partition lists in the TPU runtime)."""

import numpy as np
import pandas as pd
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, RayShardingMode, train


def main():
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    df = pd.DataFrame(data, columns=[f"f{i}" for i in range(data.shape[1])])
    df["label"] = labels

    # split into 4 partitions, the analog of ray.put() per chunk
    partitions = [df.iloc[i::4].reset_index(drop=True) for i in range(4)]

    train_set = RayDMatrix(partitions, "label", sharding=RayShardingMode.BATCH)

    evals_result = {}
    train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"]},
        train_set,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(num_actors=2),
    )
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))


if __name__ == "__main__":
    main()
