"""HIGGS from partitioned parquet with distributed loading (parity with
``examples/higgs_parquet.py``)."""

import argparse
import glob
import os
import time

import numpy as np
import pandas as pd

from xgboost_ray_tpu import RayDMatrix, RayFileType, RayParams, train

try:
    from examples.higgs import make_synthetic
except ImportError:  # running as a plain script from examples/
    from higgs import make_synthetic


def ensure_parquet_dir(path: str, n_files: int = 8):
    if os.path.isdir(path) and glob.glob(os.path.join(path, "*.parquet")):
        return
    os.makedirs(path, exist_ok=True)
    x, y = make_synthetic()
    df = pd.DataFrame(x, columns=[f"feature-{i:02d}" for i in range(x.shape[1])])
    df["label"] = y
    rows_per = len(df) // n_files
    for i in range(n_files):
        df.iloc[i * rows_per : (i + 1) * rows_per].to_parquet(
            os.path.join(path, f"higgs-{i:03d}.parquet")
        )


def main(path, num_actors):
    ensure_parquet_dir(path)
    dtrain = RayDMatrix(path, label="label", filetype=RayFileType.PARQUET)

    config = {"tree_method": "hist", "eval_metric": ["logloss", "error"]}
    evals_result = {}
    start = time.time()
    train(
        config,
        dtrain,
        evals_result=evals_result,
        ray_params=RayParams(max_actor_restarts=1, num_actors=num_actors),
        num_boost_round=100,
        evals=[(dtrain, "train")],
        verbose_eval=False,
    )
    print(f"TRAIN TIME TAKEN: {time.time() - start:.2f} seconds")
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("path", nargs="?", default="higgs_parquet")
    parser.add_argument("--num-actors", type=int, default=8)
    args = parser.parse_args()
    main(args.path, args.num_actors)
