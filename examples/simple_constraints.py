"""Monotone + interaction constraints (xgboost param parity).

The reference forwards ``monotone_constraints``/``interaction_constraints``
to xgboost's hist updater untouched (``xgboost_ray/main.py:745-752``);
here both are enforced inside the compiled split scan. This example shows
a +1-constrained feature staying monotone on data with a deliberate local
reversal, and interaction groups confining every tree path.
"""

import argparse

import numpy as np

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def main(num_actors):
    rng = np.random.RandomState(0)
    n = 1500
    x = rng.uniform(-2, 2, size=(n, 4)).astype(np.float32)
    dip = -1.5 * np.exp(-4.0 * (x[:, 0] - 0.5) ** 2)  # local reversal in x0
    y = (0.8 * x[:, 0] + dip + 0.5 * x[:, 1] * x[:, 2]
         + 0.05 * rng.randn(n)).astype(np.float32)

    bst = train(
        {
            "objective": "reg:squarederror",
            "max_depth": 4,
            "eta": 0.3,
            "monotone_constraints": "(1,0,0,0)",  # f(x0) must not decrease
            "interaction_constraints": [[0], [1, 2], [3]],
        },
        RayDMatrix(x, y),
        num_boost_round=20,
        ray_params=RayParams(num_actors=num_actors),
    )

    grid = np.zeros((50, 4), np.float32)
    grid[:, 0] = np.linspace(-2, 2, 50)
    margins = bst.predict(grid, output_margin=True)
    print("monotone in x0:", bool((np.diff(margins) >= -1e-5).all()))

    feat = np.asarray(bst.forest.feature)
    leaf = np.asarray(bst.forest.is_leaf)
    groups = [frozenset(g) for g in ([0], [1, 2], [3])]
    ok = True
    for t in range(feat.shape[0]):
        stack = [(0, frozenset())]
        while stack:
            h, used = stack.pop()
            if leaf[t, h] or feat[t, h] < 0 or 2 * h + 2 >= feat.shape[1]:
                if used and not any(used <= g for g in groups):
                    ok = False
                continue
            u2 = used | {int(feat[t, h])}
            stack.append((2 * h + 1, u2))
            stack.append((2 * h + 2, u2))
    print("interaction groups respected:", ok)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.num_actors)
