"""Categorical features with one-vs-rest splits (``enable_categorical``).

No reference analog (upstream demos live in xgboost itself); shows the
pandas-category auto-encoding path and the explicit feature_types path.
"""

import numpy as np
import pandas as pd

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def main():
    rng = np.random.RandomState(0)
    color = rng.choice(["red", "green", "blue", "teal"], size=2000)
    size = rng.randn(2000).astype(np.float32)
    # non-ordinal target: membership of {green, teal}
    y = np.isin(color, ["green", "teal"]).astype(np.float32)

    df = pd.DataFrame({"color": pd.Categorical(color), "size": size})
    train_set = RayDMatrix(df, y, enable_categorical=True)

    evals_result = {}
    bst = train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"],
         "max_depth": 3},
        train_set,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(num_actors=2),
    )
    print(f"Training error: {evals_result['train']['error'][-1]:.4f}")
    print(f"Feature split counts: {bst.get_fscore()}")

    # equivalent explicit-codes path
    codes = pd.Categorical(color).codes.astype(np.float32)
    x = np.stack([codes, size], axis=1)
    bst2 = train(
        {"objective": "binary:logistic", "max_depth": 3},
        RayDMatrix(x, y, feature_types=["c", "q"]),
        num_boost_round=10,
        ray_params=RayParams(num_actors=2),
    )
    pred = bst2.predict(x)
    print(f"Explicit-codes accuracy: {((pred > 0.5) == y).mean():.4f}")


if __name__ == "__main__":
    main()
