"""Serving scale-out: replica pool, chaos kill, and a canary refresh.

Builds on ``simple_serve.py``: the endpoint now fronts a *pool* of
predictor replicas behind a least-loaded router (all replicas share one
compiled-program cache, so N replicas still cost one XLA compile per
program), uses the FIL-style breadth-first node-array layout for lower
tail latency, survives a replica being killed mid-traffic without
failing a single request, and swaps in a warm-started refresh through a
shadow + canary gate that auto-rolls-back on metric regression.
"""

import json
import urllib.request

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu import serve


def _post(url, path, doc):
    req = urllib.request.Request(
        url + path, json.dumps(doc).encode("utf-8"),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return json.loads(r.read())


def main():
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    x = data.astype(np.float32)
    y = labels.astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3}

    bst = train(params, RayDMatrix(x, y), num_boost_round=8,
                ray_params=RayParams(num_actors=2))

    # 2 replicas behind a least-loaded router, node-array predictor layout
    handle = serve.create_server(bst, n_replicas=2, layout="node_array",
                                 max_batch=128, max_delay_ms=2.0)
    router = handle.batcher
    print(f"serving at {handle.url} with {router.live_replicas()} replicas")

    for _ in range(4):
        r = _post(handle.url, "/predict", {"data": x[:8].tolist()})
        assert np.allclose(r["predictions"], bst.predict(x[:8]))
    print(f"v{r['model_version']} predictions: "
          f"{np.round(r['predictions'], 4).tolist()}")

    # chaos: kill replica 0 mid-service — capacity sheds, availability
    # doesn't; every request keeps succeeding on the survivor
    router.kill(0)
    r = _post(handle.url, "/predict", {"data": x[:8].tolist()})
    assert np.allclose(r["predictions"], bst.predict(x[:8]))
    print(f"killed replica 0 -> {router.live_replicas()} live, "
          f"requests still served")
    slot = router.rejoin()
    print(f"replica rejoined at slot {slot} -> {router.live_replicas()} live")

    # continual refresh: warm-start 4 more rounds from the live booster,
    # then publish through the shadow + canary gate
    refreshed = serve.refresh(bst, params, RayDMatrix(x, y),
                              num_boost_round=4,
                              ray_params=RayParams(num_actors=2))
    canary = serve.CanaryController(handle.registry, metrics=handle.metrics)
    verdict = canary.publish(refreshed, x[:128], y[:128], shadow_x=x[:16])
    print(f"canary verdict: promoted={verdict['promoted']} "
          f"reason={verdict['reason']} now serving v{verdict['version']}")
    assert verdict["promoted"]

    r = _post(handle.url, "/predict", {"data": x[:8].tolist()})
    assert r["model_version"] == verdict["version"]
    assert np.allclose(r["predictions"], refreshed.predict(x[:8]))

    # a bad candidate (labels shuffled) is rolled back automatically
    rng = np.random.default_rng(0)
    bad = train(params, RayDMatrix(x, rng.permutation(y)),
                num_boost_round=8, ray_params=RayParams(num_actors=2))
    verdict = canary.publish(bad, x[:128], y[:128])
    print(f"bad candidate: promoted={verdict['promoted']} "
          f"reason={verdict['reason']} still serving v{verdict['version']}")
    assert not verdict["promoted"]

    with urllib.request.urlopen(handle.url + "/metrics", timeout=10.0) as resp:
        m = json.loads(resp.read())
    print(f"metrics: qps={m['qps']} p99={m['latency_p99_ms']}ms "
          f"replicas={m['replicas']} promotions={m['canary_promotions']} "
          f"rollbacks={m['canary_rollbacks']}")

    handle.shutdown()
    print("done")


if __name__ == "__main__":
    main()
