"""The README quick-start (parity with ``examples/readme.py``):
breast_cancer binary classification on 2 mesh workers."""

import numpy as np
from sklearn.datasets import load_breast_cancer

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def main():
    data = load_breast_cancer()
    train_x = data.data.astype(np.float32)
    train_y = data.target.astype(np.float32)

    train_set = RayDMatrix(train_x, train_y)

    evals_result = {}
    bst = train(
        {
            "objective": "binary:logistic",
            "eval_metric": ["logloss", "error"],
        },
        train_set,
        num_boost_round=10,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        ray_params=RayParams(num_actors=2, cpus_per_actor=1),
    )

    bst.save_model("model.json")
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))


if __name__ == "__main__":
    main()
