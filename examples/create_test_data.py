"""Generate partitioned test parquet data (parity with ``examples/create_test_data.py``)."""

import argparse
import os

import numpy as np
import pandas as pd
from sklearn.datasets import make_classification


def create_parquet(
    filename: str,
    num_rows: int = 1000,
    num_features: int = 4,
    num_classes: int = 2,
    num_partitions: int = 1,
):
    x, y = make_classification(
        n_samples=num_rows,
        n_features=num_features,
        n_informative=max(2, num_features - 2),
        n_redundant=0,
        n_classes=num_classes,
        random_state=0,
    )
    df = pd.DataFrame(x.astype(np.float32), columns=[f"f{i}" for i in range(num_features)])
    df["labels"] = y.astype(np.float32)
    if num_partitions > 1:
        df["partition"] = df.index % num_partitions
        df.to_parquet(filename, partition_cols=["partition"])
    else:
        df.to_parquet(filename)
    return filename


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("filename", type=str, nargs="?", default="parted.parquet")
    parser.add_argument("--num-rows", type=int, default=1_000_000)
    parser.add_argument("--num-features", type=int, default=8)
    parser.add_argument("--num-classes", type=int, default=2)
    parser.add_argument("--num-partitions", type=int, default=100)
    args = parser.parse_args()
    create_parquet(
        args.filename,
        num_rows=args.num_rows,
        num_features=args.num_features,
        num_classes=args.num_classes,
        num_partitions=args.num_partitions,
    )
    print(f"Wrote {args.filename}")


if __name__ == "__main__":
    main()
