"""Train on a pandas dataframe with sharded rows (parity with ``examples/simple.py``)."""

import argparse

import numpy as np
import pandas as pd
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def main(cpus_per_actor, num_actors):
    # Load dataset
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    df = pd.DataFrame(data)
    df["label"] = labels

    train_set = RayDMatrix(df, "label")

    evals_result = {}
    bst = train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"]},
        train_set,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(cpus_per_actor=cpus_per_actor, num_actors=num_actors),
    )

    model_path = "simple.json"
    bst.save_model(model_path)
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--cpus-per-actor", type=int, default=1)
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.cpus_per_actor, args.num_actors)
