"""Elastic continuation smoke: a mid-training kill absorbed in-flight.

Trains a small model with ``elastic_training=True`` and immediate
reintegration (resource check + grace period at zero). With a fault plan
installed — programmatically here, or via the ``RXGB_FAULT_PLAN`` env var
(the CI smoke injects a kill that way) — the scheduled rank death is
absorbed WITHOUT restarting the attempt: training continues from the
in-memory booster with zero rounds replayed, and the killed rank is
reintegrated before the next round starts.

Run directly:        python examples/elastic_continuation.py
CI smoke (kill + reintegrate via env):
    RXGB_FAULT_PLAN='{"rules": [{"site": "actor.train_round",
        "action": "raise", "ranks": [1], "match": {"round": 3}}]}' \
    python examples/elastic_continuation.py

Config knobs (the CI smokes run the 2D-mesh, streamed, and domain-kill
variants through the same script — every shipped gbtree configuration
continues in-flight):
    RXGB_SMOKE_FEATURE_PARALLEL=2   # train on the 2D (R, C) mesh
    RXGB_SMOKE_STREAM=1             # streamed (out-of-core) ingestion
    RXGB_SMOKE_ACTORS=4             # world size (the domain smoke needs a
                                    # multi-rank fault domain)
    RXGB_FAULT_DOMAINS=2            # partition ranks into fault domains so
                                    # a domain_kill plan takes out a whole
                                    # "host" at once
"""

import os

import numpy as np

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def main():
    os.environ.setdefault("RXGB_ELASTIC_RESTART_RESOURCE_CHECK_S", "0")
    os.environ.setdefault("RXGB_ELASTIC_RESTART_GRACE_PERIOD_S", "0")

    rng = np.random.RandomState(0)
    x = rng.randn(2048, 8).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)

    params = {"objective": "binary:logistic", "eval_metric": ["logloss"],
              "max_depth": 4}
    fp = int(os.environ.get("RXGB_SMOKE_FEATURE_PARALLEL", "1"))
    if fp > 1:
        params["feature_parallel"] = fp
    if os.environ.get("RXGB_SMOKE_STREAM") == "1":
        # multi-chunk so the real streamed branch runs (single-chunk loads
        # degrade to the materialized path by design)
        dtrain = RayDMatrix(x, y, stream=True, chunk_rows=256)
    else:
        dtrain = RayDMatrix(x, y)

    actors = int(os.environ.get("RXGB_SMOKE_ACTORS", "2"))
    res = {}
    bst = train(
        params,
        dtrain,
        8,
        additional_results=res,
        ray_params=RayParams(num_actors=actors, elastic_training=True,
                             max_failed_actors=actors - 1,
                             max_actor_restarts=2,
                             checkpoint_frequency=2),
    )
    rob = res["robustness"]
    print(f"model rounds: {bst.num_boosted_rounds()}")
    print(f"robustness:   {rob}")

    assert bst.num_boosted_rounds() == 8
    if os.environ.get("RXGB_FAULT_PLAN"):
        # the CI smoke's kill must be absorbed in-flight: nothing replayed,
        # no attempt restart, the rank reintegrated (grow) before the end
        assert rob["rounds_replayed"] == 0, rob
        assert rob["restarts"] == 0, rob
        assert rob["shrinks"] + rob["grows"] >= 1, rob
        assert res["total_n"] == len(x), res["total_n"]
        if os.environ.get("RXGB_FAULT_DOMAINS"):
            # the domain smoke's correlated kill must read as ONE incident:
            # a lost domain, its extra deaths folded into the same recovery
            assert rob["domains_lost"] >= 1, rob
            assert rob["deaths_coalesced"] >= 1, rob
        print("elastic continuation smoke OK (zero replay, world restored)")


if __name__ == "__main__":
    main()
