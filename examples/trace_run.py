"""Traced-training smoke: the obs plane's queryable run timeline.

Trains 5 rounds with tracing on (the default) plus per-rank JSONL
streaming (``RXGB_TRACE_DIR``) and fenced phase profiling
(``RXGB_TRACE_PHASES=1``), then:

* validates BOTH the in-memory timeline (``additional_results["obs"]``)
  and the streamed JSONL file against the shared trace schema
  (``xgboost_ray_tpu.validate_trace_records`` — the same checker the
  tests use, so the CI example and the suite cannot drift apart), and
* prints the per-phase table (sample / hist / split / partition / margin /
  allreduce, compile vs execute separated) that traced production runs
  emit — the per-round/per-collective breakdown the XGBoost GPU paper
  attributes its wins with, now available outside the benchmark harness.

Run directly: python examples/trace_run.py
"""

import json
import os
import tempfile

import numpy as np

from xgboost_ray_tpu import RayDMatrix, RayParams, train, validate_trace_records


def main():
    rounds = 5
    rng = np.random.RandomState(0)
    x = rng.randn(4096, 12).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)

    with tempfile.TemporaryDirectory() as trace_dir:
        os.environ["RXGB_TRACE_DIR"] = trace_dir
        os.environ["RXGB_TRACE_PHASES"] = "1"
        try:
            res = {}
            bst = train(
                {"objective": "binary:logistic", "eval_metric": ["logloss"],
                 "max_depth": 4},
                RayDMatrix(x, y),
                rounds,
                additional_results=res,
                ray_params=RayParams(num_actors=2, checkpoint_frequency=2),
            )
        finally:
            os.environ.pop("RXGB_TRACE_DIR", None)
            os.environ.pop("RXGB_TRACE_PHASES", None)

        assert bst.num_boosted_rounds() == rounds
        obs = res["obs"]

        # schema validation: in-memory timeline AND the streamed JSONL
        problems = validate_trace_records(obs["timeline"])
        assert not problems, problems
        stream_path = os.path.join(trace_dir, "trace-rank0.jsonl")
        with open(stream_path) as f:
            streamed = [json.loads(line) for line in f]
        problems = validate_trace_records(streamed)
        assert not problems, problems
        print(f"trace schema OK: {len(obs['timeline'])} buffered records, "
              f"{len(streamed)} streamed lines, "
              f"{obs['dropped_spans']} dropped")

    # the queryable views: one span per round, lifecycle events
    assert [r["round"] for r in obs["rounds"]] == list(range(rounds))
    print("\nround  dur_s     world  rows")
    for r in obs["rounds"]:
        print(f"{r['round']:>5}  {r['dur_s']:<8.4f}  {r['world']:>5}  "
              f"{r['rows']}")
    events = [(e["name"], e.get("round")) for e in obs["events"]]
    print(f"events: {events}")
    assert any(name == "checkpoint.commit" for name, _ in events)

    # the per-phase table from the fenced profile
    prof = obs["phase_profile"]
    print(f"\nphase profile ({prof['rows_per_shard']} rows/shard, "
          f"world {prof['config']['world']}):")
    print(f"{'phase':<10} {'compile_ms':>11} {'execute_ms':>11}")
    for name in ("sample", "hist", "split", "partition", "margin",
                 "allreduce"):
        p = prof["phases"][name]
        print(f"{name:<10} {p['compile_ms']:>11.3f} {p['execute_ms']:>11.3f}")
    print(f"total execute: {prof['total_execute_ms']:.3f} ms/round "
          f"(phase-share approximation)")
    print("\ntraced run OK")


if __name__ == "__main__":
    main()
