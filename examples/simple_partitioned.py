"""Train from a ``__partitioned__``-protocol frame (parity with
``examples/simple_partitioned.py``)."""

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, train


class PartitionedFrame:
    """Minimal object implementing the __partitioned__ protocol."""

    def __init__(self, arrays):
        start = 0
        parts = {}
        for i, arr in enumerate(arrays):
            parts[(i, 0)] = {"start": (start, 0), "shape": arr.shape, "data": arr}
            start += arr.shape[0]
        self.__partitioned__ = {
            "shape": (start, arrays[0].shape[1]),
            "partition_tiling": (len(arrays), 1),
            "partitions": parts,
            "get": lambda x: x,
        }


def main():
    import pandas as pd

    data, labels = datasets.load_breast_cancer(return_X_y=True)
    df = pd.DataFrame(data, columns=[f"f{i}" for i in range(data.shape[1])])
    df["label"] = labels
    frames = [df.iloc[:200], df.iloc[200:400], df.iloc[400:]]
    pf = PartitionedFrame(frames)

    train_set = RayDMatrix(pf, "label")
    evals_result = {}
    train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"]},
        train_set,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(num_actors=2),
    )
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))


if __name__ == "__main__":
    main()
