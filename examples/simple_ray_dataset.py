"""Train from a Ray Dataset (parity with ``examples/simple_ray_dataset.py``).

Gated: prints a notice and exits cleanly when ray.data is not installed (it
is not part of the TPU image), exactly like the reference example does.
"""

import argparse

import numpy as np
import pandas as pd

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu.data_sources.ray_dataset import _ray_data_installed


def main(num_actors: int):
    if not _ray_data_installed():
        print("ray.data is not installed. Install with `pip install "
              "'ray[data]'` to run this example; the Ray Dataset data source "
              "activates automatically.")
        return

    import ray

    x = np.repeat(range(8), 16).reshape((32, 4))
    y = np.tile(np.repeat(range(2), 4), 4)
    bits_to_flip = np.random.choice(32, size=6, replace=False)
    y[bits_to_flip] = 1 - y[bits_to_flip]

    data = pd.DataFrame(x, columns=[f"f{i}" for i in range(4)])
    data["label"] = y
    ds = ray.data.from_pandas(data)

    train_set = RayDMatrix(ds, "label")
    evals_result = {}
    bst = train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"]},
        train_set,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(num_actors=num_actors),
    )
    bst.save_model("simple_ray_dataset.json")
    print(f"Final training error: {evals_result['train']['error'][-1]:.4f}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.num_actors)
