"""Custom objective + custom eval metric (parity with the reference's custom
objective coverage, ``xgboost_ray/tests/test_xgboost_api.py:77-150``)."""

import numpy as np
from sklearn.datasets import load_breast_cancer

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def squared_log_obj(preds, dtrain):
    labels = dtrain.get_label()
    preds = np.maximum(preds, -1 + 1e-6)
    grad = (np.log1p(preds) - np.log1p(labels)) / (preds + 1)
    hess = np.maximum((-np.log1p(preds) + np.log1p(labels) + 1) / ((preds + 1) ** 2), 1e-6)
    return grad, hess


def rmsle_metric(preds, dtrain):
    labels = dtrain.get_label()
    preds = np.maximum(preds, -1 + 1e-6)
    return "rmsle", float(np.sqrt(np.mean((np.log1p(preds) - np.log1p(labels)) ** 2)))


def main():
    data, labels = load_breast_cancer(return_X_y=True)
    dtrain = RayDMatrix(data.astype(np.float32), labels.astype(np.float32))
    evals_result = {}
    train(
        {"max_depth": 3, "eta": 0.1, "eval_metric": ["rmse"]},
        dtrain,
        num_boost_round=20,
        evals=[(dtrain, "train")],
        evals_result=evals_result,
        obj=squared_log_obj,
        feval=rmsle_metric,
        verbose_eval=False,
        ray_params=RayParams(num_actors=2),
    )
    print("Final rmsle: {:.4f}".format(evals_result["train"]["rmsle"][-1]))


if __name__ == "__main__":
    main()
