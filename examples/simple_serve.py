"""Online inference serving: train, serve over HTTP, hot-swap a retrain.

Demonstrates the ``xgboost_ray_tpu.serve`` subsystem end to end on the
local mesh: a trained booster goes into a loopback HTTP endpoint
(microbatched, padded-bucket compiled predictor), clients POST /predict,
a retrained model is hot-swapped in with zero downtime, and /metrics
reports QPS / latency percentiles / padding waste / recompile count.
"""

import json
import urllib.request

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu import serve


def _post(url, path, doc):
    req = urllib.request.Request(
        url + path, json.dumps(doc).encode("utf-8"),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return json.loads(r.read())


def main():
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    x = data.astype(np.float32)
    y = labels.astype(np.float32)

    bst = train(
        {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3},
        RayDMatrix(x, y), num_boost_round=8,
        ray_params=RayParams(num_actors=2),
    )

    # serve it: ephemeral loopback port, 2 ms microbatch deadline
    handle = serve.create_server(bst, max_batch=128, max_delay_ms=2.0)
    print(f"serving at {handle.url}")

    r = _post(handle.url, "/predict", {"data": x[:8].tolist()})
    print(f"v{r['model_version']} predictions: "
          f"{np.round(r['predictions'], 4).tolist()}")
    assert np.allclose(r["predictions"], bst.predict(x[:8]))

    # margins and SHAP contributions ride the same endpoint
    r = _post(handle.url, "/predict", {"data": x[:2].tolist(),
                                       "kind": "contribs"})
    contribs = np.asarray(r["predictions"])
    print(f"contribs rows sum to margins: "
          f"{np.round(contribs.sum(axis=1), 4).tolist()}")

    # retrain (e.g. on fresh data) and hot-swap: drains in-flight batches,
    # then flips atomically — no restart, no dropped requests
    bst2 = train(
        {"objective": "binary:logistic", "max_depth": 4, "eta": 0.1},
        RayDMatrix(x, y), num_boost_round=8,
        ray_params=RayParams(num_actors=2),
    )
    v2 = handle.registry.load(bst2)
    r = _post(handle.url, "/predict", {"data": x[:8].tolist()})
    assert r["model_version"] == v2
    assert np.allclose(r["predictions"], bst2.predict(x[:8]))
    print(f"hot-swapped to v{v2}")

    with urllib.request.urlopen(handle.url + "/metrics", timeout=10.0) as resp:
        m = json.loads(resp.read())
    print(f"metrics: qps={m['qps']} p50={m['latency_p50_ms']}ms "
          f"p99={m['latency_p99_ms']}ms padding_waste={m['padding_waste']} "
          f"recompiles={m['recompile_count']} swaps={m['model_swaps']}")

    handle.shutdown()
    print("done")


if __name__ == "__main__":
    main()
