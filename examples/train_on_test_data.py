"""Train + evaluate on a generated parquet dataset (parity with
``examples/train_on_test_data.py``)."""

import argparse
import os
import shutil
import tempfile
import time

from xgboost_ray_tpu import RayDMatrix, RayFileType, RayParams, predict, train
from examples.create_test_data import create_parquet


def main(num_rows, num_partitions, num_features, num_actors):
    tmpdir = tempfile.mkdtemp()
    path = os.path.join(tmpdir, "parted.parquet")
    create_parquet(
        path,
        num_rows=num_rows,
        num_partitions=num_partitions,
        num_features=num_features,
    )
    dtrain = RayDMatrix(path, label="labels", ignore=["partition"])

    config = {"tree_method": "hist", "eval_metric": ["logloss", "error"]}
    evals_result = {}
    start = time.time()
    bst = train(
        config,
        dtrain,
        evals_result=evals_result,
        ray_params=RayParams(max_actor_restarts=0, num_actors=num_actors),
        num_boost_round=10,
        evals=[(dtrain, "train")],
        verbose_eval=False,
    )
    print(f"TRAIN TIME TAKEN: {time.time() - start:.2f} seconds")
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))

    pred = predict(bst, dtrain, ray_params=RayParams(num_actors=num_actors))
    print("Predictions:", pred[:10])
    shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-rows", type=int, default=100_000)
    parser.add_argument("--num-partitions", type=int, default=8)
    parser.add_argument("--num-features", type=int, default=8)
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.num_rows, args.num_partitions, args.num_features, args.num_actors)
