"""README sklearn-API quick-start (parity with ``examples/readme_sklearn_api.py``)."""

from sklearn.datasets import load_breast_cancer
from sklearn.model_selection import train_test_split

from xgboost_ray_tpu import RayParams
from xgboost_ray_tpu.sklearn import RayXGBClassifier


def main():
    seed = 42
    x, y = load_breast_cancer(return_X_y=True)
    x_train, x_test, y_train, y_test = train_test_split(
        x, y, train_size=0.25, random_state=42
    )

    clf = RayXGBClassifier(n_jobs=2, random_state=seed)
    clf.fit(x_train, y_train)

    pred_ray = clf.predict(x_test)
    print(pred_ray[:10])

    pred_proba_ray = clf.predict_proba(x_test)
    print(pred_proba_ray[:5])

    # also test with num_actors=1
    clf = RayXGBClassifier(n_jobs=1, random_state=seed)
    clf.fit(x_train, y_train)
    print(clf.predict(x_test)[:10])


if __name__ == "__main__":
    main()
