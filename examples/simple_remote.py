"""Train and predict through the remote-execution tier (thin-driver mode).

The analog of the reference's Ray-client example flow
(``xgboost_ray/main.py:1413-1452``: a thin client re-runs train as a remote
task on the server): ``_remote=True`` ships the call to a spawned server
process that owns the accelerator, so this driver process never initializes
the device. Note the ``__main__`` guard — required by multiprocessing spawn.
"""

import argparse

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, predict, train


def main(num_actors):
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    data = data.astype(np.float32)

    evals_result = {}
    bst = train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"]},
        RayDMatrix(data, labels),
        num_boost_round=10,
        evals_result=evals_result,
        evals=[(RayDMatrix(data, labels), "train")],
        ray_params=RayParams(num_actors=num_actors),
        _remote=True,
    )
    bst.save_model("simple_remote.json")
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))

    pred = predict(bst, RayDMatrix(data),
                   ray_params=RayParams(num_actors=num_actors), _remote=True)
    acc = ((pred > 0.5) == labels).mean()
    print("Prediction accuracy (remote predict): {:.4f}".format(acc))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.num_actors)
