"""Train from a Modin DataFrame (parity with ``examples/simple_modin.py``).

Gated: prints a notice and exits cleanly when modin is not installed (it is
not part of the TPU image), exactly like the reference example does.
"""

import argparse

import numpy as np
import pandas as pd

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu.data_sources.modin import _modin_installed


def main(num_actors: int):
    if not _modin_installed():
        print("Modin is not installed. Install with `pip install modin` to "
              "run this example; the Modin data source activates "
              "automatically.")
        return

    import modin.pandas as mpd

    x = np.repeat(range(8), 16).reshape((32, 4))
    y = np.tile(np.repeat(range(2), 4), 4)
    bits_to_flip = np.random.choice(32, size=6, replace=False)
    y[bits_to_flip] = 1 - y[bits_to_flip]

    data = pd.DataFrame(x, columns=[f"f{i}" for i in range(4)])
    data["label"] = y
    modin_df = mpd.DataFrame(data)

    train_set = RayDMatrix(modin_df, "label")
    evals_result = {}
    bst = train(
        {"objective": "binary:logistic", "eval_metric": ["logloss", "error"]},
        train_set,
        evals_result=evals_result,
        evals=[(train_set, "train")],
        verbose_eval=False,
        num_boost_round=10,
        ray_params=RayParams(num_actors=num_actors),
    )
    bst.save_model("simple_modin.json")
    print(f"Final training error: {evals_result['train']['error'][-1]:.4f}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.num_actors)
