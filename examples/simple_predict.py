"""Distributed prediction with a saved model (parity with ``examples/simple_predict.py``)."""

import os

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import RayDMatrix, RayParams, RayXGBoostBooster, predict, train


def main():
    if not os.path.exists("simple.json"):
        data, labels = datasets.load_breast_cancer(return_X_y=True)
        train_set = RayDMatrix(data.astype(np.float32), labels.astype(np.float32))
        bst = train(
            {"objective": "binary:logistic"},
            train_set,
            num_boost_round=10,
            ray_params=RayParams(num_actors=2),
        )
        bst.save_model("simple.json")

    data, labels = datasets.load_breast_cancer(return_X_y=True)
    dpred = RayDMatrix(data.astype(np.float32))
    bst = RayXGBoostBooster.load_model("simple.json")
    pred_ray = predict(bst, dpred, ray_params=RayParams(num_actors=2))
    print(pred_ray[:10])
    acc = float(((pred_ray > 0.5) == labels).mean())
    print(f"Accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
