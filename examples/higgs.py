"""HIGGS benchmark example (parity with ``examples/higgs.py``: 11M x 28 CSV,
100 boosting rounds, logloss+error).

Download HIGGS.csv.gz from the UCI repository and pass its path; without a
path, a synthetic HIGGS-shaped dataset is generated so the example runs in
air-gapped environments.
"""

import argparse
import os
import time

import numpy as np

from xgboost_ray_tpu import RayDMatrix, RayParams, train

FILENAME_CSV = "HIGGS.csv.gz"


def make_synthetic(n_rows=1_000_000, n_features=28, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.standard_normal((n_rows, n_features)).astype(np.float32)
    logits = 0.8 * x[:, 0] - 0.6 * x[:, 1] + 0.4 * x[:, 2] * x[:, 3]
    y = (logits + rng.standard_normal(n_rows) > 0).astype(np.float32)
    return x, y


def main(path, num_actors):
    if path and os.path.exists(path):
        colnames = ["label"] + ["feature-%02d" % i for i in range(1, 29)]
        dtrain = RayDMatrix(path, label="label", names=colnames)
    else:
        print("HIGGS.csv.gz not found; using synthetic HIGGS-shaped data.")
        x, y = make_synthetic()
        dtrain = RayDMatrix(x, y)

    config = {
        "tree_method": "hist",
        "eval_metric": ["logloss", "error"],
    }

    evals_result = {}
    start = time.time()
    bst = train(
        config,
        dtrain,
        evals_result=evals_result,
        ray_params=RayParams(max_actor_restarts=1, num_actors=num_actors),
        num_boost_round=100,
        evals=[(dtrain, "train")],
        verbose_eval=False,
    )
    taken = time.time() - start
    print(f"TRAIN TIME TAKEN: {taken:.2f} seconds")

    bst.save_model("higgs.json")
    print("Final training error: {:.4f}".format(evals_result["train"]["error"][-1]))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("path", nargs="?", default=FILENAME_CSV)
    parser.add_argument("--num-actors", type=int, default=8)
    args = parser.parse_args()
    main(args.path, args.num_actors)
