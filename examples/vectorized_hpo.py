"""Vectorized HPO: train K hyperparameter candidates as lanes of ONE
compiled program, with ASHA successive halving pruning losing lanes at
round boundaries.

``VectorizedTrainable`` is the data-first counterpart of the callable
trainable ``examples/simple_tune.py`` uses: instead of each trial running
its own ``train()`` (one compile per trial), lane-compatible trials pack
into a single vmapped-K ``engine.step_vmapped`` program — one compile, one
dispatch per round, per-lane params (eta, lambda, subsample, depth, seed)
carried as runtime arrays. On the 8-device CPU mesh this turns a K=4 sweep
into roughly half the wall clock of 4 sequential trials (see the bench
``hpo`` section); on real accelerators the compile amortization is larger.
"""

import numpy as np
from sklearn import datasets

from xgboost_ray_tpu import obs
from xgboost_ray_tpu.tuner import (
    ASHAScheduler,
    Tuner,
    VectorizedTrainable,
    grid_search,
)


def main():
    data, labels = datasets.load_breast_cancer(return_X_y=True)
    shards = [{
        "data": data.astype(np.float32),
        "label": labels.astype(np.float32),
    }]

    # every key here except eta is shared across lanes; eta is
    # lane-vectorizable, so all four candidates ride one program
    search_space = {
        "objective": "binary:logistic",
        "eval_metric": ["logloss"],
        "max_depth": 4,
        "seed": 42,
        "eta": grid_search([0.5, 0.3, 0.1, 0.02]),
    }
    spec = VectorizedTrainable(
        shards=shards,
        num_actors=8,
        num_boost_round=8,
        max_lanes=8,
    )
    tracer = obs.Tracer(enabled=True)
    with obs.use_tracer(tracer):
        tuner = Tuner(
            spec,
            search_space,
            metric="train-logloss",
            mode="min",
            scheduler=ASHAScheduler("train-logloss", mode="min",
                                    grace_rounds=2, eta=2),
        )
        result = tuner.fit()

    for trial in result.trials:
        print(
            f"trial {trial.trial_id}: eta={trial.config['eta']:<5} "
            f"rounds={len(trial.results)} "
            f"logloss={trial.last_result['train-logloss']:.5f}"
            f"{'  (pruned)' if trial.stopped_early else ''}"
        )
    print("Best hyperparameters", result.best_config)
    # the halving schedule is reconstructible from the trace timeline
    hpo_events = [r for r in tracer.records()
                  if r["name"] in ("hpo.lane_prune", "hpo.repack")]
    for ev in hpo_events:
        print(f"  {ev['name']}: {ev.get('attrs')}")
    assert result.best_config is not None
    assert all(t.checkpoint_path for t in result.trials)


if __name__ == "__main__":
    main()
