"""Linear booster (gblinear): elastic-net coordinate descent on the mesh.

Mirrors the reference's params passthrough (``booster="gblinear"`` goes
straight to xgboost there); here the cyclic pass runs as one jitted
shard_map program per round with psum-merged coordinate sums.
"""

import argparse

import numpy as np

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def main(num_actors):
    rng = np.random.RandomState(0)
    x = rng.randn(2000, 8).astype(np.float32)
    w_true = np.array([2.0, -1.5, 0.0, 0.0, 1.0, 0.0, 0.0, 3.0], np.float32)
    y = x @ w_true + 0.5 + 0.1 * rng.randn(2000).astype(np.float32)

    evals_result = {}
    train_set = RayDMatrix(x, y)
    bst = train(
        {
            "objective": "reg:squarederror",
            "booster": "gblinear",
            "eta": 0.5,
            "alpha": 0.02,  # L1: prunes the irrelevant coordinates
        },
        train_set,
        evals=[(train_set, "train")],
        evals_result=evals_result,
        num_boost_round=30,
        ray_params=RayParams(num_actors=num_actors),
    )
    print(f"rmse: {evals_result['train']['rmse'][-1]:.4f}")
    print("weights:", np.round(bst.weights[:, 0], 2))
    nz = int(np.sum(np.abs(bst.weights[:, 0]) > 1e-6))
    print(f"non-zero coordinates: {nz}/8 (true model has 4)")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-actors", type=int, default=2)
    args = parser.parse_args()
    main(args.num_actors)
