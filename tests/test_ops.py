"""Unit tests for the core device ops: binning, histograms, splits, growth.

Models the reference's unit layer (``xgboost_ray/tests/test_matrix.py`` level
of granularity) but for the compute core our build owns.
"""

import numpy as np
import os
import pytest

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops import binning
from xgboost_ray_tpu.ops.histogram import build_histogram, hist_onehot, hist_scatter, node_sums
from xgboost_ray_tpu.ops.split import SplitParams, find_splits, leaf_weight
from xgboost_ray_tpu.ops.grow import GrowConfig, build_tree, predict_tree_binned
from xgboost_ray_tpu.ops.objectives import get_objective
from xgboost_ray_tpu.ops.metrics import compute_metric


def test_binning_roundtrip_basic():
    rng = np.random.RandomState(0)
    x = rng.randn(500, 4).astype(np.float32)
    cuts = binning.sketch_cuts_np(x, max_bin=16)
    assert cuts.shape == (4, 15)
    assert np.all(np.diff(cuts, axis=1) >= 0)
    b = binning.bin_matrix_np(x, cuts, max_bin=16)
    assert b.dtype == np.uint8
    assert b.max() <= 15  # no missing values present
    # roughly equal occupancy per bin
    counts = np.bincount(b[:, 0], minlength=16)
    assert counts.min() > 0


def test_binning_missing_goes_to_reserved_bin():
    x = np.array([[1.0], [np.nan], [2.0], [3.0]], dtype=np.float32)
    cuts = binning.sketch_cuts_np(x, max_bin=4)
    b = binning.bin_matrix_np(x, cuts, max_bin=4)
    assert b[1, 0] == 4  # missing bucket
    assert b[0, 0] < 4


def test_binning_device_matches_host():
    rng = np.random.RandomState(1)
    x = rng.randn(200, 3).astype(np.float32)
    x[5, 1] = np.nan
    cuts = binning.sketch_cuts_np(x, max_bin=8)
    host = binning.bin_matrix_np(x, cuts, max_bin=8)
    dev = np.asarray(binning.bin_matrix(jnp.asarray(x), jnp.asarray(cuts), 8))
    np.testing.assert_array_equal(host, dev)


def test_device_sketch_close_to_exact_quantiles():
    rng = np.random.RandomState(2)
    x = rng.randn(20000, 2).astype(np.float32)
    valid = jnp.ones((x.shape[0],), bool)
    mn, mx = binning.feature_min_max(jnp.asarray(x), valid)
    hist = binning.sketch_histogram(jnp.asarray(x), valid, mn, mx)
    cuts = np.asarray(binning.cuts_from_sketch(mn, mx, hist, max_bin=16))
    exact = binning.sketch_cuts_np(x, max_bin=16)
    assert np.max(np.abs(cuts - exact)) < 0.05  # fine-histogram approximation


def test_histogram_impls_agree():
    rng = np.random.RandomState(3)
    n, f, nb = 300, 5, 8
    bins = rng.randint(0, nb + 1, size=(n, f)).astype(np.uint8)
    gh = rng.randn(n, 2).astype(np.float32)
    pos = rng.randint(0, 4, size=n).astype(np.int32)
    h1 = np.asarray(hist_scatter(jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(pos), 4, nb + 1))
    h2 = np.asarray(
        hist_onehot(jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(pos), 4, nb + 1, chunk=64)
    )
    np.testing.assert_allclose(h1, h2, atol=1e-4)
    # cross-check against numpy accumulation
    ref = np.zeros((4, f, nb + 1, 2), np.float32)
    for i in range(n):
        for j in range(f):
            ref[pos[i], j, bins[i, j]] += gh[i]
    np.testing.assert_allclose(h1, ref, atol=1e-4)


def test_node_sums():
    gh = jnp.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    pos = jnp.array([0, 1, 0])
    s = np.asarray(node_sums(gh, pos, 2))
    np.testing.assert_allclose(s, [[6.0, 8.0], [3.0, 4.0]])


def test_find_splits_picks_obvious_split():
    # one node, one feature, 4 bins: grads +1 in low bins, -1 in high bins
    nbt = 5  # 4 bins + missing
    hist = np.zeros((1, 1, nbt, 2), np.float32)
    hist[0, 0, 0] = [10.0, 10.0]
    hist[0, 0, 1] = [10.0, 10.0]
    hist[0, 0, 2] = [-10.0, 10.0]
    hist[0, 0, 3] = [-10.0, 10.0]
    node_gh = jnp.asarray(hist[:, 0, :, :].sum(axis=1))
    sp = find_splits(jnp.asarray(hist), node_gh, SplitParams(min_child_weight=0.0))
    assert bool(sp.valid[0])
    assert int(sp.split_bin[0]) == 1  # bins {0,1} left, {2,3} right
    assert float(sp.gain[0]) > 0


def test_find_splits_respects_min_child_weight():
    nbt = 5
    hist = np.zeros((1, 1, nbt, 2), np.float32)
    hist[0, 0, 0] = [5.0, 0.5]
    hist[0, 0, 3] = [-5.0, 0.5]
    node_gh = jnp.asarray(hist[:, 0, :, :].sum(axis=1))
    sp = find_splits(jnp.asarray(hist), node_gh, SplitParams(min_child_weight=10.0))
    assert not bool(sp.valid[0])


def test_find_splits_learns_missing_direction():
    # missing rows have negative grads -> should go right with the negative bin
    nbt = 4  # 3 bins + missing
    hist = np.zeros((1, 1, nbt, 2), np.float32)
    hist[0, 0, 0] = [8.0, 8.0]
    hist[0, 0, 2] = [-8.0, 8.0]
    hist[0, 0, 3] = [-4.0, 4.0]  # missing bucket, negative grad
    node_gh = jnp.asarray(hist[:, 0, :, :].sum(axis=1))
    sp = find_splits(jnp.asarray(hist), node_gh, SplitParams(min_child_weight=0.0))
    assert bool(sp.valid[0])
    assert not bool(sp.default_left[0])  # missing joins the negative (right) side


def _fit_one_tree(x, g, h, max_depth=3, max_bin=8, **split_kw):
    cuts = binning.sketch_cuts_np(x, max_bin=max_bin)
    bins = binning.bin_matrix_np(x, cuts, max_bin=max_bin)
    gh = jnp.asarray(np.stack([g, h], axis=1).astype(np.float32))
    cfg = GrowConfig(
        max_depth=max_depth,
        max_bin=max_bin,
        split=SplitParams(learning_rate=1.0, reg_lambda=0.0, min_child_weight=0.0, **split_kw),
    )
    tree, row_value = build_tree(jnp.asarray(bins), gh, jnp.asarray(cuts), cfg)
    return tree, np.asarray(row_value), bins, cfg


def test_build_tree_fits_step_function():
    # discrete feature values so quantile cuts separate classes exactly;
    # y = 1 for x<0 else -1; squarederror from margin 0 -> g = -y, h = 1
    rng = np.random.RandomState(4)
    x = rng.choice([-0.75, -0.25, 0.25, 0.75], size=(400, 1)).astype(np.float32)
    y = np.where(x[:, 0] < 0, 1.0, -1.0).astype(np.float32)
    tree, row_value, bins, cfg = _fit_one_tree(x, -y, np.ones_like(y), max_depth=2)
    np.testing.assert_allclose(row_value, y, atol=1e-3)
    # binned walk agrees with row_value from training
    walked = np.asarray(
        predict_tree_binned(tree, jnp.asarray(bins), cfg.max_depth, cfg.max_bin)
    )
    np.testing.assert_allclose(walked, row_value, atol=1e-5)


def test_build_tree_row_values_match_leaf_math():
    rng = np.random.RandomState(5)
    x = rng.randn(200, 3).astype(np.float32)
    g = rng.randn(200).astype(np.float32)
    h = np.ones(200, np.float32)
    tree, row_value, bins, cfg = _fit_one_tree(x, g, h, max_depth=3)
    # each row's value must equal a leaf value of the tree
    leaf_vals = np.asarray(tree.value)[np.asarray(tree.is_leaf)]
    for v in row_value[:20]:
        assert np.min(np.abs(leaf_vals - v)) < 1e-5


def test_objectives_shapes_and_values():
    m = jnp.zeros((5, 1))
    y = jnp.array([0.0, 1.0, 1.0, 0.0, 1.0])
    w = jnp.ones((5,))
    obj = get_objective("binary:logistic")
    g, h = obj.grad_hess(m, y, w)
    np.testing.assert_allclose(np.asarray(g[:, 0]), [0.5, -0.5, -0.5, 0.5, -0.5])
    np.testing.assert_allclose(np.asarray(h[:, 0]), [0.25] * 5)
    obj2 = get_objective("reg:squarederror")
    g2, h2 = obj2.grad_hess(jnp.full((5, 1), 2.0), y, w)
    np.testing.assert_allclose(np.asarray(g2[:, 0]), np.asarray(2.0 - y))
    obj3 = get_objective("multi:softprob", num_class=3)
    g3, h3 = obj3.grad_hess(jnp.zeros((5, 3)), jnp.array([0.0, 1.0, 2.0, 0.0, 1.0]), w)
    assert g3.shape == (5, 3)
    np.testing.assert_allclose(np.asarray(g3).sum(axis=1), 0.0, atol=1e-6)


def test_metrics_basic():
    m = np.array([10.0, 10.0, -10.0, 10.0])
    y = np.array([0.0, 1.0, 0.0, 1.0])
    assert compute_metric("error", m, y) == pytest.approx(0.25)
    assert compute_metric("logloss", np.array([-10.0, 10.0, -10.0, 10.0]), y) < 0.2
    r = compute_metric("rmse", np.array([1.0, 2.0]), np.array([0.0, 4.0]))
    assert r == pytest.approx(np.sqrt((1 + 4) / 2))
    auc = compute_metric("auc", np.array([0.1, 0.9, 0.2, 0.8]), np.array([0, 1, 0, 1]))
    assert auc == pytest.approx(1.0)


def test_ndcg_metric_perfect_and_inverted():
    ptr = np.array([0, 3, 6])
    y = np.array([2.0, 1.0, 0.0, 0.0, 1.0, 2.0])
    perfect = np.array([3.0, 2.0, 1.0, 1.0, 2.0, 3.0])
    assert compute_metric("ndcg", perfect, y, group_ptr=ptr) == pytest.approx(1.0)
    inverted = -perfect
    assert compute_metric("ndcg", inverted, y, group_ptr=ptr) < 0.8


def test_ranking_gradients_point_the_right_way():
    from xgboost_ray_tpu.ops.ranking import build_group_rows, make_rank_grad_hess

    qid = np.array([0, 0, 0, 1, 1])
    rows, ptr = build_group_rows(qid)
    assert rows.shape == (2, 3)
    label = jnp.array([2.0, 1.0, 0.0, 1.0, 0.0])
    margin = jnp.zeros((5, 1))
    w = jnp.ones((5,))
    gh = make_rank_grad_hess("rank:pairwise")
    g, h = gh(margin, label, w, jnp.asarray(rows))
    g = np.asarray(g[:, 0])
    assert g[0] < g[1] < g[2]  # most relevant gets most negative grad (pushed up)
    assert g[3] < g[4]
    assert np.all(np.asarray(h) > 0)


def test_hist_partition_matches_scatter():
    from xgboost_ray_tpu.ops.histogram import hist_partition

    rng = np.random.RandomState(7)
    n, f, nb = 700, 6, 8
    bins = rng.randint(0, nb + 1, size=(n, f)).astype(np.uint8)
    gh = rng.randn(n, 2).astype(np.float32)
    for n_nodes in (1, 4, 16):
        pos = rng.randint(0, n_nodes, size=n).astype(np.int32)
        ref = np.asarray(
            hist_scatter(jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(pos),
                         n_nodes, nb + 1)
        )
        out = np.asarray(
            hist_partition(jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(pos),
                           n_nodes, nb + 1, block=32, block_chunk=8)
        )
        np.testing.assert_allclose(out, ref, atol=1e-4)


def test_hist_partition_skewed_nodes():
    from xgboost_ray_tpu.ops.histogram import hist_partition

    rng = np.random.RandomState(8)
    n, f, nb, n_nodes = 500, 3, 4, 8
    bins = rng.randint(0, nb + 1, size=(n, f)).astype(np.uint8)
    gh = rng.randn(n, 2).astype(np.float32)
    # extreme skew: almost everything in node 0, some nodes empty
    pos = np.zeros(n, np.int32)
    pos[:20] = rng.randint(1, n_nodes, size=20)
    ref = np.asarray(
        hist_scatter(jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(pos),
                     n_nodes, nb + 1)
    )
    out = np.asarray(
        hist_partition(jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(pos),
                       n_nodes, nb + 1, block=64, block_chunk=4)
    )
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_unknown_hist_impl_rejected():
    """hist_impl='pallas' was REMOVED in r5 (the hand-written kernel lost to
    the identical-layout XLA einsum on-chip — rationale in ops/grow.py's
    module docstring); an explicit request must fail loudly at parse time,
    never silently run a different impl."""
    from xgboost_ray_tpu.params import parse_params

    with pytest.raises(ValueError, match="Pallas kernel was removed"):
        parse_params({"hist_impl": "pallas"})
    with pytest.raises(ValueError, match="Unknown hist_impl"):
        parse_params({"hist_impl": "bogus"})


def test_build_tree_impls_produce_identical_trees():
    """scatter / partition (incremental ordering) / mixed must grow the exact
    same tree — the partition path's O(N) order maintenance is pure layout."""
    rng = np.random.RandomState(12)
    x = rng.randn(800, 6).astype(np.float32)
    g = rng.randn(800).astype(np.float32)
    h = np.ones(800, np.float32)
    cuts = binning.sketch_cuts_np(x, max_bin=16)
    bins = binning.bin_matrix_np(x, cuts, max_bin=16)
    gh = jnp.asarray(np.stack([g, h], 1))
    outs = {}
    for impl in ("scatter", "partition", "mixed"):
        cfg = GrowConfig(max_depth=5, max_bin=16,
                         split=SplitParams(learning_rate=1.0), hist_impl=impl)
        tree, rv = build_tree(jnp.asarray(bins), gh, jnp.asarray(cuts), cfg)
        outs[impl] = (np.asarray(rv), np.asarray(tree.feature),
                      np.asarray(tree.value))
    for impl in ("partition", "mixed"):
        np.testing.assert_allclose(outs[impl][0], outs["scatter"][0], atol=1e-4)
        np.testing.assert_array_equal(outs[impl][1], outs["scatter"][1])
        np.testing.assert_allclose(outs[impl][2], outs["scatter"][2], atol=1e-4)


def test_sibling_subtraction_matches_direct_build():
    """Deriving the larger child as parent - smaller child must grow the same
    tree as building both children directly (fp-subtraction noise aside)."""
    rng = np.random.RandomState(21)
    x = rng.randn(1000, 5).astype(np.float32)
    g = rng.randn(1000).astype(np.float32)
    h = np.abs(rng.randn(1000)).astype(np.float32) + 0.5
    cuts = binning.sketch_cuts_np(x, max_bin=32)
    bins = binning.bin_matrix_np(x, cuts, max_bin=32)
    gh = jnp.asarray(np.stack([g, h], 1))
    outs = {}
    for impl in ("scatter", "mixed"):
        for sib in (True, False):
            cfg = GrowConfig(max_depth=6, max_bin=32,
                             split=SplitParams(learning_rate=1.0),
                             hist_impl=impl, sibling_subtract=sib)
            tree, rv = build_tree(jnp.asarray(bins), gh, jnp.asarray(cuts), cfg)
            outs[(impl, sib)] = (np.asarray(rv), np.asarray(tree.feature),
                                 np.asarray(tree.value))
    for impl in ("scatter", "mixed"):
        np.testing.assert_array_equal(
            outs[(impl, True)][1], outs[(impl, False)][1]
        )
        np.testing.assert_allclose(
            outs[(impl, True)][0], outs[(impl, False)][0], atol=1e-3
        )
        np.testing.assert_allclose(
            outs[(impl, True)][2], outs[(impl, False)][2], atol=1e-3
        )


def test_update_partition_order_maintains_sorted_invariant():
    from xgboost_ray_tpu.ops.histogram import update_partition_order

    rng = np.random.RandomState(13)
    n = 500
    order = jnp.arange(n, dtype=jnp.int32)
    counts = jnp.full((1,), n, jnp.int32)
    pos = np.zeros(n, np.int64)
    for level in range(4):
        go_right = rng.rand(n) < 0.4
        new_pos = pos * 2 + go_right
        order, counts = update_partition_order(
            order, counts, jnp.asarray(go_right)
        )
        pos = new_pos
        o = np.asarray(order)
        assert sorted(o.tolist()) == list(range(n))  # a permutation
        assert np.all(np.diff(pos[o]) >= 0)  # sorted by node
        np.testing.assert_array_equal(
            np.asarray(counts), np.bincount(pos, minlength=2 ** (level + 1))
        )
        # stability: within a node, original relative order preserved
        for node in np.unique(pos):
            rows = o[pos[o] == node]
            assert np.all(np.diff(rows) > 0) or len(rows) <= 1


def test_new_objectives_train_and_improve():
    """binary:hinge / reg:squaredlogerror / reg:pseudohubererror train
    end-to-end and their default metrics improve."""
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(30)
    x = rng.randn(400, 4).astype(np.float32)
    yb = (x[:, 0] > 0).astype(np.float32)
    ypos = np.exp(x[:, 0] * 0.5 + 0.1 * rng.randn(400)).astype(np.float32)
    yreg = (2.0 * x[:, 0] + rng.randn(400) * 0.3).astype(np.float32)
    cases = [
        ("binary:hinge", yb, "error"),
        ("reg:squaredlogerror", ypos, "rmsle"),
        ("reg:pseudohubererror", yreg, "mphe"),
    ]
    for objective, y, metric in cases:
        er = {}
        bst = train({"objective": objective, "eval_metric": [metric]},
                    RayDMatrix(x, y), 10,
                    evals=[(RayDMatrix(x, y), "t")], evals_result=er,
                    ray_params=RayParams(num_actors=2))
        trace = er["t"][metric]
        assert trace[-1] <= trace[0], (objective, er)
        assert trace[-1] < 0.5, (objective, er)
        assert bst.num_boosted_rounds() == 10


def test_hinge_predicts_hard_labels():
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(31)
    x = rng.randn(300, 3).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = train({"objective": "binary:hinge"}, RayDMatrix(x, y), 8,
                ray_params=RayParams(num_actors=2))
    pred = bst.predict(x)
    assert set(np.unique(pred)) <= {0.0, 1.0}
    assert (pred == y).mean() > 0.9


def test_mape_rmsle_metrics_values():
    from xgboost_ray_tpu.ops.metrics import compute_metric

    pred = np.array([1.0, 2.0, 4.0], np.float32)
    y = np.array([1.0, 1.0, 2.0], np.float32)
    mape = compute_metric("mape", pred, y)
    assert abs(mape - np.mean([0.0, 1.0, 1.0])) < 1e-6
    rmsle = compute_metric("rmsle", pred, y)
    expect = np.sqrt(np.mean((np.log1p(pred) - np.log1p(y)) ** 2))
    assert abs(rmsle - expect) < 1e-6


def test_huber_slope_changes_model_and_sle_validates():
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(32)
    x = rng.randn(300, 3).astype(np.float32)
    y = (2 * x[:, 0] + rng.randn(300)).astype(np.float32)
    preds = {}
    for slope in (1.0, 5.0):
        bst = train({"objective": "reg:pseudohubererror", "huber_slope": slope},
                    RayDMatrix(x, y), 5, ray_params=RayParams(num_actors=2))
        preds[slope] = bst.predict(x)
    assert not np.allclose(preds[1.0], preds[5.0])

    with pytest.raises(ValueError, match="labels > -1"):
        train({"objective": "reg:squaredlogerror"},
              RayDMatrix(x, np.full(300, -2.0, np.float32)), 2,
              ray_params=RayParams(num_actors=2))


def test_hist_missing_bucket_reconstruction():
    """All impls build only the regular bins on the MXU and reconstruct the
    missing bucket as node_total - sum(regular); verify against scatter."""
    import numpy as np
    import jax.numpy as jnp
    from xgboost_ray_tpu.ops.histogram import (
        hist_onehot, hist_partition, hist_scatter)

    rng = np.random.RandomState(3)
    n, f, nbt = 5000, 5, 17  # max_bin=16, bucket 16 == missing
    bins = rng.randint(0, nbt, size=(n, f)).astype(np.int32)
    gh = rng.randn(n, 2).astype(np.float32)
    pos = rng.randint(0, 4, size=n).astype(np.int32)
    ref = np.asarray(hist_scatter(jnp.asarray(bins), jnp.asarray(gh),
                                  jnp.asarray(pos), 4, nbt))
    assert np.abs(ref[:, :, nbt - 1, :]).max() > 0  # missing bucket populated
    for impl in (hist_onehot, hist_partition):
        got = np.asarray(impl(jnp.asarray(bins), jnp.asarray(gh),
                              jnp.asarray(pos), 4, nbt))
        np.testing.assert_allclose(got, ref, atol=2e-3)


def test_hist_precision_param_accepted_and_fast_close():
    """hist_precision plumbs through params; "fast" (bf16 one-hot + bf16 gh,
    ~0.2% bin-sum rounding) must not change model QUALITY — individual
    predictions may shift slightly where a split threshold moves by one bin."""
    import numpy as np
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(4)
    x = rng.randn(2000, 6).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    preds = {}
    for prec in ("highest", "fast"):
        bst = train({"objective": "binary:logistic", "max_depth": 4,
                     "hist_precision": prec, "hist_impl": "onehot"},
                    RayDMatrix(x, y), 5,
                    ray_params=RayParams(num_actors=2))
        preds[prec] = bst.predict(x)
    # same hard labels, tiny mean probability shift
    assert ((preds["fast"] > 0.5) == (preds["highest"] > 0.5)).mean() > 0.995
    assert np.abs(preds["fast"] - preds["highest"]).mean() < 2e-3


def test_select_small_child_rows_edges():
    """Compaction helper: empty children, fully one-sided splits, sentinel
    rows for unused capacity."""
    import numpy as np
    import jax.numpy as jnp
    from xgboost_ray_tpu.ops.histogram import select_small_child_rows

    # parent 0: all rows left (right child empty -> right is 'smaller');
    # parent 1: 3 left / 5 right -> left smaller
    pos = np.array([0] * 6 + [2] * 3 + [3] * 5, np.int32)
    n = pos.shape[0]
    order = np.argsort(pos, kind="stable").astype(np.int32)
    counts = np.bincount(pos, minlength=4).astype(np.int32)
    small_is_right = counts[1::2] <= counts[0::2]  # [True, False]
    rows, pc, valid, counts_sel = map(np.asarray, select_small_child_rows(
        jnp.asarray(order), jnp.asarray(counts), jnp.asarray(small_is_right)))
    assert counts_sel.tolist() == [0, 3]
    assert valid.sum() == 3
    # the selected rows are exactly parent 1's left-child rows
    assert set(rows[valid].tolist()) == set(np.where(pos == 2)[0].tolist())
    assert (pc[valid] == 1).all()
    # unused slots carry the sentinel row id n
    assert (rows[~valid] == n).all()


def test_sibling_compaction_overflow_falls_back():
    """The smaller child is chosen from GLOBAL (allreduced) counts; on a
    skewed shard its local rows can exceed the N//2 compaction buffer. Fake
    the count allreduce so the 'global' choice is the locally-BIGGER child:
    the lax.cond must fall back to the gh-zeroed full-row build and still
    grow exactly the tree the direct (no-subtraction) build grows."""
    import numpy as np
    import jax.numpy as jnp
    from xgboost_ray_tpu.ops import binning
    from xgboost_ray_tpu.ops.grow import GrowConfig, build_tree
    from xgboost_ray_tpu.ops.split import SplitParams

    rng = np.random.RandomState(22)
    x = rng.randn(1200, 5).astype(np.float32)
    g = rng.randn(1200).astype(np.float32)
    h = np.abs(rng.randn(1200)).astype(np.float32) + 0.5
    cuts = binning.sketch_cuts_np(x, max_bin=32)
    bins = binning.bin_matrix_np(x, cuts, max_bin=32)
    gh = jnp.asarray(np.stack([g, h], 1))

    def skew_allreduce(t):
        # pretend a peer shard holds 3x this shard's rows with left/right
        # swapped within every parent: the globally-smaller child becomes
        # this shard's locally-bigger one
        if t.ndim == 1 and t.shape[0] % 2 == 0:
            swapped = t.reshape(-1, 2)[:, ::-1].reshape(-1)
            return t + 3.0 * swapped
        return t

    outs = {}
    for sib in (True, False):
        cfg = GrowConfig(max_depth=5, max_bin=32,
                         split=SplitParams(learning_rate=1.0),
                         hist_impl="mixed", sibling_subtract=sib)
        tree, rv = build_tree(jnp.asarray(bins), gh, jnp.asarray(cuts), cfg,
                              allreduce=skew_allreduce)
        outs[sib] = (np.asarray(tree.feature), np.asarray(rv))
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    np.testing.assert_allclose(outs[True][1], outs[False][1], atol=1e-3)


def test_quantile_regression_single_and_multi():
    """reg:quantileerror (xgboost >= 2.0 pinball loss): empirical coverage of
    each predicted quantile matches its alpha, multi-alpha outputs are
    ordered, and the "quantile" eval metric decreases."""
    import numpy as np
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(5)
    n = 4000
    x = rng.randn(n, 3).astype(np.float32)
    y = (2.0 * x[:, 0] + rng.standard_normal(n)).astype(np.float32)

    res = {}
    bst = train({"objective": "reg:quantileerror",
                 "quantile_alpha": [0.1, 0.5, 0.9],
                 "eval_metric": ["quantile"], "max_depth": 4, "eta": 0.3},
                RayDMatrix(x, y), 30,
                evals=[(RayDMatrix(x, y), "train")], evals_result=res,
                ray_params=RayParams(num_actors=2))
    pin = res["train"]["quantile"]
    assert pin[-1] < pin[0]
    pred = bst.predict(x)
    assert pred.shape == (n, 3)
    for k, a in enumerate([0.1, 0.5, 0.9]):
        cov = float((y <= pred[:, k]).mean())
        assert abs(cov - a) < 0.08, (a, cov)
    # quantile crossing should be rare on train data
    assert float((pred[:, 0] <= pred[:, 2]).mean()) > 0.95

    bst1 = train({"objective": "reg:quantileerror", "quantile_alpha": 0.75,
                  "max_depth": 4, "eta": 0.3},
                 RayDMatrix(x, y), 25, ray_params=RayParams(num_actors=2))
    p1 = bst1.predict(x)
    assert p1.shape == (n,)
    assert abs(float((y <= p1).mean()) - 0.75) < 0.08


def test_quantile_save_load_and_sklearn():
    """quantile_alpha survives serialization (multi-output predict after
    load) and flows through the sklearn regressor params."""
    import numpy as np
    from xgboost_ray_tpu import RayDMatrix, RayParams, train
    from xgboost_ray_tpu.models.booster import Booster
    from xgboost_ray_tpu.sklearn import RayXGBRegressor

    rng = np.random.RandomState(6)
    x = rng.randn(600, 3).astype(np.float32)
    y = (x[:, 0] + 0.3 * rng.standard_normal(600)).astype(np.float32)
    bst = train({"objective": "reg:quantileerror",
                 "quantile_alpha": [0.25, 0.75], "max_depth": 3},
                RayDMatrix(x, y), 6, ray_params=RayParams(num_actors=2))
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "m.json")
        bst.save_model(p)
        loaded = Booster.load_model(p)
    assert loaded.num_outputs == 2
    np.testing.assert_allclose(loaded.predict(x), bst.predict(x), atol=1e-6)

    reg = RayXGBRegressor(objective="reg:quantileerror", quantile_alpha=0.5,
                          n_estimators=5, max_depth=3,
                          ray_params=RayParams(num_actors=2))
    reg.fit(x, y)
    p = reg.predict(x)
    assert p.shape == (600,)


def test_quantile_metric_alpha_threading_and_mismatch_guard():
    """compute_metric/elementwise_contrib take quantile_alpha (ADVICE r2:
    host-side evaluation silently scored with alpha=0.5); a margin/alpha
    count mismatch with >1 alphas raises instead of broadcasting."""
    import numpy as np
    import pytest
    from xgboost_ray_tpu.ops.metrics import compute_metric

    y = np.array([0.0, 1.0, 2.0, 4.0], np.float32)
    m = np.array([1.0, 1.0, 1.0, 1.0], np.float32)
    v10 = compute_metric("quantile", m, y, quantile_alpha=0.1)
    v90 = compute_metric("quantile", m, y, quantile_alpha=0.9)
    # pinball: alpha * max(y-m, 0) + (1-alpha) * max(m-y, 0)
    def pinball(a):
        d = y - m
        return float(np.mean(np.maximum(a * d, (a - 1) * d)))
    assert v10 == pytest.approx(pinball(0.1), rel=1e-5)
    assert v90 == pytest.approx(pinball(0.9), rel=1e-5)
    assert v10 != pytest.approx(v90)
    # one alpha broadcasts over multi-output margins; >1 mismatched raises
    m2 = np.stack([m, m], axis=1)
    compute_metric("quantile", m2, y, quantile_alpha=0.5)
    with pytest.raises(ValueError, match="must align"):
        compute_metric("quantile", m2, y, quantile_alpha=(0.1, 0.5, 0.9))


def test_mphe_metric_huber_slope_threading():
    import numpy as np
    import pytest
    from xgboost_ray_tpu.ops.metrics import compute_metric

    y = np.zeros(4, np.float32)
    m = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
    v1 = compute_metric("mphe", m, y, huber_slope=1.0)
    v3 = compute_metric("mphe", m, y, huber_slope=3.0)
    def mphe(s):
        return float(np.mean(s * s * (np.sqrt(1 + (m / s) ** 2) - 1)))
    assert v1 == pytest.approx(mphe(1.0), rel=1e-5)
    assert v3 == pytest.approx(mphe(3.0), rel=1e-5)
    assert v1 != pytest.approx(v3)
