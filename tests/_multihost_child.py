"""Child process for the multi-host rehearsal test (see test_multihost.py).

Each invocation is one "host": it joins a 2-process jax.distributed world of
4 CPU devices each (8 global), feeds only its own ranks' shards into
TpuEngine, trains, and checks the result against the single-process
expectations the parent computed.

Usage: python _multihost_child.py <coordinator> <process_id> <expected.npz>
"""

import sys

import numpy as np


def main() -> int:
    coordinator, pid, expected_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    # same hermeticity trick as conftest.py: drop any non-CPU PJRT factory the
    # sitecustomize-registered TPU plugin added, or this process can hang on a
    # wedged TPU tunnel even under JAX_PLATFORMS=cpu
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    for _name in list(_xb._backend_factories):
        if _name not in ("cpu",):
            _xb._backend_factories.pop(_name, None)

    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4
    # the engine's row layout assumes process-contiguous device order
    procs = [d.process_index for d in jax.devices()]
    assert procs == sorted(procs), procs

    from xgboost_ray_tpu.distributed import put_rows_global
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.matrix import RayShardingMode, _get_sharding_indices
    from xgboost_ray_tpu.params import parse_params

    exp = np.load(expected_path)
    x, y = exp["x"], exp["y"]
    n = x.shape[0]
    num_actors = 8

    # --- put_rows_global over a 2-process mesh ------------------------------
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("actors",))
    sharding = NamedSharding(mesh, P("actors"))
    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    local = full[pid * 4 : (pid + 1) * 4]
    arr = put_rows_global(local, sharding)
    assert not arr.is_fully_addressable
    gathered = np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    np.testing.assert_array_equal(gathered, full)

    # --- short training with per-process rank shards ------------------------
    my_ranks = range(pid * 4, (pid + 1) * 4)
    shards = []
    for rank in my_ranks:
        idx = _get_sharding_indices(RayShardingMode.INTERLEAVED, rank, num_actors, n)
        shards.append({
            "data": x[idx], "label": y[idx], "weight": None,
            "base_margin": None, "label_lower_bound": None,
            "label_upper_bound": None, "qid": None,
        })
    params = parse_params({"objective": "binary:logistic",
                           "eval_metric": ["logloss", "auc"], "max_depth": 3})
    eng = TpuEngine(shards, params, num_actors=num_actors,
                    evals=[(shards, "train")])
    assert eng.n_rows == n, (eng.n_rows, n)
    results = [eng.step(i) for i in range(int(exp["rounds"]))]
    lls = [r["train"]["logloss"] for r in results]
    assert lls[-1] < lls[0], lls

    # metrics must match the single-process run (same mesh math, psum merged)
    np.testing.assert_allclose(lls, exp["logloss"], atol=1e-5)
    np.testing.assert_allclose(
        [r["train"]["auc"] for r in results], exp["auc"], atol=1e-5
    )

    # margins gather across hosts (the VERDICT get_margins fix)
    margins = eng.get_margins()
    assert margins.shape[0] == n
    # rows are in rank-shard order: invert the interleave to compare
    order = np.concatenate([
        _get_sharding_indices(RayShardingMode.INTERLEAVED, r, num_actors, n)
        for r in range(num_actors)
    ])
    restored = np.empty_like(margins)
    restored[order] = margins
    np.testing.assert_allclose(restored[:, 0], exp["margins"], atol=1e-4)

    # the booster is replicated: predictions must match the expectation
    bst = eng.get_booster()
    np.testing.assert_allclose(
        bst.predict(x, output_margin=True), exp["margins"], atol=1e-4
    )

    # --- ranking: group layouts + device ndcg over the 2-host mesh ----------
    xr, yr, qid = exp["xr"], exp["yr"], exp["qid"]
    qn = xr.shape[0]
    rshards = []
    for rank in my_ranks:
        idx = _get_sharding_indices(RayShardingMode.BATCH, rank, num_actors, qn)
        rshards.append({
            "data": xr[idx], "label": yr[idx], "weight": None,
            "base_margin": None, "label_lower_bound": None,
            "label_upper_bound": None, "qid": qid[idx],
        })
    rparams = parse_params({"objective": "rank:pairwise",
                            "eval_metric": ["ndcg@4"], "max_depth": 3})
    reng = TpuEngine(rshards, rparams, num_actors=num_actors,
                     evals=[(rshards, "train")])
    rresults = [reng.step(i) for i in range(int(exp["rounds"]))]
    np.testing.assert_allclose(
        [r["train"]["ndcg@4"] for r in rresults], exp["rank_ndcg"], atol=1e-5
    )

    # --- survival: batched rounds + device aft-nloglik on the 2-host mesh ---
    sx, s_lo, s_hi = exp["sx"], exp["s_lo"], exp["s_hi"]
    qn = sx.shape[0]
    sshards = []
    for rank in my_ranks:
        idx = _get_sharding_indices(RayShardingMode.BATCH, rank, num_actors, qn)
        sshards.append({
            "data": sx[idx], "label": None, "weight": None,
            "base_margin": None, "label_lower_bound": s_lo[idx],
            "label_upper_bound": s_hi[idx], "qid": None,
        })
    sparams = parse_params({"objective": "survival:aft",
                            "eval_metric": ["aft-nloglik"], "max_depth": 3})
    seng = TpuEngine(sshards, sparams, num_actors=num_actors,
                     evals=[(sshards, "train")])
    assert seng.can_batch_rounds()
    sresults = seng.step_many(0, int(exp["rounds"]))
    np.testing.assert_allclose(
        [r["train"]["aft-nloglik"] for r in sresults], exp["aft_nll"], atol=1e-5
    )

    # --- custom objective + host feval over the 2-host mesh -----------------
    # Each process computes grad/hess and the host metric from ITS OWN rows
    # (get_margins_local + local label_np) — the reference's per-actor local
    # computation (``xgboost_ray/main.py:745-752``); combine_host_scalar
    # merges the per-process metric. Must match the single-process run
    # bit-for-bit (gradients are identical, placement is identical).
    ceng = TpuEngine(shards, params, num_actors=num_actors,
                     evals=[(shards, "train")])
    c_logloss, c_merror = [], []
    for i in range(int(exp["rounds"])):
        m = ceng.get_margins_local()[:, 0]
        assert m.shape[0] == ceng.label_np.shape[0] == n // 2
        p = 1.0 / (1.0 + np.exp(-m))
        g = (p - ceng.label_np).astype(np.float32)
        h = (p * (1.0 - p)).astype(np.float32)
        r = ceng.step(i, gh_custom=(g, h))
        c_logloss.append(r["train"]["logloss"])
        p2 = 1.0 / (1.0 + np.exp(-ceng.get_margins_local()[:, 0]))
        merr = float(((p2 > 0.5) != (ceng.label_np > 0.5)).mean())
        c_merror.append(ceng.combine_host_scalar(merr, ceng.evals[0]))
    np.testing.assert_allclose(c_logloss, exp["c_logloss"], atol=1e-5)
    np.testing.assert_allclose(c_merror, exp["c_merror"], atol=1e-6)
    np.testing.assert_allclose(
        ceng.get_booster().predict(x, output_margin=True),
        exp["c_margins"], atol=1e-4,
    )

    # --- multi-process SPMD predict (VERDICT r4 #4) -------------------------
    # Each process predicts its own (UNEVEN — exercises the allgathered
    # block layout + per-device padding) local rows through the public
    # predict() path; the global mesh walks all rows in one lockstep program.
    from xgboost_ray_tpu import RayDMatrix, RayParams
    from xgboost_ray_tpu import main as rxgb_main

    cut = 300
    local_x = x[:cut] if pid == 0 else x[cut:]
    expect = exp["margins"][:cut] if pid == 0 else exp["margins"][cut:]
    pm = rxgb_main.predict(
        bst, RayDMatrix(local_x),
        ray_params=RayParams(num_actors=2), output_margin=True,
    )
    np.testing.assert_allclose(np.asarray(pm).ravel(), expect, atol=1e-4)
    # booster-level entry with explicit devices agrees
    pm2 = bst.predict_margin_spmd(local_x, list(jax.devices()))[:, 0]
    np.testing.assert_allclose(pm2, expect, atol=1e-4)

    print(f"CHILD{pid} OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
