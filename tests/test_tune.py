"""Tune/HPO integration tests (parity targets: ``xgboost_ray/tests/test_tune.py``)."""

import os

import numpy as np
import pytest

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu import tune as tune_mod
from xgboost_ray_tpu.tune import (
    TuneReportCheckpointCallback,
    load_model,
)
from xgboost_ray_tpu.tuner import ExperimentResult, Tuner, choice, grid_search


@pytest.fixture
def xy():
    rng = np.random.RandomState(0)
    x = rng.randn(128, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    return x, y


@pytest.fixture(autouse=True)
def _clean_session():
    yield
    tune_mod.shutdown_session()


_PARAMS = {"objective": "binary:logistic", "eval_metric": ["logloss", "error"],
           "max_depth": 3}


def test_callback_reports_every_round(tmp_path, xy):
    x, y = xy
    session = tune_mod.init_session(str(tmp_path))
    dtrain = RayDMatrix(x, y)
    train(_PARAMS, dtrain, 6, evals=[(dtrain, "train")],
          ray_params=RayParams(num_actors=2))
    assert len(session.results) == 6  # auto-injected callback fired per round
    assert "train-logloss" in session.results[0]
    assert session.results[0]["training_iteration"] == 1


def test_callback_not_injected_outside_session(xy):
    x, y = xy
    dtrain = RayDMatrix(x, y)
    additional = {}
    train(_PARAMS, dtrain, 3, evals=[(dtrain, "train")],
          ray_params=RayParams(num_actors=2), additional_results=additional)
    assert tune_mod.get_session() is None


def test_checkpoints_written_and_loadable(tmp_path, xy):
    x, y = xy
    session = tune_mod.init_session(str(tmp_path))
    dtrain = RayDMatrix(x, y)
    train(
        _PARAMS, dtrain, 10, evals=[(dtrain, "train")],
        ray_params=RayParams(num_actors=2),
        callbacks=[TuneReportCheckpointCallback(frequency=5)],
    )
    assert session.last_checkpoint_path is not None
    bst = load_model(session.last_checkpoint_path)
    pred = bst.predict(x)
    assert pred.shape == (128,)


def test_explicit_callback_not_duplicated(tmp_path, xy):
    x, y = xy
    session = tune_mod.init_session(str(tmp_path))
    dtrain = RayDMatrix(x, y)
    train(
        _PARAMS, dtrain, 4, evals=[(dtrain, "train")],
        ray_params=RayParams(num_actors=2),
        callbacks=[TuneReportCheckpointCallback(frequency=2)],
    )
    # one report per round, not two (injection skipped when already present)
    assert len(session.results) == 4


def test_metric_selection_mapping(tmp_path, xy):
    x, y = xy
    session = tune_mod.init_session(str(tmp_path))
    dtrain = RayDMatrix(x, y)
    train(
        _PARAMS, dtrain, 3, evals=[(dtrain, "train")],
        ray_params=RayParams(num_actors=2),
        callbacks=[TuneReportCheckpointCallback(
            metrics={"loss": "train-logloss"}, frequency=100)],
    )
    assert "loss" in session.results[-1]


def test_get_tune_resources():
    rp = RayParams(num_actors=4, cpus_per_actor=2, tpus_per_actor=1)
    pgf = rp.get_tune_resources()
    assert len(pgf.bundles) == 5  # head + 4 actors
    assert pgf.strategy == "PACK"
    total = pgf.required_resources()
    assert total["CPU"] == 1 + 4 * 2
    assert total["TPU"] == 4
    with pytest.raises(ValueError):
        RayParams(num_actors=0).get_tune_resources()


def test_placement_options_passthrough():
    rp = RayParams(num_actors=2, cpus_per_actor=1,
                   placement_options={"strategy": "SPREAD",
                                      "_max_cpu_fraction_per_node": 0.8})
    pgf = rp.get_tune_resources()
    assert pgf.strategy == "SPREAD"
    assert pgf.options["_max_cpu_fraction_per_node"] == 0.8


def test_tuner_grid_search_end_to_end(tmp_path, xy):
    x, y = xy

    def trainable(config):
        dtrain = RayDMatrix(x, y)
        params = dict(_PARAMS, max_depth=config["max_depth"], eta=config["eta"])
        train(params, dtrain, 5, evals=[(dtrain, "train")],
              ray_params=RayParams(num_actors=2))

    tuner = Tuner(
        trainable,
        {"max_depth": grid_search([2, 3]), "eta": 0.3},
        metric="train-logloss",
        mode="min",
        experiment_dir=str(tmp_path),
        raise_on_failed_trial=True,
    )
    result = tuner.fit()
    assert len(result.trials) == 2
    best = result.get_best_trial()
    assert best is not None
    assert best.config["max_depth"] in (2, 3)
    assert best.last_result["train-logloss"] < 0.7
    assert result.best_config == best.config


def test_tuner_isolates_trial_failures(tmp_path, xy):
    x, y = xy

    def trainable(config):
        if config["max_depth"] == 99:
            raise RuntimeError("boom")
        dtrain = RayDMatrix(x, y)
        train(dict(_PARAMS, max_depth=config["max_depth"]), dtrain, 2,
              evals=[(dtrain, "train")], ray_params=RayParams(num_actors=2))

    tuner = Tuner(
        trainable, {"max_depth": grid_search([2, 99])},
        metric="train-logloss", mode="min", experiment_dir=str(tmp_path),
    )
    result = tuner.fit()
    assert result.trials[1].error is not None
    assert result.get_best_trial().config["max_depth"] == 2


def test_placement_strategy_selection(monkeypatch, tmp_path):
    from xgboost_ray_tpu.main import _get_placement_strategy

    assert _get_placement_strategy(in_tune_session=False) == "SPREAD"
    assert _get_placement_strategy(in_tune_session=True) == "PACK"
    monkeypatch.setenv("RXGB_USE_SPREAD_STRATEGY", "0")
    assert _get_placement_strategy(in_tune_session=False) == "PACK"


def test_tuner_concurrent_trials(tmp_path, xy):
    """max_concurrent_trials partitions the mesh into disjoint device slices
    and runs trials in parallel threads; results match the sequential path."""
    import jax

    from xgboost_ray_tpu.tuner import Tuner, grid_search

    x, y = xy

    seen_devices = []

    def trainable(config):
        from xgboost_ray_tpu import tune as tune_mod

        sess = tune_mod.get_session()
        seen_devices.append(tuple(sess.devices))
        evals_result = {}
        train(
            {"objective": "binary:logistic", "eval_metric": ["logloss"],
             "eta": config["eta"]},
            RayDMatrix(x, y), 3,
            evals=[(RayDMatrix(x, y), "train")], evals_result=evals_result,
            ray_params=RayParams(num_actors=2),
        )

    tuner = Tuner(
        trainable, {"eta": grid_search([0.1, 0.3, 0.5, 0.7])},
        metric="train-logloss", mode="min",
        experiment_dir=str(tmp_path), max_concurrent_trials=2,
    )
    result = tuner.fit()
    assert len(result.trials) == 4
    assert all(t.error is None for t in result.trials)
    assert result.get_best_trial() is not None
    # two disjoint slices of the 8-device mesh were used
    assert len(set(seen_devices)) == 2
    a, b = sorted(set(seen_devices), key=lambda ds: ds[0].id)
    assert not (set(a) & set(b))
    assert len(a) == len(jax.devices()) // 2


def test_partition_devices_uses_every_device():
    """Slot math must distribute the remainder instead of dropping trailing
    devices when len(devices) % n_slots != 0 (tuner.py slot fix)."""
    from xgboost_ray_tpu.tuner import _partition_devices

    for n_dev in (8, 7, 5):
        devs = list(range(n_dev))
        for n_slots in (1, 2, 3, 4, 5):
            slots = _partition_devices(devs, n_slots)
            assert len(slots) == min(n_slots, n_dev)
            flat = [d for s in slots for d in s]
            assert flat == devs  # disjoint, ordered, nothing dropped
            sizes = [len(s) for s in slots]
            assert max(sizes) - min(sizes) <= 1  # near-even split


def test_tuner_concurrent_trials_ragged_slots(tmp_path, xy):
    """3 slots over the 8-device mesh: sizes 3/3/2, union == all devices."""
    import jax

    from xgboost_ray_tpu.tuner import Tuner, grid_search

    x, y = xy
    seen_devices = []

    def trainable(config):
        from xgboost_ray_tpu import tune as tune_mod

        sess = tune_mod.get_session()
        seen_devices.append(tuple(sess.devices))
        train(
            {"objective": "binary:logistic", "eta": config["eta"]},
            RayDMatrix(x, y), 2,
            ray_params=RayParams(num_actors=2),
        )

    tuner = Tuner(
        trainable, {"eta": grid_search([0.1, 0.3, 0.5])},
        metric="train-logloss", mode="min",
        experiment_dir=str(tmp_path), max_concurrent_trials=3,
    )
    result = tuner.fit()
    assert all(t.error is None for t in result.trials)
    slices = set(seen_devices)
    used = {d for s in slices for d in s}
    assert used == set(jax.devices())  # no trailing devices idle
    sizes = sorted(len(s) for s in slices)
    assert max(sizes) - min(sizes) <= 1


def test_asha_scheduler_unit():
    """ASHA rung logic: at rung r, values outside the top 1/eta stop."""
    from xgboost_ray_tpu.tuner import ASHAScheduler

    s = ASHAScheduler(metric="loss", mode="min", grace_rounds=2, eta=2)
    assert s.rungs[:3] == [2, 4, 8]
    # non-rung iterations never stop
    assert not s.on_report("a", 1, {"loss": 9.0})
    # first value at a rung is the cutoff itself -> continues
    assert not s.on_report("a", 2, {"loss": 1.0})
    # clearly worse at the same rung -> stopped
    assert s.on_report("b", 2, {"loss": 5.0})
    # better than the cutoff -> continues
    assert not s.on_report("c", 2, {"loss": 0.5})
    # mode="max" flips the comparison
    smax = ASHAScheduler(metric="auc", mode="max", grace_rounds=2, eta=2)
    assert not smax.on_report("a", 2, {"auc": 0.9})
    assert smax.on_report("b", 2, {"auc": 0.2})


def test_median_stopping_rule_unit():
    from xgboost_ray_tpu.tuner import MedianStoppingRule

    s = MedianStoppingRule(metric="loss", mode="min", grace_rounds=3,
                           min_trials=2)
    # trial a: good curve, full history
    for i, v in enumerate([1.0, 0.8, 0.6, 0.5], start=1):
        assert not s.on_report("a", i, {"loss": v})
    # trial b: within grace -> never stopped, even though it's worse
    assert not s.on_report("b", 1, {"loss": 2.0})
    assert not s.on_report("b", 2, {"loss": 1.9})
    # past grace and worse than a's running best median -> stopped
    assert s.on_report("b", 3, {"loss": 1.8})


def test_tuner_asha_stops_bad_trial_early(tmp_path, xy):
    """End-to-end: a clearly-worse config is terminated at a rung while the
    good config runs to completion (the Ray-Tune-scheduler capability,
    standalone)."""
    from xgboost_ray_tpu.tuner import ASHAScheduler

    x, y = xy
    rounds = 12

    def trainable(config):
        train(
            {"objective": "binary:logistic", "eval_metric": ["logloss"],
             "max_depth": 3, "eta": config["eta"], "seed": 0},
            RayDMatrix(x, y), rounds,
            evals=[(RayDMatrix(x, y), "train")],
            ray_params=RayParams(num_actors=2, checkpoint_frequency=0),
        )

    tuner = Tuner(
        trainable,
        {"eta": grid_search([0.5, 1e-6])},  # good, then hopeless
        metric="train-logloss", mode="min",
        experiment_dir=str(tmp_path),
        scheduler=ASHAScheduler(metric="train-logloss", mode="min",
                                grace_rounds=3, eta=2),
    )
    result = tuner.fit()
    good, bad = result.trials
    assert not good.stopped_early
    assert len(good.results) == rounds
    assert bad.stopped_early
    assert len(bad.results) < rounds
    best = result.get_best_trial()
    assert best.config["eta"] == 0.5


def test_median_stopping_rule_sparse_peer_histories():
    """ADVICE r4: a peer whose history holds only LATER iterations than the
    current report (manual/skipped-report pattern) must not crash the inner
    min() — it is simply not comparable at this iteration."""
    from xgboost_ray_tpu.tuner import MedianStoppingRule

    s = MedianStoppingRule(metric="loss", mode="min", grace_rounds=1,
                           min_trials=2)
    # peer 'a' reports ONLY at iteration 10 (manual reporting)
    assert not s.on_report("a", 10, {"loss": 0.1})
    # trial 'b' reports at iteration 5: 'a' has entries >= 5 but none <= 5;
    # previously this raised ValueError (min of empty sequence) out of
    # session.report and failed the trial
    assert not s.on_report("b", 5, {"loss": 9.9})
    # once 'a' has a comparable early entry, the rule stops 'b' again
    assert not s.on_report("a", 3, {"loss": 0.2})
    assert s.on_report("b", 6, {"loss": 9.8})


def test_asha_rung_arrival_order_semantics():
    """VERDICT r4 weak #6: async-SHA rung statistics are self-inclusive, so
    the FIRST trial to reach a rung always survives it (cutoff == itself) —
    by design, not by accident. Pin the arrival-order behavior so the
    near-serial trial scheduling on small thread pools can't silently
    change semantics: a bad first arrival passes, and is retroactively
    out-competed as better values fill the rung."""
    from xgboost_ray_tpu.tuner import ASHAScheduler

    s = ASHAScheduler(metric="loss", mode="min", grace_rounds=2, eta=2)
    # first at the rung: terrible, but cutoff == itself -> survives
    assert not s.on_report("bad_first", 2, {"loss": 100.0})
    # a better value arrives: rung {1, 100}, top-1/2 cutoff = 1 -> survives
    assert not s.on_report("good", 2, {"loss": 1.0})
    # middling late arrival: rung {1, 50, 100}, cutoff still 1 -> stopped
    assert s.on_report("mid", 2, {"loss": 50.0})
    # had the order been reversed, the bad trial would be cut at the rung:
    s2 = ASHAScheduler(metric="loss", mode="min", grace_rounds=2, eta=2)
    assert not s2.on_report("good", 2, {"loss": 1.0})
    assert s2.on_report("bad_late", 2, {"loss": 100.0})
