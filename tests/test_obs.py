"""Unified observability plane (xgboost_ray_tpu/obs/) tests.

Covers the plane's own guarantees (span nesting, ring-buffer truncation
accounting, histogram edge cases, Prometheus exposition stability, the
shared trace-schema validator) and the instrumentation contract: a traced
``train()`` returns a queryable timeline under
``additional_results["obs"]``, the ``after_round`` callback streams round
records live, and a chaos run's shrink→grow story is reconstructible from
the timeline alone — no driver-log reading, no counter re-derivation.
"""

import json
import os
import threading

import numpy as np
import pytest

from xgboost_ray_tpu import (
    DistributedCallback,
    RayDMatrix,
    RayParams,
    faults,
    obs,
    train,
    validate_trace_records,
)
from xgboost_ray_tpu.obs.metrics import (
    BUCKET_BOUNDS_MS,
    LatencyHistogram,
    MetricsRegistry,
)
from xgboost_ray_tpu.obs.trace import Tracer, recovery_time_s, use_tracer

_PARAMS = {"objective": "binary:logistic", "eval_metric": ["logloss"],
           "max_depth": 3}


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    return x, y


# ---------------------------------------------------------------------------
# tracer: spans, events, ring buffer
# ---------------------------------------------------------------------------


def test_span_nesting_records_parent_and_orders_by_end_time():
    t = Tracer(enabled=True, trace_dir="")
    with t.span("outer"):
        with t.span("inner") as attrs:
            attrs["k"] = 1
        t.event("mark", round=2, flag=True)
    recs = t.records()
    assert [r["name"] for r in recs] == ["inner", "mark", "outer"]
    inner, mark, outer = recs
    # seq preserves START order: outer started first
    assert outer["seq"] < inner["seq"] < mark["seq"]
    assert inner["parent"] == outer["seq"]
    assert outer["parent"] is None
    assert inner["attrs"] == {"k": 1}
    assert mark["kind"] == "event" and mark["round"] == 2
    assert mark["attrs"] == {"flag": True}
    assert outer["dur_s"] >= inner["dur_s"] >= 0.0
    assert validate_trace_records(recs) == []


def test_span_yields_mutable_attrs_measured_inside():
    t = Tracer(enabled=True, trace_dir="")
    with t.span("work", round=7) as attrs:
        attrs["bytes"] = 1024
    (rec,) = t.records()
    assert rec["round"] == 7
    assert rec["attrs"]["bytes"] == 1024


def test_ring_buffer_truncation_is_accounted_never_silent():
    t = Tracer(capacity=8, enabled=True, trace_dir="")
    for i in range(20):
        t.event(f"e{i}")
    recs = t.records()
    assert len(recs) == 8
    # oldest dropped, newest kept
    assert [r["name"] for r in recs] == [f"e{i}" for i in range(12, 20)]
    assert t.dropped == 12
    snap = t.snapshot()
    assert snap == {"records": 8, "dropped_spans": 12, "capacity": 8}


def test_disabled_tracer_records_nothing():
    t = Tracer(enabled=False, trace_dir="")
    with t.span("outer"):
        t.event("e")
    assert t.records() == []
    assert t.snapshot()["records"] == 0


def test_rxgb_trace_env_disables(monkeypatch):
    monkeypatch.setenv("RXGB_TRACE", "0")
    assert Tracer().enabled is False
    monkeypatch.setenv("RXGB_TRACE", "1")
    assert Tracer().enabled is True


def test_trace_dir_streams_jsonl_matching_ring(tmp_path):
    t = Tracer(enabled=True, trace_dir=str(tmp_path), rank=3)
    t.event("a", x=1)
    with t.span("b"):
        pass
    t.close()
    path = tmp_path / "trace-rank3.jsonl"
    assert path.exists()
    streamed = [json.loads(line) for line in path.read_text().splitlines()]
    assert streamed == t.records()
    assert validate_trace_records(streamed) == []


def test_export_jsonl_roundtrip(tmp_path):
    t = Tracer(enabled=True, trace_dir="")
    t.event("a")
    t.event("b")
    out = tmp_path / "trace.jsonl"
    assert t.export_jsonl(str(out)) == 2
    assert [json.loads(line)["name"]
            for line in out.read_text().splitlines()] == ["a", "b"]


def test_use_tracer_scopes_current_thread():
    scoped = Tracer(enabled=True, trace_dir="")
    with use_tracer(scoped):
        obs.get_tracer().event("inside")
    assert obs.get_tracer() is not scoped
    assert [r["name"] for r in scoped.records()] == ["inside"]


# ---------------------------------------------------------------------------
# schema validator + timeline queries
# ---------------------------------------------------------------------------


def test_validate_trace_records_flags_malformed():
    bad = [
        {"kind": "span", "name": "a", "ts": 0.0, "seq": 1, "dur_s": 0.1,
         "parent": None, "extra": 1},                     # unknown key
        {"kind": "event", "name": "b", "ts": 0.0, "seq": 1},  # dup seq
        {"kind": "event", "name": "c", "ts": 0.0, "seq": 2, "dur_s": 0.5},
        {"kind": "nope", "name": "d", "ts": 0.0, "seq": 3},   # bad kind
        {"kind": "span", "name": "", "ts": "x", "seq": 4, "dur_s": -1,
         "parent": "p"},
    ]
    problems = validate_trace_records(bad)
    text = "\n".join(problems)
    assert "unknown keys" in text
    assert "duplicate seq" in text
    assert "event carries dur_s" in text
    assert "bad kind 'nope'" in text
    assert "bad name" in text and "bad ts" in text
    assert "bad dur_s" in text and "bad parent" in text


def test_recovery_time_s_pairs_failures_with_recoveries():
    def ev(name, ts):
        return {"kind": "event", "name": name, "ts": ts, "seq": int(ts * 10)}

    records = [
        ev("failure.detected", 10.0),
        ev("recovered", 12.0),          # 2 s
        ev("failure.detected", 20.0),   # clock restarted by the next one:
        ev("failure.detected", 23.0),   # repeated failure before progress
        ev("recovered", 24.0),          # 1 s (from the LATEST failure)
        ev("recovered", 30.0),          # unmatched: no open clock, ignored
    ]
    assert recovery_time_s(records) == pytest.approx(3.0)
    assert recovery_time_s([]) == 0.0


# ---------------------------------------------------------------------------
# metrics: histogram edge cases, registry, Prometheus exposition
# ---------------------------------------------------------------------------


def test_histogram_percentile_interpolates_at_bucket_boundaries():
    h = LatencyHistogram("h")
    # one sample: p100 walks to the sample's bucket upper bound; p50 lands
    # mid-bucket by linear interpolation
    h.record(1.0)
    idx = next(
        i for i, b in enumerate(BUCKET_BOUNDS_MS) if 1.0 <= b
    )
    lo = BUCKET_BOUNDS_MS[idx - 1]
    hi = BUCKET_BOUNDS_MS[idx]
    assert h.percentile(1.0) == pytest.approx(hi)
    assert h.percentile(0.5) == pytest.approx(lo + 0.5 * (hi - lo))
    # a sample at/below the smallest bound interpolates from 0
    h2 = LatencyHistogram("h2")
    h2.record(0.0)
    assert 0.0 <= h2.percentile(0.5) <= BUCKET_BOUNDS_MS[0]
    # overflow bucket: beyond the largest bound, extrapolated one factor up
    h3 = LatencyHistogram("h3")
    h3.record(1e9)
    assert h3.percentile(1.0) == pytest.approx(BUCKET_BOUNDS_MS[-1] * 1.26)
    # empty histogram: 0.0, not NaN
    assert LatencyHistogram("h4").percentile(0.99) == 0.0


def test_histogram_rejects_nonfinite_and_clamps_negative():
    h = LatencyHistogram("h")
    for bad in (float("nan"), float("inf"), float("-inf")):
        h.record(bad)
    assert h.total == 0
    assert h.sum_ms == 0.0
    assert h.invalid == 3
    h.record(-5.0)  # clamps to 0: bucket 0, no sum poisoning
    assert h.total == 1
    assert h.sum_ms == 0.0
    assert h.counts[0] == 1
    snap = h.snapshot()
    assert snap["invalid"] == 3 and snap["total"] == 1
    assert np.isfinite(snap["mean_ms"])


def test_histogram_snapshot_is_consistent_under_concurrent_record():
    h = LatencyHistogram("h")
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            h.record(1.0)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            snap = h.snapshot()
            # every recorded sample is exactly 1.0 ms: a torn read shows up
            # as counts/total/sum disagreeing with each other
            assert sum(snap["counts"]) == snap["total"]
            assert snap["sum_ms"] == pytest.approx(float(snap["total"]))
    finally:
        stop.set()
        t.join(5.0)


def test_registry_get_or_create_and_type_conflicts():
    reg = MetricsRegistry()
    c = reg.counter("rxgb_test_total")
    assert reg.counter("rxgb_test_total") is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("rxgb_test_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad name!")


def test_prometheus_exposition_golden():
    """The exposition is byte-stable for a given registry state: metrics
    sorted by name, histogram buckets ascending and cumulative, counts as
    bare ints — the contract a scrape config and this golden pin rely on."""
    reg = MetricsRegistry()
    reg.counter("rxgb_b_total", "b help").inc(3)
    reg.gauge("rxgb_a").set(2.5)
    h = reg.histogram("rxgb_lat_ms")
    h.record(0.04)   # bucket 0 (le 0.05)
    h.record(0.06)   # bucket 1 (le 0.063)
    h.record(1e9)    # overflow (+Inf only)
    text = reg.prometheus_text()
    lines = text.splitlines()
    # deterministic name ordering: a, b, lat
    assert lines[0] == "# TYPE rxgb_a gauge"
    assert lines[1] == "rxgb_a 2.5"
    assert lines[2] == "# HELP rxgb_b_total b help"
    assert lines[3] == "# TYPE rxgb_b_total counter"
    assert lines[4] == "rxgb_b_total 3"
    assert lines[5] == "# TYPE rxgb_lat_ms histogram"
    assert lines[6] == 'rxgb_lat_ms_bucket{le="0.05"} 1'
    assert lines[7] == 'rxgb_lat_ms_bucket{le="0.063"} 2'
    # cumulative counts: every later bucket carries the running total
    assert 'rxgb_lat_ms_bucket{le="+Inf"} 3' in lines
    assert lines[-2] == "rxgb_lat_ms_sum 1000000000.1"
    assert lines[-1] == "rxgb_lat_ms_count 3"
    # bucket lines are sorted ascending by le
    les = [
        float(line.split('le="')[1].split('"')[0])
        for line in lines
        if 'le="' in line and "+Inf" not in line
    ]
    assert les == sorted(les)
    # a second render of the same state is byte-identical
    assert reg.prometheus_text() == text


def test_registry_snapshot_flattens_and_live_gauge():
    reg = MetricsRegistry()
    reg.counter("rxgb_c_total").inc(2)
    reg.gauge("rxgb_live", fn=lambda: 7)
    reg.histogram("rxgb_h_ms").record(3.0)
    snap = reg.snapshot()
    assert snap["rxgb_c_total"] == 2
    assert snap["rxgb_live"] == 7
    assert "counts" not in snap["rxgb_h_ms"]
    assert snap["rxgb_h_ms"]["total"] == 1
    # a dead live-gauge probe must not kill the export
    reg.gauge("rxgb_dead", fn=lambda: 1 / 0)
    assert np.isnan(reg.snapshot()["rxgb_dead"])
    assert "rxgb_dead NaN" in reg.prometheus_text()


# ---------------------------------------------------------------------------
# instrumentation contract: train() timeline, after_round, chaos story
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _fast_restarts(monkeypatch):
    monkeypatch.setenv("RXGB_RESTART_BACKOFF_BASE_S", "0")
    yield
    faults.clear_plan()


def test_train_returns_queryable_timeline():
    x, y = _data()
    res = {}
    train(_PARAMS, RayDMatrix(x, y), 3, additional_results=res,
          ray_params=RayParams(num_actors=2, checkpoint_frequency=2))
    o = res["obs"]
    assert validate_trace_records(o["timeline"]) == []
    assert o["dropped_spans"] == 0
    # one round record per boosting round, attributed with world/rows
    assert [r["round"] for r in o["rounds"]] == [0, 1, 2]
    assert all(r["world"] == 2 and r["rows"] == len(x) for r in o["rounds"])
    assert all(r["dur_s"] >= 0 for r in o["rounds"])
    # lifecycle events: checkpoint commits carry their round index
    ck = [e for e in o["events"] if e["name"] == "checkpoint.commit"]
    assert [e["round"] for e in ck] == [1, 2]
    # the attempt span closes over the whole run
    attempts = [r for r in o["timeline"]
                if r["kind"] == "span" and r["name"] == "attempt"]
    assert len(attempts) == 1
    assert attempts[0]["attrs"]["outcome"] == "ok"


def test_train_trace_disabled_omits_obs(monkeypatch):
    monkeypatch.setenv("RXGB_TRACE", "0")
    x, y = _data()
    res = {}
    train(_PARAMS, RayDMatrix(x, y), 2, additional_results=res,
          ray_params=RayParams(num_actors=2, checkpoint_frequency=0))
    assert "obs" not in res


def test_train_streams_per_rank_jsonl(monkeypatch, tmp_path):
    monkeypatch.setenv("RXGB_TRACE_DIR", str(tmp_path))
    x, y = _data()
    res = {}
    train(_PARAMS, RayDMatrix(x, y), 2, additional_results=res,
          ray_params=RayParams(num_actors=2, checkpoint_frequency=0))
    files = sorted(os.listdir(tmp_path))
    assert files == ["trace-rank0.jsonl"]
    streamed = [
        json.loads(line)
        for line in (tmp_path / "trace-rank0.jsonl").read_text().splitlines()
    ]
    assert validate_trace_records(streamed) == []
    names = {r["name"] for r in streamed}
    assert "round" in names


def test_after_round_callback_streams_round_records():
    class Collect(DistributedCallback):
        def __init__(self):
            self.records = []

        def after_round(self, actor, record, *args, **kwargs):
            self.records.append((actor.rank, record))

    cb = Collect()
    x, y = _data()
    dtrain = RayDMatrix(x, y)
    train(_PARAMS, dtrain, 3, evals=[(dtrain, "train")],
          ray_params=RayParams(num_actors=2, checkpoint_frequency=0,
                               distributed_callbacks=[cb]))
    # fan-out: one record per (round, actor)
    assert len(cb.records) == 3 * 2
    rounds_seen = sorted({rec["round"] for _, rec in cb.records})
    assert rounds_seen == [0, 1, 2]
    for rank, rec in cb.records:
        assert rec["world"] == 2
        assert rec["duration_s"] >= 0
        assert "logloss" in rec["metrics"]["train"]


def test_pre_obs_callbacks_without_after_round_still_work():
    """Duck-typed callbacks written against the original (pre-obs) hook
    surface — no after_round at all — must keep working through the
    container fan-out."""

    class Legacy:  # deliberately NOT a DistributedCallback subclass
        hooks = []

        def on_init(self, actor, *args, **kwargs):
            self.hooks.append("on_init")

        def before_data_loading(self, actor, data, *args, **kwargs):
            pass

        def after_data_loading(self, actor, data, *args, **kwargs):
            pass

        def before_train(self, actor, *args, **kwargs):
            pass

        def after_train(self, actor, result_dict, *args, **kwargs):
            self.hooks.append("after_train")

        def before_predict(self, actor, *args, **kwargs):
            pass

        def after_predict(self, actor, predictions, *args, **kwargs):
            pass

    x, y = _data()
    train(_PARAMS, RayDMatrix(x, y), 2,
          ray_params=RayParams(num_actors=2, checkpoint_frequency=0,
                               distributed_callbacks=[Legacy()]))
    assert "after_train" in Legacy.hooks


def test_chaos_shrink_grow_sequence_reconstructible_from_timeline(monkeypatch):
    """The acceptance scenario: kill → shrink → boundary grow leaves a
    machine-readable timeline — fault.injected, failure.detected,
    world.shrink and world.grow events in order with correct round
    indices — so the chaos story no longer needs driver logs or counter
    re-derivation."""
    monkeypatch.setenv("RXGB_ELASTIC_RESTART_RESOURCE_CHECK_S", "0")
    monkeypatch.setenv("RXGB_ELASTIC_RESTART_GRACE_PERIOD_S", "0")
    x, y = _data(512)
    kill_round = 3
    plan = faults.FaultPlan(rules=[
        {"site": "actor.train_round", "action": "raise", "ranks": [1],
         "match": {"round": kill_round}},
        # hold rank 1's reload past the scheduler's fast path so the world
        # actually shrinks, then grows back at a later round boundary
        {"site": "actor.load_shard", "action": "delay", "delay_s": 2.0,
         "match": {"rank": 1}, "at": 2},
    ])
    res = {}
    with faults.active_plan(plan):
        bst = train(_PARAMS, RayDMatrix(x, y), 16, additional_results=res,
                    ray_params=RayParams(num_actors=2, elastic_training=True,
                                         max_failed_actors=1,
                                         max_actor_restarts=2,
                                         checkpoint_frequency=4))
    assert bst.num_boosted_rounds() == 16
    o = res["obs"]
    assert validate_trace_records(o["timeline"]) == []

    by_name = {}
    for e in o["events"]:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["fault.injected"]) >= 1
    assert by_name["fault.injected"][0]["attrs"]["site"] == \
        "actor.train_round"
    (shrink,) = by_name["world.shrink"]
    (grow,) = by_name["world.grow"]
    # rounds 0..kill_round-1 boosted before the kill: the shrunk world takes
    # over AT the kill round; the grow lands at a later round boundary
    assert shrink["round"] == kill_round
    assert shrink["attrs"]["world"] == 1
    assert shrink["attrs"]["orphaned_rows"] == len(x) // 2
    assert grow["round"] > kill_round
    assert grow["attrs"]["world"] == 2
    # ordering: injection → detection → shrink → grow, by seq
    seqs = [
        by_name["fault.injected"][0]["seq"],
        by_name["failure.detected"][0]["seq"],
        shrink["seq"],
        grow["seq"],
    ]
    assert seqs == sorted(seqs)
    # per-round spans attribute the world size through the change: full
    # world before the kill, survivor world at the kill round, full world
    # again from the grow boundary on
    worlds = {r["round"]: r["world"] for r in o["rounds"]}
    assert worlds[kill_round - 1] == 2
    assert worlds[kill_round] == 1
    if grow["round"] < 16:
        assert worlds[grow["round"]] == 2
    # the timeline's failure→recovery clock matches the robustness dict's
    ttr = recovery_time_s(o["timeline"])
    assert ttr == pytest.approx(
        res["robustness"]["time_to_recover_s"], abs=0.05
    )
