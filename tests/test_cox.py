"""survival:cox objective tests (Breslow partial likelihood).

The reference gets Cox regression by passing ``objective="survival:cox"``
through to xgboost (``xgboost_ray/main.py:745-752``; negative labels =
right-censored). Here the risk sets span every mesh shard, so grad/hess
are computed from all_gathered rows inside the sharded step
(``ops/objectives.py cox_risk_terms``) — these tests pin the math against
an independent numpy likelihood, the censoring convention, tie handling,
and multi-actor model identity.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu.ops.objectives import get_objective

RP1 = RayParams(num_actors=1)
RP2 = RayParams(num_actors=2)


def _cox_nll_np(m, label, w):
    """Independent O(N^2) Breslow negative partial log-likelihood."""
    t = np.abs(label)
    delta = label > 0
    nll = 0.0
    for i in range(len(m)):
        if delta[i] and w[i] > 0:
            risk = t >= t[i]
            D = np.sum(w[risk] * np.exp(m[risk]))
            nll -= w[i] * (m[i] - np.log(D))
    return nll


def _surv_data(n=400, seed=0, censor=0.3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    hazard = np.exp(0.8 * x[:, 0] - 0.5 * x[:, 1])
    times = rng.exponential(1.0 / hazard).astype(np.float32) + 1e-3
    censored = rng.rand(n) < censor
    label = np.where(censored, -times, times).astype(np.float32)
    return x, label


def test_cox_grad_hess_matches_finite_difference():
    rng = np.random.RandomState(1)
    n = 40
    m = rng.randn(n).astype(np.float64) * 0.5
    t = rng.exponential(1.0, n) + 0.01
    label = np.where(rng.rand(n) < 0.3, -t, t)
    # duplicate some times to exercise tie-inclusive risk sets
    label[5] = label[7] = label[9]
    w = rng.uniform(0.5, 2.0, n)

    obj = get_objective("survival:cox")
    g, h = obj.grad_hess(
        jnp.asarray(m[:, None], jnp.float32), jnp.asarray(label, jnp.float32),
        jnp.asarray(w, jnp.float32),
    )
    g = np.asarray(g)[:, 0]
    h = np.asarray(h)[:, 0]

    eps = 1e-5
    for i in range(0, n, 3):
        mp, mm = m.copy(), m.copy()
        mp[i] += eps
        mm[i] -= eps
        num = (_cox_nll_np(mp, label, w) - _cox_nll_np(mm, label, w)) / (2 * eps)
        np.testing.assert_allclose(g[i], num, rtol=1e-3, atol=1e-4)
    assert (h > 0).all()


def test_cox_training_reduces_nloglik_and_orders_risk():
    x, label = _surv_data()
    dm = RayDMatrix(x, label)
    bst = train({"objective": "survival:cox", "max_depth": 3, "eta": 0.3},
                dm, 15, ray_params=RP2, evals=[(dm, "train")],
                evals_result=(res := {}))
    nll = res["train"]["cox-nloglik"]
    assert nll[-1] < nll[0], nll
    # predictions are hazard ratios: higher for the high-risk profile
    hr = bst.predict(np.array([[2.0, -2.0, 0, 0], [-2.0, 2.0, 0, 0]],
                              np.float32))
    assert hr[0] > hr[1]
    assert (hr > 0).all()  # hazard-ratio scale, exp transform


def test_cox_multi_actor_model_identity():
    x, label = _surv_data(seed=2)
    kw = {"objective": "survival:cox", "max_depth": 3, "eta": 0.3, "seed": 0}
    a = train(kw, RayDMatrix(x, label), 6, ray_params=RP1)
    b = train(kw, RayDMatrix(x, label), 6, ray_params=RP2)
    for field in ("feature", "split_bin", "is_leaf"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.forest, field)),
            np.asarray(getattr(b.forest, field)), err_msg=field,
        )
    np.testing.assert_allclose(
        a.predict(x, output_margin=True), b.predict(x, output_margin=True),
        atol=1e-5,
    )


def test_cox_censored_rows_shape_risk_but_not_events():
    """A heavily-censored copy of an event must change the likelihood only
    through the risk set: metric denominators count events only."""
    from xgboost_ray_tpu.ops.metrics import compute_metric

    m = np.array([0.5, -0.2, 0.1, 0.3], np.float32)
    label = np.array([1.0, 2.0, -3.0, -0.5], np.float32)  # 2 events
    v = compute_metric("cox-nloglik", m, label)
    want = _cox_nll_np(m.astype(np.float64), label, np.ones(4)) / 2.0
    np.testing.assert_allclose(v, want, rtol=1e-5)
