"""Monotone and interaction constraint tests.

The reference gets both constraints by forwarding the params dict to
xgboost's hist updater untouched (``xgboost_ray/main.py:745-752``); here they
are re-implemented inside the split scan (``ops/split.py`` bound-clamped
gains, ``ops/grow.py`` bound/allowed-set propagation), so these tests pin
the SEMANTICS: constrained models are actually monotone on adversarial
data, interaction-constrained trees never mix features across groups, and
the multi-actor model identity the engine guarantees elsewhere still holds.
"""

import numpy as np
import pytest

from xgboost_ray_tpu import RayDMatrix, RayParams, train

RP1 = RayParams(num_actors=1)
RP2 = RayParams(num_actors=2)


def _wiggle_data(seed=0, n=600):
    """y rises with x0 overall but has a strong LOCAL DIP (adversarial
    non-monotone signal) + a second informative feature."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(-2, 2, size=(n, 3)).astype(np.float32)
    dip = -1.6 * np.exp(-4.0 * (x[:, 0] - 0.5) ** 2)  # local reversal
    y = (0.8 * x[:, 0] + dip + 0.5 * x[:, 1]
         + 0.05 * rng.randn(n)).astype(np.float32)
    return x, y


def _grid_margins(bst, f, lo=-2, hi=2, k=64, bases=3, seed=1):
    """Margins along a grid in feature f with the other features frozen at a
    few random base rows -> [bases, k]."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(bases):
        base = rng.uniform(-2, 2, size=(3,)).astype(np.float32)
        g = np.tile(base, (k, 1))
        g[:, f] = np.linspace(lo, hi, k, dtype=np.float32)
        out.append(bst.predict(g, output_margin=True))
    return np.stack(out)


def _path_feature_sets(bst):
    """Distinct feature sets along every root->leaf path of every tree."""
    feat = np.asarray(bst.forest.feature)
    leaf = np.asarray(bst.forest.is_leaf)
    heap = feat.shape[1]
    sets = []
    for t in range(feat.shape[0]):
        stack = [(0, frozenset())]
        while stack:
            h, used = stack.pop()
            if leaf[t, h] or feat[t, h] < 0 or 2 * h + 2 >= heap:
                if used:
                    sets.append(used)
                continue
            u2 = used | {int(feat[t, h])}
            stack.append((2 * h + 1, u2))
            stack.append((2 * h + 2, u2))
    return sets


def test_unconstrained_model_is_not_monotone():
    """Sanity: the dip is strong enough that a free model learns it."""
    x, y = _wiggle_data()
    bst = train({"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
                 "seed": 0}, RayDMatrix(x, y), 20, ray_params=RP1)
    grids = _grid_margins(bst, 0)
    diffs = np.diff(grids, axis=1)
    assert diffs.min() < -0.05  # clearly decreasing somewhere


@pytest.mark.parametrize("sign", [1, -1])
def test_monotone_constraint_enforced(sign):
    x, y = _wiggle_data()
    if sign < 0:
        y = -y
    bst = train({"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
                 "monotone_constraints": f"({sign},0,0)", "seed": 0},
                RayDMatrix(x, y), 20, ray_params=RP2)
    grids = _grid_margins(bst, 0)
    diffs = np.diff(grids, axis=1) * sign
    assert diffs.min() >= -1e-4, diffs.min()
    # the constrained model still learns the global trend + free features
    pred = bst.predict(x)
    base = np.full_like(y, y.mean())
    assert np.mean((pred - y) ** 2) < 0.5 * np.mean((base - y) ** 2)


def test_monotone_string_and_tuple_forms_agree():
    x, y = _wiggle_data(seed=3)
    kw = {"objective": "reg:squarederror", "max_depth": 3, "eta": 0.4,
          "seed": 0}
    a = train(dict(kw, monotone_constraints="(1,0,0)"), RayDMatrix(x, y), 6,
              ray_params=RP1)
    b = train(dict(kw, monotone_constraints=[1, 0, 0]), RayDMatrix(x, y), 6,
              ray_params=RP1)
    np.testing.assert_allclose(a.predict(x), b.predict(x), atol=0)
    # short tuples pad with 0 (xgboost behavior)
    c = train(dict(kw, monotone_constraints=(1,)), RayDMatrix(x, y), 6,
              ray_params=RP1)
    np.testing.assert_allclose(a.predict(x), c.predict(x), atol=0)


def test_monotone_multi_actor_model_identity():
    """Bound propagation rides allreduced histograms only -> sharding must
    not change the model (the engine's world-size invariance)."""
    x, y = _wiggle_data(seed=4)
    kw = {"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
          "monotone_constraints": "(1,-1,0)", "seed": 0}
    a = train(kw, RayDMatrix(x, y), 8, ray_params=RP1)
    b = train(kw, RayDMatrix(x, y), 8, ray_params=RP2)
    # STRUCTURE is bit-identical across shardings; the float stat fields
    # (gain/cover) carry psum merge-order float32 noise, so they get rtol
    fa, fb = a.forest, b.forest
    for field in ("feature", "split_bin", "is_leaf", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fa, field)), np.asarray(getattr(fb, field)),
            err_msg=field,
        )
    for field in ("threshold", "value", "base_weight"):
        np.testing.assert_allclose(
            np.asarray(getattr(fa, field)), np.asarray(getattr(fb, field)),
            atol=1e-5, err_msg=field,
        )
    for field in ("gain", "cover"):
        np.testing.assert_allclose(
            np.asarray(getattr(fa, field)), np.asarray(getattr(fb, field)),
            rtol=1e-4, atol=1e-4, err_msg=field,
        )
    np.testing.assert_allclose(a.predict(x), b.predict(x), atol=1e-5)


def test_interaction_constraints_respected():
    """Groups ((0,1),(2,3),(4,)): every root->leaf path must keep its
    features inside ONE group (xgboost's cumulative active-set semantics)."""
    rng = np.random.RandomState(5)
    n = 800
    x = rng.uniform(-1, 1, size=(n, 5)).astype(np.float32)
    # cross-group products make violations profitable for a free model
    y = (x[:, 0] * x[:, 2] + x[:, 1] * x[:, 4] + 0.5 * x[:, 3]
         + 0.02 * rng.randn(n)).astype(np.float32)
    groups = [[0, 1], [2, 3], [4]]
    bst = train({"objective": "reg:squarederror", "max_depth": 5, "eta": 0.3,
                 "interaction_constraints": groups, "seed": 0},
                RayDMatrix(x, y), 15, ray_params=RP2)
    gsets = [frozenset(g) for g in groups]
    for path in _path_feature_sets(bst):
        assert any(path <= g for g in gsets), f"path {set(path)} crosses groups"
    # sanity: the free model DOES cross groups on this signal
    free = train({"objective": "reg:squarederror", "max_depth": 5,
                  "eta": 0.3, "seed": 0}, RayDMatrix(x, y), 15,
                 ray_params=RP2)
    assert any(
        not any(path <= g for g in gsets) for path in _path_feature_sets(free)
    )


def test_interaction_string_form_and_identity():
    rng = np.random.RandomState(6)
    x = rng.uniform(-1, 1, size=(400, 4)).astype(np.float32)
    y = (x[:, 0] * x[:, 1] + x[:, 2] + 0.02 * rng.randn(400)).astype(np.float32)
    kw = {"objective": "reg:squarederror", "max_depth": 4, "eta": 0.4,
          "seed": 0}
    a = train(dict(kw, interaction_constraints="[[0, 1], [2, 3]]"),
              RayDMatrix(x, y), 6, ray_params=RP1)
    b = train(dict(kw, interaction_constraints=((0, 1), (2, 3))),
              RayDMatrix(x, y), 6, ray_params=RP2)
    for fa, fb in zip(a.forest, b.forest):
        np.testing.assert_allclose(np.asarray(fa), np.asarray(fb), atol=1e-5)


def test_monotone_and_interaction_combined():
    x, y = _wiggle_data(seed=7)
    bst = train({"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
                 "monotone_constraints": "(1,0,0)",
                 "interaction_constraints": [[0, 1], [2]], "seed": 0},
                RayDMatrix(x, y), 12, ray_params=RP2)
    diffs = np.diff(_grid_margins(bst, 0), axis=1)
    assert diffs.min() >= -1e-4
    gsets = [frozenset(g) for g in [[0, 1], [2]]]
    for path in _path_feature_sets(bst):
        assert any(path <= g for g in gsets)


def test_constraint_validation_errors():
    x = np.random.RandomState(0).randn(50, 3).astype(np.float32)
    y = x[:, 0].astype(np.float32)
    with pytest.raises(ValueError, match="-1, 0, or"):
        train({"objective": "reg:squarederror",
               "monotone_constraints": "(2,0)"}, RayDMatrix(x, y), 1,
              ray_params=RP1)
    with pytest.raises(ValueError, match="dict-form"):
        train({"objective": "reg:squarederror",
               "monotone_constraints": {"f0": 1}}, RayDMatrix(x, y), 1,
              ray_params=RP1)
    with pytest.raises(ValueError, match="entries but the data"):
        train({"objective": "reg:squarederror",
               "monotone_constraints": "(1,0,0,0)"}, RayDMatrix(x, y), 1,
              ray_params=RP1)
    with pytest.raises(ValueError, match="feature indices"):
        train({"objective": "reg:squarederror",
               "interaction_constraints": [[0, 7]]}, RayDMatrix(x, y), 1,
              ray_params=RP1)
    xc = x.copy()
    xc[:, 2] = np.random.RandomState(1).randint(0, 4, 50)  # valid cat codes
    with pytest.raises(ValueError, match="no order to be monotone"):
        train({"objective": "reg:squarederror",
               "monotone_constraints": "(0,0,1)"},
              RayDMatrix(xc, y, feature_types=["q", "q", "c"]), 1,
              ray_params=RP1)
