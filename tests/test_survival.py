"""survival:aft objective tests (label-bounds path end-to-end)."""

import numpy as np
import pytest

from xgboost_ray_tpu import RayDMatrix, RayParams, train


def _survival_data(n=400, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    # true log-time depends on features
    log_t = 1.0 + 0.8 * x[:, 0] - 0.5 * x[:, 1] + 0.1 * rng.randn(n)
    t = np.exp(log_t).astype(np.float32)
    # right-censor 30% of rows at a random earlier time
    censored = rng.rand(n) < 0.3
    lower = t.copy()
    upper = t.copy()
    cens_time = (t * rng.uniform(0.3, 0.9, n)).astype(np.float32)
    lower[censored] = cens_time[censored]
    upper[censored] = np.inf
    return x, lower, upper, t


def test_aft_learns_survival_times():
    x, lower, upper, t = _survival_data()
    dtrain = RayDMatrix(x, label_lower_bound=lower, label_upper_bound=upper)
    evals_result = {}
    bst = train(
        {"objective": "survival:aft", "eval_metric": ["aft-nloglik"],
         "max_depth": 4, "eta": 0.3, "aft_loss_distribution": "normal",
         "aft_loss_distribution_scale": 1.0},
        dtrain, 30, evals=[(dtrain, "train")], evals_result=evals_result,
        ray_params=RayParams(num_actors=2),
    )
    nll = evals_result["train"]["aft-nloglik"]
    assert nll[-1] < nll[0]
    pred = bst.predict(x)  # predicted survival times (exp of margin)
    assert pred.shape == (400,)
    assert np.all(pred > 0)
    # predictions correlate with the true times
    corr = np.corrcoef(np.log(pred), np.log(t))[0, 1]
    assert corr > 0.8


def test_aft_with_dart_booster_computes_host_metric():
    """dart + survival:aft must use the same aft-nloglik host fallback as the
    regular step path (ADVICE r3: step_dart previously hit compute_metric
    directly and raised at metric time)."""
    x, lower, upper, _ = _survival_data(seed=3)
    dtrain = RayDMatrix(x, label_lower_bound=lower, label_upper_bound=upper)
    evals_result = {}
    bst = train(
        {"objective": "survival:aft", "booster": "dart", "rate_drop": 0.1,
         "eval_metric": ["aft-nloglik"], "max_depth": 3,
         "aft_loss_distribution": "normal", "aft_loss_distribution_scale": 1.0},
        dtrain, 8, evals=[(dtrain, "train")], evals_result=evals_result,
        ray_params=RayParams(num_actors=2),
    )
    nll = evals_result["train"]["aft-nloglik"]
    assert len(nll) == 8
    assert np.isfinite(nll).all()
    assert bst.num_boosted_rounds() == 8


def test_aft_logistic_distribution_runs():
    x, lower, upper, _ = _survival_data(seed=1)
    dtrain = RayDMatrix(x, label_lower_bound=lower, label_upper_bound=upper)
    bst = train(
        {"objective": "survival:aft", "aft_loss_distribution": "logistic",
         "eval_metric": ["aft-nloglik"], "max_depth": 3},
        dtrain, 10, ray_params=RayParams(num_actors=2),
    )
    assert bst.num_boosted_rounds() == 10


def test_aft_plain_label_is_uncensored():
    rng = np.random.RandomState(2)
    x = rng.randn(200, 3).astype(np.float32)
    t = np.exp(1.0 + x[:, 0]).astype(np.float32)
    dtrain = RayDMatrix(x, label=t)
    bst = train({"objective": "survival:aft", "eval_metric": ["aft-nloglik"]},
                dtrain, 15, ray_params=RayParams(num_actors=2))
    pred = bst.predict(x)
    assert np.corrcoef(np.log(pred), np.log(t))[0, 1] > 0.9


def test_gamma_and_tweedie_objectives():
    rng = np.random.RandomState(3)
    x = rng.randn(300, 3).astype(np.float32)
    mu = np.exp(0.5 + 0.8 * x[:, 0])
    y = (mu * rng.gamma(2.0, 0.5, 300)).astype(np.float32)
    for objective in ("reg:gamma", "reg:tweedie"):
        dtrain = RayDMatrix(x, y)
        bst = train({"objective": objective, "eval_metric": ["rmse"],
                     "max_depth": 3, "eta": 0.2},
                    dtrain, 20, ray_params=RayParams(num_actors=2))
        pred = bst.predict(x)
        assert np.all(pred > 0)
        assert np.corrcoef(np.log(pred), np.log(mu))[0, 1] > 0.8, objective


def test_aft_nloglik_device_contrib_matches_host():
    """Device (num, den) contribution == host scipy implementation across
    censoring kinds and both distributions (VERDICT r2 #6)."""
    import jax.numpy as jnp
    import numpy as np
    from xgboost_ray_tpu.ops.survival import aft_nloglik_contrib, aft_nloglik_np

    rng = np.random.RandomState(9)
    n = 400
    margin = rng.randn(n, 1).astype(np.float32)
    lower = np.exp(rng.randn(n).astype(np.float32))
    kind = rng.randint(0, 3, size=n)
    upper = np.where(
        kind == 0, lower,                      # uncensored
        np.where(kind == 1, np.inf, lower * 2.0)  # right- / interval-censored
    ).astype(np.float32)
    weight = rng.rand(n).astype(np.float32) + 0.5
    for dist in ("normal", "logistic"):
        for sigma in (1.0, 1.7):
            num, den = aft_nloglik_contrib(
                jnp.asarray(margin), jnp.asarray(lower), jnp.asarray(upper),
                jnp.asarray(weight), distribution=dist, sigma=sigma,
            )
            got = float(num) / float(den)
            want = aft_nloglik_np(margin, lower, upper, weight,
                                  distribution=dist, sigma=sigma)
            assert abs(got - want) < 5e-4 * max(1.0, abs(want)), (dist, sigma)


def test_aft_batches_rounds_with_device_metric():
    """survival:aft + aft-nloglik no longer forces per-round host stepping:
    the engine reports batchable and the scan path reproduces the per-round
    metric series."""
    import numpy as np
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.params import parse_params

    rng = np.random.RandomState(10)
    n = 600
    x = rng.randn(n, 4).astype(np.float32)
    t = np.exp(0.7 * x[:, 0] + 0.2 * rng.randn(n)).astype(np.float32)
    hi = np.where(rng.rand(n) < 0.3, np.inf, t).astype(np.float32)
    shards = [{"data": x, "label": None, "weight": None, "base_margin": None,
               "label_lower_bound": t, "label_upper_bound": hi, "qid": None}]
    params = parse_params({"objective": "survival:aft",
                           "eval_metric": ["aft-nloglik"], "max_depth": 3})
    eng = TpuEngine(shards, params, num_actors=2, evals=[(shards, "train")])
    assert eng.can_batch_rounds()
    assert eng._device_metrics == ["aft-nloglik"] and not eng._host_metrics
    batched = [r["train"]["aft-nloglik"] for r in eng.step_many(0, 5)]
    assert batched[-1] < batched[0]

    eng2 = TpuEngine(shards, params, num_actors=2, evals=[(shards, "train")])
    stepped = [eng2.step(i)["train"]["aft-nloglik"] for i in range(5)]
    np.testing.assert_allclose(batched, stepped, atol=1e-5)
