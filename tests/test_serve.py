"""Online serving subsystem (xgboost_ray_tpu/serve/).

Pins the three serving invariants the subsystem is built around:

(a) served predictions are BIT-IDENTICAL to the batch ``predict()`` path
    for every output kind served (padding rows cannot leak into real rows);
(b) steady-state traffic causes ZERO recompiles: after warmup, 100+
    mixed-size requests never trace a new program (compile counter);
(c) hot-swap under concurrent load drains in-flight batches and drops or
    mixes no responses — every response is wholly from the model version
    it reports.

All HTTP tests run against a loopback ThreadingHTTPServer on an ephemeral
port; everything runs on the hermetic 8-device CPU mesh from conftest.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu import serve
from xgboost_ray_tpu.serve.predictor import bucket_rows

RP = RayParams(num_actors=2)


def _train_binary(seed=0, eta=0.3, rounds=4):
    rng = np.random.RandomState(seed)
    x = rng.randn(300, 6).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    bst = train(
        {"objective": "binary:logistic", "max_depth": 3, "eta": eta,
         "seed": seed},
        RayDMatrix(x, y), rounds, ray_params=RP,
    )
    return bst, x


@pytest.fixture(scope="module")
def binary_model():
    return _train_binary(seed=0)


@pytest.fixture(scope="module")
def binary_model_b():
    # same shape (rounds/depth/features) as binary_model, different trees:
    # the retrain-and-swap shape, which must reuse every compiled program
    return _train_binary(seed=1, eta=0.05)


def _post(url, path, doc, timeout=30.0):
    req = urllib.request.Request(
        url + path, json.dumps(doc).encode("utf-8"),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, path, timeout=30.0):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------


def test_bucket_rows_pow2_and_mesh_multiple():
    assert bucket_rows(1, 8, 1) == 8
    assert bucket_rows(8, 8, 1) == 8
    assert bucket_rows(9, 8, 1) == 16
    assert bucket_rows(100, 8, 1) == 128
    assert bucket_rows(100, 8, 8) == 128
    # non-power-of-two mesh: rounded up to a device multiple
    assert bucket_rows(5, 1, 3) % 3 == 0
    assert bucket_rows(0, 1, 1) == 1


def test_bucket_rows_idempotent_and_warmup_covers_live_buckets():
    """On non-power-of-two device counts the bucket ladder must be
    idempotent, else warmup compiles buckets live requests never hit and
    the first post-swap request pays a compile on the serving path."""
    for n_dev in (1, 2, 3, 5, 7, 8):
        live = {bucket_rows(n, 8, n_dev) for n in range(1, 257)}
        assert all(bucket_rows(b, 8, n_dev) == b for b in live), n_dev
        assert all(b % n_dev == 0 for b in live), n_dev
        # the warmup enumeration (bucket + 1 stepping) hits exactly `live`
        warm, n, top = set(), 1, bucket_rows(256, 8, n_dev)
        while True:
            b = bucket_rows(n, 8, n_dev)
            warm.add(b)
            if b >= top:
                break
            n = b + 1
        assert warm == live, (n_dev, warm ^ live)


# ---------------------------------------------------------------------------
# (a) bit-identity vs the batch predict() path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_dev", [1, 8])
def test_served_bit_identical_to_batch_predict(binary_model, n_dev):
    bst, x = binary_model
    devices = jax.devices()[:n_dev] if n_dev > 1 else None
    pred = serve.CompiledPredictor(bst, devices=devices)
    q = x[:37]
    refs = {
        "value": bst.predict(q),
        "margin": bst.predict(q, output_margin=True),
        "leaf": bst.predict(q, pred_leaf=True),
        "contribs": bst.predict(q, pred_contribs=True),
    }
    for kind in serve.KINDS:
        got = pred.predict(q.astype(np.float32), kind)
        assert np.array_equal(np.asarray(got), np.asarray(refs[kind])), kind


def test_served_bit_identical_multiclass():
    rng = np.random.RandomState(3)
    x = rng.randn(240, 5).astype(np.float32)
    y = (np.abs(x[:, 0]) + x[:, 1] > 0.6).astype(np.float32) + (
        x[:, 2] > 0.8
    ).astype(np.float32)
    bst = train(
        {"objective": "multi:softprob", "num_class": 3, "max_depth": 3,
         "eta": 0.3, "seed": 0},
        RayDMatrix(x, y), 3, ray_params=RP,
    )
    pred = serve.CompiledPredictor(bst, devices=jax.devices())
    q = x[:21].astype(np.float32)
    assert np.array_equal(pred.predict(q, "value"), bst.predict(q))
    assert np.array_equal(
        pred.predict(q, "margin"), bst.predict(q, output_margin=True)
    )
    assert np.array_equal(
        pred.predict(q, "contribs"), bst.predict(q, pred_contribs=True)
    )


def test_served_bit_identical_through_http(binary_model):
    bst, x = binary_model
    h = serve.create_server(bst, max_batch=64, max_delay_ms=1.0)
    try:
        for kind in serve.KINDS:
            status, r = _post(
                h.url, "/predict", {"data": x[:9].tolist(), "kind": kind}
            )
            assert status == 200
            ref = {
                "value": bst.predict(x[:9]),
                "margin": bst.predict(x[:9], output_margin=True),
                "leaf": bst.predict(x[:9], pred_leaf=True),
                "contribs": bst.predict(x[:9], pred_contribs=True),
            }[kind]
            got = np.asarray(r["predictions"], np.asarray(ref).dtype)
            assert np.array_equal(got, np.asarray(ref)), kind
            assert r["model_version"] == 1
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# (b) zero recompiles in steady state
# ---------------------------------------------------------------------------


def test_zero_recompiles_after_warmup(binary_model):
    bst, x = binary_model
    pred = serve.CompiledPredictor(bst, devices=jax.devices())
    warmed = pred.warmup(kinds=serve.KINDS, max_batch=64)
    assert warmed > 0  # fresh model: warmup really compiled something
    rng = np.random.RandomState(0)
    c0 = serve.compile_count()
    kinds = list(serve.KINDS)
    for i in range(120):  # >= 100 mixed-size requests across all kinds
        n = int(rng.randint(1, 65))
        pred.predict(x[:n].astype(np.float32), kinds[i % len(kinds)])
    assert serve.compile_count() == c0


def test_same_shape_hot_swap_reuses_programs(binary_model, binary_model_b):
    bst_a, x = binary_model
    bst_b, _ = binary_model_b
    assert bst_a.signature() == bst_b.signature()
    reg = serve.ModelRegistry(devices=jax.devices(), warm_kinds=("value",),
                              warm_max_batch=32)
    reg.load(bst_a)
    c0 = serve.compile_count()
    reg.load(bst_b)  # same signature: warmup must hit the cached programs
    assert serve.compile_count() == c0
    with reg.lease() as entry:
        got = entry.predictor.predict(x[:7].astype(np.float32), "value")
    assert np.array_equal(got, bst_b.predict(x[:7]))


# ---------------------------------------------------------------------------
# microbatching
# ---------------------------------------------------------------------------


def test_microbatcher_coalesces_concurrent_requests(binary_model):
    bst, x = binary_model
    metrics = serve.ServeMetrics()
    reg = serve.ModelRegistry(warm_kinds=("value",), warm_max_batch=64)
    reg.load(bst)
    batcher = serve.MicroBatcher(reg, max_batch=64, max_delay_ms=50.0,
                                 metrics=metrics)
    try:
        results = [None] * 8
        barrier = threading.Barrier(8)

        def client(i):
            barrier.wait()
            results[i] = batcher.submit(x[i * 3 : i * 3 + 3], "value")

        threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        for i, (out, version) in enumerate(results):
            assert version == 1
            assert np.array_equal(out, bst.predict(x[i * 3 : i * 3 + 3]))
        snap = metrics.snapshot()
        assert snap["requests"] == 8
        # 8 near-simultaneous requests within one 50 ms window must coalesce
        assert snap["batches"] < 8
        assert snap["mean_batch_rows"] > 3
    finally:
        batcher.shutdown()


def test_oversized_request_flushes_alone(binary_model):
    bst, x = binary_model
    reg = serve.ModelRegistry(warm_kinds=())
    reg.load(bst, warm=False)
    batcher = serve.MicroBatcher(reg, max_batch=16, max_delay_ms=1.0)
    try:
        out, _ = batcher.submit(x[:100], "value")  # > max_batch rows
        assert np.array_equal(out, bst.predict(x[:100]))
    finally:
        batcher.shutdown()


def test_padding_waste_accounting(binary_model):
    bst, x = binary_model
    metrics = serve.ServeMetrics()
    reg = serve.ModelRegistry(warm_kinds=())
    reg.load(bst, warm=False)
    batcher = serve.MicroBatcher(reg, max_batch=64, max_delay_ms=1.0,
                                 metrics=metrics)
    try:
        batcher.submit(x[:5], "value")  # bucket 8 -> 3 padded rows
        snap = metrics.snapshot()
        assert snap["batches"] == 1
        assert snap["padding_waste"] == pytest.approx(3 / 8)
    finally:
        batcher.shutdown()


# ---------------------------------------------------------------------------
# (c) hot-swap under concurrent load
# ---------------------------------------------------------------------------


def test_hot_swap_under_load_no_dropped_or_mixed(binary_model, binary_model_b):
    bst_a, x = binary_model
    bst_b, _ = binary_model_b
    q = x[:4]
    ref = {1: bst_a.predict(q), 2: bst_b.predict(q)}
    h = serve.create_server(bst_a, max_batch=32, max_delay_ms=1.0)
    errors, responses = [], []
    resp_lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                status, r = _post(h.url, "/predict", {"data": q.tolist()})
                with resp_lock:
                    responses.append((status, r["model_version"],
                                      np.asarray(r["predictions"])))
            except Exception as exc:  # noqa: BLE001 - recorded as failure
                with resp_lock:
                    errors.append(repr(exc))

    threads = [threading.Thread(target=client) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        v2 = h.registry.load(bst_b)  # drains in-flight, then flips
        assert v2 == 2
        time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(30.0)
        h.shutdown()
    assert not errors, errors[:3]  # nothing dropped
    assert len(responses) > 10
    versions = {v for _, v, _ in responses}
    assert versions <= {1, 2} and 2 in versions
    for status, v, pred in responses:  # nothing mixed: bitwise per version
        assert status == 200
        assert np.array_equal(pred.astype(np.float32),
                              ref[v].astype(np.float32)), v


def test_canary_rollback_then_promote_under_load(binary_model):
    """Satellite acceptance (serving scale-out PR): the canary gate in the
    hot-swap-hammer loop. A regressing candidate publish rolls back
    automatically — the old version keeps serving BIT-IDENTICALLY for
    every concurrent request — then a passing warm-start refresh flips
    with zero dropped requests."""
    bst, x = binary_model
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    rng = np.random.RandomState(7)
    bad = train(  # trained on shuffled labels: must fail the logloss gate
        {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
         "seed": 7},
        RayDMatrix(x, rng.permutation(y)), 4, ray_params=RP,
    )
    good = serve.refresh(  # 2 more rounds warm-started from the live model
        bst, {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
              "seed": 0},
        RayDMatrix(x, y), 2, ray_params=RP,
    )
    q = x[:4]
    ref = {1: bst.predict(q), 2: good.predict(q)}
    h = serve.create_server(bst, max_batch=32, max_delay_ms=1.0)
    ctl = serve.CanaryController(h.registry, metrics=h.metrics)
    errors, responses = [], []
    resp_lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                status, r = _post(h.url, "/predict", {"data": q.tolist()})
                with resp_lock:
                    responses.append((status, r["model_version"],
                                      np.asarray(r["predictions"])))
            except Exception as exc:  # noqa: BLE001 - recorded as failure
                with resp_lock:
                    errors.append(repr(exc))

    threads = [threading.Thread(target=client) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        verdict = ctl.publish(bad, x[:100], y[:100], shadow_x=x[:16])
        assert verdict["promoted"] is False
        assert verdict["reason"] == "metric_regression"
        assert h.registry.version == 1  # rollback = the flip never happened
        time.sleep(0.3)
        with resp_lock:
            n_before_promote = len(responses)
        verdict = ctl.publish(good, x[:100], y[:100])
        assert verdict["promoted"] is True and verdict["version"] == 2
        time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(30.0)
        h.shutdown()
    assert not errors, errors[:3]  # zero drops through both publishes
    assert len(responses) > n_before_promote > 10
    # every response between the rollback and the promote was version 1 —
    # the bad candidate never served a single request
    versions = [v for _, v, _ in responses]
    assert set(versions) <= {1, 2} and 2 in versions
    assert set(versions[:n_before_promote]) == {1}
    for status, v, pred in responses:  # bitwise per reported version
        assert status == 200
        assert np.array_equal(pred.astype(np.float32),
                              ref[v].astype(np.float32)), v
    snap = h.metrics.snapshot()
    assert snap["canary_rollbacks"] == 1 and snap["canary_promotions"] == 1


def test_http_handlers_concurrent_with_hot_swap(binary_model, binary_model_b):
    """Satellite acceptance (rxgbrace PR): /predict, /metrics and /healthz
    all running concurrently with registry hot-swaps — no request may ever
    observe a half-swapped model: every /predict response's predictions are
    bitwise those of the version it reports, /healthz always reports a
    committed version (never a mid-drain intermediate), and /metrics stays
    servable and internally consistent throughout."""
    bst_a, x = binary_model
    bst_b, _ = binary_model_b
    q = x[:3]
    ref = {}  # committed version -> expected predictions
    h = serve.create_server(bst_a, max_batch=32, max_delay_ms=1.0)
    ref[1] = bst_a.predict(q)
    errors, preds, healths, metrics = [], [], [], []
    lock = threading.Lock()
    stop = threading.Event()

    def predict_client():
        while not stop.is_set():
            try:
                status, r = _post(h.url, "/predict", {"data": q.tolist()})
                with lock:
                    preds.append((status, r["model_version"],
                                  np.asarray(r["predictions"])))
            except Exception as exc:  # noqa: BLE001 - recorded
                with lock:
                    errors.append(("predict", repr(exc)))

    def health_client():
        while not stop.is_set():
            try:
                status, r = _get(h.url, "/healthz")
                with lock:
                    healths.append((status, r))
            except Exception as exc:  # noqa: BLE001 - recorded
                with lock:
                    errors.append(("healthz", repr(exc)))

    def metrics_client():
        while not stop.is_set():
            try:
                status, r = _get(h.url, "/metrics")
                with lock:
                    metrics.append((status, r))
            except Exception as exc:  # noqa: BLE001 - recorded
                with lock:
                    errors.append(("metrics", repr(exc)))

    threads = [
        threading.Thread(target=predict_client),
        threading.Thread(target=predict_client),
        threading.Thread(target=health_client),
        threading.Thread(target=metrics_client),
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(0.2)
        # two hot-swaps under sustained mixed traffic (A -> B -> A shape:
        # same buckets, different trees)
        assert h.registry.load(bst_b) == 2
        ref[2] = bst_b.predict(q)
        time.sleep(0.2)
        assert h.registry.load(bst_a) == 3
        ref[3] = ref[1]
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(30.0)
        h.shutdown()
    assert not errors, errors[:3]
    assert len(preds) > 10 and len(healths) > 3 and len(metrics) > 3
    seen_versions = {v for _, v, _ in preds}
    assert seen_versions <= {1, 2, 3} and len(seen_versions) >= 2
    for status, version, got in preds:
        # the half-swap pin: the response is wholly from the version it
        # reports — bitwise equal to that committed model's predictions
        assert status == 200
        assert np.array_equal(
            got.astype(np.float32), ref[version].astype(np.float32)
        ), f"half-swapped response for v{version}"
    for status, doc in healths:
        assert status == 200, doc
        assert doc["status"] == "ok"
        assert doc["model_version"] in (1, 2, 3), (
            f"/healthz reported uncommitted version: {doc}"
        )
    swaps_seen = 0
    for status, doc in metrics:
        assert status == 200
        # every successful /predict records requests+=1 and rows+=3 under
        # ONE lock, and snapshot() cuts under the same lock: any mid-run
        # snapshot must see them exactly in lockstep
        assert doc["rows"] == doc["requests"] * 3, f"torn counters: {doc}"
        swaps_seen = max(swaps_seen, doc["model_swaps"])
    assert swaps_seen <= 2  # two live swaps (initial load is not a swap)


# ---------------------------------------------------------------------------
# registry loading surfaces
# ---------------------------------------------------------------------------


def test_registry_loads_checkpoint_path_and_xgb_json(binary_model, tmp_path):
    bst, x = binary_model
    native = tmp_path / "model.json"
    bst.save_model(str(native))
    xgb_json = bst.export_xgboost_json()

    reg = serve.ModelRegistry(warm_kinds=())
    v1 = reg.load(str(native), warm=False)  # native checkpoint path
    with reg.lease() as entry:
        got = entry.predictor.predict(x[:6].astype(np.float32), "value")
    assert np.allclose(got, bst.predict(x[:6]), atol=1e-6)

    v2 = reg.load(xgb_json, warm=False)  # xgboost JSON document string
    assert v2 == v1 + 1
    with reg.lease() as entry:
        got = entry.predictor.predict(x[:6].astype(np.float32), "margin")
    assert np.allclose(got, bst.predict(x[:6], output_margin=True), atol=1e-5)

    import pickle

    v3 = reg.load(pickle.dumps(bst), warm=False)  # checkpoint bytes
    assert v3 == v2 + 1


def test_serve_contribs_rejects_pre_stats_model(binary_model):
    """A model without per-node stats must error on served contribs (as
    the batch path does), never 200 with all-zero SHAP values."""
    import copy

    bst, x = binary_model
    old = copy.deepcopy(bst)
    old._has_node_stats = False  # what _from_dict sets for pre-stats saves
    pred = serve.CompiledPredictor(old)
    with pytest.raises(ValueError, match="contributions"):
        pred.predict(x[:4].astype(np.float32), "contribs")
    # other kinds still serve
    assert np.array_equal(pred.predict(x[:4].astype(np.float32), "value"),
                          old.predict(x[:4]))


def test_registry_rejects_gblinear():
    from xgboost_ray_tpu.linear import RayLinearBooster

    rng = np.random.RandomState(0)
    x = rng.randn(200, 4).astype(np.float32)
    y = x[:, 0].astype(np.float32)
    bst = train(
        {"objective": "reg:squarederror", "booster": "gblinear", "eta": 0.5},
        RayDMatrix(x, y), 3, ray_params=RP,
    )
    assert isinstance(bst, RayLinearBooster)
    reg = serve.ModelRegistry(warm_kinds=())
    with pytest.raises(TypeError, match="gblinear"):
        reg.load(bst, warm=False)


def test_batch_feature_mismatch_fails_only_bad_requests(binary_model):
    """A request whose width doesn't match the leased model (e.g. a
    hot-swap raced the HTTP-level check) fails alone; the rest of its
    batch still gets served."""
    bst, x = binary_model
    reg = serve.ModelRegistry(warm_kinds=())
    reg.load(bst, warm=False)
    batcher = serve.MicroBatcher(reg, max_batch=64, max_delay_ms=30.0)
    try:
        results = {}
        errors = {}
        barrier = threading.Barrier(3)

        def good(i):
            barrier.wait()
            results[i] = batcher.submit(x[i * 2 : i * 2 + 2], "value")

        def bad():
            barrier.wait()
            try:
                batcher.submit(x[:2, :4], "value")  # wrong feature count
            except ValueError as exc:
                errors["bad"] = str(exc)

        threads = [threading.Thread(target=good, args=(i,)) for i in range(2)]
        threads.append(threading.Thread(target=bad))
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert "feature shape mismatch" in errors["bad"]
        for i in range(2):
            out, _ = results[i]
            assert np.array_equal(out, bst.predict(x[i * 2 : i * 2 + 2]))
    finally:
        batcher.shutdown()


def test_train_rejects_gblinear_serve_registry_before_training():
    """The unservable-booster check must fire BEFORE boosting, not after."""
    rng = np.random.RandomState(0)
    x = rng.randn(100, 4).astype(np.float32)
    y = x[:, 0].astype(np.float32)
    with pytest.raises(ValueError, match="gblinear"):
        train(
            {"objective": "reg:squarederror", "booster": "gblinear"},
            RayDMatrix(x, y), 2, ray_params=RP,
            serve_registry=serve.ModelRegistry(),
        )


def test_train_publishes_into_serve_registry():
    rng = np.random.RandomState(2)
    x = rng.randn(200, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    reg = serve.ModelRegistry(warm_kinds=())
    extra = {}
    bst = train(
        {"objective": "binary:logistic", "max_depth": 2, "eta": 0.3},
        RayDMatrix(x, y), 2, ray_params=RP, serve_registry=reg,
        additional_results=extra,
    )
    assert reg.version == 1
    assert extra["serve_model_version"] == 1
    with reg.lease() as entry:
        got = entry.predictor.predict(x[:5], "value")
    assert np.array_equal(got, bst.predict(x[:5]))


# ---------------------------------------------------------------------------
# HTTP surface: health, metrics, errors
# ---------------------------------------------------------------------------


def test_healthz_and_metrics_endpoints(binary_model):
    bst, x = binary_model
    h = serve.ServeHandle(max_batch=32, max_delay_ms=1.0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(h.url, "/healthz")
        assert ei.value.code == 503  # no model yet
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(h.url, "/predict", {"data": x[:2].tolist()})
        assert ei.value.code == 503

        h.registry.load(bst, warm=False)
        status, doc = _get(h.url, "/healthz")
        assert (status, doc["status"]) == (200, "ok")

        for _ in range(5):
            _post(h.url, "/predict", {"data": x[:4].tolist()})
        status, m = _get(h.url, "/metrics")
        assert status == 200
        for key in ("qps", "queue_depth", "latency_p50_ms", "latency_p95_ms",
                    "latency_p99_ms", "padding_waste", "recompile_count",
                    "requests", "batches", "model_swaps"):
            assert key in m, key
        assert m["requests"] == 5
        assert m["rows"] == 20
        assert 0.0 <= m["padding_waste"] < 1.0
        assert m["latency_p99_ms"] >= m["latency_p50_ms"] > 0.0
    finally:
        h.shutdown()


def test_http_error_codes(binary_model):
    bst, x = binary_model
    h = serve.create_server(bst, max_batch=32, max_delay_ms=1.0)
    try:
        for doc, frag in [
            ({"data": x[:2, :3].tolist()}, "shape mismatch"),
            ({"data": x[:2].tolist(), "kind": "nope"}, "output kind"),
            ({}, "missing 'data'"),
        ]:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(h.url, "/predict", doc)
            assert ei.value.code == 400
            assert frag in json.loads(ei.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(h.url, "/nope")
        assert ei.value.code == 404
    finally:
        h.shutdown()


def test_http_hot_swap_endpoint(binary_model, binary_model_b, tmp_path):
    bst_a, x = binary_model
    bst_b, _ = binary_model_b
    path = tmp_path / "next.json"
    bst_b.save_model(str(path))
    h = serve.create_server(bst_a, max_batch=32, max_delay_ms=1.0)
    try:
        status, r = _post(h.url, "/models", {"path": str(path)})
        assert (status, r["model_version"]) == (200, 2)
        status, r = _post(h.url, "/predict", {"data": x[:3].tolist()})
        assert r["model_version"] == 2
        assert np.array_equal(
            np.asarray(r["predictions"], np.float32), bst_b.predict(x[:3])
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(h.url, "/models", {"path": str(tmp_path / "missing.json")})
        assert ei.value.code == 400
    finally:
        h.shutdown()
