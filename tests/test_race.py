"""rxgbrace: instrumentation, vector-clock/lockset detector, deterministic
schedule explorer, shipped scenarios, catalog cross-check, SARIF golden.

The heavyweight scenarios (batcher, tracer — ~1100/~750 schedules) are
exercised by the ``python -m tools.rxgbrace`` CI gate in run_ci_tests.sh;
the pytest tier keeps the fast subset so the suite stays quick while every
scenario still runs clean somewhere in tier-1.
"""

import ast
import json
import os
import textwrap
import threading
import time

import pytest

from tools.rxgbrace import RACE_RULES
from tools.rxgbrace.detector import detect, race003_findings
from tools.rxgbrace.events import Recorder
from tools.rxgbrace.explore import (
    events_digest,
    explore,
    fingerprint_of,
    parse_fingerprint,
    replay,
    run_scenario,
)
from tools.rxgbrace.instrument import Instrumentation, resolve_catalog_classes
from tools.rxgbrace.scenarios import SCENARIOS, Scenario, by_name


# ---------------------------------------------------------------------------
# satellite: the lock-owning-class catalog is ONE list shared by both tools
# ---------------------------------------------------------------------------


def test_lock_owning_catalog_contents():
    from tools.rxgblint import catalog

    recs = {r.qualname: r for r in catalog.lock_owning_classes()}
    expected = {
        "FaultPlan", "Tracer", "MicroBatcher", "ModelRegistry",
        "ServeMetrics", "Counter", "Gauge", "LatencyHistogram",
        "MetricsRegistry", "PendingActor",
    }
    assert expected <= set(recs), sorted(recs)
    assert dict(recs["ModelRegistry"].locks) == {
        "_cond": "condition", "_load_lock": "lock",
    }
    assert dict(recs["MicroBatcher"].locks) == {"_cond": "condition"}
    # the PR's race fix: PendingActor is now catalogued with its guarded set
    assert set(recs["PendingActor"].shared) == {"_ready_at", "_error"}
    assert "_swapping" in recs["ModelRegistry"].shared
    assert "_seq" in recs["Tracer"].shared


def test_catalog_agreement_lint_vs_instrumenter():
    """Cross-check: rxgblint's LOCK001 and rxgbrace's instrumenter must
    agree on which classes own locks — structurally (LOCK001 delegates to
    the same extraction) AND at runtime (every record resolves to a real
    class of the same name, with no import errors)."""
    from tools.rxgblint import catalog, rules

    # AST side: LOCK001's per-class extraction == the catalog's
    for path in catalog._package_files(catalog.REPO_ROOT):
        with open(path, "r", encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                assert rules._lock_attrs_of_class(node) == set(
                    catalog.lock_attr_kinds(node)
                ), f"{path}:{node.name}"
    # runtime side: the instrumenter resolves the identical list
    pairs, errors = resolve_catalog_classes()
    assert errors == []
    resolved = {cls.__qualname__ for cls, _ in pairs}
    assert resolved == {r.qualname for r in catalog.lock_owning_classes()}


# ---------------------------------------------------------------------------
# instrumentation + detector (record-only mode)
# ---------------------------------------------------------------------------


class _Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def bump_bare(self):
        self._n += 1


def _two_threads(*targets):
    ts = [
        threading.Thread(target=t, name=f"t{i}")
        for i, t in enumerate(targets)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_record_only_capture_and_locksets():
    rec = Recorder()
    with Instrumentation(recorder=rec, classes=[(_Guarded, ["_n"])]):
        g = _Guarded()
        g.bump()
    ops = [(e.op, e.obj, e.attr) for e in rec.snapshot()]
    assert ("acquire", "Lock#1", "") in ops
    assert ("release", "Lock#1", "") in ops
    reads = [e for e in rec.snapshot() if e.op == "read" and e.attr == "_n"]
    writes = [e for e in rec.snapshot() if e.op == "write" and e.attr == "_n"]
    assert reads and writes
    # the += under the lock carries the held lockset
    assert writes[-1].locks == ("Lock#1",)


def test_race001_true_positive_and_clean_negative():
    rec = Recorder()
    with Instrumentation(recorder=rec, classes=[(_Guarded, ["_n"])]):
        g = _Guarded()
        _two_threads(g.bump, g.bump_bare)
    races = [f for f in detect(rec.snapshot()) if f.rule == "RACE001"]
    assert races and "_n" in races[0].message

    rec2 = Recorder()
    with Instrumentation(recorder=rec2, classes=[(_Guarded, ["_n"])]):
        g = _Guarded()
        _two_threads(g.bump, g.bump)
    assert detect(rec2.snapshot()) == []


def test_race001_fork_join_edges_order_accesses():
    """__init__ writes by the parent are fork-ordered before child reads;
    a parent write AFTER forking (without joining first) races."""
    rec = Recorder()
    with Instrumentation(recorder=rec, classes=[(_Guarded, ["_n"])]):
        g = _Guarded()  # parent writes _n = 0
        t = threading.Thread(target=g.bump_bare, name="child")
        t.start()
        t.join()
        g.bump_bare()  # ordered AFTER the join: no race either
    assert detect(rec.snapshot()) == []

    rec2 = Recorder()
    with Instrumentation(recorder=rec2, classes=[(_Guarded, ["_n"])]):
        g = _Guarded()
        t = threading.Thread(target=g.bump_bare, name="child")
        t.start()
        g.bump_bare()  # concurrent with the child: races
        t.join()
    assert any(f.rule == "RACE001" for f in detect(rec2.snapshot()))


def test_race001_event_edge_orders_handoff():
    """producer-write -> Event.set -> consumer-wait -> consumer-read is the
    batcher's result-handoff pattern; the set->wait edge must order it."""
    rec = Recorder()
    with Instrumentation(recorder=rec, classes=[(_Guarded, ["_n"])]):
        g = _Guarded()
        done = threading.Event()

        def producer():
            g.bump_bare()
            done.set()

        def consumer():
            done.wait()
            assert g._n == 1

        _two_threads(producer, consumer)
    assert detect(rec.snapshot()) == []


def test_race002_lock_order_inversion_and_clean():
    rec = Recorder()
    with Instrumentation(recorder=rec, classes=None):
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        # run sequentially: the cycle is detected from the GRAPH, not from
        # an actual deadlock occurring
        t = threading.Thread(target=ab, name="x")
        t.start()
        t.join()
        t = threading.Thread(target=ba, name="y")
        t.start()
        t.join()
    races = [f for f in detect(rec.snapshot()) if f.rule == "RACE002"]
    assert races and "inversion cycle" in races[0].message

    rec2 = Recorder()
    with Instrumentation(recorder=rec2, classes=None):
        a, b = threading.Lock(), threading.Lock()

        def ab2():
            with a:
                with b:
                    pass

        for _ in range(2):
            t = threading.Thread(target=ab2, name="z")
            t.start()
            t.join()
    assert [f for f in detect(rec2.snapshot()) if f.rule == "RACE002"] == []


# ---------------------------------------------------------------------------
# RACE003 (static)
# ---------------------------------------------------------------------------


def _fixture_pkg(tmp_path, source: str) -> str:
    pkg = tmp_path / "xgboost_ray_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return str(tmp_path)


def test_race003_wait_outside_loop(tmp_path):
    root = _fixture_pkg(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cond = threading.Condition(threading.Lock())
                self._items = []

            def bad_get(self):
                with self._cond:
                    if not self._items:
                        self._cond.wait()   # planted: if, not while
                    return self._items.pop()

            def good_get(self):
                with self._cond:
                    while not self._items:
                        self._cond.wait()
                    return self._items.pop()
    """)
    fs = race003_findings(root)
    assert len(fs) == 1 and fs[0].rule == "RACE003"
    assert "bad_get" in fs[0].message and "_cond" in fs[0].message


def test_race003_shipped_package_clean():
    assert race003_findings() == []


# ---------------------------------------------------------------------------
# scheduler + explorer
# ---------------------------------------------------------------------------


class _Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1


def _toy_scenario():
    def body(ctx):
        b = ctx.box = _Box()
        _two_threads(b.bump, b.bump)

    def inv(ctx):
        # explicit raise: pytest's assert-rewrite would embed object reprs
        # (memory addresses) in the message and break stable failure dedup
        if ctx.box._n != 2:
            raise AssertionError(f"lost update: {ctx.box._n}")

    return Scenario("toy", "toy", body, inv, classes=[(_Box, ["_n"])])


def test_explorer_exhaustive_and_deterministic():
    scn = _toy_scenario()
    res = explore(scn)
    assert res.clean and res.schedules >= 2 and not res.truncated
    r1 = run_scenario(scn, [1])
    r2 = run_scenario(scn, [1])
    assert events_digest(r1.events) == events_digest(r2.events)


class _ClaimFlag:
    """check-then-act across two critical sections: a classic TOCTOU only
    visible to interleaving exploration (each section alone is guarded, so
    no data race exists for the detector)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._claimed = False

    def try_claim(self) -> bool:
        with self._lock:
            c = self._claimed
        if not c:
            with self._lock:
                self._claimed = True
            return True
        return False


def _claim_scenario():
    def body(ctx):
        f = ctx.flag = _ClaimFlag()
        ctx.w0 = False
        ctx.w1 = False

        def worker(tag):
            if f.try_claim():
                setattr(ctx, tag, True)

        _two_threads(lambda: worker("w0"), lambda: worker("w1"))

    def inv(ctx):
        if ctx.w0 + ctx.w1 != 1:  # explicit raise: stable message for dedup
            raise AssertionError(f"double claim: {ctx.w0}, {ctx.w1}")

    return Scenario("claim", "x", body, inv, classes=[(_ClaimFlag, ["_claimed"])])


def test_explorer_finds_toctou_and_replays_bit_identically():
    scn = _claim_scenario()
    res = explore(scn)
    fails = [f for f in res.failures if f.kind == "invariant"]
    assert fails, "the double-claim schedule was not found"
    fp = fails[0].fingerprint
    name, forced = parse_fingerprint(fp)
    assert name == "claim" and forced
    assert fingerprint_of(name, forced) == fp
    r1 = replay(scn, fp)
    r2 = replay(scn, fp)
    assert r1.invariant_error and r2.invariant_error == r1.invariant_error
    assert events_digest(r1.events) == events_digest(r2.events)


def test_pruning_preserves_findings():
    scn = _claim_scenario()
    pruned = explore(scn, prune=True)
    full = explore(scn, prune=False)
    get = lambda r: {f.detail for f in r.failures if f.kind == "invariant"}
    assert get(pruned) == get(full) != set()
    assert full.schedules >= pruned.schedules
    assert pruned.pruned > 0


def test_explorer_detects_real_deadlock():
    def body(ctx):
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        _two_threads(ab, ba)

    scn = Scenario("dl", "x", body, lambda ctx: None, classes=None)
    res = explore(scn)
    assert any(f.kind == "deadlock" for f in res.failures)
    assert any(f.rule == "RACE002" for f in res.races)


# ---------------------------------------------------------------------------
# shipped scenarios (fast subset; the CLI gate runs all seven)
# ---------------------------------------------------------------------------

_FAST_SCENARIOS = (
    "registry_hot_swap",
    "ckpt_writer_commit_vs_restart",
    "faultplan_fire_vs_reset",
    "metrics_record_vs_render",
    "elastic_pending_load_vs_poll",
    # ~60 s to explore exhaustively (3 threads); listed in slow_tests.txt
    "domain_death_coalesce_vs_grow_poll",
)


@pytest.mark.parametrize("name", _FAST_SCENARIOS)
def test_shipped_scenario_explores_clean(name):
    res = explore(by_name(name))
    assert not res.truncated, "scenario outgrew its exhaustiveness cap"
    assert res.schedules >= 1
    assert res.failures == [], [
        (f.kind, f.fingerprint, f.detail) for f in res.failures
    ]
    assert res.races == [], [f.render() for f in res.races]


def test_scenario_suite_covers_six_plus():
    assert len(SCENARIOS) >= 6
    assert len({s.name for s in SCENARIOS}) == len(SCENARIOS)


class _OldPendingActor:
    """Replica of the PRE-FIX elastic.PendingActor hot path: ready_at
    written by the load thread, polled by the driver, no lock — pins that
    rxgbrace catches exactly the shipped bug this PR fixed."""

    def __init__(self):
        self._lock = threading.Lock()  # existed, but the hot path skipped it
        self._ready_at = None

    def mark_ready_bare(self):
        self._ready_at = time.time()

    @property
    def ready(self):
        return self._ready_at is not None


def test_prefix_pendingactor_shape_is_flagged():
    def body(ctx):
        p = _OldPendingActor()

        def loader():
            p.mark_ready_bare()

        def driver():
            ctx.outs = [p.ready for _ in range(2)]

        _two_threads(loader, driver)

    scn = Scenario(
        "old_pending", "x", body, lambda ctx: None,
        classes=[(_OldPendingActor, ["_ready_at"])],
    )
    res = explore(scn)
    assert any(
        f.rule == "RACE001" and "_ready_at" in f.message for f in res.races
    )


def test_fixed_pendingactor_scenario_is_clean():
    # the shipped scenario instruments the REAL PendingActor via the
    # catalog; post-fix it must run exhaustively clean
    res = explore(by_name("elastic_pending_load_vs_poll"))
    assert res.clean, ([f.render() for f in res.races], res.failures)


# ---------------------------------------------------------------------------
# SARIF golden (byte-exact RACE001 document) + CLI
# ---------------------------------------------------------------------------

_GOLDEN = os.path.join(
    os.path.dirname(__file__), "goldens", "sarif_race_golden.json"
)


def test_sarif_race001_golden_file():
    """Byte-stable RACE001 SARIF document through the shared writer —
    the same pin test_sarif_golden_file gives rxgbverify."""
    from tools.sarif import to_sarif_json

    doc = to_sarif_json(
        "rxgbrace", RACE_RULES,
        [
            {
                "rule": "RACE001",
                "message": (
                    "unordered write/read of PendingActor#1._ready_at: "
                    "elastic-load-rank-0 vs driver — no ordering edge, "
                    "disjoint locksets"
                ),
                "path": "xgboost_ray_tpu/elastic.py",
                "line": 92,
            },
        ],
    )
    with open(_GOLDEN) as fh:
        assert json.loads(doc) == json.load(fh)
        fh.seek(0)
        assert doc + "\n" == fh.read()  # byte-for-byte, trailing newline


def test_cli_lists_and_single_scenario_gate(tmp_path):
    from tools.rxgbrace.__main__ import main

    assert main(["--list-rules"]) == 0
    assert main(["--list-scenarios"]) == 0
    j = tmp_path / "race.json"
    s = tmp_path / "race.sarif"
    rc = main([
        "--scenario", "faultplan_fire_vs_reset",
        "--json", str(j), "--sarif", str(s),
    ])
    assert rc == 0
    doc = json.loads(j.read_text())
    assert doc["tool"] == "rxgbrace" and doc["findings"] == []
    rep = doc["scenarios"]["faultplan_fire_vs_reset"]
    assert rep["schedules"] >= 2 and rep["status"] == "clean"
    assert not rep["truncated"]
    sarif_doc = json.loads(s.read_text())
    assert sarif_doc["runs"][0]["results"] == []
    assert sarif_doc["runs"][0]["tool"]["driver"]["name"] == "rxgbrace"
    rules = {r["id"] for r in sarif_doc["runs"][0]["tool"]["driver"]["rules"]}
    assert rules == set(RACE_RULES)


def test_cli_replay_roundtrip(capsys):
    from tools.rxgbrace.__main__ import main

    rc = main(["--replay", "faultplan_fire_vs_reset@0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "status=complete" in out and "digest=" in out
    # unknown scenario name is a usage error, not a crash
    assert main(["--replay", "nope@1"]) == 2


def test_instrumentation_restores_threading(tmp_path):
    real_lock = threading.Lock
    real_thread = threading.Thread
    with Instrumentation(classes=None):
        assert threading.Lock is not real_lock
    assert threading.Lock is real_lock
    assert threading.Thread is real_thread
    assert time.monotonic.__module__ == "time"
