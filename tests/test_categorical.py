"""Categorical-feature support (``enable_categorical``, one-vs-rest splits).

Reference surface: ``xgboost_ray/sklearn.py:404-407`` passes
``enable_categorical`` through to xgboost. Here categorical bins ARE the
category codes and the split search evaluates one-vs-rest partitions
(xgboost's one-hot categorical splits), routed by code equality.
"""

import numpy as np
import pandas as pd
import pytest

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu.sklearn import RayXGBClassifier

_RP = RayParams(num_actors=2)


def _nonordinal_fixture(n=600, seed=0):
    """y depends on category membership {1, 3} — hostile to ordinal
    thresholds, trivial for one-vs-rest splits."""
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, 5, n).astype(np.float32)
    noise = rng.randn(n).astype(np.float32)
    y = np.isin(cat, [1, 3]).astype(np.float32)
    x = np.stack([cat, noise], axis=1)
    return x, y


def test_categorical_beats_numeric_coding_at_fixed_depth():
    x, y = _nonordinal_fixture()
    params = {"objective": "binary:logistic", "eval_metric": ["error"],
              "max_depth": 2, "eta": 1.0}
    res_cat, res_num = {}, {}
    # one round: a single depth-2 tree. One-vs-rest splits isolate {1} and
    # {3} exactly; ordinal thresholds on the same codes cannot.
    train(params, RayDMatrix(x, y, feature_types=["c", "q"]), 1,
          evals=[(RayDMatrix(x, y, feature_types=["c", "q"]), "t")],
          evals_result=res_cat, ray_params=_RP)
    train(params, RayDMatrix(x, y), 1,
          evals=[(RayDMatrix(x, y), "t")], evals_result=res_num,
          ray_params=_RP)
    assert res_cat["t"]["error"][-1] == 0.0
    assert res_num["t"]["error"][-1] > 0.1


def test_categorical_predict_matches_training_margins():
    """Raw-x equality routing must agree with the binned training walk."""
    x, y = _nonordinal_fixture(seed=1)
    er = {}
    bst = train({"objective": "binary:logistic", "eval_metric": ["logloss"],
                 "max_depth": 3},
                RayDMatrix(x, y, feature_types=["c", "q"]), 5,
                evals=[(RayDMatrix(x, y, feature_types=["c", "q"]), "t")],
                evals_result=er, ray_params=_RP)
    from xgboost_ray_tpu.ops.metrics import compute_metric

    margin = bst.predict(x, output_margin=True)
    ll = compute_metric("logloss", margin, y)
    assert abs(ll - er["t"]["logloss"][-1]) < 1e-5
    # pred_leaf and contribs run through the same categorical routing
    leaves = bst.predict(x, pred_leaf=True)
    assert leaves.shape == (x.shape[0], 5)
    contribs = bst.predict(x, pred_contribs=True, approx_contribs=True)
    np.testing.assert_allclose(contribs.sum(1), margin, atol=1e-4)


def test_pandas_category_dtype_auto_encoding():
    rng = np.random.RandomState(2)
    color = rng.choice(["red", "green", "blue", "teal"], size=400)
    z = rng.randn(400).astype(np.float32)
    y = ((color == "green") | (color == "teal")).astype(np.float32)
    df = pd.DataFrame({"color": pd.Categorical(color), "z": z})
    dm = RayDMatrix(df, y, enable_categorical=True)
    dm.get_data(0, 2)  # triggers loading, which resolves the type map
    assert dm.resolved_feature_types == ["c", "q"]
    er = {}
    bst = train({"objective": "binary:logistic", "eval_metric": ["error"],
                 "max_depth": 2, "eta": 1.0},
                dm, 4, evals=[(dm, "t")], evals_result=er, ray_params=_RP)
    assert er["t"]["error"][-1] == 0.0
    # model predicts on the encoded representation
    codes = pd.Categorical(color).codes.astype(np.float32)
    pred = bst.predict(np.stack([codes, z], 1))
    assert ((pred > 0.5) == y).mean() == 1.0


def test_object_column_without_flag_raises():
    df = pd.DataFrame({"s": ["a", "b", "a", "c"], "v": [1.0, 2.0, 3.0, 4.0]})
    y = np.array([0, 1, 0, 1], np.float32)
    dm = RayDMatrix(df, y)
    with pytest.raises(ValueError, match="enable_categorical"):
        dm.get_data(0, 1)


def test_category_codes_out_of_range_raise():
    x = np.stack([np.arange(100, dtype=np.float32) * 10,  # codes up to 990
                  np.random.RandomState(3).randn(100).astype(np.float32)], 1)
    y = (np.arange(100) % 2).astype(np.float32)
    with pytest.raises(ValueError, match="max_bin"):
        train({"objective": "binary:logistic", "max_bin": 64},
              RayDMatrix(x, y, feature_types=["c", "q"]), 2, ray_params=_RP)


def test_categorical_missing_values_follow_learned_default():
    x, y = _nonordinal_fixture(seed=4)
    x = x.copy()
    x[::7, 0] = np.nan
    bst = train({"objective": "binary:logistic", "max_depth": 3},
                RayDMatrix(x, y, feature_types=["c", "q"]), 5, ray_params=_RP)
    pred = bst.predict(x)
    mask = ~np.isnan(x[:, 0])
    assert ((pred[mask] > 0.5) == y[mask]).mean() > 0.95


def test_categorical_save_load_roundtrip(tmp_path):
    x, y = _nonordinal_fixture(seed=5)
    bst = train({"objective": "binary:logistic", "max_depth": 3},
                RayDMatrix(x, y, feature_types=["c", "q"]), 4, ray_params=_RP)
    p = str(tmp_path / "m.json")
    bst.save_model(p)
    from xgboost_ray_tpu.models.booster import Booster

    loaded = Booster.load_model(p)
    assert loaded.cat_features == (0,)
    np.testing.assert_allclose(loaded.predict(x), bst.predict(x), atol=1e-6)


def test_sklearn_enable_categorical_passthrough():
    rng = np.random.RandomState(6)
    color = rng.choice(["a", "b", "c", "d"], size=300)
    df = pd.DataFrame({
        "cat": pd.Categorical(color),
        "num": rng.randn(300).astype(np.float32),
    })
    y = np.isin(color, ["b", "d"]).astype(np.float32)
    clf = RayXGBClassifier(n_estimators=4, max_depth=2, learning_rate=1.0,
                           enable_categorical=True, ray_params=_RP)
    clf.fit(df, y)
    assert (clf.predict(df) == y).mean() == 1.0


def test_category_mapping_survives_different_frames():
    """A predict/eval frame whose category SET differs from training must be
    encoded with the TRAINING mapping — not its own — or equality splits
    route values down wrong branches."""
    rng = np.random.RandomState(7)
    color = rng.choice(["a", "b", "c"], size=600)
    z = rng.randn(600).astype(np.float32)
    y = (color == "c").astype(np.float32)
    df = pd.DataFrame({"color": pd.Categorical(color), "z": z})
    bst = train({"objective": "binary:logistic", "max_depth": 2, "eta": 1.0},
                RayDMatrix(df, y, enable_categorical=True), 3, ray_params=_RP)
    assert bst.categories == {0: ("a", "b", "c")}

    # booster.predict on a frame containing ONLY 'c' (its own codes would
    # call it 0 == 'a'); the stored mapping must route it as 'c'
    only_c = pd.DataFrame({
        "color": pd.Categorical(["c"] * 10),
        "z": np.zeros(10, np.float32),
    })
    pred = bst.predict(only_c)
    assert (pred > 0.5).all()

    # unseen category -> NaN -> learned default direction, no crash
    unseen = pd.DataFrame({
        "color": pd.Categorical(["zzz"] * 5, categories=["zzz"]),
        "z": np.zeros(5, np.float32),
    })
    assert bst.predict(unseen).shape == (5,)

    # distributed predict() path translates shard codes too
    from xgboost_ray_tpu import predict as ray_predict

    pred2 = ray_predict(bst, RayDMatrix(only_c, enable_categorical=True),
                        ray_params=_RP)
    np.testing.assert_allclose(pred2, pred, atol=1e-6)

    # mapping survives save/load
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "m.json")
        bst.save_model(p)
        from xgboost_ray_tpu.models.booster import Booster
        loaded = Booster.load_model(p)
        assert loaded.categories == {0: ("a", "b", "c")}
        np.testing.assert_allclose(loaded.predict(only_c), pred, atol=1e-6)


def test_eval_set_with_different_category_subset():
    """Eval frames holding a category subset must evaluate correctly (codes
    re-mapped onto the training mapping before binning)."""
    rng = np.random.RandomState(8)
    color = rng.choice(["a", "b", "c", "d"], size=800)
    z = rng.randn(800).astype(np.float32)
    y = np.isin(color, ["b", "d"]).astype(np.float32)
    df = pd.DataFrame({"color": pd.Categorical(color), "z": z})

    # eval set: only rows with colors {b, d} -> its own codes would be {0,1}
    mask = np.isin(color, ["b", "d"])
    df_eval = pd.DataFrame({
        "color": pd.Categorical(color[mask]),
        "z": z[mask],
    })
    er = {}
    train({"objective": "binary:logistic", "eval_metric": ["error"],
           "max_depth": 2, "eta": 1.0},
          RayDMatrix(df, y, enable_categorical=True), 3,
          evals=[(RayDMatrix(df_eval, y[mask], enable_categorical=True), "v")],
          evals_result=er, ray_params=_RP)
    # all eval rows are positive-class categories: a correctly-mapped eval
    # reaches zero error; a code-drifted one would misroute half of them
    assert er["v"]["error"][-1] == 0.0
