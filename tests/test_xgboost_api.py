"""xgboost API-surface parity tests (targets: ``xgboost_ray/tests/test_xgboost_api.py``:
custom objective, custom metric, user callbacks)."""

import numpy as np
import pytest

from xgboost_ray_tpu import RayDMatrix, RayParams, train
from xgboost_ray_tpu.callback import TrainingCallback


@pytest.fixture
def xy():
    rng = np.random.RandomState(0)
    x = rng.randn(200, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    return x, y


RP = RayParams(num_actors=2)


def test_custom_objective_logreg(xy):
    x, y = xy

    def logregobj(preds, dtrain):
        labels = dtrain.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - labels, p * (1.0 - p)

    dtrain = RayDMatrix(x, y)
    evals_result = {}
    bst = train(
        {"max_depth": 3, "eta": 0.3, "eval_metric": ["error"],
         "base_score": 0.5},
        dtrain, 15, evals=[(dtrain, "train")], evals_result=evals_result,
        obj=logregobj, ray_params=RP,
    )
    assert evals_result["train"]["error"][-1] < 0.05
    margin = bst.predict(x, output_margin=True)
    acc = ((margin > 0) == y).mean()
    assert acc > 0.95


def test_custom_metric_receives_dmatrix_accessors(xy):
    x, y = xy
    seen = {}

    def metric(preds, dtrain):
        seen["n"] = dtrain.num_row()
        seen["labels"] = dtrain.get_label().shape
        return "const_metric", 42.0

    dtrain = RayDMatrix(x, y)
    evals_result = {}
    train({"objective": "binary:logistic"}, dtrain, 3,
          evals=[(dtrain, "train")], evals_result=evals_result,
          feval=metric, ray_params=RP)
    assert seen["n"] == 200
    assert seen["labels"] == (200,)
    assert evals_result["train"]["const_metric"] == [42.0] * 3


def test_callback_hooks_order_and_model_access(xy):
    x, y = xy
    events = []

    class Probe(TrainingCallback):
        def before_training(self, model):
            events.append("before_training")
            return model

        def before_iteration(self, model, epoch, evals_log):
            events.append(f"before_{epoch}")
            return False

        def after_iteration(self, model, epoch, evals_log):
            events.append(f"after_{epoch}")
            # lazy booster proxy must expose real booster attributes
            assert model.num_boosted_rounds() == epoch + 1
            return False

        def after_training(self, model):
            events.append("after_training")
            return model

    train({"objective": "binary:logistic"}, RayDMatrix(x, y), 3,
          callbacks=[Probe()], ray_params=RP)
    assert events == [
        "before_training", "before_0", "after_0", "before_1", "after_1",
        "before_2", "after_2", "after_training",
    ]


def test_callback_early_stop_via_return_value(xy):
    x, y = xy

    class StopAt(TrainingCallback):
        def after_iteration(self, model, epoch, evals_log):
            return epoch >= 4

    evals_result = {}
    dtrain = RayDMatrix(x, y)
    train({"objective": "binary:logistic"}, dtrain, 100,
          evals=[(dtrain, "train")], evals_result=evals_result,
          callbacks=[StopAt()], ray_params=RP)
    assert len(evals_result["train"]["logloss"]) == 5


def test_multiple_eval_metrics_recorded(xy):
    x, y = xy
    dtrain = RayDMatrix(x, y)
    evals_result = {}
    train({"objective": "binary:logistic",
           "eval_metric": ["logloss", "error", "auc"]},
          dtrain, 4, evals=[(dtrain, "train")], evals_result=evals_result,
          ray_params=RP)
    assert set(evals_result["train"]) == {"logloss", "error", "auc"}
    assert len(evals_result["train"]["auc"]) == 4
    assert evals_result["train"]["auc"][-1] > 0.95


def test_legacy_function_callback(xy):
    """Function-style callback(env) support (reference compat/__init__.py)."""
    x, y = xy
    seen = []

    def legacy_cb(env):
        seen.append((env.iteration, dict(env.evaluation_result_list)))

    dtrain = RayDMatrix(x, y)
    train({"objective": "binary:logistic", "eval_metric": ["error"]},
          dtrain, 3, evals=[(dtrain, "train")], callbacks=[legacy_cb],
          ray_params=RP)
    assert [i for i, _ in seen] == [0, 1, 2]
    assert "train-error" in seen[-1][1]


def test_profiling_round_times(xy, monkeypatch, tmp_path):
    x, y = xy
    monkeypatch.setenv("RXGB_PROFILE_DIR", str(tmp_path))
    dtrain = RayDMatrix(x, y)
    additional = {}
    train({"objective": "binary:logistic"}, dtrain, 4,
          additional_results=additional, ray_params=RP)
    assert len(additional["round_times_s"]) == 4
    assert all(t >= 0 for t in additional["round_times_s"])
    import os
    assert any(os.scandir(str(tmp_path)))  # a trace was written
