"""Placement behavior (analog of ``xgboost_ray/tests/test_colocation.py``).

The reference asserts WHERE work lands: SPREAD places training actors across
nodes, PACK keeps a tune trial together, Queue/Event stay on the driver node
(``test_colocation.py:17-139``). The TPU analog is device selection: which
physical devices form the training mesh. These tests assert the actual
chosen devices, not a strategy string.
"""

import numpy as np
import pytest

import jax

from xgboost_ray_tpu.main import _select_mesh_devices, _get_placement_strategy


class _FakeDev:
    def __init__(self, i, proc):
        self.id = i
        self.process_index = proc

    def __repr__(self):
        return f"dev({self.id}@p{self.process_index})"


def _fake_world(n_procs, per_proc):
    return [
        _FakeDev(p * per_proc + i, p) for p in range(n_procs) for i in range(per_proc)
    ]


def test_pack_fills_hosts_in_order():
    devs = _fake_world(4, 4)
    sel = _select_mesh_devices(4, "PACK", devs)
    assert [d.id for d in sel] == [0, 1, 2, 3]
    assert {d.process_index for d in sel} == {0}  # one host touched
    sel8 = _select_mesh_devices(8, "PACK", devs)
    assert {d.process_index for d in sel8} == {0, 1}


def test_spread_takes_equal_share_from_every_host():
    devs = _fake_world(4, 4)
    sel = _select_mesh_devices(4, "SPREAD", devs)
    assert [d.process_index for d in sel] == [0, 1, 2, 3]  # fault isolation
    sel8 = _select_mesh_devices(8, "SPREAD", devs)
    # two per host, strided within each host's ring
    per_host = {}
    for d in sel8:
        per_host.setdefault(d.process_index, []).append(d.id % 4)
    assert all(len(v) == 2 for v in per_host.values())
    assert all(v == [0, 2] for v in per_host.values())


def test_spread_single_host_strides_the_ring():
    devs = _fake_world(1, 8)
    sel = _select_mesh_devices(4, "SPREAD", devs)
    assert [d.id for d in sel] == [0, 2, 4, 6]
    assert [d.id for d in _select_mesh_devices(3, "SPREAD", devs)] == [0, 2, 5]


def test_selection_preserves_process_contiguous_order():
    devs = _fake_world(2, 4)
    sel = _select_mesh_devices(6, "SPREAD", devs)
    procs = [d.process_index for d in sel]
    assert procs == sorted(procs)  # engine's multi-host layout requirement
    assert len(sel) == 6


def test_spread_redistributes_uneven_host_deficit():
    """A host with fewer devices than its even share must not shrink the
    mesh (ADVICE r3): the deficit is redistributed to hosts with spare
    devices so exactly ``num`` devices come back."""
    # host 0 has 1 device, hosts 1-2 have 4 each
    devs = [_FakeDev(0, 0)] + [
        _FakeDev(1 + p * 4 + i, p + 1) for p in range(2) for i in range(4)
    ]
    sel = _select_mesh_devices(6, "SPREAD", devs)
    assert len(sel) == 6
    per_host = {}
    for d in sel:
        per_host.setdefault(d.process_index, 0)
        per_host[d.process_index] += 1
    # even share would be 2/2/2; host 0 can only give 1 → 1/3/2 or 1/2/3
    assert per_host[0] == 1 and per_host[1] + per_host[2] == 5
    procs = [d.process_index for d in sel]
    assert procs == sorted(procs)


def test_oversubscription_returns_all_devices():
    devs = _fake_world(2, 2)
    assert _select_mesh_devices(9, "SPREAD", devs) == devs
    assert _select_mesh_devices(9, "PACK", devs) == devs


def test_strategy_choice_matches_reference_semantics(monkeypatch):
    assert _get_placement_strategy(in_tune_session=True) == "PACK"
    assert _get_placement_strategy(in_tune_session=False) == "SPREAD"
    monkeypatch.setenv("RXGB_USE_SPREAD_STRATEGY", "0")
    assert _get_placement_strategy(in_tune_session=False) == "PACK"


def test_training_mesh_actually_spreads_on_virtual_mesh():
    """End-to-end: with 4 actors on the 8-device mesh, SPREAD trains on the
    strided devices and PACK (via placement_options) on the first four."""
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(0)
    x = rng.randn(512, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    all_devs = jax.devices()
    captured = {}

    from xgboost_ray_tpu import engine as engine_mod

    orig_init = engine_mod.TpuEngine.__init__

    def spy_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        captured[captured.pop("key")] = list(self.mesh.devices.ravel())

    engine_mod.TpuEngine.__init__ = spy_init
    try:
        captured["key"] = "spread"
        train({"objective": "binary:logistic", "max_depth": 3}, RayDMatrix(x, y),
              2, ray_params=RayParams(num_actors=4))
        captured["key"] = "pack"
        train({"objective": "binary:logistic", "max_depth": 3}, RayDMatrix(x, y),
              2, ray_params=RayParams(num_actors=4,
                                      placement_options={"strategy": "PACK"}))
    finally:
        engine_mod.TpuEngine.__init__ = orig_init

    assert captured["pack"] == list(all_devs[:4])
    assert captured["spread"] == [all_devs[i] for i in (0, 2, 4, 6)]
    assert captured["spread"] != captured["pack"]
