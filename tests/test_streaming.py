"""Streamed ingestion (stream/): parity, sketch guarantees, memory budget.

Pins the PR's non-negotiable contracts:

* a single-chunk streamed load is BITWISE-identical (bins, cuts, trained
  forest) to the in-memory path;
* merged sketches are invariant to chunking (same rows, any chunk size ->
  bitwise-same summary) and deterministic;
* the sketch's runtime rank-error certificate really bounds the observed
  error against exact quantiles;
* NaN/missing and weighted rows are handled;
* a dataset whose raw f32 form exceeds ``RXGB_STREAM_BUDGET_MB`` trains
  with measured peak RSS under the budget;
* gh_precision=int8 composes; warm start rides the binned forest walk
  (with the cut-drift gate pinned);
* the vectorized host sketch/bin are bitwise-equal to the loop oracles;
* a streamed load is reconstructible from the obs timeline.
"""

import gc
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xgboost_ray_tpu import obs  # noqa: E402
from xgboost_ray_tpu.engine import TpuEngine  # noqa: E402
from xgboost_ray_tpu.ops import binning  # noqa: E402
from xgboost_ray_tpu.params import parse_params, validate_streaming_params  # noqa: E402
from xgboost_ray_tpu.stream.reader import (  # noqa: E402
    StreamConfig,
    array_shard_stream,
    npy_shard_stream,
)
from xgboost_ray_tpu.stream.sketch import StreamSketch  # noqa: E402

_PARAMS = {
    "objective": "binary:logistic",
    "max_depth": 3,
    "eval_metric": ["logloss"],
}


def _data(n=4000, f=6, seed=7, nan_frac=0.05):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    if nan_frac:
        x[rng.rand(n, f) < nan_frac] = np.nan
    y = (np.nan_to_num(x[:, 0]) + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return x, y


def _forest_fields(eng):
    booster = eng.get_booster()
    return [np.asarray(f) for f in booster.forest]


# ---------------------------------------------------------------------------
# parity contracts
# ---------------------------------------------------------------------------


def test_single_chunk_stream_is_bitwise_identical():
    """One-chunk streams degrade to the materialized path: cuts, bins and
    the trained forest must be BITWISE equal, not merely close."""
    x, y = _data()
    p = parse_params(_PARAMS)
    eng_m = TpuEngine([{"data": x, "label": y}], p, num_actors=4)
    eng_s = TpuEngine(
        [array_shard_stream(x, label=y, chunk_rows=x.shape[0])], p,
        num_actors=4,
    )
    assert not eng_s._streamed  # the degrade path IS the materialized path
    assert np.array_equal(np.asarray(eng_m.cuts), np.asarray(eng_s.cuts))
    assert np.array_equal(np.asarray(eng_m.bins), np.asarray(eng_s.bins))
    for i in range(3):
        eng_m.step(i)
        eng_s.step(i)
    for fm, fs in zip(_forest_fields(eng_m), _forest_fields(eng_s)):
        assert np.array_equal(fm, fs)


def test_single_chunk_stream_with_train_eval_alias():
    """The degrade path must preserve eval-set aliasing: an eval entry that
    IS the train shard list keeps the train-set eval fast path after
    materialization (regression pin for the rebind bug)."""
    x, y = _data(n=2000, f=4, seed=16)
    shards = [array_shard_stream(x, label=y, chunk_rows=x.shape[0])]
    eng = TpuEngine(shards, parse_params(_PARAMS), num_actors=2,
                    evals=[(shards, "train")])
    assert not eng._streamed
    assert eng.evals and eng.evals[0].is_train
    res = eng.step(0)
    assert np.isfinite(res["train"]["logloss"])


def test_single_chunk_streamed_eval_degrades_with_materialized_train():
    """A single-chunk streamed eval set degrades to materialized fields no
    matter how the TRAIN set arrived (the same contract as the train-side
    degrade); only genuinely multi-chunk eval streams hit the gate."""
    x, y = _data(n=3000, f=4, seed=21)
    xe, ye = _data(n=1000, f=4, seed=22)
    p = parse_params(_PARAMS)
    eng = TpuEngine(
        [{"data": x, "label": y}], p, num_actors=2,
        evals=[([array_shard_stream(xe, label=ye, chunk_rows=xe.shape[0])],
                "ev")],
    )
    res = eng.step(0)
    assert np.isfinite(res["ev"]["logloss"])
    with pytest.raises(NotImplementedError, match="streamed"):
        TpuEngine(
            [{"data": x, "label": y}], p, num_actors=2,
            evals=[([array_shard_stream(xe, label=ye, chunk_rows=100)],
                    "ev")],
        )


def test_multi_chunk_assembled_bins_match_host_binning():
    """The double-buffered upload + on-device assembly must reproduce
    exactly bin_matrix_np(x, streamed_cuts) in row order, with the padding
    tail in the missing bucket."""
    x, y = _data(n=3001, f=5)
    p = parse_params(_PARAMS)
    eng = TpuEngine(
        [array_shard_stream(x, label=y, chunk_rows=257)], p, num_actors=4
    )
    assert eng._streamed
    got = np.asarray(eng.bins)
    ref = binning.bin_matrix_np(x, eng._stream_cuts_np, p.max_bin)
    assert np.array_equal(got[: x.shape[0]], ref)
    assert (got[x.shape[0]:] == p.max_bin).all()


def test_multi_chunk_stream_trains_close_to_materialized():
    """The sketch path's cuts differ from the materialized sketch only
    within the rank-error certificate; final logloss must land within 5e-4
    (the bench `streaming` section pins the same bound at 200k scale)."""
    x, y = _data(n=20000, f=8, seed=1)
    p = parse_params(_PARAMS)
    eng_m = TpuEngine([{"data": x, "label": y}], p, num_actors=4,
                      evals=[([{"data": x, "label": y}], "train")])
    eng_s = TpuEngine(
        [array_shard_stream(x, label=y, chunk_rows=3000)], p, num_actors=4,
        evals=[([{"data": x, "label": y}], "train")],
    )
    assert eng_s._streamed
    for i in range(8):
        m = eng_m.step(i)
        s = eng_s.step(i)
    delta = abs(m["train"]["logloss"] - s["train"]["logloss"])
    assert delta <= 5e-4, f"final logloss drifted {delta}"


def test_streamed_composes_with_gh_precision_int8():
    x, y = _data(n=6000, f=6, seed=2)
    p = parse_params({**_PARAMS, "gh_precision": "int8"})
    eng_s = TpuEngine(
        [array_shard_stream(x, label=y, chunk_rows=1000)], p, num_actors=4,
        evals=[([{"data": x, "label": y}], "train")],
    )
    assert eng_s._streamed
    eng_m = TpuEngine([{"data": x, "label": y}], p, num_actors=4,
                      evals=[([{"data": x, "label": y}], "train")])
    for i in range(5):
        s = eng_s.step(i)
        m = eng_m.step(i)
    assert np.isfinite(s["train"]["logloss"])
    assert abs(s["train"]["logloss"] - m["train"]["logloss"]) <= 5e-4


def test_streamed_composes_with_feature_parallel():
    """2D row x feature sharding happens post-bin, so it composes: the
    streamed (R, C) engine must train, and match the streamed (R, 1) run
    bitwise (the PR 10 mesh-parity contract on streamed bins)."""
    x, y = _data(n=2000, f=6, seed=4)
    p1 = parse_params(_PARAMS)
    p2 = parse_params({**_PARAMS, "feature_parallel": 2})
    shards = lambda: [array_shard_stream(x, label=y, chunk_rows=333)]  # noqa: E731
    e1 = TpuEngine(shards(), p1, num_actors=4)
    e2 = TpuEngine(shards(), p2, num_actors=4)
    assert e1._streamed and e2._streamed
    for i in range(3):
        e1.step(i)
        e2.step(i)
    for f1, f2 in zip(_forest_fields(e1), _forest_fields(e2)):
        assert np.array_equal(f1, f2)


# ---------------------------------------------------------------------------
# sketch guarantees
# ---------------------------------------------------------------------------


def test_sketch_chunking_invariance_bitwise():
    """Same rows, ANY chunking -> bitwise-identical exported summary (the
    state is a function of the row prefix only)."""
    x, _ = _data(n=5000, f=4, seed=3)
    w = np.abs(np.random.RandomState(5).randn(5000)).astype(np.float32)
    for weights in (None, w):
        exports = []
        for chunk in (1, 7, 64, 977, 5000):
            sk = StreamSketch(4, capacity=256)
            for lo in range(0, 5000, chunk):
                wc = None if weights is None else weights[lo : lo + chunk]
                sk.update(x[lo : lo + chunk], weight=wc)
            exports.append(sk.export(1024))
        ref_vals, ref_wts, ref_err = exports[0]
        for vals, wts, err in exports[1:]:
            assert np.array_equal(vals, ref_vals)
            assert np.array_equal(wts, ref_wts)
            assert np.array_equal(err, ref_err)


def test_sketch_rank_error_bound_vs_exact_quantiles():
    """The runtime certificate really bounds the observed rank error of
    sketch quantiles against exact quantiles."""
    rng = np.random.RandomState(11)
    n, f = 30000, 3
    x = np.stack([
        rng.randn(n), rng.lognormal(size=n), rng.randint(0, 50, n).astype(float)
    ], axis=1).astype(np.float32)
    sk = StreamSketch(f, capacity=512)
    for lo in range(0, n, 1000):
        sk.update(x[lo : lo + 1000])
    qs = np.arange(1, 32) / 32.0
    est = sk.quantiles(qs)
    bound = sk.rank_error_bound()
    assert (bound < 0.05 * n).all(), "certificate uselessly loose"
    for fi in range(f):
        col = np.sort(x[:, fi])
        for qi, q in enumerate(qs):
            # observed rank of the estimate vs the target rank: the
            # certificate must cover it (ties give a rank interval)
            v = est[fi, qi]
            rank_lo = np.searchsorted(col, v, side="left")
            rank_hi = np.searchsorted(col, v, side="right")
            target = q * n
            err = max(0.0, max(rank_lo - target, target - rank_hi))
            assert err <= bound[fi] + 1e-6, (
                f"feature {fi} q={q}: err {err} > certified {bound[fi]}"
            )


def test_sketch_merge_and_missing_handling():
    """Actor-merge equals a single sketch over the union (within the summed
    certificate); NaN rows never contribute mass but are tracked."""
    x, _ = _data(n=8000, f=5, seed=6, nan_frac=0.2)
    x[:, 3] = np.nan  # all-missing feature
    parts = np.array_split(x, 3)
    sks = []
    for part in parts:
        sk = StreamSketch(5, capacity=256)
        sk.update(part)
        sks.append(sk)
    merged = sks[0].merge(sks[1]).merge(sks[2])
    n_missing = np.isnan(x).sum(axis=0)
    assert np.allclose(merged.missing_weight, n_missing)
    assert np.allclose(
        merged.total_weight, x.shape[0] - n_missing
    )
    assert merged.n_rows == x.shape[0]
    # quantiles over non-missing values stay within the certificate
    qs = np.array([0.25, 0.5, 0.75])
    est = merged.quantiles(qs)
    bound = merged.rank_error_bound()
    for fi in (0, 1, 2, 4):
        col = np.sort(x[:, fi][~np.isnan(x[:, fi])])
        w_total = col.size
        for qi, q in enumerate(qs):
            v = est[fi, qi]
            rank_lo = np.searchsorted(col, v, side="left")
            rank_hi = np.searchsorted(col, v, side="right")
            target = q * w_total
            err = max(0.0, max(rank_lo - target, target - rank_hi))
            assert err <= bound[fi] + 1e-6
    # the all-missing feature yields zero mass and a zero placeholder
    assert merged.total_weight[3] == 0.0
    assert (est[3] == 0.0).all()


def test_weighted_sketch_matches_replicated_rows():
    """Integer weights must act like row replication (the xgboost weighted
    quantile semantics), within the certificate."""
    rng = np.random.RandomState(9)
    n = 4000
    x = rng.randn(n, 2).astype(np.float32)
    w = rng.randint(1, 4, n).astype(np.float32)
    sk = StreamSketch(2, capacity=512)
    sk.update(x, weight=w)
    qs = np.array([0.1, 0.5, 0.9])
    est = sk.quantiles(qs)
    bound = sk.rank_error_bound()
    for fi in range(2):
        rep = np.sort(np.repeat(x[:, fi], w.astype(int)))
        w_total = rep.size
        for qi, q in enumerate(qs):
            v = est[fi, qi]
            rank_lo = np.searchsorted(rep, v, side="left")
            rank_hi = np.searchsorted(rep, v, side="right")
            target = q * w_total
            err = max(0.0, max(rank_lo - target, target - rank_hi))
            assert err <= bound[fi] + 1e-6


def test_streamed_engine_weighted_rows_reach_the_sketch():
    """Row weights must shift streamed cuts (weight-aware sketch), mirroring
    the materialized weighted sketch behavior."""
    rng = np.random.RandomState(13)
    n = 6000
    x = rng.randn(n, 3).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    w = np.where(x[:, 0] > 1.0, 50.0, 1.0).astype(np.float32)
    p = parse_params(_PARAMS)
    eng_u = TpuEngine([array_shard_stream(x, label=y, chunk_rows=1000)],
                      p, num_actors=2)
    eng_w = TpuEngine(
        [array_shard_stream(x, label=y, weight=w, chunk_rows=1000)],
        p, num_actors=2,
    )
    assert eng_u._streamed and eng_w._streamed
    cu, cw = eng_u._stream_cuts_np, eng_w._stream_cuts_np
    # upweighting the right tail must drag median-region cuts right
    mid = cu.shape[1] // 2
    assert cw[0, mid] > cu[0, mid]


# ---------------------------------------------------------------------------
# vectorized host binning == loop oracles (satellite: binning on the
# streaming hot path)
# ---------------------------------------------------------------------------


def test_vectorized_host_sketch_and_bin_bitwise_equal_loop():
    rng = np.random.RandomState(21)
    for n, f, b in ((1000, 7, 256), (513, 3, 16), (64, 2, 4), (200, 33, 64)):
        x = rng.randn(n, f).astype(np.float32)
        x[rng.rand(n, f) < 0.15] = np.nan
        x[rng.rand(n, f) < 0.2] = np.float32(0.5)  # ties
        x[rng.rand(n, f) < 0.05] = np.float32(-0.0)  # signed-zero boundary
        if f > 2:
            x[:, 1] = np.nan  # all-missing feature
        assert np.array_equal(
            binning.sketch_cuts_np(x, b),
            binning._sketch_cuts_np_loop(x, b),
        )
        w = rng.rand(n).astype(np.float32)
        w[rng.rand(n) < 0.1] = 0.0
        assert np.array_equal(
            binning.sketch_cuts_np(x, b, sample_weight=w),
            binning._sketch_cuts_np_loop(x, b, sample_weight=w),
        )
        cuts = binning._sketch_cuts_np_loop(x, b)
        assert np.array_equal(
            binning.bin_matrix_np(x, cuts, b),
            binning._bin_matrix_np_loop(x, cuts, b),
        )


# ---------------------------------------------------------------------------
# warm start / elastic-restart resume
# ---------------------------------------------------------------------------


def test_streamed_warm_start_resumes_via_binned_walk():
    """Restart-from-checkpoint over an unchanged streamed world: the init
    forest walks the binned matrix (no raw rows exist) and training must
    continue exactly like an unbroken run (same cuts -> same split_bin
    routing -> bitwise margins)."""
    x, y = _data(n=6000, f=6, seed=8)
    p = parse_params(_PARAMS)
    mk = lambda **kw: TpuEngine(  # noqa: E731
        [array_shard_stream(x, label=y, chunk_rows=1000)], p, num_actors=4,
        evals=[([{"data": x, "label": y}], "train")], **kw,
    )
    full = mk()
    assert full._streamed
    for i in range(4):
        unbroken = full.step(i)
    seg1 = mk()
    for i in range(2):
        seg1.step(i)
    ckpt = seg1.get_booster()
    seg2 = mk(init_booster=ckpt)
    assert seg2.iteration_offset == 2
    for i in range(2):
        resumed = seg2.step(i)
    assert resumed["train"]["logloss"] == unbroken["train"]["logloss"]


def test_streamed_warm_start_gates_on_cut_drift():
    """A checkpoint grown against different cuts cannot ride split_bin
    routing over re-binned rows: pinned loud gate, not silent corruption."""
    x, y = _data(n=6000, f=6, seed=8)
    p = parse_params(_PARAMS)
    other_x = x + np.float32(1.7)  # different data -> different cuts
    donor = TpuEngine(
        [array_shard_stream(other_x, label=y, chunk_rows=1000)], p,
        num_actors=4,
    )
    donor.step(0)
    ckpt = donor.get_booster()
    with pytest.raises(NotImplementedError, match="cuts"):
        TpuEngine(
            [array_shard_stream(x, label=y, chunk_rows=1000)], p,
            num_actors=4, init_booster=ckpt,
        )


def test_streamed_engine_reshards_in_flight():
    """Streamed engines re-shard now: ``can_reshard()`` is True, a reset
    against the SAME shard streams rebuilds margins via the binned forest
    walk (retained cuts — no re-stream, no re-sketch), and a reset against
    different streams (or materialized shards) is loudly rejected."""
    x, y = _data(n=3000, f=4, seed=10)
    p = parse_params(_PARAMS)
    mk = lambda: [array_shard_stream(x, label=y, chunk_rows=500)]  # noqa: E731
    shards = mk()
    eng = TpuEngine(shards, p, num_actors=2)
    assert eng._streamed
    assert eng.can_reshard()
    for i in range(3):
        eng.step(i)
    bst = eng.get_booster()
    step_fn = eng._step_fn
    eng.reset_from_booster(shards, [], bst)
    assert eng._step_fn is step_fn  # compiled round program retained
    assert eng.iteration_offset == 3
    eng.step(0)
    # materialized shards / different streams cannot ride the reset
    with pytest.raises(ValueError, match="streamed shard identity"):
        eng.reset_from_booster([{"data": x, "label": y}], [], bst)
    with pytest.raises(ValueError, match="streamed shard identity"):
        eng.reset_from_booster(
            [array_shard_stream(x, label=y, chunk_rows=250)], [], bst
        )


def test_streamed_shrink_reuses_donor_bins_zero_resketch():
    """The PR's streamed keystone at engine level: a shrunken world built
    with ``stream_donor=`` reuses the survivors' binned blocks and FROZEN
    cuts — the timeline after the donor build shows bin-reuse spans and
    ZERO sketch/bin-chunk work, and the shrunken engine's cuts are bitwise
    the donor's."""
    from xgboost_ray_tpu.stream.reader import StreamConfig, fields_shard_stream

    x, y = _data(n=3000, f=5, seed=11)
    cfg = lambda: StreamConfig(chunk_rows=400)  # noqa: E731

    def shard(lo, hi, rank):
        return {"stream": fields_shard_stream(
            {"data": x[lo:hi], "label": y[lo:hi]}, config=cfg(),
            source_token=("central", "uid", rank),
        )}

    p = parse_params(_PARAMS)
    donor = TpuEngine([shard(0, 1500, 0), shard(1500, 3000, 1)], p,
                      num_actors=2)
    for i in range(3):
        donor.step(i)
    bst = donor.get_booster()

    tracer = obs.Tracer(enabled=True)
    with obs.use_tracer(tracer):
        surv = TpuEngine([shard(0, 1500, 0)], p, num_actors=1,
                         init_booster=bst, stream_donor=donor)
    names = [r["name"] for r in tracer.records()]
    assert "data.sketch_chunk" not in names
    assert "data.bin_chunk" not in names
    assert "data.cuts_merge" not in names
    assert "data.bin_reuse" in names
    assert surv._stream_stats["reused_from_donor"] is True
    assert np.array_equal(surv._stream_cuts_np, donor._stream_cuts_np)
    assert surv.iteration_offset == 3
    surv.step(0)

    # the shrunken world's binned rows are bitwise the donor's survivor rows
    assert np.array_equal(
        np.asarray(surv.bins)[:1500], np.asarray(donor.bins)[:1500]
    )


def test_streamed_growback_restreams_only_the_new_shard():
    """Grow-back onto a brand-new replacement shard (engine-cache miss):
    the donor seeds every surviving shard from memory and only the ONE new
    shard re-streams — binned against the donor's frozen cuts, with
    bin-chunk spans for that shard alone and still zero sketch work."""
    from xgboost_ray_tpu.stream.reader import StreamConfig, fields_shard_stream

    x, y = _data(n=3000, f=5, seed=12)

    def shard(lo, hi, rank, uid="uid"):
        return {"stream": fields_shard_stream(
            {"data": x[lo:hi], "label": y[lo:hi]},
            config=StreamConfig(chunk_rows=400),
            source_token=("central", uid, rank),
        )}

    p = parse_params(_PARAMS)
    donor = TpuEngine([shard(0, 1500, 0)], p, num_actors=1)
    for i in range(2):
        donor.step(i)
    bst = donor.get_booster()

    tracer = obs.Tracer(enabled=True)
    with obs.use_tracer(tracer):
        grown = TpuEngine(
            [shard(0, 1500, 0), shard(1500, 3000, 1, uid="uid2")], p,
            num_actors=2, init_booster=bst, stream_donor=donor,
        )
    names = [r["name"] for r in tracer.records()]
    assert "data.sketch_chunk" not in names
    assert "data.cuts_merge" not in names
    assert "data.bin_chunk" in names  # the one re-streamed shard
    st = grown._stream_stats
    assert st["reused_shards"] == 1 and st["restreamed_shards"] == 1
    assert st["restreamed_rows"] == 1500
    assert np.array_equal(grown._stream_cuts_np, donor._stream_cuts_np)
    grown.step(0)
    # the re-streamed shard binned against the frozen cuts lands bitwise
    # where a direct host binning of its raw rows would
    expect = binning.bin_matrix_np(
        x[1500:3000], donor._stream_cuts_np, p.max_bin
    )
    assert np.array_equal(np.asarray(grown.bins)[1500:3000], expect)


def test_streamed_growback_restream_is_budget_prevalidated():
    """A grow-back re-stream that cannot fit the host budget must fail
    BEFORE the new shard's first byte streams (the reuse pass runs the
    same validate_budget model as the original ingest)."""
    from xgboost_ray_tpu.stream.reader import (
        ShardStream, StreamConfig, fields_shard_stream,
    )

    x, y = _data(n=3000, f=5, seed=13)

    def shard(lo, hi, rank):
        return {"stream": fields_shard_stream(
            {"data": x[lo:hi], "label": y[lo:hi]},
            config=StreamConfig(chunk_rows=400),
            source_token=("central", "uid", rank),
        )}

    p = parse_params(_PARAMS)
    donor = TpuEngine([shard(0, 1500, 0)], p, num_actors=1)
    donor.step(0)
    bst = donor.get_booster()

    reads = {"n": 0}

    def bomb_chunk_fn(lo, hi):
        reads["n"] += 1
        return {"data": x[1500 + lo:1500 + hi], "label": y[1500 + lo:1500 + hi]}

    bomb = {"stream": ShardStream(
        1500, 5, bomb_chunk_fn,
        config=StreamConfig(chunk_rows=400, budget_mb=0.001),
        source_token=("central", "uid2", 1),
    )}
    with pytest.raises(ValueError, match="cannot hold"):
        TpuEngine([shard(0, 1500, 0), bomb], p, num_actors=2,
                  init_booster=bst, stream_donor=donor)
    assert reads["n"] == 0, "budget must reject before any byte streams"


# ---------------------------------------------------------------------------
# composition gates
# ---------------------------------------------------------------------------


def test_streaming_composition_gates():
    validate_streaming_params(parse_params(_PARAMS))  # tree boosters pass
    validate_streaming_params(parse_params({**_PARAMS, "booster": "dart"}))
    with pytest.raises(NotImplementedError, match="gblinear"):
        validate_streaming_params(
            parse_params({"objective": "reg:squarederror",
                          "booster": "gblinear"})
        )
    with pytest.raises(NotImplementedError, match="rank"):
        validate_streaming_params(
            parse_params({"objective": "rank:pairwise"})
        )


def test_streamed_eval_set_is_gated():
    x, y = _data(n=2000, f=4, seed=12)
    p = parse_params(_PARAMS)
    with pytest.raises(NotImplementedError, match="eval"):
        TpuEngine(
            [array_shard_stream(x, label=y, chunk_rows=400)], p,
            num_actors=2,
            evals=[([array_shard_stream(x, label=y, chunk_rows=400)], "ev")],
        )


def test_streamed_qid_is_gated():
    x, _ = _data(n=1000, f=3, seed=14)
    qid = np.repeat(np.arange(100), 10).astype(np.float32)
    shard = array_shard_stream(x, label=None, chunk_rows=100)
    inner = shard["stream"]._chunk_fn

    def with_qid(lo, hi):
        out = inner(lo, hi)
        out["qid"] = qid[lo:hi]
        return out

    shard["stream"]._chunk_fn = with_qid
    with pytest.raises(NotImplementedError, match="qid"):
        TpuEngine([shard], parse_params(_PARAMS), num_actors=2)


# ---------------------------------------------------------------------------
# obs timeline: a streamed load is reconstructible from spans alone
# ---------------------------------------------------------------------------


def test_streamed_load_emits_catalogued_ingest_spans():
    for name in ("data.sketch_chunk", "data.bin_chunk", "data.h2d",
                 "data.cuts_merge"):
        assert name in obs.TRACE_NAMES
    x, y = _data(n=3000, f=4, seed=15)
    tracer = obs.Tracer(capacity=4096, enabled=True, trace_dir="")
    with obs.use_tracer(tracer):
        eng = TpuEngine(
            [array_shard_stream(x, label=y, chunk_rows=500)],
            parse_params(_PARAMS), num_actors=4,
        )
    assert eng._streamed
    recs = tracer.records()
    assert obs.validate_trace_records(recs, known_names=obs.TRACE_NAMES) == []
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    n_chunks = eng._stream_stats["chunks"]
    assert len(by_name["data.sketch_chunk"]) == n_chunks
    assert len(by_name["data.bin_chunk"]) == n_chunks
    assert len(by_name["data.cuts_merge"]) == 1
    # every uploaded part is fenced, with byte accounting
    h2d = by_name["data.h2d"]
    assert len(h2d) == eng._stream_stats["transfers"]
    assert sum(r["attrs"]["bytes"] for r in h2d) == eng._stream_stats["bytes"]


# ---------------------------------------------------------------------------
# beyond-budget training with RSS under the budget
# ---------------------------------------------------------------------------


def _write_big_npy(path, n, f, seed=0, block=50000):
    """Stream a synthetic [n, f] float32 .npy to disk without ever holding
    it in memory (the test process's RSS baseline must stay small)."""
    header = {"descr": "<f4", "fortran_order": False, "shape": (n, f)}
    rng = np.random.RandomState(seed)
    with open(path, "wb") as fh:
        np.lib.format.write_array_header_2_0(fh, header)
        for lo in range(0, n, block):
            hi = min(lo + block, n)
            fh.write(rng.randn(hi - lo, f).astype(np.float32).tobytes())


def test_csv_stream_counts_rows_without_trailing_newline(tmp_path):
    """Raw newline counting would drop the last row of a file without a
    trailing newline; the counting parse must see every row."""
    import pandas as pd

    from xgboost_ray_tpu.stream.reader import file_shard_stream

    path = str(tmp_path / "part.csv")
    with open(path, "w") as fh:
        fh.write("f0,f1,label\n1.0,2.0,0\n3.0,4.0,1")  # no trailing newline

    def split_fn(df):
        y = df["label"].to_numpy(np.float32)
        return {"data": df[["f0", "f1"]].to_numpy(np.float32), "label": y}

    s = file_shard_stream([path], split_fn, "csv",
                          config=StreamConfig(chunk_rows=1))
    assert s.n_rows == 2
    rows = [c for c in s.chunks()]
    assert len(rows) == 2
    assert np.array_equal(rows[1]["data"], [[3.0, 4.0]])


def test_npy_stream_gates_unsupported_side_fields(tmp_path):
    """base_margin/bounds/qid/missing/ignore cannot ride the .npy reader —
    they must fail loudly, never be silently dropped (a `missing` sentinel
    would be sketched and binned as real feature values)."""
    from xgboost_ray_tpu import RayShardingMode, RayStreamingDMatrix

    xp, yp = str(tmp_path / "x.npy"), str(tmp_path / "y.npy")
    np.save(xp, np.zeros((64, 3), np.float32))
    np.save(yp, np.zeros(64, np.float32))
    with pytest.raises(NotImplementedError, match="base_margin"):
        RayStreamingDMatrix(
            xp, label=yp, base_margin=np.zeros(64, np.float32),
            chunk_rows=16, sharding=RayShardingMode.BATCH, num_actors=2,
        )
    with pytest.raises(NotImplementedError, match="missing"):
        RayStreamingDMatrix(
            xp, label=yp, missing=-999.0,
            chunk_rows=16, sharding=RayShardingMode.BATCH, num_actors=2,
        )
    with pytest.raises(NotImplementedError, match="ignore"):
        RayStreamingDMatrix(
            xp, label=yp, ignore=["f0"],
            chunk_rows=16, sharding=RayShardingMode.BATCH, num_actors=2,
        )
    # missing=NaN is the default sentinel — equivalent to None, stays legal
    dm = RayStreamingDMatrix(
        xp, label=yp, missing=np.nan,
        chunk_rows=16, sharding=RayShardingMode.BATCH, num_actors=2,
    )
    assert dm.streamed


def test_stream_config_prefetch_respected():
    """prefetch=1 must reach the uploader (memory-minimizing configs) and
    RXGB_STREAM_PREFETCH=0 must raise like an explicit 0 does."""
    assert StreamConfig(prefetch=1).prefetch == 1
    with pytest.raises(ValueError, match="prefetch"):
        StreamConfig(prefetch=0)
    os.environ["RXGB_STREAM_PREFETCH"] = "0"
    try:
        with pytest.raises(ValueError, match="prefetch"):
            StreamConfig()
    finally:
        del os.environ["RXGB_STREAM_PREFETCH"]


def test_streamed_oversized_feature_types_error_is_loud():
    x, y = _data(n=500, f=3, seed=17, nan_frac=0.0)
    with pytest.raises(ValueError, match="more entries than features"):
        TpuEngine(
            [array_shard_stream(x, label=y, chunk_rows=100)],
            parse_params(_PARAMS), num_actors=2,
            feature_types=["q", "q", "q", "c", "c"],
        )


def test_budget_validation_rejects_oversized_chunking():
    """RXGB_STREAM_BUDGET_MB is enforced up front: a chunk/sketch config
    that cannot fit the budget fails loudly before any byte streams."""
    cfg = StreamConfig(chunk_rows=1_000_000, budget_mb=8.0)
    with pytest.raises(ValueError, match="BUDGET"):
        cfg.validate_budget(
            n_rows=2_000_000, n_features=96, chunk_rows=1_000_000,
            sketch_bytes=1 << 20,
        )


def test_bin_matrix_np_rejects_nan_cuts():
    """NaN cuts (a feature whose quantiles mix -inf and +inf) break the
    flat key array's sortedness and would bin silently differently from
    the per-feature oracle — must fail loudly instead."""
    x = np.array([[0.0], [1.0]], np.float32)
    cuts = np.array([[0.5, np.nan]], np.float32)
    with pytest.raises(ValueError, match="NaN"):
        binning.bin_matrix_np(x, cuts, max_bin=4)


def test_npy_stream_rejects_wide_side_files(tmp_path):
    """A [N, k>1] label/weight side file must be rejected at header read —
    ravel()ed it would flow downstream as a k*N column and die far from
    the cause (or silently misalign)."""
    from xgboost_ray_tpu.stream.reader import npy_shard_stream

    xp, yp = str(tmp_path / "x.npy"), str(tmp_path / "y2.npy")
    np.save(xp, np.zeros((32, 3), np.float32))
    np.save(yp, np.zeros((32, 2), np.float32))  # accidentally one-hot
    with pytest.raises(ValueError, match="1-D"):
        npy_shard_stream(xp, label_path=yp)


def test_explicit_sketch_capacity_is_validated_not_rewritten():
    """An explicit (user/env) sketch_capacity that StreamSketch itself
    would reject must raise, not be silently rounded to a capacity the
    user never configured."""
    x = np.zeros((16, 2), np.float32)
    with pytest.raises(ValueError, match="capacity"):
        array_shard_stream(x, config=StreamConfig(sketch_capacity=6))
    with pytest.raises(ValueError, match="capacity"):
        array_shard_stream(x, config=StreamConfig(sketch_capacity=9))


def test_block_budget_term_fails_before_any_byte_streams(monkeypatch):
    """The N-scaling block-buffer budget term is checkable from declared
    row counts alone, so a violating config must be rejected BEFORE pass 1
    streams the dataset (not after hours of I/O, in pass 2)."""
    from xgboost_ray_tpu.stream.reader import ShardStream

    x, y = _data(n=200_000, f=64, seed=23, nan_frac=0.0)
    # budget fits chunk+sketch (small chunks, tiny cap) but NOT the
    # per-actor block buffers of a 200k-row world on few actors
    cfg = StreamConfig(chunk_rows=512, budget_mb=8.0, sketch_capacity=64)
    shards = [array_shard_stream(x, label=y, config=cfg)]

    def bomb(self):
        raise AssertionError("a chunk streamed before the budget check")

    monkeypatch.setattr(ShardStream, "chunks", bomb)
    with pytest.raises(ValueError, match="block buffers"):
        TpuEngine(shards, parse_params(_PARAMS), num_actors=2)


def test_budget_counts_cuts_merge_summaries():
    """The cuts merge stacks [n_devices, F, export_cap] f32 vals+wts
    summaries — at wide F that term alone can dwarf the chunk/sketch
    terms, so the up-front fail-fast must charge it."""
    from xgboost_ray_tpu.stream import ingest

    x = np.zeros((512, 2000), np.float32)
    cfg = StreamConfig(chunk_rows=64, budget_mb=32.0, sketch_capacity=64)
    s = array_shard_stream(x, config=cfg)["stream"]
    with pytest.raises(ValueError, match="cuts-merge"):
        ingest.prevalidate_budget(
            [s], block_rows=64, bin_itemsize=1, n_devices=8
        )
    cfg2 = StreamConfig(chunk_rows=64, budget_mb=256.0, sketch_capacity=64)
    s2 = array_shard_stream(x, config=cfg2)["stream"]
    ingest.prevalidate_budget(
        [s2], block_rows=64, bin_itemsize=1, n_devices=8
    )


def test_budget_derived_chunk_fits_its_own_budget():
    """The budget-derived chunk size must never be a config
    validate_budget then rejects (the old 1024-row efficiency floor could
    inflate a tiny budget's derived chunk past the budget itself)."""
    cfg = StreamConfig(budget_mb=4.0)
    rows = cfg.resolve_chunk_rows(n_rows=1_000_000, n_features=1000)
    assert 1 <= rows < 1024  # the floor must not win over the budget
    cfg.validate_budget(1_000_000, 1000, rows, sketch_bytes=0)


def test_budget_validation_sums_sketches_across_shards():
    """The driver holds EVERY shard's sketch concurrently through pass 1,
    so the fail-fast must reject a budget that each shard's own sketch
    would fit but the sum does not — before any byte streams."""
    from xgboost_ray_tpu.stream import ingest

    cfg = StreamConfig(chunk_rows=500, budget_mb=16.0, sketch_capacity=1024)
    rng = np.random.RandomState(3)
    streams = []
    for _ in range(8):
        x = rng.randn(2000, 256).astype(np.float32)
        streams.append(array_shard_stream(x, config=cfg)["stream"])
    one = ingest.sketch_pass(streams[:1], max_bin=256)  # alone: fits
    assert one.n_rows == 2000
    with pytest.raises(ValueError, match="BUDGET"):
        ingest.sketch_pass(streams, max_bin=256)


def test_beyond_budget_training_respects_rss_budget(tmp_path, monkeypatch):
    """A dataset whose raw f32 form exceeds the enforced
    RXGB_STREAM_BUDGET_MB ingests with measured peak RSS delta under the
    budget, then trains successfully (the streaming data plane's acceptance
    criterion).

    The budget governs the INGEST host plane (chunk + sketch + per-actor
    bin blocks + upload); the round step's histogram scratch afterwards
    lives in HBM on real accelerators — on this CPU test backend it shares
    process RSS, so the budget window closes at the end of ingestion and
    training is asserted for completion only. The materialized path would
    blow the window by construction: raw host concat + raw device copy are
    each bigger than the whole budget.
    """
    n, f = 375_000, 256
    raw_mb = n * f * 4 / 2**20  # ~366 MB raw f32
    budget_mb = 320.0
    assert raw_mb > budget_mb
    xp = str(tmp_path / "x.npy")
    yp = str(tmp_path / "y.npy")
    _write_big_npy(xp, n, f, seed=31)
    rng = np.random.RandomState(32)
    np.save(yp, (rng.rand(n) > 0.5).astype(np.float32))
    monkeypatch.setenv("RXGB_STREAM_BUDGET_MB", str(budget_mb))
    monkeypatch.setenv("RXGB_STREAM_CHUNK_ROWS", "16384")
    monkeypatch.setenv("RXGB_STREAM_SKETCH_CAP", "512")
    p = parse_params({**_PARAMS, "max_depth": 3, "max_bin": 64})
    cfg = StreamConfig()  # everything from the enforced env knobs
    assert cfg.budget_mb == budget_mb
    # warm the runtime before opening the budget window: XLA's compile
    # arena and the backend allocator's pools grow once per process and are
    # one-time runtime overhead, not data-plane memory the budget governs
    warm_x, warm_y = _data(n=4096, f=f, seed=33, nan_frac=0.0)
    warm = TpuEngine(
        [array_shard_stream(warm_x, label=warm_y, chunk_rows=1024)],
        p, num_actors=8,
    )
    assert warm._streamed
    del warm, warm_x, warm_y
    import bench

    gc.collect()
    with bench._RssPeakSampler() as rss:  # the bench section's sampler
        shards = [{"stream": npy_shard_stream(
            xp, label_path=yp, config=cfg,
            row_range=(0, n),
        )}]
        eng = TpuEngine(shards, p, num_actors=8)
    assert eng._streamed
    ingest_peak_mb = rss.delta_mb
    assert ingest_peak_mb < budget_mb, (
        f"ingest peak RSS delta {ingest_peak_mb:.1f} MB >= budget "
        f"{budget_mb} MB"
    )
    for i in range(2):
        eng.step(i)
    assert eng.n_rows == n
