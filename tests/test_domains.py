"""Unit tests for the fault-domain plane (``xgboost_ray_tpu.domains``).

The domain map is the static rank -> failure-unit assignment the elastic
driver derives once per attempt; these tests pin the three-tier derivation
order (env partition > multi-host process_index > per-rank default) and the
DeathCoalescer mailbox semantics the coalesced-shrink path depends on.
"""

import threading

from xgboost_ray_tpu.domains import (
    DeathCoalescer,
    DomainMap,
    derive_domain_map,
    logical_domain_of,
)


class _Dev:
    """Minimal stand-in for a jax device: only process_index is consulted."""

    def __init__(self, process_index):
        self.process_index = process_index


def test_logical_partition_is_contiguous_and_clamped():
    # H=2 over 4 ranks: two contiguous halves
    assert [logical_domain_of(r, 4, 2) for r in range(4)] == [0, 0, 1, 1]
    # H=3 over 8 ranks: contiguous groups, sizes as even as floor-div allows
    assert [logical_domain_of(r, 8, 3) for r in range(8)] == \
        [0, 0, 0, 1, 1, 1, 2, 2]
    # more domains than ranks clamps to per-rank
    assert [logical_domain_of(r, 4, 8) for r in range(4)] == [0, 1, 2, 3]
    # H<=1 degenerates to a single domain
    assert [logical_domain_of(r, 4, 1) for r in range(4)] == [0, 0, 0, 0]


def test_domain_map_api():
    dm = DomainMap({0: 0, 1: 0, 2: 1})
    assert dm.domain_of(1) == 0 and dm.domain_of(2) == 1
    assert dm.ranks_of(0) == (0, 1)
    assert dm.ranks_of(1) == (2,)
    assert dm.ranks_of(99) == ()  # unknown domain: empty, not KeyError
    assert dm.domains() == [0, 1]
    assert dm.domains_of([1, 2]) == [0, 1]
    assert dm.domains_of([2, 7]) == [1]  # unknown ranks are ignored
    assert dm.num_ranks == 3 and dm.num_domains == 2


def test_derive_env_partition_wins_over_devices():
    """Tier 1: an explicit RXGB_FAULT_DOMAINS partition overrides whatever
    the device layout says — that's what makes host-loss behavior testable
    on the single-process CI mesh."""
    devices = [_Dev(0)] * 2 + [_Dev(1)] * 2
    dm = derive_domain_map(4, devices=devices, logical_domains=2)
    assert [dm.domain_of(r) for r in range(4)] == [0, 0, 1, 1]
    dm3 = derive_domain_map(4, devices=devices, logical_domains=4)
    assert [dm3.domain_of(r) for r in range(4)] == [0, 1, 2, 3]


def test_derive_process_index_grouping():
    """Tier 2: on a real multi-host mesh (distinct process_index values),
    ranks inherit the host of their first backing device."""
    devices = [_Dev(0)] * 4 + [_Dev(1)] * 4  # 4 actors x 2 devices each
    dm = derive_domain_map(4, devices=devices, logical_domains=0)
    assert [dm.domain_of(r) for r in range(4)] == [0, 0, 1, 1]
    assert dm.ranks_of(1) == (2, 3)


def test_derive_default_is_per_rank():
    """Tier 3: single process, no override — every rank is its own domain,
    preserving pre-domain per-rank elastic semantics exactly."""
    for devices in (None, [], [_Dev(0)] * 4):
        dm = derive_domain_map(4, devices=devices, logical_domains=0)
        assert [dm.domain_of(r) for r in range(4)] == [0, 1, 2, 3]
        assert dm.num_domains == 4


def test_death_coalescer_note_drain():
    co = DeathCoalescer()
    assert not co.pending
    co.note(2, domain=1)
    co.note(3, domain=1)
    co.note(2, domain=7)  # idempotent: first note's attribution wins
    assert co.pending
    assert co.drain() == {2: 1, 3: 1}
    assert not co.pending
    assert co.drain() == {}  # drain clears


def test_death_coalescer_concurrent_notes_land_once():
    """Ranks noted from many threads land in exactly one drained batch."""
    co = DeathCoalescer()
    drained = {}
    stop = threading.Event()

    def drainer():
        while not stop.is_set():
            drained.update(co.drain())
        drained.update(co.drain())

    t = threading.Thread(target=drainer)
    t.start()
    noters = [
        threading.Thread(target=co.note, args=(r,), kwargs={"domain": r % 2})
        for r in range(32)
    ]
    for n in noters:
        n.start()
    for n in noters:
        n.join()
    stop.set()
    t.join()
    assert sorted(drained) == list(range(32))
    assert all(drained[r] == r % 2 for r in drained)


def test_launcher_process_domain(monkeypatch):
    """The launcher attributes cross-process failures with the same
    contiguous RXGB_FAULT_DOMAINS layout the elastic plane uses; unset or
    unparseable partitions attribute nothing (None, never a guess)."""
    from xgboost_ray_tpu.launcher import _process_domain

    monkeypatch.delenv("RXGB_FAULT_DOMAINS", raising=False)
    assert _process_domain(1, 4) is None
    monkeypatch.setenv("RXGB_FAULT_DOMAINS", "2")
    assert [_process_domain(p, 4) for p in range(4)] == [0, 0, 1, 1]
    monkeypatch.setenv("RXGB_FAULT_DOMAINS", "bogus")
    assert _process_domain(1, 4) is None
    monkeypatch.setenv("RXGB_FAULT_DOMAINS", "0")
    assert _process_domain(1, 4) is None
