"""Vectorized HPO: vmapped-K lane parity, ASHA equivalence, and guards.

The contract under test (ISSUE 16): K boosters trained as lanes of ONE
``engine.step_vmapped`` program must be *the same boosters* the sequential
path would have produced — bitwise when the lane params equal the program
statics (no masks engaged), <= 1e-5 on eval metrics when depth/subsample
masks are engaged — and ASHA pruning over the packed lanes must make the
same decisions as ASHA over sequential trials.
"""

import dataclasses

import numpy as np
import pytest

from xgboost_ray_tpu.engine import TpuEngine
from xgboost_ray_tpu.params import parse_params, vectorize_params


def _data(rows=256, feats=6, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(rows, feats).astype(np.float32)
    y = (x[:, 0] + 0.3 * rng.rand(rows) > 0.6).astype(np.float32)
    return x, y


def _shards(x, y):
    return [{"data": x, "label": y}]


_BASE = {
    "objective": "binary:logistic",
    "eval_metric": ["logloss"],
    "max_depth": 3,
    "seed": 7,
}


def _sequential_run(shards, cfg, rounds, actors=8):
    eng = TpuEngine(shards, parse_params(cfg), num_actors=actors,
                    evals=[(shards, "train")])
    history = []
    for it in range(rounds):
        res = eng.step(it)
        history.append(float(res["train"]["logloss"]))
    return history, eng.get_booster()


# ---------------------------------------------------------------------------
# lane-by-lane parity vs sequential
# ---------------------------------------------------------------------------


def test_vmapped_lane_parity_bitwise_unmasked():
    """eta/lambda-only lanes engage no masks: every lane's round program is
    the exact FP-op sequence of its sequential twin, so metrics AND final
    booster predictions must match bitwise, lane by lane."""
    x, y = _data()
    shards = _shards(x, y)
    rounds = 3
    configs = [
        dict(_BASE, eta=0.3),
        dict(_BASE, eta=0.1, reg_lambda=2.0),
        dict(_BASE, eta=0.05, reg_alpha=0.5, min_child_weight=2.0),
    ]
    lp = vectorize_params(configs)
    eng = TpuEngine(shards, lp.base, num_actors=8,
                    evals=[(shards, "train")])
    eng.enable_lanes(lp)
    vm_hist = [[] for _ in configs]
    for it in range(rounds):
        for lane, res in enumerate(eng.step_vmapped(it)):
            vm_hist[lane].append(float(res["train"]["logloss"]))
    for lane, cfg in enumerate(configs):
        seq_hist, seq_booster = _sequential_run(shards, cfg, rounds)
        assert vm_hist[lane] == seq_hist, f"lane {lane} metric drift"
        lane_booster = eng.get_booster_lane(lane)
        np.testing.assert_array_equal(
            lane_booster.predict(x), seq_booster.predict(x),
            err_msg=f"lane {lane} forest drift",
        )


def test_vmapped_lane_parity_masked_depth_subsample():
    """A lane at reduced depth + subsample rides the level/budget masks:
    metric parity within 1e-5 of its sequential twin (mask arithmetic vs
    the sequential program's natural shapes), while the full-depth lane
    stays bitwise."""
    x, y = _data(seed=1)
    shards = _shards(x, y)
    rounds = 3
    configs = [
        dict(_BASE, eta=0.3),
        dict(_BASE, eta=0.1, max_depth=2, subsample=0.8),
    ]
    lp = vectorize_params(configs)
    assert lp.base.max_depth == 3 and float(lp.base.subsample) == 1.0
    eng = TpuEngine(shards, lp.base, num_actors=8,
                    evals=[(shards, "train")])
    eng.enable_lanes(lp)
    vm_hist = [[] for _ in configs]
    for it in range(rounds):
        for lane, res in enumerate(eng.step_vmapped(it)):
            vm_hist[lane].append(float(res["train"]["logloss"]))
    seq0, _ = _sequential_run(shards, configs[0], rounds)
    assert vm_hist[0] == seq0, "full-depth lane must stay bitwise"
    seq1, _ = _sequential_run(shards, configs[1], rounds)
    np.testing.assert_allclose(vm_hist[1], seq1, rtol=0, atol=1e-5)


def test_repack_then_continue_matches_sequential():
    """Pruning lanes mid-training must not perturb the survivors: after a
    repack the continuing lane's rounds still match its sequential twin."""
    x, y = _data(seed=2)
    shards = _shards(x, y)
    configs = [dict(_BASE, eta=0.3), dict(_BASE, eta=0.1)]
    lp = vectorize_params(configs)
    eng = TpuEngine(shards, lp.base, num_actors=8,
                    evals=[(shards, "train")])
    eng.enable_lanes(lp)
    hist1 = []
    for it in range(2):
        res = eng.step_vmapped(it)
        hist1.append(float(res[1]["train"]["logloss"]))
    eng.repack_lanes([1])
    assert eng.lane_ids() == [1]
    for it in range(2, 4):
        res = eng.step_vmapped(it)
        hist1.append(float(res[0]["train"]["logloss"]))
    seq1, seq_booster = _sequential_run(shards, configs[1], 4)
    assert hist1 == seq1
    np.testing.assert_array_equal(
        eng.get_booster_lane(0).predict(x), seq_booster.predict(x)
    )


# ---------------------------------------------------------------------------
# validation: never silently train a wrong lane
# ---------------------------------------------------------------------------


def test_vectorize_params_names_offending_key():
    with pytest.raises(NotImplementedError, match="'max_bin'"):
        vectorize_params([dict(_BASE), dict(_BASE, max_bin=64)])
    with pytest.raises(NotImplementedError, match="'grow_policy'"):
        vectorize_params([
            dict(_BASE),
            dict(_BASE, grow_policy="lossguide", max_leaves=8),
        ])


def test_vectorize_params_lossguide_depth_and_goss_subsample():
    lg = dict(_BASE, grow_policy="lossguide", max_leaves=8)
    with pytest.raises(NotImplementedError, match="max_depth"):
        vectorize_params([dict(lg, max_depth=3), dict(lg, max_depth=2)])
    goss = dict(_BASE, sampling_method="gradient_based", subsample=0.5)
    with pytest.raises(NotImplementedError, match="subsample"):
        vectorize_params([goss, dict(goss, subsample=0.3)])
    with pytest.raises(NotImplementedError, match="booster"):
        vectorize_params([dict(_BASE, booster="dart")] * 2)


def test_enable_lanes_mode_guards():
    x, y = _data(seed=3)
    shards = _shards(x, y)
    lp = vectorize_params([dict(_BASE, eta=0.3), dict(_BASE, eta=0.1)])
    eng = TpuEngine(shards, lp.base, num_actors=8)
    eng.enable_lanes(lp)
    with pytest.raises(RuntimeError, match="step_vmapped"):
        eng.step(0)
    with pytest.raises(RuntimeError, match="step_vmapped"):
        eng.step_many(0, 2)
    with pytest.raises(RuntimeError, match="get_booster_lane"):
        eng.get_booster()
    with pytest.raises(RuntimeError, match="already"):
        eng.enable_lanes(lp)
    # a non-fresh engine cannot be re-armed as a pack
    eng2 = TpuEngine(shards, parse_params(dict(_BASE)), num_actors=8)
    eng2.step(0)
    with pytest.raises(RuntimeError, match="fresh"):
        eng2.enable_lanes(lp)


def test_reset_lanes_requires_sliced_pack_and_reuses_programs():
    x, y = _data(seed=4)
    shards = _shards(x, y)
    configs = [dict(_BASE, eta=0.3), dict(_BASE, eta=0.1)]
    lp = vectorize_params(configs)
    eng = TpuEngine(shards, lp.base, num_actors=8,
                    evals=[(shards, "train")])
    eng.enable_lanes(lp)
    first = float(eng.step_vmapped(0)[0]["train"]["logloss"])
    fns_before = dict(eng._vk_fns)
    # a foreign pack (different base statics) must be rejected, not traced
    other = vectorize_params([dict(_BASE, eta=0.3, max_bin=64)])
    with pytest.raises(ValueError, match="base"):
        eng.reset_lanes(other)
    # a sliced pack from the engine's own group resets WITHOUT recompiling
    lp0 = dataclasses.replace(lp, lanes=(lp.lanes[0],))
    eng.reset_lanes(lp0)
    again = float(eng.step_vmapped(0)[0]["train"]["logloss"])
    assert again == first, "reset lane 0 must replay round 0 bitwise"
    eng.reset_lanes(lp)
    assert float(eng.step_vmapped(0)[0]["train"]["logloss"]) == first
    for k, fn in fns_before.items():
        assert eng._vk_fns.get(k) is fn, "reset_lanes recompiled a program"


# ---------------------------------------------------------------------------
# ASHA decision equivalence + trace timeline
# ---------------------------------------------------------------------------


def _asha_space_and_trainable(shards, rounds):
    from xgboost_ray_tpu.tuner import VectorizedTrainable, grid_search

    space = dict(_BASE, eta=grid_search([0.5, 0.3, 0.1, 0.02]))
    spec = VectorizedTrainable(shards=shards, num_actors=8,
                               num_boost_round=rounds)
    return space, spec


def test_asha_pruning_decision_equivalence():
    """The vectorized Tuner's ASHA decisions (which trials stop, at which
    round) must equal ASHA over fully sequential trials: within a rung the
    pack reports in trial order — the same arrival order per rung as the
    sequential sweep — and the lane metrics are the sequential metrics."""
    from xgboost_ray_tpu.tuner import ASHAScheduler, Tuner

    x, y = _data(seed=5)
    shards = _shards(x, y)
    rounds = 6
    space, spec = _asha_space_and_trainable(shards, rounds)
    etas = [0.5, 0.3, 0.1, 0.02]

    # sequential reference: each trial trains alone, reporting every round
    # to its own fresh ASHA instance in trial order
    seq_sched = ASHAScheduler("train-logloss", mode="min",
                              grace_rounds=2, eta=2)
    seq_stop = {}
    for j, eta in enumerate(etas):
        eng = TpuEngine(shards, parse_params(dict(_BASE, eta=eta)),
                        num_actors=8, evals=[(shards, "train")])
        for it in range(rounds):
            res = eng.step(it)
            flat = {"train-logloss": float(res["train"]["logloss"]),
                    "training_iteration": it + 1}
            if seq_sched.on_report(f"trial_{j}", it + 1, flat):
                seq_stop[j] = it + 1
                break

    tuner = Tuner(
        spec, space, metric="train-logloss", mode="min",
        scheduler=ASHAScheduler("train-logloss", mode="min",
                                grace_rounds=2, eta=2),
    )
    res = tuner.fit()
    assert len(res.trials) == len(etas)
    vm_stop = {
        j: len(t.results)
        for j, t in enumerate(res.trials) if t.stopped_early
    }
    assert vm_stop == seq_stop
    # at least one lane must actually have been pruned for this test to
    # exercise the repack path at all
    assert seq_stop, "ASHA never pruned: test configuration is degenerate"
    best_j = min(
        range(len(etas)),
        key=lambda j: res.trials[j].last_result["train-logloss"]
        if j not in seq_stop else float("inf"),
    )
    assert res.best_config["eta"] == etas[best_j]


def test_hpo_trace_events_timeline():
    """hpo.lane_prune / hpo.repack are catalogued trace events, and on a
    pruning run the timeline shows every prune for a round preceding the
    repack that commits it (prune events carry the trial/lane/round, the
    repack carries k_before/k_after)."""
    from xgboost_ray_tpu import obs
    from xgboost_ray_tpu.obs.trace import TRACE_NAMES
    from xgboost_ray_tpu.tuner import ASHAScheduler, Tuner

    assert "hpo.lane_prune" in TRACE_NAMES
    assert "hpo.repack" in TRACE_NAMES
    x, y = _data(seed=6)
    shards = _shards(x, y)
    space, spec = _asha_space_and_trainable(shards, rounds=6)
    tracer = obs.Tracer(enabled=True)
    with obs.use_tracer(tracer):
        Tuner(
            spec, space, metric="train-logloss", mode="min",
            scheduler=ASHAScheduler("train-logloss", mode="min",
                                    grace_rounds=2, eta=2),
        ).fit()
    recs = [r for r in tracer.records()
            if r["name"].startswith("hpo.")]
    assert recs, "no hpo.* events on a pruning run"
    prunes = [r for r in recs if r["name"] == "hpo.lane_prune"]
    repacks = [r for r in recs if r["name"] == "hpo.repack"]
    assert prunes and repacks
    for ev in prunes:
        assert {"trial", "lane", "round", "metric"} <= set(ev["attrs"])
    for ev in repacks:
        a = ev["attrs"]
        assert a["k_before"] > a["k_after"] >= 1
        # every prune for this round was emitted before its repack
        same_round = [p for p in prunes
                      if p["attrs"]["round"] == a["round"]]
        assert same_round
        assert all(p["seq"] < ev["seq"] for p in same_round)
        assert a["k_before"] - a["k_after"] == len(same_round)


def test_sequential_group_dedupe_shares_compile():
    """vectorized=False routes a lane-compatible trial group through ONE
    K=1 engine: trial 0 compiles, later trials reset_lanes into the same
    program — and the results still match per-trial sequential training."""
    from xgboost_ray_tpu.tuner import Tuner, VectorizedTrainable, grid_search

    x, y = _data(seed=7)
    shards = _shards(x, y)
    rounds = 3
    space = dict(_BASE, eta=grid_search([0.3, 0.1]))
    spec = VectorizedTrainable(shards=shards, num_actors=8,
                               num_boost_round=rounds, vectorized=False)
    tuner = Tuner(spec, space, metric="train-logloss", mode="min")
    res = tuner.fit()
    assert len(tuner.engine_cache) == 1
    (eng,) = tuner.engine_cache.values()
    assert list(eng._vk_fns) == [1], "group shares one K=1 program"
    for t, eta in zip(res.trials, [0.3, 0.1]):
        seq_hist, _ = _sequential_run(shards, dict(_BASE, eta=eta), rounds)
        got = [r["train-logloss"] for r in t.results]
        assert got == seq_hist, f"dedupe drifted trial eta={eta}"
