"""Engine-level integration tests on the 8-device virtual CPU mesh.

Re-implements the reference's keystone correctness strategy
(``xgboost_ray/tests/test_end_to_end.py:56-211``): a tiny deterministic
one-hot dataset split so each half alone overfits differently, while joint
data-parallel training — whose histograms are psum-merged across mesh shards,
our analog of Rabit's allreduce — recovers 100% accuracy on the full set.
"""

import numpy as np
import pytest

from xgboost_ray_tpu.engine import TpuEngine
from xgboost_ray_tpu.params import parse_params


def _one_hot_fixture():
    """32 rows: first half only patterns {0,1}, second half only {2,3}."""
    eye = np.eye(4, dtype=np.float32)
    first = np.tile(eye[[0, 1]], (8, 1))  # 16 rows
    second = np.tile(eye[[2, 3]], (8, 1))
    x = np.concatenate([first, second])
    y = np.concatenate([np.tile([1.0, 0.0], 8), np.tile([1.0, 0.0], 8)]).astype(np.float32)
    return x, y, eye


_PARAMS = {
    "objective": "binary:logistic",
    "max_depth": 3,
    "eta": 0.5,
    "eval_metric": ["logloss", "error"],
    "reg_lambda": 0.0,
    "min_child_weight": 0.0,
}


def _train(shards, num_actors, rounds=10, params=None, **engine_kw):
    p = parse_params(params or _PARAMS)
    eng = TpuEngine(shards, p, num_actors=num_actors, **engine_kw)
    last = None
    for i in range(rounds):
        last = eng.step(i)
    return eng, last


def test_half_training_overfits():
    x, y, eye = _one_hot_fixture()
    eng, _ = _train([{"data": x[:16], "label": y[:16]}], num_actors=1)
    bst = eng.get_booster()
    pred = bst.predict(eye)
    # patterns 0/1 are fit; pattern 2 (positive, never seen) is misclassified
    # because it falls into the f0=0 branch learned from pattern 1
    assert pred[0] > 0.9 and pred[1] < 0.1
    labels = np.array([1.0, 0.0, 1.0, 0.0])
    acc = np.mean((pred > 0.5) == (labels > 0.5))
    assert acc < 1.0
    assert pred[2] < 0.5  # the unseen positive pattern is wrong


def test_joint_training_recovers_full_accuracy():
    x, y, eye = _one_hot_fixture()
    shards = [
        {"data": x[:16], "label": y[:16]},
        {"data": x[16:], "label": y[16:]},
    ]
    eng, metrics = _train(shards, num_actors=2, evals=[(shards, "train")])
    bst = eng.get_booster()
    pred = bst.predict(eye)
    assert pred[0] > 0.9 and pred[2] > 0.9
    assert pred[1] < 0.1 and pred[3] < 0.1
    assert metrics["train"]["error"] == 0.0


def test_world_size_invariance():
    """The model must not depend on how rows are sharded (allreduce merges)."""
    x, y, _ = _one_hot_fixture()
    rng = np.random.RandomState(0)
    perm = rng.permutation(x.shape[0])
    x, y = x[perm], y[perm]
    preds = []
    for num_actors in (1, 2, 8):
        shards = [
            {"data": x[i::num_actors], "label": y[i::num_actors]}
            for i in range(num_actors)
        ]
        eng, _ = _train(shards, num_actors=num_actors)
        preds.append(eng.get_booster().predict(x))
    np.testing.assert_allclose(preds[0], preds[1], atol=1e-5)
    np.testing.assert_allclose(preds[0], preds[2], atol=1e-5)


def test_regression_converges():
    rng = np.random.RandomState(1)
    x = rng.randn(512, 6).astype(np.float32)
    y = (x[:, 0] * 2 + np.sin(x[:, 1]) + 0.1 * rng.randn(512)).astype(np.float32)
    params = {"objective": "reg:squarederror", "max_depth": 4, "eta": 0.3,
              "eval_metric": ["rmse"]}
    shards = [{"data": x, "label": y}]
    eng, metrics = _train(shards, 4, rounds=25, params=params, evals=[(shards, "train")])
    assert metrics["train"]["rmse"] < 0.35


def test_multiclass_softprob():
    rng = np.random.RandomState(2)
    n = 600
    y = rng.randint(0, 3, size=n).astype(np.float32)
    x = np.zeros((n, 3), np.float32)
    x[np.arange(n), y.astype(int)] = 1.0
    x += 0.01 * rng.randn(n, 3).astype(np.float32)
    params = {"objective": "multi:softprob", "num_class": 3, "max_depth": 3,
              "eta": 0.5, "eval_metric": ["mlogloss", "merror"]}
    shards = [{"data": x, "label": y}]
    eng, metrics = _train(shards, 2, rounds=10, params=params, evals=[(shards, "train")])
    assert metrics["train"]["merror"] == 0.0
    bst = eng.get_booster()
    proba = bst.predict(x[:10])
    assert proba.shape == (10, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    hard = bst.predict(x[:10]) .argmax(axis=1)
    np.testing.assert_array_equal(hard, y[:10].astype(int))


def test_eval_set_tracks_generalization():
    rng = np.random.RandomState(3)
    x = rng.randn(400, 5).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    train = [{"data": x[:300], "label": y[:300]}]
    valid = [{"data": x[300:], "label": y[300:]}]
    p = parse_params(_PARAMS)
    eng = TpuEngine(train, p, 2, evals=[(train, "train"), (valid, "valid")])
    hist = []
    for i in range(8):
        hist.append(eng.step(i))
    assert "valid" in hist[-1] and "logloss" in hist[-1]["valid"]
    assert hist[-1]["valid"]["logloss"] < hist[0]["valid"]["logloss"]
    assert hist[-1]["valid"]["error"] < 0.1


def test_resume_from_booster_matches_uninterrupted():
    """Checkpoint/restart determinism — the reference's crown-jewel guarantee
    (``test_fault_tolerance.py:401-449``): resuming from a mid-training
    checkpoint yields (numerically) the same model as training straight
    through."""
    rng = np.random.RandomState(4)
    x = rng.randn(300, 4).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    shards = [{"data": x, "label": y}]
    p = parse_params(_PARAMS)

    eng_full = TpuEngine(shards, p, 2)
    for i in range(10):
        eng_full.step(i)
    full = eng_full.get_booster()

    eng_a = TpuEngine(shards, p, 2)
    for i in range(5):
        eng_a.step(i)
    ckpt = eng_a.get_booster()
    eng_b = TpuEngine(shards, p, 2, init_booster=ckpt)
    for i in range(5):
        eng_b.step(i)
    resumed = eng_b.get_booster()

    assert resumed.num_boosted_rounds() == full.num_boosted_rounds() == 10
    np.testing.assert_allclose(
        full.predict(x, output_margin=True),
        resumed.predict(x, output_margin=True),
        atol=1e-4,
    )


def test_weights_shift_the_model():
    x = np.array([[0.0], [1.0]] * 50, np.float32)
    y = np.array([0.0, 1.0] * 50, np.float32)
    w_heavy0 = np.where(x[:, 0] == 0, 10.0, 1.0).astype(np.float32)
    params = {"objective": "reg:squarederror", "max_depth": 1, "eta": 1.0}
    eng1, _ = _train([{"data": x, "label": y}], 2, rounds=3, params=params)
    eng2, _ = _train([{"data": x, "label": y, "weight": w_heavy0}], 2, rounds=3, params=params)
    # weighting should not change this separable problem's fit much, but the
    # base-margin pull differs on the first rounds; both must converge to y
    np.testing.assert_allclose(eng1.get_booster().predict(x), y, atol=0.05)
    np.testing.assert_allclose(eng2.get_booster().predict(x), y, atol=0.05)


def test_subsample_colsample_still_learn():
    rng = np.random.RandomState(5)
    x = rng.randn(500, 8).astype(np.float32)
    y = (x[:, 2] > 0).astype(np.float32)
    params = dict(_PARAMS)
    params.update(subsample=0.7, colsample_bytree=0.8, colsample_bylevel=0.8)
    shards = [{"data": x, "label": y}]
    eng, metrics = _train(shards, 2, rounds=15, params=params, evals=[(shards, "train")])
    assert metrics["train"]["error"] < 0.05


def test_ranking_improves_ndcg():
    rng = np.random.RandomState(6)
    n_groups, per_group = 30, 8
    n = n_groups * per_group
    x = rng.randn(n, 4).astype(np.float32)
    rel = (x[:, 0] > 0.5).astype(np.float32) + (x[:, 1] > 0).astype(np.float32)
    qid = np.repeat(np.arange(n_groups), per_group)
    params = {"objective": "rank:ndcg", "max_depth": 3, "eta": 0.3,
              "eval_metric": ["ndcg@4"]}
    shards = [{"data": x, "label": rel, "qid": qid}]
    p = parse_params(params)
    eng = TpuEngine(shards, p, 2, evals=[(shards, "train")])
    first = eng.step(0)["train"]["ndcg@4"]
    last = None
    for i in range(1, 12):
        last = eng.step(i)["train"]["ndcg@4"]
    assert last > first
    assert last > 0.9


def test_base_margin_offsets_predictions():
    rng = np.random.RandomState(7)
    x = rng.randn(200, 3).astype(np.float32)
    y = x[:, 0].astype(np.float32)
    bm = np.full(200, 5.0, np.float32)
    params = {"objective": "reg:squarederror", "max_depth": 3, "eta": 0.5}
    eng, _ = _train(
        [{"data": x, "label": y + 5.0, "base_margin": bm}], 1, rounds=8, params=params
    )
    bst = eng.get_booster()
    pred = bst.predict(x, base_margin=bm)
    assert np.abs(pred - (y + 5.0)).mean() < 0.5


def test_missing_values_routed_by_learned_default():
    rng = np.random.RandomState(8)
    n = 400
    x = rng.randn(n, 2).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    # make 30% of feature 0 missing, with missingness correlated to label 1
    miss = (rng.rand(n) < 0.3) & (y == 1)
    x[miss, 0] = np.nan
    shards = [{"data": x, "label": y}]
    eng, metrics = _train(shards, 2, rounds=10, evals=[(shards, "train")])
    assert metrics["train"]["error"] < 0.05


def test_colsample_bynode_still_learns():
    rng = np.random.RandomState(10)
    x = rng.randn(400, 8).astype(np.float32)
    y = (x[:, 3] > 0).astype(np.float32)
    params = dict(_PARAMS)
    params.update(colsample_bynode=0.6)
    shards = [{"data": x, "label": y}]
    eng, metrics = _train(shards, 2, rounds=15, params=params,
                          evals=[(shards, "train")])
    assert metrics["train"]["error"] < 0.05


def test_incremental_forest_stacking_consistent():
    """get_booster() between checkpoint intervals must see the same forest as
    a from-scratch stack (the cache appends instead of re-concatenating)."""
    import numpy as np
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.models.booster import stack_trees
    from xgboost_ray_tpu.params import parse_params

    rng = np.random.RandomState(17)
    x = rng.randn(200, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    shards = [{"data": x, "label": y, "weight": None, "base_margin": None,
               "label_lower_bound": None, "label_upper_bound": None,
               "qid": None}]
    eng = TpuEngine(shards, parse_params({"objective": "binary:logistic",
                                          "max_depth": 3}), num_actors=1)
    snapshots = []
    for i in range(6):
        eng.step(i)
        if i % 2 == 1:
            snapshots.append(eng.get_booster())
    direct = stack_trees(eng.trees)
    cached = eng._stacked_forest()
    for a, b in zip(direct, cached):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # earlier snapshots must be unaffected by later appends
    assert snapshots[0].forest.feature.shape[0] < snapshots[-1].forest.feature.shape[0]
    p0 = snapshots[0].predict(x, output_margin=True)
    eng.step(6)
    np.testing.assert_array_equal(p0, snapshots[0].predict(x, output_margin=True))


def test_feat_has_missing_mask_and_phantom_zeroing():
    """The global per-feature has-missing mask is computed at bin time
    (padding rows excluded) and drives exact zeroing of the reconstructed
    missing bucket for features with no missing values (ADVICE r2: under
    hist_precision='fast' the bf16 rounding residue otherwise lands in the
    missing bucket and can steer the learned default direction)."""
    import numpy as np
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.params import parse_params

    rng = np.random.RandomState(21)
    x = rng.randn(299, 4).astype(np.float32)
    x[::7, 2] = np.nan  # only feature 2 has missing values
    y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float32)
    shards = [{"data": x, "label": y}]
    eng = TpuEngine(
        shards, parse_params({"objective": "binary:logistic", "max_depth": 3}),
        num_actors=2,
    )
    mask = np.asarray(eng._feat_has_missing)
    np.testing.assert_array_equal(mask, [False, False, True, False])
    # rows pad 299 -> 300 on the 2-device mesh with NaN fill; those padding
    # rows must NOT mark features as having missing values
    assert eng.pad_to > 299
    for i in range(3):
        eng.step(i)
    bst = eng.get_booster()
    acc = ((bst.predict(np.nan_to_num(x)) > 0.5) == y).mean()
    assert acc > 0.9


def test_fast_precision_no_missing_matches_highest_on_cpu():
    """With no missing values anywhere, fast and highest precision produce
    identical models on CPU (where both run f32) — exercises the
    phantom-missing zeroing path in both precision modes."""
    import numpy as np
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rng = np.random.RandomState(22)
    x = rng.randn(1500, 5).astype(np.float32)
    y = (x[:, 0] + 0.3 * x[:, 1] > 0).astype(np.float32)
    preds = {}
    for prec in ("highest", "fast"):
        bst = train({"objective": "binary:logistic", "max_depth": 4,
                     "hist_precision": prec},
                    RayDMatrix(x, y), 4, ray_params=RayParams(num_actors=2))
        preds[prec] = bst.predict(x)
    np.testing.assert_allclose(preds["fast"], preds["highest"], atol=1e-5)


def test_interleaved_step_and_scan_preserve_forest_order():
    """step_many defers whole stacked chunks while step() defers single
    rounds; a mixed sequence must flush into the exact per-round order and
    match a pure per-round run bit-for-bit (the deferred-transfer change)."""
    rng = np.random.RandomState(11)
    x = rng.randn(300, 5).astype(np.float32)
    y = (x[:, 0] + 0.3 * x[:, 1] > 0).astype(np.float32)
    p = parse_params({"objective": "binary:logistic", "max_depth": 3,
                      "eta": 0.4})
    shards = [{"data": x, "label": y}]

    eng_mixed = TpuEngine(shards, p, num_actors=2)
    assert eng_mixed.can_batch_rounds()
    eng_mixed.step_many(0, 4)         # chunk entry (stacked, n=4)
    eng_mixed.step(4)                 # single entry
    eng_mixed.step(5)                 # single entry
    eng_mixed.step_many(6, 3)         # another chunk
    assert eng_mixed.num_round_trees == 9
    bst_mixed = eng_mixed.get_booster()
    assert bst_mixed.num_boosted_rounds() == 9

    eng_seq = TpuEngine(shards, p, num_actors=2)
    for i in range(9):
        eng_seq.step(i)
    bst_seq = eng_seq.get_booster()

    np.testing.assert_allclose(
        bst_mixed.predict(x, output_margin=True),
        bst_seq.predict(x, output_margin=True), atol=1e-5,
    )
    # stacked forest fields match elementwise — round ORDER preserved, not
    # just the ensemble sum
    for t_m, t_s in zip(bst_mixed.forest, bst_seq.forest):
        np.testing.assert_allclose(np.asarray(t_m), np.asarray(t_s), atol=1e-5)


def test_scan_path_transfer_count_regression(monkeypatch):
    """Pin the r4 transfer batching (VERDICT r4 #8): a fused scan chunk must
    perform O(1) device->host reads — ONE stacked metric transfer per chunk
    (forest transfers deferred to get_booster, which then reads each Tree
    field once, batched) — regardless of how many rounds the chunk holds.
    A regression re-adding per-round reads multiplies the count by
    n_rounds and cannot pass the bounds below."""
    x, y, _ = _one_hot_fixture()
    shards = [{"data": x[i::2], "label": y[i::2]} for i in range(2)]
    p = parse_params(_PARAMS)
    eng = TpuEngine(shards, p, num_actors=2, evals=[(shards, "train")])
    assert eng.can_batch_rounds()
    eng.step_many(0, 4)  # warm-up: compiles the 4-round chunk program

    import inspect

    try:
        from jax._src import array as _jarr
    except ImportError:  # pragma: no cover - jax internals moved
        pytest.skip(
            "jax._src.array moved in this jax version; the transfer-count "
            "hook point is gone — re-find the host-materialization "
            "chokepoint before trusting transfer counts."
        )

    counts = {"d2h": 0}
    # count at the `_value` property — the single host-materialization
    # chokepoint behind np.asarray, float(), and .item() alike, so a
    # regression rewritten as per-round float(scalar) reads cannot evade
    # the bound. CI installs unpinned `-U jax`, so a PRIVATE-attribute move
    # must skip loudly instead of failing the suite for a non-repo reason
    # (ADVICE r5).
    orig = inspect.getattr_static(
        getattr(_jarr, "ArrayImpl", object), "_value", None
    )
    if not isinstance(orig, property):
        pytest.skip(
            "private jax attribute ArrayImpl._value is no longer a "
            "property in this jax version; the transfer-count "
            "instrumentation point moved — update the hook, the batching "
            "itself is untested here."
        )

    def counting_value(self):
        counts["d2h"] += 1
        return orig.fget(self)

    monkeypatch.setattr(_jarr.ArrayImpl, "_value", property(counting_value))

    eng.step_many(4, 4)  # same shape -> no recompile, pure steady state
    chunk_reads = counts["d2h"]
    assert chunk_reads <= 3, (
        f"{chunk_reads} device->host reads in one 4-round scan chunk; "
        f"expected one stacked metric transfer (the r4 batching)"
    )

    counts["d2h"] = 0
    eng.get_booster()
    flush_reads = counts["d2h"]
    # one batched read per Tree field (9) + cuts + small constant slack;
    # NOT proportional to the 8 trained rounds
    assert flush_reads <= 14, (
        f"{flush_reads} device->host reads in get_booster(); forest "
        f"flush must stay one batched read per field"
    )
