"""tools/rxgbverify: jaxpr-level verifier tests.

Fixture programs are hand-built ``progreg.ProgramRecord``s traced through
the real walker — every true-positive below is a program that would pass
rxgblint's AST rules (the hazard lives in the traced jaxpr, which is the
whole point of the second layer). The quick-matrix test is the tier-1 gate
that the SHIPPED package verifies clean, mirroring test_lint's
shipped-package-lints-clean pattern.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tools.rxgblint import catalog
from tools.rxgbverify import checks, walker
from tools.rxgbverify.matrix import trace_matrix
from xgboost_ray_tpu import progreg
from xgboost_ray_tpu.compat import shard_map_compat as shard_map
from xgboost_ray_tpu.constants import AXIS_ACTORS
from xgboost_ray_tpu.engine import TpuEngine
from xgboost_ray_tpu.ops.histogram import quantized_hist_allreduce
from xgboost_ray_tpu.params import parse_params

MESH_AXES = catalog.mesh_axes()


def _meta(**over):
    meta = {
        "world": 4, "grower": "depthwise", "hist_quant": "none",
        "sampling": "none", "n_outputs": 1, "max_depth": 3, "max_leaves": 0,
    }
    meta.update(over)
    return meta


def _trace(fn, avals, name="engine.step", donate=(), **meta_over):
    rec = progreg.ProgramRecord(
        name=name, fn=fn, abstract_args=tuple(avals),
        donate_argnums=tuple(donate), meta=_meta(**meta_over),
        source=(os.path.abspath(__file__), 1),
    )
    return walker.trace_record(rec)


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), (AXIS_ACTORS,))


def _sharded(body, n=4, n_in=1):
    specs = tuple(P(AXIS_ACTORS) for _ in range(n_in))
    return shard_map(
        body, mesh=_mesh(n),
        in_specs=specs if n_in > 1 else specs[0],
        out_specs=P(AXIS_ACTORS),
    )


F32V = jax.ShapeDtypeStruct((8, 16), "float32")


# ---------------------------------------------------------------------------
# walker
# ---------------------------------------------------------------------------

def test_walker_extracts_ordered_schedule():
    def body(x):
        s = jax.lax.psum(x, AXIS_ACTORS)
        m = jax.lax.pmax(x, AXIS_ACTORS)
        return s + m

    t = _trace(_sharded(body), (F32V,))
    assert t.ok, t.error
    prims = [c.prim for c in t.analysis.collectives]
    assert prims == ["psum", "pmax"]
    for c in t.analysis.collectives:
        assert c.axes == (AXIS_ACTORS,)
        assert c.dtype == "float32"
        assert "shard_map" in c.path


def test_walker_recurses_scan_and_flags_cond():
    def body(x):
        def step(carry, _):
            return jax.lax.psum(carry, AXIS_ACTORS), ()

        x, _ = jax.lax.scan(step, x, None, length=3)
        # a collective only SOME ranks reach: the cond-branch hazard
        return jax.lax.cond(
            x[0, 0] > 0,
            lambda v: jax.lax.pmax(v, AXIS_ACTORS),
            lambda v: v,
            x,
        )

    t = _trace(_sharded(body), (F32V,))
    assert t.ok, t.error
    by_prim = {c.prim: c for c in t.analysis.collectives}
    assert "scan" in by_prim["psum"].path and not by_prim["psum"].in_cond
    assert by_prim["pmax"].in_cond
    findings = checks.check_cond_collectives([t])
    assert [f.rule for f in findings] == ["VER002"]
    assert "cond branch" in findings[0].message


def test_fingerprint_stable_and_sensitive():
    body = _sharded(lambda x: jax.lax.psum(x, AXIS_ACTORS))
    t1 = _trace(body, (F32V,))
    t2 = _trace(body, (F32V,))
    assert t1.fingerprint == t2.fingerprint  # same program -> same hash
    bigger = jax.ShapeDtypeStruct((16, 16), "float32")
    t3 = _trace(body, (bigger,))
    assert t3.fingerprint != t1.fingerprint  # aval drift is visible
    # donation is part of the program identity
    assert walker.fingerprint(t1.closed_jaxpr, (0,)) != t1.fingerprint


# ---------------------------------------------------------------------------
# VER001 cross-world schedule identity (true positive + clean negative)
# ---------------------------------------------------------------------------

def _world_pair(body2, body4):
    t2 = _trace(_sharded(body2, n=2), (F32V,), world=2)
    t4 = _trace(_sharded(body4, n=4), (F32V,), world=4)
    return [t2, t4]


def test_schedule_identity_true_positive():
    # world=2 runs psum->pmax, world=4 runs pmax->psum: on an elastic
    # grow-back these two compiled programs would interleave mismatched
    # collectives — the torn-allreduce hang. Shapes/AST are identical.
    def b2(x):
        return jax.lax.pmax(jax.lax.psum(x, AXIS_ACTORS), AXIS_ACTORS)

    def b4(x):
        return jax.lax.psum(jax.lax.pmax(x, AXIS_ACTORS), AXIS_ACTORS)

    findings = checks.check_schedule_identity(_world_pair(b2, b4))
    assert [f.rule for f in findings] == ["VER001"]
    assert "world=4" in findings[0].message
    # the true positive fails the gate end to end
    assert checks.run_checks(_world_pair(b2, b4), MESH_AXES)


def test_schedule_identity_clean_across_shard_extents():
    # identical schedule, different world (so different shard extents after
    # shard_map division): must NOT alarm — that is exactly the legitimate
    # shrink/grow recompile delta
    def body(x):
        return jax.lax.psum(x * 2, AXIS_ACTORS)

    assert checks.check_schedule_identity(_world_pair(body, body)) == []


def test_schedule_identity_dtype_drift_is_flagged():
    def b2(x):
        return jax.lax.psum(x, AXIS_ACTORS)

    def b4(x):
        return jax.lax.psum(x.astype(jnp.bfloat16), AXIS_ACTORS).astype(
            jnp.float32
        )

    findings = checks.check_schedule_identity(_world_pair(b2, b4))
    assert [f.rule for f in findings] == ["VER001"]


# ---------------------------------------------------------------------------
# VER003 axis catalog / VER005 f64 / VER006 donation / TRACE
# ---------------------------------------------------------------------------

def test_axis_name_true_positive():
    mesh = Mesh(np.array(jax.devices()[:4]), ("workers",))
    body = shard_map(
        lambda x: jax.lax.psum(x, "workers"), mesh=mesh,
        in_specs=P("workers"), out_specs=P("workers"),
    )
    t = _trace(body, (F32V,))
    findings = checks.check_axis_names([t], MESH_AXES)
    assert [f.rule for f in findings] == ["VER003"]
    assert "workers" in findings[0].message


def test_axis_catalog_accepts_declared_axis():
    t = _trace(_sharded(lambda x: jax.lax.psum(x, AXIS_ACTORS)), (F32V,))
    assert checks.check_axis_names([t], MESH_AXES) == []


def test_no_f64_true_positive():
    def body(x):
        return x.astype(jnp.float64).sum()

    with jax.experimental.enable_x64():
        t = _trace(body, (F32V,))
    assert t.ok, t.error
    findings = checks.check_no_f64([t])
    assert [f.rule for f in findings] == ["VER005"]
    assert "float64" in findings[0].message


def test_donation_unused_true_positive():
    # donated [8,16] f32 input, but the only output is a scalar: XLA can
    # alias nothing — the donation only invalidates the caller's buffer
    t = _trace(lambda x: x.sum(), (F32V,), donate=(0,))
    findings = checks.check_donation([t])
    assert [f.rule for f in findings] == ["VER006"]
    assert "matches no output" in findings[0].message
    # matching shape+dtype output: clean
    t2 = _trace(lambda x: x * 2, (F32V,), donate=(0,))
    assert checks.check_donation([t2]) == []


def test_trace_failure_is_a_finding():
    def broken(x):
        raise ValueError("planted")

    t = _trace(broken, (F32V,))
    assert not t.ok
    findings = checks.check_trace_failures([t])
    assert [f.rule for f in findings] == ["TRACE"]
    assert "planted" in findings[0].message


# ---------------------------------------------------------------------------
# VER004 precision flow (true positives + the golden int8 schedule)
# ---------------------------------------------------------------------------

def _quant_body(mode, n, upcast=False):
    def body(h):
        if upcast:
            # the planted defect: one convert_element_type -> f32 before
            # the wire, silently re-inflating every quantized byte
            q = jnp.clip(jnp.round(h), -127, 127).astype(jnp.int8)
            w = q.astype(jnp.float32)
            out = jax.lax.all_to_all(w.reshape(n, -1), AXIS_ACTORS, 0, 0)
            acc = out.sum(0).astype(jnp.int8)
            g = jax.lax.all_gather(acc, AXIS_ACTORS, tiled=True)
            return g.astype(jnp.float32).reshape(h.shape)
        return quantized_hist_allreduce(h, AXIS_ACTORS, mode, n, None,
                                        min_bytes=0)

    return body


_HIST = jax.ShapeDtypeStruct((8, 7, 16, 2), "float32")  # sharded dim0 by 4


def test_precision_flow_upcast_true_positive():
    t = _trace(_sharded(_quant_body("int8", 4, upcast=True)), (_HIST,),
               hist_quant="int8")
    findings = checks.check_precision_flow([t])
    assert any(f.rule == "VER004" and "upcast before the wire" in f.message
               for f in findings)
    assert checks.run_checks([t], MESH_AXES)  # fails the gate


def test_precision_flow_fallback_psum_true_positive():
    # hist_quant=int8 config whose program still psums the full f32
    # histogram (the min_bytes fallback engaging where it must not): the
    # quantization was silently defeated
    def body(h):
        return jax.lax.psum(h, AXIS_ACTORS)

    t = _trace(_sharded(body), (_HIST,), hist_quant="int8")
    findings = checks.check_precision_flow([t])
    rules = {f.rule for f in findings}
    assert rules == {"VER004"}
    assert any("f32 histogram psum survives" in f.message for f in findings)


def test_precision_flow_ignores_unquantized_programs():
    def body(h):
        return jax.lax.psum(h, AXIS_ACTORS)

    t = _trace(_sharded(body), (_HIST,), hist_quant="none")
    assert checks.check_precision_flow([t]) == []


@pytest.mark.parametrize("mode,narrow", [("int8", "int8"), ("int16", "int16")])
def test_quantized_hist_allreduce_golden_schedule(mode, narrow):
    """Golden jaxpr schedule for ops/histogram.py's quantized path: exactly
    pmax(f32 scales) -> all_to_all(narrow) -> all_gather(narrow), with NO
    psum of the main payload — the program-level proof that the int8 wire
    format of PR 1 is what actually ships."""
    t = _trace(_sharded(_quant_body(mode, 4)), (_HIST,), hist_quant=mode)
    assert t.ok, t.error
    sched = [(c.prim, c.dtype) for c in t.analysis.collectives]
    assert sched == [
        ("pmax", "float32"),       # shared per-(node,feature) scales
        ("all_to_all", narrow),    # reduce-scatter, narrow wire
        ("all_gather", narrow),    # requantized gather (scales ride inside)
    ]
    assert checks.check_precision_flow([t]) == []


def test_unquantized_hist_allreduce_golden_schedule():
    t = _trace(_sharded(_quant_body("none", 4)), (_HIST,), hist_quant="none")
    sched = [(c.prim, c.dtype) for c in t.analysis.collectives]
    assert sched == [("psum", "float32")]


@pytest.mark.parametrize(
    "mode,narrow", [("int8_block", "int8"), ("int16_block", "int16")]
)
def test_block_hist_allreduce_golden_schedule(mode, narrow):
    """Golden jaxpr schedule for the block-scaled (EQuARX) path: exactly
    n-1 narrow ppermute ring hops then one narrow all_gather — NO absmax
    pmax pre-pass, NO all_to_all, NO psum of the payload. The deleted
    full-latency collective is pinned absent at the program level."""
    t = _trace(_sharded(_quant_body(mode, 4)), (_HIST,), hist_quant=mode)
    assert t.ok, t.error
    sched = [(c.prim, c.dtype) for c in t.analysis.collectives]
    assert sched == [("ppermute", narrow)] * 3 + [("all_gather", narrow)]
    assert checks.check_precision_flow([t]) == []


def test_block_precision_flow_row_program_claiming_block_meta():
    """Planted lie, direction 1: a ROW-scale program shipped under block
    meta must flag every way — the pmax pre-pass survives, the ring is
    missing, and the row all_to_all survives."""
    t = _trace(_sharded(_quant_body("int8", 4)), (_HIST,),
               hist_quant="int8_block")
    findings = checks.check_precision_flow([t])
    msgs = [f.message for f in findings]
    assert all(f.rule == "VER004" for f in findings)
    assert any("pmax pre-pass survives" in m for m in msgs)
    assert any("no ppermute" in m for m in msgs)
    assert any("all_to_all reduce-scatter survives" in m for m in msgs)
    assert checks.run_checks([t], MESH_AXES)  # fails the gate


def test_block_precision_flow_block_program_claiming_row_meta():
    """Planted lie, direction 2: a BLOCK-scale program shipped under row
    meta must flag too — the row contract's all_to_all stage is missing."""
    t = _trace(_sharded(_quant_body("int8_block", 4)), (_HIST,),
               hist_quant="int8")
    findings = checks.check_precision_flow([t])
    assert any(f.rule == "VER004" and "no all_to_all" in f.message
               for f in findings)


def test_block_precision_flow_upcast_ring_true_positive():
    """A ppermute ring whose hop payload was upcast to f32 defeats the
    narrow wire — flagged per hop."""
    def body(h):
        perm = [(i, (i + 1) % 4) for i in range(4)]
        cur = h.reshape(-1)
        for _ in range(3):
            q = jnp.clip(jnp.round(cur), -127, 127).astype(jnp.int8)
            cur = jax.lax.ppermute(
                q.astype(jnp.float32), AXIS_ACTORS, perm
            )
        g = jax.lax.all_gather(
            cur.astype(jnp.int8), AXIS_ACTORS, tiled=True
        )
        return g.astype(jnp.float32)[:h.size].reshape(h.shape)

    t = _trace(_sharded(body), (_HIST,), hist_quant="int8_block")
    assert t.ok, t.error
    findings = checks.check_precision_flow([t])
    assert any(
        f.rule == "VER004" and "ppermute hop payload is float32" in f.message
        for f in findings
    )


def test_schedule_identity_collapses_ring_hops_across_worlds():
    """VER001 canonicalization: world 2 traces 1 ring hop, world 4 traces 3
    — the same collapsed pattern, NOT a divergence (the hop count derives
    from the axis size every rank agrees on). A dtype drift inside the ring
    still flags."""
    def ring(n, dtype):
        def body(h):
            perm = [(i, (i + 1) % n) for i in range(n)]
            cur = jnp.clip(jnp.round(h.reshape(-1)), -127, 127).astype(dtype)
            for _ in range(n - 1):
                cur = jax.lax.ppermute(cur, AXIS_ACTORS, perm)
            g = jax.lax.all_gather(cur, AXIS_ACTORS, tiled=True)
            return g.astype(jnp.float32)[:h.size].reshape(h.shape)
        return body

    shard2 = jax.ShapeDtypeStruct((16, 7, 16, 2), "float32")
    t2 = _trace(_sharded(ring(2, jnp.int8), n=2), (shard2,), world=2,
                hist_quant="int8_block")
    t4 = _trace(_sharded(ring(4, jnp.int8)), (_HIST,), world=4,
                hist_quant="int8_block")
    assert t2.ok and t4.ok, (t2.error, t4.error)
    assert checks.check_schedule_identity([t2, t4]) == []

    t4_wide = _trace(_sharded(ring(4, jnp.int16)), (_HIST,), world=4,
                     hist_quant="int8_block")
    findings = checks.check_schedule_identity([t2, t4_wide])
    assert [f.rule for f in findings] == ["VER001"]


# ---------------------------------------------------------------------------
# registry + engine integration
# ---------------------------------------------------------------------------

def _tiny_shards(rows=32, feats=4, seed=3):
    rng = np.random.RandomState(seed)
    x = rng.rand(rows, feats).astype(np.float32)
    y = (rng.rand(rows) > 0.5).astype(np.float32)
    return [{"data": x, "label": y}]


_TINY_PARAMS = {"objective": "binary:logistic", "max_depth": 2,
                "eval_metric": ["logloss"]}


def test_registry_capture_gating():
    shards = _tiny_shards()
    progreg.clear()
    eng = TpuEngine(shards, parse_params(_TINY_PARAMS), num_actors=4)
    eng.build_programs()
    assert progreg.records() == []  # capture off: production pays nothing
    with progreg.capture():
        progreg.clear()
        eng2 = TpuEngine(shards, parse_params(_TINY_PARAMS), num_actors=4)
        eng2.build_programs()
        names = {r.name for r in progreg.records()}
    progreg.clear()
    assert "engine.step" in names and "engine.sketch_cuts" in names


def test_growback_same_record_same_fingerprint():
    """The elastic no-silent-recompile pin: (a) ``reset_from_booster`` — the
    engine-cache grow-back path — reuses the SAME compiled step program
    object, and (b) rebuilding the same config over the same shard layout
    re-registers into the SAME registry record (registrations bump, no new
    key) whose abstract re-trace yields the IDENTICAL fingerprint."""
    shards = _tiny_shards()
    with progreg.capture():
        progreg.clear()
        eng = TpuEngine(shards, parse_params(_TINY_PARAMS), num_actors=4)
        eng.step(0)
        rec1 = [r for r in progreg.records() if r.name == "engine.step"]
        assert len(rec1) == 1
        fp1 = walker.trace_record(rec1[0]).fingerprint
        assert fp1 and not fp1.startswith("trace-error")

        # (a) in-place grow-back: compiled program survives the reset
        step_fn = eng._step_fn
        eng.reset_from_booster(shards, [], eng.get_booster())
        assert eng._step_fn is step_fn
        eng.step(1)  # still dispatches (and re-registers nothing new)

        # (b) cache-miss rebuild of the same world: dedupes onto the record
        eng2 = TpuEngine(shards, parse_params(_TINY_PARAMS), num_actors=4)
        eng2.build_programs()
        rec2 = [r for r in progreg.records() if r.name == "engine.step"]
        assert len(rec2) == 1 and rec2[0].registrations >= 2
        assert walker.trace_record(rec2[0]).fingerprint == fp1
    progreg.clear()


def test_quick_matrix_ships_clean():
    """Tier-1 gate: the shipped package's programs verify clean over the
    quick matrix (depthwise f32 + int8, worlds 2 and 4)."""
    traced = trace_matrix(quick=True)
    assert traced and all(t.ok for t in traced), [t.error for t in traced]
    findings = checks.run_checks(traced, MESH_AXES, root=catalog.REPO_ROOT)
    assert findings == [], [f.render() for f in findings]
    # guard against a vacuous pass: the VER001 grouping must actually see
    # multiple worlds of the same config, and VER004 must see int8 programs
    worlds = {t.record.meta["world"] for t in traced
              if t.record.name == "engine.step"}
    assert {2, 4} <= worlds
    assert any(t.record.meta.get("hist_quant") == "int8" for t in traced)
    # and the int8 rows really carry the narrow wire the check certifies
    int8_steps = [t for t in traced
                  if t.record.name == "engine.step"
                  and t.record.meta.get("hist_quant") == "int8"]
    assert int8_steps
    for t in int8_steps:
        assert any(c.prim == "all_to_all" and c.dtype == "int8"
                   for c in t.analysis.collectives)
    # the gh_precision rows really carry the quantized gradient plane the
    # VER004 gh sub-checks certify: int8 avals present, and the histogram
    # merge is the exact int32 psum (not a silent f32 upcast)
    int8gh_steps = [t for t in traced
                    if t.record.name == "engine.step"
                    and t.record.meta.get("gh_precision") == "int8"]
    assert int8gh_steps
    for t in int8gh_steps:
        assert "int8" in t.analysis.dtypes
        assert any(c.prim == "psum" and c.dtype == "int32"
                   and len(c.shape) >= 4
                   for c in t.analysis.collectives)
        assert not any(c.prim == "psum" and c.dtype == "float32"
                       and len(c.shape) >= 4
                       for c in t.analysis.collectives)


# ---------------------------------------------------------------------------
# RXGB_STRICT runtime transfer guard (the SYNC001 runtime counterpart)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("booster", ["gbtree", "dart"])
def test_strict_guard_clean_training(monkeypatch, booster):
    # dart pins the per-round scalar uploads (drop weights, tree index)
    # being built BEFORE the guard — they are legitimate dispatch inputs,
    # not smuggled syncs
    monkeypatch.setenv("RXGB_STRICT", "1")
    shards = _tiny_shards()
    params = parse_params({**_TINY_PARAMS, "booster": booster})
    eng = TpuEngine(shards, params, num_actors=4,
                    **({"total_rounds": 3} if booster == "dart" else {}))
    for i in range(3):  # cold compile + two guarded warm rounds
        eng.step(i)
    pred = eng.get_booster().predict(shards[0]["data"])
    assert np.all(np.isfinite(pred))


def test_strict_guard_trips_on_planted_host_sync(monkeypatch):
    """A smuggled host round-trip in the round dispatch (read a device
    value to host, feed the host copy back) must raise under RXGB_STRICT=1
    on the warm path — and pass silently without the knob (the bug class
    this guards: every round quietly re-uploading, serializing the
    pipeline)."""
    shards = _tiny_shards()
    eng = TpuEngine(shards, parse_params(_TINY_PARAMS), num_actors=4)
    eng.step(0)  # warm: arms the guard for subsequent dispatches

    real_fn = eng._step_fn

    def smuggled(*args):
        args = list(args)
        args[4] = np.asarray(args[4])  # .item()-style host read of margins
        return real_fn(*args)  # ...fed back: an implicit re-upload per round

    eng._step_fn = smuggled
    monkeypatch.delenv("RXGB_STRICT", raising=False)
    eng.step(1)  # without the knob the sync passes silently
    monkeypatch.setenv("RXGB_STRICT", "1")
    with pytest.raises(Exception, match="[Dd]isallowed.*transfer"):
        eng.step(2)
    eng._step_fn = real_fn
    eng.step(2)  # un-smuggled engine recovers under the same knob


# ---------------------------------------------------------------------------
# SARIF output (golden-file + CLI)
# ---------------------------------------------------------------------------

_GOLDEN = os.path.join(os.path.dirname(__file__), "goldens",
                       "sarif_golden.json")


def test_sarif_golden_file():
    """Byte-stable SARIF shape shared by both tools: serialization drift
    (key order, schema uri, location shape) breaks annotation consumers
    silently, so the exact document is pinned."""
    from tools.sarif import to_sarif_json

    doc = to_sarif_json(
        "rxgbverify",
        {"VER001": "schedule mismatch", "VER004": "precision flow"},
        [
            {"rule": "VER004", "message": "upcast before the wire",
             "path": "xgboost_ray_tpu/engine.py", "line": 42},
            {"rule": "XXX999", "message": "unknown rule keeps no index",
             "path": "a.py", "line": 0, "level": "warning"},
        ],
    )
    with open(_GOLDEN) as fh:
        assert json.loads(doc) == json.load(fh)
        fh.seek(0)
        assert doc + "\n" == fh.read()  # byte-for-byte, trailing newline


def test_rxgblint_cli_sarif(tmp_path):
    from tools.rxgblint.__main__ import main as lint_main

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n"
        "def f(rank, h):\n"
        "    if rank == 0:\n"
        "        return jax.lax.psum(h, 'actors')\n"
        "    return h\n"
    )
    out = tmp_path / "out.sarif"
    rc = lint_main([str(bad), "--baseline", "", "--sarif", str(out)])
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "rxgblint"
    results = run["results"]
    assert results and results[0]["ruleId"] == "SPMD001"
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 5


def test_rxgbverify_cli_quick(tmp_path):
    """End-to-end CLI over the quick matrix: exit 0, JSON artifact carries
    fingerprints + collectives per program, SARIF is empty-but-valid."""
    from tools.rxgbverify.__main__ import main as verify_main

    j = tmp_path / "v.json"
    s = tmp_path / "v.sarif"
    fp = tmp_path / "fp.json"
    rc = verify_main(["--quick", "--json", str(j), "--sarif", str(s),
                      "--fingerprints", str(fp)])
    assert rc == 0
    doc = json.loads(j.read_text())
    assert doc["tool"] == "rxgbverify" and doc["findings"] == []
    assert doc["programs"]
    for entry in doc["programs"].values():
        assert entry["fingerprint"]
    fps = json.loads(fp.read_text())["programs"]
    assert set(fps) == set(doc["programs"])
    sarif_doc = json.loads(s.read_text())
    assert sarif_doc["runs"][0]["results"] == []
