"""Tests for tools/rxgblint: per-rule true-positive + clean-negative
fixtures, pragma and baseline behavior, and the tier-1 gate asserting the
shipped package lints clean (a future regression fails here, same pattern
as the bench tripwires).

Pure-stdlib: the linter never imports the package under analysis, so these
tests run without jax.
"""

import json
import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.rxgblint import (  # noqa: E402
    BaselineError,
    RULES,
    lint_source,
    report_to_json,
    run_lint,
)
from tools.rxgblint.baseline import DEFAULT_BASELINE  # noqa: E402
from tools.rxgblint.catalog import REPO_ROOT  # noqa: E402

PKG = os.path.join(REPO_ROOT, "xgboost_ray_tpu")


def codes(findings, include_suppressed=False):
    return [
        f.rule for f in findings if include_suppressed or not f.suppressed
    ]


def lint(src, path="mod.py", **kw):
    return lint_source(textwrap.dedent(src), path=path, **kw)


# ---------------------------------------------------------------------------
# SPMD001 — collectives under rank-dependent control flow
# ---------------------------------------------------------------------------


def test_spmd001_true_positive_rank_branch():
    findings = lint("""
        import jax
        def f(x, rank):
            if rank == 0:
                return jax.lax.psum(x, "actors")
            return x
    """)
    assert codes(findings) == ["SPMD001"]
    assert "hang" in findings[0].message


def test_spmd001_true_positive_process_index_call():
    findings = lint("""
        import jax
        def f(x):
            if jax.process_index() == 0:
                x = jax.lax.all_gather(x, "actors")
            return x
    """)
    assert "SPMD001" in codes(findings)


def test_spmd001_clean_uniform_branch_and_hoisted_collective():
    findings = lint("""
        import jax
        def f(x, n_actors, rank):
            s = jax.lax.psum(x, "actors")      # unconditional: fine
            if n_actors > 1:                    # world-uniform condition
                s = jax.lax.pmax(s, "actors")
            idx = jax.lax.axis_index("actors")  # divergence-safe primitive
            if rank == 0:
                s = s + idx                     # no collective in branch
            return s
    """)
    assert codes(findings) == []


# ---------------------------------------------------------------------------
# SPMD002 — axis names from the mesh catalog
# ---------------------------------------------------------------------------


def test_spmd002_true_positive_unknown_axis():
    findings = lint("""
        import jax
        def f(x):
            return jax.lax.psum(x, "actorz")
    """)
    assert codes(findings) == ["SPMD002"]
    assert "actorz" in findings[0].message


def test_spmd002_clean_catalog_axis_and_axis_name_param():
    findings = lint("""
        import jax
        def helper(x, axis_name):
            return jax.lax.psum(x, axis_name)
        def f(x):
            return jax.lax.pmax(helper(x, "actors"), "actors")
    """)
    assert codes(findings) == []


def test_spmd002_opaque_variable_axis_flagged():
    findings = lint("""
        import jax
        def f(x, ax):
            return jax.lax.psum(x, ax)
    """)
    assert codes(findings) == ["SPMD002"]


# ---------------------------------------------------------------------------
# DET001 — nondeterminism sources
# ---------------------------------------------------------------------------


def test_det001_true_positive_global_np_random():
    findings = lint("""
        import numpy as np
        def f(n):
            return np.random.rand(n)
    """)
    assert codes(findings) == ["DET001"]


def test_det001_true_positive_time_in_traced():
    findings = lint("""
        import jax, time
        def f(x):
            return x + time.time()
        g = jax.jit(f)
    """)
    assert codes(findings) == ["DET001"]
    assert "trace time" in findings[0].message


def test_det001_true_positive_unsalted_fold_literal():
    findings = lint("""
        import jax
        def f(key):
            return jax.random.fold_in(key, 1234)
    """)
    assert codes(findings) == ["DET001"]
    assert "SALT_" in findings[0].message


def test_det001_true_positive_prngkey_from_clock():
    findings = lint("""
        import jax, time
        def f():
            return jax.random.PRNGKey(time.time_ns())
    """)
    assert "DET001" in codes(findings)


def test_det001_true_positive_set_iteration():
    findings = lint("""
        def f(items):
            out = []
            for x in set(items):
                out.append(x)
            return out
    """)
    assert codes(findings) == ["DET001"]
    assert "sorted" in findings[0].message


def test_det001_clean_seeded_and_salted():
    findings = lint("""
        import jax, time
        import numpy as np
        from xgboost_ray_tpu.ops.grow import SALT_BYTREE
        def f(params, iteration, items):
            rng = np.random.RandomState(0)           # seeded: fine
            key = jax.random.PRNGKey(params.seed)     # from a seed: fine
            key = jax.random.fold_in(key, iteration)  # non-literal: fine
            key = jax.random.fold_in(key, SALT_BYTREE)
            t0 = time.time()                          # host code: fine
            return sorted(set(items)), key, t0
    """)
    assert codes(findings) == []


def test_det001_sr_salt_catalogued_and_neighbors_still_flag():
    """SALT_SR (stochastic gh rounding, gh_precision) is auto-extracted
    into the DET001 salt domain — its literal value folds clean without a
    pragma — while an uncatalogued neighbor value still flags: the domain
    grew by exactly the declared constant, not by becoming vacuous."""
    from tools.rxgblint import catalog

    assert 0x51D6 in catalog.salt_values()  # SALT_SR (ops/grow.py)
    clean = lint("""
        import jax
        def f(key):
            return jax.random.fold_in(key, 0x51D6)
    """)
    assert codes(clean) == []
    flagged = lint("""
        import jax
        def f(key):
            return jax.random.fold_in(key, 0x51D7)
    """)
    assert codes(flagged) == ["DET001"]
    assert "SALT_" in flagged[0].message


# ---------------------------------------------------------------------------
# SYNC001 — host syncs in traced code
# ---------------------------------------------------------------------------


def test_sync001_true_positive_float_and_item_in_traced():
    findings = lint("""
        import jax
        import numpy as np
        def f(x):
            a = float(x.sum())
            b = x.max().item()
            c = np.asarray(x)
            return a + b + c[0]
        g = jax.jit(f)
    """)
    assert codes(findings) == ["SYNC001"] * 3


def test_sync001_true_positive_shard_map_closure():
    findings = lint("""
        from xgboost_ray_tpu.compat import shard_map_compat
        def build(mesh, specs):
            def fn(x):
                return bool(x.any())
            return shard_map_compat(fn, mesh=mesh, in_specs=specs,
                                    out_specs=specs)
    """)
    assert codes(findings) == ["SYNC001"]


def test_sync001_clean_host_code_and_jnp():
    findings = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np
        def f(x):
            return jnp.asarray(x) + 1
        g = jax.jit(f)
        def host(result):
            return float(np.asarray(result).sum())  # untraced: fine
    """)
    assert codes(findings) == []


def test_sync001_clean_literal_args_in_traced():
    # float("inf")/bool(0) sentinels inside traced code touch no traced
    # value — flagging them would force pragmas on idiomatic init code
    findings = lint("""
        import jax
        import jax.numpy as jnp
        def f(x):
            lo = jnp.full(x.shape, float("-inf"))
            return jnp.maximum(x, lo) + float("inf") * 0
        g = jax.jit(f)
    """)
    assert codes(findings) == []


def test_sync001_method_name_collision_is_not_traced():
    # a method sharing its name with a traced inner closure elsewhere must
    # not inherit traced status (lexical scoping, not global name match)
    findings = lint("""
        import jax
        class Engine:
            def _make(self):
                def step(x):
                    return x
                return jax.jit(step)
            def step(self, x):
                return float(x)  # host-side driver method: fine
    """)
    assert codes(findings) == []


# ---------------------------------------------------------------------------
# LOCK001 — shared state outside the lock
# ---------------------------------------------------------------------------


def test_lock001_true_positive_unguarded_write():
    findings = lint("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def inc(self):
                with self._lock:
                    self._n += 1
            def smash(self):
                self._n = 0
    """)
    assert codes(findings) == ["LOCK001"]
    assert "write" in findings[0].message
    assert findings[0].scope == "C.smash"


def test_lock001_true_positive_unguarded_read_and_condition_lock():
    findings = lint("""
        import threading
        class C:
            def __init__(self):
                self._cond = threading.Condition(threading.Lock())
                self._depth = 0
            def push(self):
                with self._cond:
                    self._depth += 1
            def peek(self):
                return self._depth
    """)
    assert codes(findings) == ["LOCK001"]
    assert "read" in findings[0].message


def test_lock001_locked_suffix_contract_both_ends():
    findings = lint("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def _bump_locked(self):
                self._n += 1     # exempt: caller holds the lock
            def ok(self):
                with self._lock:
                    self._bump_locked()
            def bad(self):
                self._bump_locked()   # contract breach: no lock held
    """)
    assert codes(findings) == ["LOCK001"]
    assert "_locked" in findings[0].message
    assert findings[0].scope == "C.bad"


def test_lock001_clean_guarded_class_and_lockless_class():
    findings = lint("""
        import threading
        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def inc(self):
                with self._lock:
                    self._n += 1
            def get(self):
                with self._lock:
                    return self._n
        class Plain:  # no lock declared: not subject to the rule
            def __init__(self):
                self._n = 0
            def inc(self):
                self._n += 1
    """)
    assert codes(findings) == []


def test_lock001_wrong_lock_flagged_nested_locks_clean():
    # holding SOME lock of the class is not holding THE lock that guards
    # the attribute's writes — a wrong-lock read tears just like no lock
    findings = lint("""
        import threading
        class TwoLocks:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()
                self._x = 0
            def inc(self):
                with self._lock:
                    self._x += 1
            def get(self):
                with self._other:
                    return self._x
    """)
    assert codes(findings) == ["LOCK001"]
    assert "wrong lock" in findings[0].message
    # nested acquisition (outer serializer + inner guard) stays clean:
    # the owning lock IS among those held (the ModelRegistry.load shape)
    findings = lint("""
        import threading
        class Nested:
            def __init__(self):
                self._outer = threading.Lock()
                self._lock = threading.Lock()
                self._x = 0
            def swap(self):
                with self._outer:
                    with self._lock:
                        self._x += 1
            def get(self):
                with self._lock:
                    return self._x
    """)
    assert codes(findings) == []


# ---------------------------------------------------------------------------
# FAULT001 — fault sites must come from faults.SITES
# ---------------------------------------------------------------------------


def test_fault001_true_positive_typo_site():
    findings = lint("""
        from xgboost_ray_tpu import faults
        def f():
            faults.fire("actor.train_rund", round=1)
    """)
    assert codes(findings) == ["FAULT001"]
    assert "actor.train_rund" in findings[0].message


def test_fault001_true_positive_dynamic_site():
    findings = lint("""
        from xgboost_ray_tpu import faults
        def f(site):
            faults.fire(site, round=1)
    """)
    assert codes(findings) == ["FAULT001"]


def test_fault001_clean_catalogued_sites():
    findings = lint("""
        from xgboost_ray_tpu import faults
        def f(path):
            faults.fire("actor.train_round", round=1)
            faults.fire_file("checkpoint.save", path, round=2)
            return faults.plan_targets("serve.predict")
    """)
    assert codes(findings) == []


def test_fault001_reverse_coverage(tmp_path):
    # a catalogued site with no call site anywhere is a finding anchored
    # at faults.py
    pkg = tmp_path / "xgboost_ray_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "faults.py").write_text(
        'SITES = ("used.site", "orphan.site")\n'
        "def fire(site, **ctx):\n    pass\n"
    )
    (pkg / "user.py").write_text(
        "from xgboost_ray_tpu import faults\n"
        'def f():\n    faults.fire("used.site")\n'
    )
    report = run_lint([str(pkg)], root=str(tmp_path), baseline_path="")
    msgs = [f.message for f in report["open"] if f.rule == "FAULT001"]
    assert len(msgs) == 1 and "orphan.site" in msgs[0]


# ---------------------------------------------------------------------------
# OBS001 — span/event names from the trace-name catalog
# ---------------------------------------------------------------------------


def test_obs001_true_positive_uncatalogued_and_fstring():
    findings = lint("""
        from xgboost_ray_tpu import obs
        def f(i):
            obs.get_tracer().event("unknown_name_xyz")
            obs.get_tracer().event(f"round.{i}")
    """)
    assert codes(findings) == ["OBS001", "OBS001"]
    assert "TRACE_NAMES" in findings[0].message
    assert "f-string" in findings[1].message


def test_obs001_true_positive_bad_shape():
    findings = lint("""
        def f(tracer):
            tracer.event("Not A Valid Name")
    """)
    assert codes(findings) == ["OBS001"]
    assert "shape" in findings[0].message


def test_obs001_clean_catalogued_names_and_conditional_literal():
    findings = lint("""
        def f(tracer, kind):
            tracer.event("recovered")
            tracer.event("world.shrink" if kind == "shrink" else "world.grow")
            with tracer.span("round", round=3):
                pass
    """)
    assert codes(findings) == []


def test_obs001_reverse_coverage(tmp_path):
    pkg = tmp_path / "xgboost_ray_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "obs" / "__init__.py").write_text("")
    (pkg / "obs" / "trace.py").write_text(
        'TRACE_NAMES = frozenset({"used.name", "orphan.name"})\n'
    )
    (pkg / "emitter.py").write_text(
        'def f(tracer):\n    tracer.event("used.name")\n'
    )
    report = run_lint([str(pkg)], root=str(tmp_path), baseline_path="")
    msgs = [f.message for f in report["open"] if f.rule == "OBS001"]
    assert len(msgs) == 1 and "orphan.name" in msgs[0]


# ---------------------------------------------------------------------------
# EXP001 — export consistency
# ---------------------------------------------------------------------------


def test_exp001_true_positive_unresolved_export():
    findings = lint("""
        x = 1
        __all__ = ["x", "ghost"]
    """, path="pkg/__init__.py")
    assert codes(findings) == ["EXP001"]
    assert "ghost" in findings[0].message


def test_exp001_true_positive_missing_required_export():
    findings = lint("""
        train = object()
        __all__ = ["train"]
    """, path="xgboost_ray_tpu/__init__.py")
    assert any(
        f.rule == "EXP001" and "recovery_time_s" in f.message
        for f in findings
    )


def test_exp001_clean_conditional_imports_and_extend():
    findings = lint("""
        from os import path
        try:
            from json import dumps
        except ImportError:
            pass
        __all__ = ["path"]
        __all__ += ["dumps"]
    """, path="pkg/__init__.py")
    assert codes(findings) == []


def test_exp001_function_local_is_not_a_module_binding():
    # a name bound only inside a function body must not satisfy __all__ —
    # `from pkg import *` would still raise AttributeError at runtime
    findings = lint("""
        __all__ = ["helper"]
        def factory():
            helper = 1
            return helper
    """, path="pkg/__init__.py")
    assert codes(findings) == ["EXP001"]
    # ...but module-level conditional/try bindings DO count
    findings = lint("""
        __all__ = ["helper", "fallback"]
        try:
            from fast import helper
        except ImportError:
            def helper():
                pass
        if True:
            fallback = 1
    """, path="pkg/__init__.py")
    assert codes(findings) == []


def test_exp001_non_init_files_ignored():
    findings = lint('__all__ = ["ghost"]\n', path="pkg/module.py")
    assert codes(findings) == []


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


def test_pragma_same_line_suppresses_named_rule():
    findings = lint("""
        import numpy as np
        def f(n):
            return np.random.rand(n)  # rxgblint: disable=DET001 - fixture
    """)
    assert codes(findings) == []
    assert codes(findings, include_suppressed=True) == ["DET001"]
    assert findings[0].suppressed == "pragma"


def test_pragma_next_line_and_all():
    findings = lint("""
        import numpy as np
        def f(n):
            # rxgblint: disable-next-line=DET001
            a = np.random.rand(n)
            # rxgblint: disable-next-line=all
            b = np.random.rand(n)
            return a + b
    """)
    assert codes(findings) == []
    assert len(codes(findings, include_suppressed=True)) == 2


def test_pragma_wrong_rule_does_not_suppress():
    findings = lint("""
        import numpy as np
        def f(n):
            return np.random.rand(n)  # rxgblint: disable=SPMD001
    """)
    assert codes(findings) == ["DET001"]


def test_pragma_inside_string_literal_does_not_suppress():
    # pragma-shaped text in a string/docstring (e.g. a module documenting
    # the pragma syntax) must never silently disable rules on its line
    findings = lint("""
        import numpy as np
        def f(n):
            return np.random.rand(n), "see  # rxgblint: disable=DET001"
    """)
    assert codes(findings) == ["DET001"]
    findings = lint('''
        import numpy as np
        def f(n):
            """Suppress with  # rxgblint: disable-next-line=all  above."""
            return np.random.rand(n)
    ''')
    assert codes(findings) == ["DET001"]


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def _write_module_with_finding(tmp_path):
    pkg = tmp_path / "xgboost_ray_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(
        "import numpy as np\n"
        "def f(n):\n"
        "    return np.random.rand(n)\n"
    )
    return pkg


def test_baseline_suppresses_with_justification(tmp_path):
    pkg = _write_module_with_finding(tmp_path)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"entries": [{
        "rule": "DET001",
        "path": "xgboost_ray_tpu/mod.py",
        "scope": "f",
        "why": "fixture: accepted finding",
    }]}))
    report = run_lint(
        [str(pkg)], root=str(tmp_path), baseline_path=str(baseline)
    )
    assert report["open"] == []
    assert report["baselined"] == 1
    assert report["stale_baseline"] == []


def test_baseline_requires_justification(tmp_path):
    pkg = _write_module_with_finding(tmp_path)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"entries": [{
        "rule": "DET001",
        "path": "xgboost_ray_tpu/mod.py",
        "scope": "f",
        "why": "   ",
    }]}))
    with pytest.raises(BaselineError):
        run_lint([str(pkg)], root=str(tmp_path), baseline_path=str(baseline))


def test_baseline_stale_entry_reported(tmp_path):
    pkg = _write_module_with_finding(tmp_path)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"entries": [{
        "rule": "LOCK001",
        "path": "xgboost_ray_tpu/gone.py",
        "scope": "C.m",
        "why": "matches nothing anymore",
    }]}))
    report = run_lint(
        [str(pkg)], root=str(tmp_path), baseline_path=str(baseline)
    )
    assert len(report["stale_baseline"]) == 1
    assert codes(report["open"]) == ["DET001"]  # nothing wrongly eaten


# ---------------------------------------------------------------------------
# the tier-1 gate: the shipped package lints clean
# ---------------------------------------------------------------------------


def test_shipped_package_lints_clean():
    report = run_lint([PKG], baseline_path=DEFAULT_BASELINE)
    open_findings = report["open"]
    assert open_findings == [], (
        "rxgblint regression — new findings:\n"
        + "\n".join(f.render() for f in open_findings)
    )


def test_shipped_baseline_is_small_and_justified():
    with open(DEFAULT_BASELINE) as f:
        entries = json.load(f)["entries"]
    assert len(entries) <= 5, "baseline should shrink, not grow"
    for e in entries:
        assert len(e["why"].strip()) > 10


def test_single_file_lint_skips_whole_package_checks():
    # reverse coverage (orphan fault sites / trace names) and stale-baseline
    # reporting are whole-package properties: linting one file must not
    # claim the rest of the package's call sites don't exist
    report = run_lint(
        [os.path.join(PKG, "util.py")], baseline_path=DEFAULT_BASELINE
    )
    assert report["files"] == 1
    assert codes(report["open"]) == []
    assert report["stale_baseline"] == []
    assert not any(
        f.rule in ("FAULT001", "OBS001")
        for f in report["findings"]
    )


def test_json_report_shape():
    report = run_lint([PKG], baseline_path=DEFAULT_BASELINE)
    doc = json.loads(report_to_json(report))
    assert doc["tool"] == "rxgblint"
    assert set(RULES) <= set(doc["rules"])
    assert isinstance(doc["findings"], list)
    assert doc["files"] > 40
    for f in doc["findings"]:
        assert {"rule", "path", "line", "scope", "message"} <= set(f)


def test_rule_catalog_documented():
    for code in ("SPMD001", "SPMD002", "DET001", "SYNC001", "LOCK001",
                 "FAULT001", "OBS001", "EXP001"):
        assert code in RULES and len(RULES[code]) > 20


def test_missing_or_empty_target_is_a_usage_error(tmp_path):
    # a typo'd path must not make the tier-1 gate pass vacuously: 0 files
    # linted has to be a loud exit-2 usage error, never "0 findings"
    from tools.rxgblint.__main__ import main
    from tools.rxgblint.runner import TargetError

    with pytest.raises(TargetError):
        run_lint([str(tmp_path / "nonexistent_typo")])
    with pytest.raises(TargetError):  # existing file, but not Python
        notpy = tmp_path / "data.json"
        notpy.write_text("{}")
        run_lint([str(notpy)])
    assert main([str(tmp_path / "nonexistent_typo")]) == 2
    empty = tmp_path / "emptydir"
    empty.mkdir()
    assert main([str(empty)]) == 2


def test_broken_pipe_does_not_mask_findings(tmp_path):
    # `rxgblint ... | head -0` closing stdout early must not flip a
    # findings run (exit 1) into a pass (exit 0)
    import subprocess

    bad = tmp_path / "bad.py"
    bad.write_text("import numpy as np\nx = np.random.rand(3)\n")
    proc = subprocess.run(
        f"{sys.executable} -m tools.rxgblint {bad} | head -0; "
        f"exit ${{PIPESTATUS[0]}}",
        shell=True, executable="/bin/bash", cwd=REPO_ROOT,
        capture_output=True,
    )
    assert proc.returncode == 1, proc.stderr.decode()


# ---------------------------------------------------------------------------
# runtime counterpart: validate_trace_records(known_names=...)
# ---------------------------------------------------------------------------


def test_validate_trace_records_known_names():
    from xgboost_ray_tpu.obs import TRACE_NAMES, validate_trace_records

    rec = {"kind": "event", "name": "recovered", "ts": 1.0, "seq": 1}
    bad = {"kind": "event", "name": "not.catalogued", "ts": 2.0, "seq": 2}
    assert validate_trace_records([rec, bad]) == []  # default: schema only
    problems = validate_trace_records([rec, bad], known_names=TRACE_NAMES)
    assert len(problems) == 1 and "not.catalogued" in problems[0]
